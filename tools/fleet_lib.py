"""Shared multi-process fleet scaffolding for the SPMD soak and the
plane-latency measurement (tools/soak_spmd.py, benchmarks/
measure_spmd.py).

Both entry points boot N full-server workers inside one
jax.distributed runtime and coordinate them over the CONTROL PLANE
(files), never over jax collectives — a pending collective parks the
local devices, and any peer progress that needs them (serving a
scattered sub-query) deadlocks the join.  That barrier discipline
lives here exactly once so a fix cannot drift between the two
harnesses.

Worker side: ``file_barrier``.  Parent side: ``free_ports`` and
``run_fleet`` (spawn, bounded wait, kill-the-whole-fleet on timeout so
a single dead worker becomes a fast failure instead of a half-hour
hang plus orphaned coordinator/HTTP ports).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import threading
import time


def file_barrier(data_dir: str, name: str, pid: int, nproc: int,
                 timeout: float = 300.0) -> None:
    """Control-plane barrier: write my flag, wait for everyone's.
    Timing out raises SystemExit — in a lockstep fleet a missing peer
    is fatal, and exiting lets run_fleet's reaper surface it fast."""
    open(f"{data_dir}/{name}.{pid}", "w").write("1")
    end = time.monotonic() + timeout
    while not all(os.path.exists(f"{data_dir}/{name}.{p}")
                  for p in range(nproc)):
        if time.monotonic() > end:
            raise SystemExit(f"barrier {name} timeout")
        time.sleep(0.02)


def norm_result(res):
    """One plane-comparable shape for any query result object —
    shared by the SPMD soak's cross-checks and measure_spmd so the
    two harnesses can never drift on normalization conventions.
    Column lists sort defensively (Row.columns() is sorted per shard;
    sorting costs nothing and removes the ordering assumption)."""
    if isinstance(res, (int, bool)):
        return res
    if hasattr(res, "columns"):  # Row: compare the column list
        return sorted(int(c) for c in res.columns())
    if hasattr(res, "val"):  # ValCount
        return (res.val, res.count)
    if hasattr(res, "id"):  # Pair (MinRow/MaxRow)
        return (res.id, res.count)
    if isinstance(res, list) and res and hasattr(res[0], "id"):
        return [(p.id, p.count) for p in res]  # TopN pairs
    if isinstance(res, list) and res and hasattr(res[0], "group"):
        return sorted(
            (tuple((fr.field, fr.row_id) for fr in gc.group), gc.count)
            for gc in res)
    return res


def norm_http_result(raw):
    """The HTTP-JSON twin of norm_result (handler serialize_result
    shapes)."""
    if isinstance(raw, dict):
        if "columns" in raw or "keys" in raw or raw == {}:
            return sorted(raw.get("columns", []))
        if "value" in raw:
            return (raw["value"], raw["count"])
        if "id" in raw:
            return (raw["id"], raw["count"])
        return raw
    if isinstance(raw, list) and raw and isinstance(raw[0], dict):
        if "group" in raw[0]:
            return sorted(
                (tuple((fr["field"], fr["rowID"]) for fr in gc["group"]),
                 gc["count"]) for gc in raw)
        if "id" in raw[0]:
            return [(p["id"], p["count"]) for p in raw]
    return raw


def free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def run_fleet(argv_per_worker: list[list[str]], env_per_worker:
              list[dict], timeout: float, label: str,
              cwd: str | None = None) -> tuple[bool, list[str], bool]:
    """Spawn one process per argv/env pair, wait ``timeout`` seconds
    for ALL of them, and on timeout kill the WHOLE fleet (one worker
    dying leaves the rest parked in a lockstep collective — the
    failure must be fast and leak no coordinator/HTTP ports).

    ``timeout`` bounds the WHOLE fleet (one shared deadline, not a
    fresh allowance per worker).  Returns (ok, outputs, timed_out) —
    ``timed_out`` distinguishes a genuine hang from a fast worker
    crash so callers classify failures correctly.  Every worker's
    pipe is drained by its own reader thread: a worker that writes
    more than the ~64 KB pipe buffer while the parent is waiting on
    an earlier worker must never block on write, or a verbose fast
    crash wedges the lockstep fleet and gets misclassified as a
    hang.  On any failure the tail of every worker's combined
    stdout/stderr is written to stderr."""
    # errors="replace": a stray non-UTF-8 byte must not kill a reader
    # thread (a dead reader stops draining and re-creates the wedge)
    procs = [subprocess.Popen(argv, env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True,
                              errors="replace", cwd=cwd)
             for argv, env in zip(argv_per_worker, env_per_worker)]
    bufs: list[list[str]] = [[] for _ in procs]

    def _drain(stream, buf: list[str]) -> None:
        while True:
            chunk = stream.read(65536)
            if not chunk:
                return
            buf.append(chunk)

    readers = [threading.Thread(target=_drain, args=(p.stdout, buf),
                                daemon=True)
               for p, buf in zip(procs, bufs)]
    for t in readers:
        t.start()
    deadline = time.monotonic() + timeout
    timed_out = False
    for p in procs:
        try:
            p.wait(timeout=max(0.1, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            timed_out = True
            break
    if timed_out:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            p.wait()
    # killed (or exited) processes close their pipe ends, so the
    # readers hit EOF; the join bound is a backstop, not a drain
    for t in readers:
        t.join(timeout=10.0)
    outs = ["".join(buf) for buf in bufs]
    if timed_out:
        sys.stderr.write(f"{label}: TIMEOUT — worker hung; fleet "
                         "killed\n")
        for i, out in enumerate(outs):
            sys.stderr.write(f"--- worker {i} tail ---\n{out[-3000:]}\n")
        return False, outs, True
    ok = all(p.returncode == 0 for p in procs)
    if not ok:
        for i, (p, out) in enumerate(zip(procs, outs)):
            sys.stderr.write(f"--- worker {i} (rc={p.returncode}) "
                             f"tail ---\n{out[-3000:]}\n")
    return ok, outs, False
