"""Multichip capture harness: the MULTICHIP_r*.json body producer.

Runs the multi-device dry run (``__graft_entry__.dryrun_multichip``)
plus a mesh-on/mesh-off A/B of the fused serving engine on an
n-device mesh, and emits ONE structured JSON body carrying the device
count and topology — earlier captures recorded those only in the
stderr log tail (MULTICHIP_r05.json's ``tail`` held nothing but an
axon_guard housekeeping notice), so the artifact now stands alone.

The A/B measures the batch32 coalesced-path workload (bench.py's
batched engine: one fused Count(Intersect) program over a [32, S, W]
operand stack) three ways:

- ``mesh``   — the shard_map program over the n-device mesh
  (parallel/meshexec.py; ONE launch spans every device, per-shard
  counts return through the shard-axis all_gather);
- ``single`` — the identical program on one device (the pre-mesh
  path, what ``?nomesh=1`` runs);
- every sampled batch is verified bit-exact against a host numpy
  recomputation before its timing counts.

Usage::

    python -m tools.multichip [--devices N] [--shards S] [--batch B]
                              [--seconds T]

Prints the JSON body on stdout.  ``bench.py`` shells out to this
module (extras.mesh) so the bench capture and the multichip capture
share one measurement path.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _setup(n_devices: int) -> None:
    import os

    # BEFORE any jax import: jax < 0.5 has no jax_num_cpu_devices
    # config, so the virtual device count must ride XLA_FLAGS into
    # backend init (the conftest.py recipe)
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags
            + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    import __graft_entry__ as ge

    ge._force_virtual_cpu_mesh(n_devices)


def measure(n_devices: int, shards: int = 64, batch: int = 32,
            seconds: float = 2.0, words: int = 1 << 13) -> dict:
    """The mesh-on/mesh-off A/B on the current backend.  Returns the
    ``mesh`` axis dict: devices, qps per engine, launches/query, and
    the scaling ratio."""
    import numpy as np

    import jax
    from pilosa_tpu.ops import bitmap as bm
    from pilosa_tpu.ops import expr
    from pilosa_tpu.parallel import meshexec

    meshexec.configure(enabled=True, axis_size=n_devices)
    mesh = meshexec.active_mesh()
    assert mesh is not None and mesh.size == n_devices, (
        "mesh failed to activate", n_devices)

    rng = np.random.default_rng(7)
    # shard axis padded to the mesh multiple, exactly as
    # Field.device_row_stack pads
    pad = ((shards + n_devices - 1) // n_devices) * n_devices
    a = np.zeros((batch, pad, words), dtype=np.uint32)
    b = np.zeros((batch, pad, words), dtype=np.uint32)
    a[:, :shards] = rng.integers(0, 1 << 32,
                                 size=(batch, shards, words),
                                 dtype=np.uint32)
    b[:, :shards] = rng.integers(0, 1 << 32,
                                 size=(batch, shards, words),
                                 dtype=np.uint32)
    want = np.unpackbits((a & b).view(np.uint8),
                         axis=-1).sum(axis=(1, 2)).astype(np.int64)
    shape = ("and", ("leaf", 0), ("leaf", 1))

    def run(use_mesh: bool) -> dict:
        m = mesh if use_mesh else None
        if use_mesh:
            ad = meshexec.ensure_placed(jax.numpy.asarray(a), mesh, 1)
            bd = meshexec.ensure_placed(jax.numpy.asarray(b), mesh, 1)
        else:
            ad = jax.device_put(a)
            bd = jax.device_put(b)
        # warm (compile) + verify bit-exactness vs the host truth
        with bm.dispatch_counter() as dc:
            out = expr.evaluate(shape, (ad, bd), counts=True, mesh=m)
        got = np.asarray(out, dtype=np.int64).sum(axis=-1)
        assert np.array_equal(got, want), "bit-exactness violated"
        launches_per_query = dc.n / batch
        reps = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < seconds:
            out = expr.evaluate(shape, (ad, bd), counts=True, mesh=m)
            jax.block_until_ready(out)
            reps += 1
        dt = time.perf_counter() - t0
        return {
            "qps": round(batch * reps / dt, 2),
            "launches_per_query": launches_per_query,
            "reps": reps,
        }

    single = run(False)
    meshed = run(True)
    return {
        "devices": n_devices,
        "shards": shards,
        "batch": batch,
        "words": words,
        "qps": meshed["qps"],
        "launches_per_query": meshed["launches_per_query"],
        "qps_single_device": single["qps"],
        "scaling_vs_single": round(meshed["qps"] / single["qps"], 3)
        if single["qps"] else None,
        "counters": meshexec.counters(),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--devices", type=int, default=4)
    ap.add_argument("--shards", type=int, default=64)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--seconds", type=float, default=2.0)
    ap.add_argument("--words", type=int, default=1 << 13)
    ap.add_argument("--skip-dryrun", action="store_true",
                    help="A/B only (bench.py's extras.mesh mode)")
    args = ap.parse_args(argv)

    _setup(args.devices)
    import jax

    devs = jax.devices()
    body: dict = {
        "devices": len(devs),
        "platform": devs[0].platform,
        "topology": [{"id": d.id, "process": d.process_index,
                      "kind": getattr(d, "device_kind", "")}
                     for d in devs],
    }
    if not args.skip_dryrun:
        import __graft_entry__ as ge

        ge.dryrun_multichip(args.devices)
        body["dryrun_ok"] = True
    body["mesh"] = measure(args.devices, shards=args.shards,
                           batch=args.batch, seconds=args.seconds,
                           words=args.words)
    print(json.dumps(body))
    return 0


if __name__ == "__main__":
    sys.exit(main())
