"""``make typecheck`` driver: run mypy over the strict scope declared
in mypy.ini, gating gracefully when mypy is not installed (the CI
image bakes its own toolchain; nothing may be pip-installed at test
time).  Exit codes: mypy's own when it runs, 0 with a loud ``skipped``
line when it cannot.

The strict scope (ops/tape.py, ops/expr.py, runtime/resultcache.py)
is the growth frontier — see mypy.ini and docs/development.md.
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: The files mypy checks (the strict trio plus anything they import is
#: followed silently per mypy.ini).
SCOPE = (
    "pilosa_tpu/ops/tape.py",
    "pilosa_tpu/ops/expr.py",
    "pilosa_tpu/runtime/resultcache.py",
)


def main() -> int:
    try:
        import mypy  # noqa: F401
    except ImportError:
        print("typecheck: skipped — mypy is not installed in this "
              "environment (the scope still gates in images that "
              "carry it; config: mypy.ini)")
        return 0
    cmd = [sys.executable, "-m", "mypy", "--config-file",
           os.path.join(REPO, "mypy.ini")]
    cmd.extend(os.path.join(REPO, p) for p in SCOPE)
    proc = subprocess.run(cmd, cwd=REPO)
    return proc.returncode


if __name__ == "__main__":
    sys.exit(main())
