"""P5 config-baseline: process-wide config mutations must carry their
restore protocol.

The incident: PR 6 spent review rounds 4-5 on the ``[ingest]`` config
— servers configure the process-wide knobs in place, and per-server
restore snapshots composed wrongly under create-A-create-B-close-A-
close-B (the last closer re-installed a sibling's override).  The fix
is the ``capture_baseline``/``restore_baseline`` protocol (first
configurer snapshots, LAST closer restores) plus the refcounted
``compactor.retain``/``release`` pair for the shared scan thread.

The pass holds every future call site to the protocol at module
granularity: a module (outside the owning definition module) that
calls a registered config mutator — ``ingest.configure(...)``, an
attribute write through an ``ingest.config()`` alias, or
``compactor.retain()`` — must also reference every name in the
mutator's registered pair.  Module granularity is deliberate: capture
happens in ``Server.open`` and restore in ``Server.close``, so
function-level pairing would be all noise; what the pass catches is
the realistic failure — a NEW call site (a tool, a test harness
promoted to product code, a second assembly) that flips process-wide
config and never restores it for library users sharing the process.
"""

from __future__ import annotations

import ast

from tools.analyze import registry as reg
from tools.analyze.core import Finding, SourceFile


def _matches(txt: str, suffixes) -> bool:
    return any(txt == s or txt.endswith("." + s) for s in suffixes)


class ConfigBaselinePass:
    rule = "config-baseline"

    def run(self, sf: SourceFile) -> list[Finding]:
        out: list[Finding] = []
        # names referenced anywhere in the module (pairing evidence)
        referenced: set[str] = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Attribute):
                referenced.add(node.attr)
            elif isinstance(node, ast.Name):
                referenced.add(node.id)

        # config() accessor aliases: per-function `x = ....config()`
        alias_writes = self._alias_writes(sf)

        for grule in reg.CONFIG_GUARDS:
            if any(sf.suffix_is(s) for s in grule.owner_suffixes):
                continue
            sites: list[tuple[int, str]] = []
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Call):
                    txt = ast.unparse(node.func)
                    if _matches(txt, grule.mutator_suffixes):
                        sites.append((node.lineno, txt))
            # accessor-alias attribute writes count against the FIRST
            # guard whose mutators share the accessor's module prefix
            if grule is reg.CONFIG_GUARDS[0]:
                sites.extend(alias_writes)
            missing = [p for p in grule.pair if p not in referenced]
            if sites and missing:
                for lineno, txt in sites:
                    out.append(Finding(
                        self.rule, sf.path, lineno,
                        f"{txt} mutates {grule.what} but this module "
                        f"never references {missing} — the mutation "
                        "outlives the mutator for every other user "
                        "of the process (see registry CONFIG_GUARDS)"))
        return out

    def _alias_writes(self, sf) -> list[tuple[int, str]]:
        """Attribute writes through ``cfg = <x>.config()`` aliases and
        direct ``<x>.config().attr = ...`` writes."""
        out: list[tuple[int, str]] = []
        for fn in ast.walk(sf.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.Module)):
                continue
            aliases: set[str] = set()
            body_nodes = list(ast.walk(fn)) if isinstance(
                fn, ast.FunctionDef) else [
                n for st in fn.body
                if not isinstance(st, (ast.FunctionDef, ast.ClassDef))
                for n in ast.walk(st)]
            for node in body_nodes:
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call) and \
                        _matches(ast.unparse(node.value.func),
                                 reg.CONFIG_ACCESSOR_SUFFIXES):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            aliases.add(t.id)
            for node in body_nodes:
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (node.targets
                               if isinstance(node, ast.Assign)
                               else [node.target])
                    for t in targets:
                        if not isinstance(t, ast.Attribute):
                            continue
                        v = t.value
                        if isinstance(v, ast.Name) and v.id in aliases:
                            out.append((
                                t.lineno,
                                f"{v.id}.{t.attr} (via "
                                f"{v.id} = ingest.config())"))
                        elif isinstance(v, ast.Call) and _matches(
                                ast.unparse(v.func),
                                reg.CONFIG_ACCESSOR_SUFFIXES):
                            out.append((
                                t.lineno,
                                f"{ast.unparse(v)}.{t.attr}"))
        return out
