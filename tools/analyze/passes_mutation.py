"""P2 generation-audit: mutation implies a generation bump.

The result cache's entire correctness argument (runtime/resultcache.py
"stamp-before-read") rests on one discipline: EVERY path that changes
a fragment's effective content moves ``_gen`` (base mutations,
compaction) or ``_delta_seq`` (delta-landing writes).  PR 5 verified
this with a parametrized hand-audit over every mutation path and PR 6
re-verified it for the delta write path — this pass is the
machine-checked form.

Model (per registered class): a method *mutates* when it writes a
target attribute (``self._rows[...] = ...``, ``.pop``/``.clear``/
``.setdefault``/``.update`` on it), calls a registered mutation
primitive, or calls a delta-plane writer (``add_bit``/
``add_positions``).  A method *bumps* when it assigns or augments a
bump attribute.  Both facts close transitively over same-class
``self.<method>()`` calls — so ``import_roaring`` inherits its bump
from nothing (it bumps inline) while ``_stacked`` inherits BOTH facts
from ``_flush_delta_locked`` and passes.  A method that (transitively)
mutates but never (transitively) bumps is the finding, anchored at its
``def`` line.  Primitives themselves and registry-exempt methods are
skipped: their callers own the bump, and the exemption reason is
recorded in the registry.

This is containment, not path-sensitivity: a method that bumps on one
branch and returns mutated-without-bump on another is out of scope
(the paranoia gate and the audit tests own runtime verification).
What this catches is the realistic review-round failure — a new
mutation path that never bumps at all.
"""

from __future__ import annotations

import ast

from tools.analyze import registry as reg
from tools.analyze.core import Finding, SourceFile

_MUTATING_CONTAINER_METHODS = ("pop", "clear", "setdefault", "update",
                               "__setitem__")


def _method_facts(fn: ast.FunctionDef, rule) -> dict:
    """(mutates, bumps, calls) facts for one method body."""
    mutates = False
    bumps = False
    calls: set[str] = set()
    for node in ast.walk(fn):
        # self.<bump> += 1 / self.<bump> = ...
        if isinstance(node, (ast.AugAssign, ast.Assign)):
            targets = ([node.target] if isinstance(node, ast.AugAssign)
                       else node.targets)
            for t in targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name):
                    if t.attr in rule.bump_attrs and \
                            t.value.id == "self":
                        bumps = True
                    if t.attr in rule.targets:
                        mutates = True  # <recv>._rows = ... (any recv)
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Attribute) and \
                        t.value.attr in rule.targets:
                    mutates = True  # <recv>._rows[...] = ...
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Attribute) and \
                        t.value.attr in rule.targets:
                    mutates = True
        elif isinstance(node, ast.Call):
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            # self.<primitive>(...) / self.<helper>(...)
            if isinstance(func.value, ast.Name) and \
                    func.value.id == "self":
                if func.attr in rule.primitives:
                    mutates = True
                else:
                    calls.add(func.attr)
            # <anything>.add_bit(...) — delta-plane write
            if func.attr in rule.delta_mutators:
                mutates = True
            # <recv>._rows.pop(...) and friends
            if func.attr in _MUTATING_CONTAINER_METHODS and \
                    isinstance(func.value, ast.Attribute) and \
                    func.value.attr in rule.targets:
                mutates = True
    return {"mutates": mutates, "bumps": bumps, "calls": calls}


class GenerationAuditPass:
    rule = "generation-audit"

    def run(self, sf: SourceFile) -> list[Finding]:
        out: list[Finding] = []
        for (suffix, cls), rule in reg.GEN_AUDIT.items():
            if not sf.suffix_is(suffix):
                continue
            for node in sf.tree.body:
                if isinstance(node, ast.ClassDef) and node.name == cls:
                    out.extend(self._check_class(sf, node, rule))
        return out

    def _check_class(self, sf, cls_node, rule) -> list[Finding]:
        methods = {m.name: m for m in cls_node.body
                   if isinstance(m, ast.FunctionDef)}
        facts = {name: _method_facts(fn, rule)
                 for name, fn in methods.items()}
        # transitive closure over same-class calls (fixpoint; the call
        # graph is tiny)
        changed = True
        while changed:
            changed = False
            for name, f in facts.items():
                for callee in f["calls"]:
                    cf = facts.get(callee)
                    if cf is None:
                        continue
                    for key in ("mutates", "bumps"):
                        if cf[key] and not f[key]:
                            f[key] = True
                            changed = True
        out = []
        for name, f in facts.items():
            if name in rule.primitives or name in rule.exempt:
                continue
            if f["mutates"] and not f["bumps"]:
                out.append(Finding(
                    self.rule, sf.path, methods[name].lineno,
                    f"{cls_node.name}.{name} mutates base words/rows "
                    "but never bumps "
                    f"{' or '.join(sorted(rule.bump_attrs))} — stale "
                    "result-cache entries would keep serving (see "
                    "registry GEN_AUDIT)"))
        return out
