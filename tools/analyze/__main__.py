"""CLI entry: ``python -m tools.analyze [--json] [--show-suppressed]
[PATH ...]`` — exit 1 on any unsuppressed finding (``make analyze``)."""

from __future__ import annotations

import sys

from tools.analyze.core import main

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
