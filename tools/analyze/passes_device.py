"""P4 recompile-hazard: free-running shapes must not reach jitted
entry points, and no jax array work at import time.

The incident this encodes: PR 6's mixed-workload acceptance run found
read p99 collapsed ~10x under sustained ingest because the coalescer
dispatched device batches at free-running occupancies (2, 3, 5, ...)
— the jitted program re-lowers per input shape, so every novel batch
size paid a fresh multi-hundred-ms XLA compile IN THE SERVING PATH,
convoying every query in the process.  The fix (pow2 batch padding +
size classes) only helps if every future call site keeps the
discipline; this pass holds them to it.

Two checks:

- **free-running batch shape**: a function that (a) calls a jitted
  entry point (``expr.evaluate`` / ``tape.execute``), AND (b) builds a
  variable-length batch stack (``jnp.stack``/``jnp.concatenate``/
  ``np.stack`` over a comprehension, starred arg, or non-literal), AND
  (c) never references a pow2/size-class helper
  (``_pow2``/``size_class``/``_pad_batch``/``_padded_rows``/...), is
  flagged at the jitted call site.  Referencing the helper is the
  evidence the batch axis was quantized; the registry lists the
  blessed helper names.
- **import-time jax**: any ``jnp.*``/``jax.*`` CALL in module-level
  statements (outside def/class bodies).  Importing a module must
  never initialize a backend or trace a program — serving processes
  import lazily and on the worker path.  ``jax.jit``/``jax.vmap``
  wrapping (decorators included) is lazy and allowed.
"""

from __future__ import annotations

import ast

from tools.analyze import registry as reg
from tools.analyze.core import Finding, SourceFile


def _is_variable_batch(call: ast.Call) -> bool:
    """Does this stack/concatenate call take a variable-length
    sequence?  A fixed literal list of exprs is a static shape; a
    comprehension, starred element, or plain name is not."""
    if not call.args:
        return False
    a = call.args[0]
    if isinstance(a, (ast.List, ast.Tuple)):
        return any(isinstance(el, ast.Starred) for el in a.elts)
    return True


class RecompileHazardPass:
    rule = "recompile-hazard"

    def run(self, sf: SourceFile) -> list[Finding]:
        out: list[Finding] = []
        out.extend(self._import_time(sf))
        for node in sf.tree.body:
            if isinstance(node, ast.FunctionDef):
                out.extend(self._function(sf, node))
            elif isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, ast.FunctionDef):
                        out.extend(self._function(sf, item))
        return out

    # ------------------------------------------------ free-running shapes

    def _function(self, sf, fn) -> list[Finding]:
        jit_calls: list[ast.Call] = []
        variable_stack: list[ast.Call] = []
        has_helper = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and \
                    node.id in reg.SHAPE_HELPER_NAMES:
                has_helper = True
            elif isinstance(node, ast.Attribute) and \
                    node.attr in reg.SHAPE_HELPER_NAMES:
                has_helper = True
            elif isinstance(node, ast.Call):
                txt = ast.unparse(node.func)
                if any(txt == s or txt.endswith("." + s)
                       for s in reg.JIT_ENTRY_SUFFIXES):
                    jit_calls.append(node)
                elif any(txt == s or txt.endswith("." + s)
                         for s in reg.STACK_BUILDER_SUFFIXES):
                    if _is_variable_batch(node):
                        variable_stack.append(node)
        if jit_calls and variable_stack and not has_helper:
            return [Finding(
                self.rule, sf.path, c.lineno,
                f"{ast.unparse(c.func)}() reached with a "
                "variable-length batch stack (built at line "
                f"{variable_stack[0].lineno}) and no pow2/size-class "
                "helper in scope — every novel occupancy re-lowers "
                "the jitted program in the serving path (the PR-6 "
                "convoy); route the batch axis through "
                f"{sorted(reg.SHAPE_HELPER_NAMES)[0]}/size_class "
                "style padding") for c in jit_calls]
        return []

    # -------------------------------------------------- import-time work

    def _import_time(self, sf) -> list[Finding]:
        out = []
        for st in sf.tree.body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef, ast.Import,
                               ast.ImportFrom)):
                continue
            for node in ast.walk(st):
                if not isinstance(node, ast.Call):
                    continue
                txt = ast.unparse(node.func)
                root = txt.split(".", 1)[0]
                if root not in reg.IMPORT_TIME_JAX_ROOTS:
                    continue
                if any(txt == a or txt.startswith(a + ".")
                       for a in reg.IMPORT_TIME_ALLOWED):
                    continue
                out.append(Finding(
                    self.rule, sf.path, node.lineno,
                    f"{txt}() runs at module import time — backend "
                    "init / tracing on import stalls every importer "
                    "(move it into the function that needs it)"))
        return out
