"""P6 metric-family drift: the static twin of
``tools/check_metrics.check_families``.

The live checker can only see families a running server happens to
emit; this pass sees every emission SITE.  It harvests each metric
name fed to the stats registry across the package — string-literal
first arguments of ``.count``/``.gauge``/``.histogram``/``.timing``/
``.count_with_tags`` calls, ``bump("...")`` module-counter feeds, and
the string keys of module-level ``_counters`` dict literals — and
diffs the result against the one declarative registry
(``pilosa_tpu/metricfamilies.py``):

- a harvested dotted name whose family is not declared -> finding at
  the emission site (a new family must be declared once, where the
  live checker and the docs checks will see it);
- a declared ``static=True`` family with no harvested emitter ->
  finding at the family's declaration line (a refactor silently
  dropped a whole telemetry family — exactly what
  ``check_families`` exists to catch, but at analysis time instead
  of against a live server);
- a family naming a ``doc`` file whose rendered prefix no longer
  appears there -> finding at the declaration line (operator docs
  rot).

Dotted names only: bare names (``threads``, ``pilosa_query_latency``)
are inventoried in ``metricfamilies.BARE_METRICS`` and skipped here.
Dynamic names (f-strings, variables) are invisible to the harvest by
design — families must keep at least one literal emitter, which every
family today has.
"""

from __future__ import annotations

import ast
import os
import re

from tools.analyze import registry as reg
from tools.analyze.core import Finding, SourceFile

_DOTTED_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_]+)+$")


def _harvest_file(sf: SourceFile) -> list[tuple[str, int]]:
    """(metric name, line) literals fed to the stats registry."""
    out: list[tuple[str, int]] = []

    def literal_name(node) -> str | None:
        if isinstance(node, ast.Constant) and \
                isinstance(node.value, str) and \
                _DOTTED_RE.match(node.value):
            return node.value
        return None

    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call):
            func = node.func
            is_stats = (isinstance(func, ast.Attribute)
                        and func.attr in reg.STATS_CALL_ATTRS)
            is_feed = ((isinstance(func, ast.Name)
                        and func.id in reg.STATS_CALL_FUNCS)
                       or (isinstance(func, ast.Attribute)
                           and func.attr in reg.STATS_CALL_FUNCS))
            if (is_stats or is_feed) and node.args:
                name = literal_name(node.args[0])
                if name is not None:
                    out.append((name, node.lineno))
        elif isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Dict):
            names = [t.id for t in node.targets
                     if isinstance(t, ast.Name)]
            if any(n in reg.STATS_DICT_NAMES for n in names):
                for key in node.value.keys:
                    name = literal_name(key)
                    if name is not None:
                        out.append((name, key.lineno))
    return out


def _registry_module():
    from pilosa_tpu import metricfamilies

    return metricfamilies


def _declaration_line(family_name: str,
                      files: list[SourceFile]) -> tuple[str, int]:
    """(path, line) of one family's declaration in
    pilosa_tpu/metricfamilies.py, for file:line-quality findings.
    Anchors at the ANALYZED file's own path spelling when the registry
    is in the sweep (absolute vs relative invocation must not detach
    the finding from its file — suppression matching is per-path)."""
    for sf in files:
        if sf.suffix_is("pilosa_tpu/metricfamilies.py"):
            for lineno, line in enumerate(sf.src.splitlines(), 1):
                if f'Family("{family_name}"' in line:
                    return sf.path, lineno
            return sf.path, 1
    mod = _registry_module()
    path = mod.__file__
    rel = os.path.relpath(path)
    try:
        with open(path, encoding="utf-8") as fh:
            for lineno, line in enumerate(fh, 1):
                if f'Family("{family_name}"' in line:
                    return rel, lineno
    except OSError:
        pass
    return rel, 1


class MetricFamilyDriftPass:
    rule = "metric-family-drift"

    def run_package(self, files: list[SourceFile]) -> list[Finding]:
        mod = _registry_module()
        declared = mod.by_name()
        out: list[Finding] = []
        seen_families: set[str] = set()
        for sf in files:
            if sf.suffix_is("metricfamilies.py"):
                continue  # the registry's own docstrings/examples
            for name, lineno in _harvest_file(sf):
                family = name.split(".", 1)[0]
                seen_families.add(family)
                if family not in declared:
                    out.append(Finding(
                        self.rule, sf.path, lineno,
                        f"metric {name!r} belongs to undeclared "
                        f"family {family!r} — declare it in "
                        "pilosa_tpu/metricfamilies.py (one "
                        "declaration feeds the live check, this "
                        "pass, and the docs check)"))
        analyzed_any = bool(files)
        for fam in mod.static_families():
            path, line = _declaration_line(fam.name, files)
            if analyzed_any and fam.name not in seen_families:
                out.append(Finding(
                    self.rule, path, line,
                    f"family {fam.name!r} is declared static but no "
                    "emitter was harvested in the analyzed tree — "
                    "the telemetry family was dropped (or its last "
                    "emitter went dynamic)"))
            if fam.doc is not None:
                doc_path = os.path.join(
                    os.path.dirname(os.path.dirname(
                        os.path.dirname(os.path.abspath(__file__)))),
                    "docs", fam.doc)
                try:
                    with open(doc_path, encoding="utf-8") as fh:
                        text = fh.read()
                except OSError:
                    text = ""
                if fam.rendered not in text and \
                        fam.name + "." not in text:
                    out.append(Finding(
                        self.rule, path, line,
                        f"family {fam.name!r} declares doc "
                        f"{fam.doc!r} but neither {fam.rendered!r} "
                        f"nor {fam.name + '.'!r} appears there — "
                        "operator docs drifted"))
        return out
