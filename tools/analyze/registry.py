"""The declarative project model pilosa-lint checks against.

Everything here is an INVARIANT REGISTRY, not analyzer configuration:
each entry names a concurrency/caching/device contract the codebase
relies on, in one place, machine-checked by the passes.  Growing the
system means growing this file — a new lock-guarded structure, metric
family, or process-wide config knob is declared here and the analyzer
holds every touch to the declared discipline from then on.

Paths are repo-relative suffixes (``models/fragment.py``) so the suite
works from any checkout root and on synthetic fixture paths in tests.
"""

from __future__ import annotations

from dataclasses import dataclass, field

# --------------------------------------------------------- P1: lock model

#: Attribute names that hold locks; ``with <recv>.<one of these>:``
#: marks a held-lock region for receiver ``<recv>``, and a bare
#: ``with <name>:`` where <name> ends in ``_lock`` marks a module-level
#: region.
LOCK_ATTR_NAMES = ("_lock", "_global_lock", "_cfg_lock", "_graph_lock",
                   "_plan_lock", "_route_lock")


@dataclass(frozen=True)
class ClassLockRule:
    """One class whose listed attributes are guarded by ``self.<lock>``.

    ``helpers`` are methods with a documented caller-holds-the-lock
    contract (the ``*_locked`` suffix is honored automatically, as is
    ``__init__`` — construction is single-threaded).  Listing a method
    here IS the declaration of that contract; the reason strings keep
    the registry reviewable.
    """

    lock: str
    attrs: frozenset
    helpers: dict = field(default_factory=dict)  # name -> contract note


CLASS_LOCKS: dict[tuple, ClassLockRule] = {
    ("models/fragment.py", "Fragment"): ClassLockRule(
        lock="_lock",
        attrs=frozenset({
            "_rows", "_gen", "_delta_seq", "_delta", "_op_n", "_wal",
            "_stack_cache", "_device_cache", "_container_cache",
            "_blocks_cache", "_snapshotting", "_closed",
        }),
        helpers={
            "_load": "construction-time replay, single-threaded",
            "_replay_wal": "construction-time replay, single-threaded",
            "_replay_wal_file": "construction-time replay",
            "_wal_append": "every caller is a mutator holding _lock",
            "_apply_set": "mutation primitive; callers hold _lock",
            "_apply_clear": "mutation primitive; callers hold _lock",
            "_apply_bulk": "mutation primitive; callers hold _lock",
            "_merge_roaring": "callers hold _lock (or _load replay)",
            "_merge_positions": "callers hold _lock (or _load replay)",
            "_row_array": "mutation primitive; callers hold _lock",
            "_maybe_snapshot": "called at the tail of locked mutators",
            "_delta_or_new": "delta write path; callers hold _lock",
            "_delta_set_bit": "delta write path; callers hold _lock",
            "_delta_row_seq": "token read under the caller's _lock",
        },
    ),
    ("ingest/compactor.py", "Compactor"): ClassLockRule(
        lock="_lock",
        attrs=frozenset({
            "_frags", "_pending_bytes", "_paused", "_thread",
            "compactions", "compacted_bits", "inline_flushes",
            "compact_skipped", "delta_writes",
        }),
    ),
    ("runtime/resultcache.py", "ResultCache"): ClassLockRule(
        lock="_lock",
        attrs=frozenset({
            "_entries", "_flights", "_noflight", "bytes", "hits",
            "misses", "fills", "evictions", "invalidations",
            "skipped_oversize", "flight_joins", "flight_served",
            "_tenant_bytes", "_tenant_lru", "_tenant_counters",
            "tenant_pref_evictions",
        }),
        helpers={
            "_tc_locked": "callers hold self._lock",
            "_tenant_track_locked": "callers hold self._lock",
            "_tenant_untrack_locked": "callers hold self._lock",
            "_tenant_touch_locked": "callers hold self._lock",
            "_victim_key_locked": "callers hold self._lock",
        },
    ),
    ("parallel/coalescer.py", "Coalescer"): ClassLockRule(
        lock="_lock",
        attrs=frozenset({"_pending"}),
        # _tape_memo is deliberately UNREGISTERED: racy-by-design
        # (a duplicate compile is wasted work, never a wrong entry —
        # see the inline comment at its definition)
    ),
    ("runtime/residency.py", "ResidencyManager"): ClassLockRule(
        lock="_lock",
        attrs=frozenset({
            "_entries", "total", "_per_device", "_by_kind",
            "evictions", "admits", "high_water",
            "_host", "_host_bytes", "_disk", "_disk_bytes",
            "_spill_seq", "demotions", "tier_hits", "tier_misses",
            "tier_spills", "tier_spill_drops", "disk_hits",
            "fallbacks", "oom_budget_shrinks", "_prefetched",
            "prefetch_useful", "_tenant_bytes", "_tenant_host_bytes",
            "_tenant_pressure",
        }),
        helpers={
            "_tenant_charge_locked": "callers hold self._lock",
            "_tenant_host_charge_locked": "callers hold self._lock",
        },
        # ``budget`` is deliberately UNREGISTERED: written only under
        # the lock (note_oom_feedback), read lock-free by the entry
        # caps and stats — the monotone-ish operator-knob discipline
        # (a stale read admits one borderline entry, never corrupts)
    ),
    ("runtime/residency.py", "Promoter"): ClassLockRule(
        lock="_lock",
        attrs=frozenset({
            "_queue", "_flights", "_workers", "_epoch", "promotions",
            "failures", "sheds", "prefetch_issued",
            "prefetch_completed", "prefetch_shed",
        }),
    ),
    ("parallel/hints.py", "HintStore"): ClassLockRule(
        lock="_lock",
        attrs=frozenset({"_queues", "_total_bytes"}),
        helpers={
            "_parse_file_locked": "called from _load under self._lock",
            "_queue_locked": "callers hold self._lock",
            "_rewrite_locked": "callers hold self._lock",
        },
    ),
    ("parallel/rebalance.py", "RebalanceCoordinator"): ClassLockRule(
        lock="_plan_lock",
        attrs=frozenset({"_plan", "_last"}),
        # _abort_requested/_thread/_halt are deliberately
        # UNREGISTERED: a bool flag checked once after the worker
        # join, a thread handle, and an Event — single-writer
        # signals, not shared mutable state
    ),
    ("parallel/cluster.py", "Cluster"): ClassLockRule(
        lock="_route_lock",
        attrs=frozenset({"_shard_routes"}),
    ),
    ("serve/admission.py", "AdmissionController"): ClassLockRule(
        lock="_lock",
        # ``_gates`` itself is immutable after construction (the dict
        # is only ever READ to find a gate; all mutable state lives in
        # gate/tenant fields touched under the lock), so it is
        # deliberately unregistered — the *_locked helper contracts
        # below are the checked surface
        attrs=frozenset(),
        helpers={
            "_wake_tenants_locked": "called from _release under "
                                    "self._lock",
            "_query_pressure_locked": "callers hold self._lock",
            "_tenant_dict_locked": "callers hold self._lock",
        },
    ),
    ("observe.py", "EventJournal"): ClassLockRule(
        lock="_lock",
        attrs=frozenset({"_ring", "_seq", "_by_kind", "_dropped"}),
        # node_id / kinds are deliberately UNREGISTERED: operator
        # knobs rebound under the module _cfg_lock and read at emit
        # time (a momentarily stale read stamps one event with the
        # old node id / filter, never corrupts the ring)
    ),
    ("parallel/cluster.py", "CircuitBreaker"): ClassLockRule(
        lock="_lock",
        attrs=frozenset({"_state", "_failures", "_opened_t",
                         "_probing", "_probe_t"}),
        # the cumulative transition counters (opened/closed/
        # half_opens/fast_fails) are deliberately UNREGISTERED:
        # monotone ints read lock-free by the gauge publisher (the
        # _gen discipline — a stale read is a stale gauge, never a
        # wrong transition)
    ),
}

#: Guarded attributes checked on NON-self receivers anywhere in the
#: sweep: ``frag._rows`` needs an active ``with frag._lock`` region.
#: mode "rw" checks loads and stores; "w" checks stores only — the
#: monotone token ints (_gen/_delta_seq) are read lock-free by design
#: (GIL-atomic int loads; the stamp-before-read discipline tolerates
#: any interleaving, see runtime/resultcache.py's module docstring).
CROSS_OBJECT_ATTRS: dict[str, str] = {
    "_rows": "rw",
    "_delta": "rw",
    "_frags": "rw",
    "_flights": "rw",
    "_noflight": "rw",
    "_gen": "w",
    "_delta_seq": "w",
}


@dataclass(frozen=True)
class ModuleGlobalRule:
    """One module-level global guarded by a module-level lock.  mode
    as above; ``attrs=True`` additionally guards attribute WRITES
    through the name (``_cfg.delta_enabled = ...``)."""

    name: str
    lock: str
    mode: str = "rw"
    attrs: bool = False


MODULE_LOCKS: dict[str, tuple] = {
    "ops/tape.py": (
        ModuleGlobalRule("_counters", "_lock", "rw"),
        ModuleGlobalRule("_lowered", "_lock", "rw"),
        ModuleGlobalRule("_vm_lowered", "_lock", "rw"),
    ),
    "ops/containers.py": (
        ModuleGlobalRule("_counters", "_lock", "rw"),
        ModuleGlobalRule("_cfg", "_cfg_lock", "w", attrs=True),
        ModuleGlobalRule("_baseline", "_cfg_lock", "rw"),
        ModuleGlobalRule("_refs", "_cfg_lock", "rw"),
        ModuleGlobalRule("_stage_memo", "_stage_lock", "w"),
        ModuleGlobalRule("_megapool_memo", "_mega_lock", "w"),
    ),
    "runtime/resultcache.py": (
        # reads are the lock-free fast path (documented); rebinds only
        # under the construction lock
        ModuleGlobalRule("_global", "_global_lock", "w"),
    ),
    "ingest/compactor.py": (
        ModuleGlobalRule("_global", "_global_lock", "w"),
        ModuleGlobalRule("_refs", "_global_lock", "w"),
    ),
    "ingest/__init__.py": (
        ModuleGlobalRule("_cfg", "_cfg_lock", "w", attrs=True),
        ModuleGlobalRule("_baseline", "_cfg_lock", "rw"),
    ),
    "serve/tenant.py": (
        # reads (policy()/enabled()/quota_for) are the lock-free hot
        # path by design — a momentarily stale policy admits one
        # borderline request, never corrupts; rebinds/attr-writes only
        # under the config lock
        ModuleGlobalRule("_cfg", "_cfg_lock", "w", attrs=True),
        ModuleGlobalRule("_baseline", "_cfg_lock", "rw"),
        ModuleGlobalRule("_refs", "_cfg_lock", "rw"),
    ),
    "parallel/meshexec.py": (
        ModuleGlobalRule("_counters", "_lock", "rw"),
        ModuleGlobalRule("_cfg", "_cfg_lock", "w", attrs=True),
        ModuleGlobalRule("_baseline", "_cfg_lock", "rw"),
        ModuleGlobalRule("_refs", "_cfg_lock", "rw"),
        ModuleGlobalRule("_mesh_cache", "_cfg_lock", "w"),
    ),
    "runtime/residency.py": (
        ModuleGlobalRule("_cfg", "_cfg_lock", "w", attrs=True),
        ModuleGlobalRule("_baseline", "_cfg_lock", "rw"),
        ModuleGlobalRule("_refs", "_cfg_lock", "rw"),
        ModuleGlobalRule("_global", "_global_lock", "w"),
    ),
    "parallel/hints.py": (
        ModuleGlobalRule("_counters", "_lock", "rw"),
        ModuleGlobalRule("_cfg", "_cfg_lock", "w", attrs=True),
        ModuleGlobalRule("_baseline", "_cfg_lock", "rw"),
        ModuleGlobalRule("_refs", "_cfg_lock", "rw"),
    ),
    "parallel/rebalance.py": (
        ModuleGlobalRule("_counters", "_lock", "rw"),
        ModuleGlobalRule("_cfg", "_cfg_lock", "w", attrs=True),
        ModuleGlobalRule("_baseline", "_cfg_lock", "rw"),
        ModuleGlobalRule("_refs", "_cfg_lock", "rw"),
    ),
    "parallel/syncer.py": (
        ModuleGlobalRule("_counters", "_lock", "rw"),
    ),
    "perfobs.py": (
        ModuleGlobalRule("_counters", "_lock", "rw"),
        ModuleGlobalRule("_table", "_lock", "rw"),
        ModuleGlobalRule("_cfg", "_cfg_lock", "w", attrs=True),
        ModuleGlobalRule("_baseline", "_cfg_lock", "rw"),
        ModuleGlobalRule("_refs", "_cfg_lock", "rw"),
        # the module-bool fast gate and the peak cache: rebinds under
        # the config lock; sites read them lock-free by design (a
        # stale read drops or takes one sample, never corrupts)
        ModuleGlobalRule("enabled", "_cfg_lock", "w"),
        # the profiler capture bookkeeping dict (the _prof_lock is the
        # start..stop exclusivity latch, not a data guard)
        ModuleGlobalRule("_prof", "_prof_state_lock", "rw", attrs=True),
    ),
    "models/fragment.py": (
        # the wal.* replay-health counters (module-level; every
        # fragment's construction-time replay can note a torn tail)
        ModuleGlobalRule("_counters", "_wal_counter_lock", "rw"),
    ),
    "observe.py": (
        # the event-journal fast gate and the journal handle itself:
        # rebinds only under the config lock; emission sites read both
        # lock-free by design (the faultinject `armed` discipline — a
        # stale read drops or keeps one event, never corrupts)
        ModuleGlobalRule("journal_on", "_cfg_lock", "w"),
        ModuleGlobalRule("_journal", "_cfg_lock", "w"),
        ModuleGlobalRule("_baseline", "_cfg_lock", "rw"),
        ModuleGlobalRule("_refs", "_cfg_lock", "rw"),
        # trace-assembly counters behind bump_trace/trace_counters
        ModuleGlobalRule("_trace_counters", "_trace_lock", "rw"),
    ),
    "faultinject.py": (
        # the failpoint registry: every read AND write of the armed
        # point table goes through the module lock (hit() is only
        # reached when something is armed, so the lock is off the
        # disarmed hot path by construction — the `armed` bool gate)
        ModuleGlobalRule("_points", "_lock", "rw"),
        # the fast gate itself: rebinds only under the lock; sites
        # read it lock-free by design (a stale read skips or probes
        # one injection window, never corrupts the registry)
        ModuleGlobalRule("armed", "_lock", "w"),
    ),
}

# ------------------------------------------------------ P2: mutation model


@dataclass(frozen=True)
class GenAuditRule:
    """Generation-audit model for one class: methods that (directly or
    via same-class helper calls) hit a mutation primitive or write a
    mutation target must also (transitively) bump a generation
    attribute.  ``primitives`` are the leaf write helpers themselves —
    their CALLERS own the bump.  ``exempt`` maps method -> reason."""

    bump_attrs: frozenset
    primitives: frozenset
    targets: frozenset          # attrs whose writes count as mutation
    delta_mutators: frozenset   # method calls that write a delta plane
    exempt: dict = field(default_factory=dict)


GEN_AUDIT: dict[tuple, GenAuditRule] = {
    ("models/fragment.py", "Fragment"): GenAuditRule(
        bump_attrs=frozenset({"_gen", "_delta_seq"}),
        primitives=frozenset({
            "_apply_set", "_apply_clear", "_apply_bulk",
            "_merge_roaring", "_merge_positions", "_row_array",
        }),
        targets=frozenset({"_rows"}),
        delta_mutators=frozenset({"add_bit", "add_positions"}),
        exempt={
            "_replay_wal_file": "WAL replay applies records one file "
                                "at a time; _replay_wal bumps _gen "
                                "once after both files",
        },
    ),
    ("models/field.py", "Field"): GenAuditRule(
        bump_attrs=frozenset({"_gen", "_delta_seq"}),
        primitives=frozenset(),
        targets=frozenset({"_rows"}),
        delta_mutators=frozenset({"add_bit", "add_positions"}),
    ),
}

# ------------------------------------------------------ P3: blocking model

#: (dotted-call suffixes, attr-call names) treated as blocking or
#: device-dispatching.  ``.join``/``.result``/``.wait`` match by attr;
#: string-constant receivers are excluded for ``join`` (str.join) and
#: receivers named in CONDITION_ATTRS for ``wait`` (Condition.wait
#: releases the lock while waiting — the one legitimate wait-under-
#: lock).
BLOCKING_CALL_SUFFIXES = (
    "time.sleep",
    "urllib.request.urlopen",
    "socket.create_connection",
    "jax.block_until_ready",
)
BLOCKING_ATTRS = ("join", "result", "wait", "block_until_ready",
                  "urlopen")
DEVICE_DISPATCH_NAMES = ("chunked_device_put", "device_put")
CONDITION_ATTRS = ("_snap_done",)

# ----------------------------------------------------- P4: recompile model

#: Call suffixes that reach a jitted program whose lowering
#: specializes on input shape.
JIT_ENTRY_SUFFIXES = ("expr.evaluate", "tape.execute", "_tape.execute",
                      "tape.execute_vm", "_tape.execute_vm",
                      "expr.evaluate_gathered",
                      "expr.evaluate_gathered_kinds",
                      "gathered_count_array_array",
                      "gathered_count_array_bitmap")
#: Batch-stack builders whose output shape tracks their (variable)
#: input length.
STACK_BUILDER_SUFFIXES = ("jnp.stack", "jnp.concatenate", "np.stack",
                          "numpy.stack")
#: Referencing any of these names in the same function is the evidence
#: the batch axis was routed through a pow2/size-class discipline.
SHAPE_HELPER_NAMES = frozenset({
    "_pow2", "pow2", "size_class", "_pad_batch", "_padded_rows",
    "MIN_BUCKET", "prewarm",
})
#: jax attribute roots whose module-import-time CALLS are flagged
#: (device init / tracing at import).  jax.jit/vmap wrapping is lazy
#: and allowed.
IMPORT_TIME_JAX_ROOTS = ("jnp", "jax")
IMPORT_TIME_ALLOWED = ("jax.jit", "jax.vmap", "functools.partial",
                       "jax.tree_util")

# -------------------------------------------------------- P5: config model


@dataclass(frozen=True)
class ConfigGuardRule:
    """One process-wide config surface: calling a mutator in a module
    requires that module to also reference every name in ``pair`` —
    the capture/restore (or retain/release) protocol that makes the
    mutation reversible.  ``owner`` modules (the definition site) and
    accessor-alias writes (``cfg = <x>.config(); cfg.attr = ...``)
    are handled by the pass."""

    mutator_suffixes: tuple
    pair: tuple
    owner_suffixes: tuple
    what: str


CONFIG_GUARDS = (
    ConfigGuardRule(
        mutator_suffixes=("ingest.configure", "_ingest.configure"),
        pair=("capture_baseline", "restore_baseline"),
        owner_suffixes=("ingest/__init__.py",),
        what="the process-wide [ingest] runtime config",
    ),
    ConfigGuardRule(
        mutator_suffixes=("compactor.retain", "_compactor.retain"),
        pair=("release",),
        owner_suffixes=("ingest/compactor.py",),
        what="the refcounted shared compactor scan thread",
    ),
    ConfigGuardRule(
        mutator_suffixes=("containers.configure",
                          "_containers.configure"),
        pair=("retain", "release"),
        owner_suffixes=("ops/containers.py",),
        what="the process-wide [containers] runtime config",
    ),
    ConfigGuardRule(
        mutator_suffixes=("containers.retain", "_containers.retain"),
        pair=("release",),
        owner_suffixes=("ops/containers.py",),
        what="the refcounted [containers] baseline",
    ),
    ConfigGuardRule(
        mutator_suffixes=("faultinject.arm", "_faultinject.arm"),
        pair=("disarm",),
        owner_suffixes=("faultinject.py",),
        what="the process-wide failpoint registry",
    ),
    ConfigGuardRule(
        mutator_suffixes=("residency.configure",
                          "_residency.configure"),
        pair=("retain", "release"),
        owner_suffixes=("runtime/residency.py",),
        what="the process-wide [residency] runtime config",
    ),
    ConfigGuardRule(
        mutator_suffixes=("residency.retain", "_residency.retain"),
        pair=("release",),
        owner_suffixes=("runtime/residency.py",),
        what="the refcounted [residency] baseline",
    ),
    ConfigGuardRule(
        mutator_suffixes=("hints.configure", "_hints.configure"),
        pair=("retain", "release"),
        owner_suffixes=("parallel/hints.py",),
        what="the process-wide [replication] runtime config",
    ),
    ConfigGuardRule(
        mutator_suffixes=("hints.retain", "_hints.retain"),
        pair=("release",),
        owner_suffixes=("parallel/hints.py",),
        what="the refcounted [replication] baseline",
    ),
    ConfigGuardRule(
        mutator_suffixes=("rebalance.configure", "_rebalance.configure",
                          "_rebalance1.configure"),
        pair=("retain", "release"),
        owner_suffixes=("parallel/rebalance.py",),
        what="the process-wide [rebalance] runtime config",
    ),
    ConfigGuardRule(
        mutator_suffixes=("rebalance.retain", "_rebalance.retain",
                          "_rebalance1.retain"),
        pair=("release",),
        owner_suffixes=("parallel/rebalance.py",),
        what="the refcounted [rebalance] baseline",
    ),
    ConfigGuardRule(
        mutator_suffixes=("tenant.configure", "_tenant.configure",
                          "_tenantcfg.configure"),
        pair=("retain", "release"),
        owner_suffixes=("serve/tenant.py",),
        what="the process-wide [tenants] runtime config",
    ),
    ConfigGuardRule(
        mutator_suffixes=("tenant.retain", "_tenant.retain",
                          "_tenantcfg.retain"),
        pair=("release",),
        owner_suffixes=("serve/tenant.py",),
        what="the refcounted [tenants] baseline",
    ),
    ConfigGuardRule(
        mutator_suffixes=("meshexec.configure", "_meshexec.configure"),
        pair=("retain", "release"),
        owner_suffixes=("parallel/meshexec.py",),
        what="the process-wide [mesh] runtime config",
    ),
    ConfigGuardRule(
        mutator_suffixes=("meshexec.retain", "_meshexec.retain"),
        pair=("release",),
        owner_suffixes=("parallel/meshexec.py",),
        what="the refcounted [mesh] baseline",
    ),
    ConfigGuardRule(
        mutator_suffixes=("observe.configure", "_observe.configure",
                          "_observe1.configure"),
        pair=("retain", "release"),
        owner_suffixes=("observe.py",),
        what="the process-wide [observe] event-journal config",
    ),
    ConfigGuardRule(
        mutator_suffixes=("observe.retain", "_observe.retain",
                          "_observe1.retain"),
        pair=("release",),
        owner_suffixes=("observe.py",),
        what="the refcounted [observe] journal baseline",
    ),
    ConfigGuardRule(
        mutator_suffixes=("perfobs.configure", "_perfobs.configure"),
        pair=("retain", "release"),
        owner_suffixes=("perfobs.py",),
        what="the process-wide engine-observatory runtime config",
    ),
    ConfigGuardRule(
        mutator_suffixes=("perfobs.retain", "_perfobs.retain"),
        pair=("release",),
        owner_suffixes=("perfobs.py",),
        what="the refcounted engine-observatory baseline",
    ),
)

#: ``<x>.config()`` accessors whose result's attribute WRITES count as
#: mutating the guarded config (same pairing requirement).
CONFIG_ACCESSOR_SUFFIXES = ("ingest.config", "_ingest.config")

# ------------------------------------------------------- P6: metric model

#: Stats-registry method names whose first string-literal argument is
#: a metric name.
STATS_CALL_ATTRS = ("count", "count_with_tags", "gauge", "histogram",
                    "timing")
#: Free functions that feed the module counter registries (published
#: as gauges at scrape time).
STATS_CALL_FUNCS = ("bump",)
#: Module-level dict literals whose string keys are metric names
#: (ops/tape.py's counter registry).
STATS_DICT_NAMES = ("_counters",)
