"""pilosa-lint core: findings, suppression handling, the driver, and
the text/JSON reporters.

The analysis model is deliberately simple — pure-AST, intra-procedural,
no imports of the analyzed code — so the suite runs in milliseconds on
every test run (tier-1) and can never be broken by an import-time side
effect in the code under analysis.  Each pass trades soundness for
reviewability: the registry (``tools/analyze/registry.py``) and the
mandatory suppression reasons ARE the documentation of every place the
approximation meets reality.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field

#: Rule ids of the six analysis passes, in pass order.
PASS_RULES = (
    "lock-discipline",
    "generation-audit",
    "blocking-under-lock",
    "recompile-hazard",
    "config-baseline",
    "metric-family-drift",
)

#: Meta rules: defects in the suppression mechanism itself.  Not
#: suppressible — a broken suppression cannot vouch for itself.
META_RULES = ("suppression", "stale-suppression")

ALL_RULES = PASS_RULES + META_RULES


@dataclass
class Finding:
    """One analysis finding, anchored to ``path:line``."""

    rule: str
    path: str
    line: int
    message: str
    suppressed: bool = False
    reason: str | None = None

    def render(self) -> str:
        tail = f"  [suppressed: {self.reason}]" if self.suppressed else ""
        return f"{self.path}:{self.line}: {self.rule}: {self.message}{tail}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "suppressed": self.suppressed,
            "reason": self.reason,
        }


# ------------------------------------------------------------ suppression

#: ``# pilosa-lint: allow(rule[, rule]) -- reason``
_DIRECTIVE_RE = re.compile(r"#\s*pilosa-lint:\s*(?P<body>.*)$")
_ALLOW_RE = re.compile(
    r"^allow\(\s*(?P<rules>[A-Za-z0-9_\-,\s]*)\s*\)"
    r"\s*(?:--\s*(?P<reason>\S.*?))?\s*$")


@dataclass
class Suppression:
    rules: tuple
    reason: str
    line: int          # line the directive sits on
    standalone: bool   # whole line is the comment -> applies to line+1
    used: bool = False

    def covers(self, line: int) -> bool:
        if line == self.line:
            return True
        return self.standalone and line == self.line + 1


def parse_suppressions(src: str, path: str
                       ) -> tuple[list[Suppression], list[Finding]]:
    """Scan one file's source for suppression directives.  Returns
    (suppressions, meta findings) — malformed directives, unknown rule
    names, and missing reasons are ``suppression`` findings (errors),
    never silently honored."""
    sups: list[Suppression] = []
    bad: list[Finding] = []
    for lineno, line in enumerate(src.splitlines(), 1):
        m = _DIRECTIVE_RE.search(line)
        if m is None:
            continue
        body = m.group("body").strip()
        am = _ALLOW_RE.match(body)
        if am is None:
            bad.append(Finding(
                "suppression", path, lineno,
                f"malformed pilosa-lint directive {body!r}: expected "
                "allow(<rule>) -- <reason>"))
            continue
        rules = tuple(r.strip() for r in am.group("rules").split(",")
                      if r.strip())
        reason = am.group("reason")
        if not rules:
            bad.append(Finding(
                "suppression", path, lineno,
                "allow() names no rule"))
            continue
        unknown = [r for r in rules if r not in PASS_RULES]
        if unknown:
            bad.append(Finding(
                "suppression", path, lineno,
                f"allow() names unknown rule(s) {unknown}; known rules: "
                f"{', '.join(PASS_RULES)}"))
            continue
        if not reason:
            bad.append(Finding(
                "suppression", path, lineno,
                f"allow({', '.join(rules)}) carries no reason — a "
                "suppression without a why is a bug with a license"))
            continue
        standalone = line.strip().startswith("#")
        sups.append(Suppression(rules, reason, lineno, standalone))
    return sups, bad


def apply_suppressions(findings: list[Finding],
                       sups: list[Suppression],
                       path: str) -> list[Finding]:
    """Mark suppressed findings in place; return stale-suppression
    findings for directives that suppressed nothing."""
    for f in findings:
        if f.rule not in PASS_RULES:
            continue  # meta findings are not suppressible
        for s in sups:
            if f.rule in s.rules and s.covers(f.line):
                f.suppressed = True
                f.reason = s.reason
                s.used = True
                break
    return [
        Finding("stale-suppression", path, s.line,
                f"allow({', '.join(s.rules)}) no longer suppresses "
                "anything here — remove it (the invariant holds "
                "without it)")
        for s in sups if not s.used
    ]


# ----------------------------------------------------------------- driver


@dataclass
class SourceFile:
    """One file under analysis: path (as reported), source, AST."""

    path: str
    src: str
    tree: ast.Module = field(repr=False, default=None)

    @classmethod
    def parse(cls, path: str, src: str) -> "SourceFile":
        return cls(path, src, ast.parse(src, filename=path))

    def suffix_is(self, suffix: str) -> bool:
        """Registry matching: does this file's normalized path end
        with ``suffix`` (posix separators)?"""
        norm = self.path.replace(os.sep, "/")
        return norm == suffix or norm.endswith("/" + suffix)


def _default_passes():
    # local import: the pass modules import core for Finding
    from tools.analyze import passes_config, passes_device, \
        passes_locks, passes_metrics, passes_mutation

    return (
        passes_locks.LockDisciplinePass(),
        passes_mutation.GenerationAuditPass(),
        passes_locks.BlockingUnderLockPass(),
        passes_device.RecompileHazardPass(),
        passes_config.ConfigBaselinePass(),
        passes_metrics.MetricFamilyDriftPass(),
    )


def analyze_sources(files: list[SourceFile],
                    passes=None) -> list[Finding]:
    """Run every pass over the given sources and fold in suppression
    semantics.  Returns ALL findings — suppressed ones carry their
    reason, plus ``suppression``/``stale-suppression`` meta findings."""
    if passes is None:
        passes = _default_passes()
    per_file: dict[str, list[Finding]] = {f.path: [] for f in files}
    sups: dict[str, list[Suppression]] = {}
    out: list[Finding] = []
    for sf in files:
        s, bad = parse_suppressions(sf.src, sf.path)
        sups[sf.path] = s
        out.extend(bad)
    for p in passes:
        if hasattr(p, "run_package"):
            found = p.run_package(files)
        else:
            found = []
            for sf in files:
                found.extend(p.run(sf))
        for f in found:
            per_file.setdefault(f.path, []).append(f)
    analyzed = {sf.path for sf in files}
    for sf in files:
        findings = per_file.get(sf.path, [])
        stale = apply_suppressions(findings, sups[sf.path], sf.path)
        out.extend(findings)
        out.extend(stale)
    # findings anchored outside the analyzed set (e.g. a package pass
    # pointing at a registry declaration under a different path
    # spelling) must still be REPORTED — dropping them would let the
    # gate false-pass; they just can't be suppressed in-file.
    for path, findings in per_file.items():
        if path not in analyzed:
            out.extend(findings)
    out.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return out


def collect_files(paths: list[str]) -> list[SourceFile]:
    """Expand files/directories into parsed SourceFiles (sorted,
    ``__pycache__`` skipped)."""
    found: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = [d for d in dirs if d != "__pycache__"]
                found.extend(os.path.join(root, n)
                             for n in names if n.endswith(".py"))
        else:
            found.append(p)
    out = []
    for path in sorted(found):
        with open(path, encoding="utf-8") as fh:
            out.append(SourceFile.parse(path, fh.read()))
    return out


def analyze_paths(paths: list[str], passes=None) -> list[Finding]:
    return analyze_sources(collect_files(paths), passes)


# -------------------------------------------------------------- reporters


def render_text(findings: list[Finding],
                show_suppressed: bool = False) -> str:
    lines = [f.render() for f in findings
             if show_suppressed or not f.suppressed]
    active = sum(1 for f in findings if not f.suppressed)
    quiet = sum(1 for f in findings if f.suppressed)
    lines.append(f"pilosa-lint: {active} finding(s), "
                 f"{quiet} suppressed")
    return "\n".join(lines)


def render_json(findings: list[Finding]) -> str:
    return json.dumps({
        "findings": [f.to_dict() for f in findings],
        "unsuppressed": sum(1 for f in findings if not f.suppressed),
    }, indent=2)


def main(argv: list[str]) -> int:
    as_json = "--json" in argv
    show_suppressed = "--show-suppressed" in argv
    paths = [a for a in argv
             if a not in ("--json", "--show-suppressed")]
    if not paths:
        paths = ["pilosa_tpu"]
    findings = analyze_paths(paths)
    if as_json:
        print(render_json(findings))
    else:
        print(render_text(findings, show_suppressed))
    return 1 if any(not f.suppressed for f in findings) else 0
