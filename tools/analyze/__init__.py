"""pilosa-lint: project-invariant static analysis for pilosa-tpu.

Generic linters check style; this suite checks the invariants THIS
codebase has been burned by — each pass encodes one recurring
review-round bug class (see ``tools/analyze/registry.py`` for the
declarative project model and ``docs/development.md`` for the
incident each pass descends from):

- **P1 lock-discipline** — every touch of a registered lock-guarded
  attribute (fragment ``_gen``/``_delta_seq``/``_rows``/``_delta``,
  the compactor registry, the result-cache LRU/flight tables, ...)
  sits inside the owning ``with <owner>._lock`` region.
- **P2 generation-audit** — every ``fragment.py``/``field.py`` method
  that mutates base words or rows bumps ``_gen`` or ``_delta_seq``
  (directly or via a helper it calls).
- **P3 blocking-under-lock** — sleeps, joins, future waits, RPC and
  device-dispatch calls flagged inside held-lock regions.
- **P4 recompile-hazard** — free-running batch shapes reaching jitted
  entry points without the pow2/size-class helpers, and ``jnp.`` work
  at module import time.
- **P5 config-baseline** — process-wide config mutations outside a
  ``capture_baseline``/``restore_baseline`` (or refcounted
  retain/release) pairing.
- **P6 metric-family-drift** — every metric-name literal fed to the
  stats registry belongs to a family declared in
  ``pilosa_tpu/metricfamilies.py``, every declared family still has
  an emitter, and documented families still appear in their docs.

Suppressions: ``# pilosa-lint: allow(<rule>) -- <reason>`` on the
flagged line or alone on the line above.  The reason is mandatory, an
unknown rule is an error, and a suppression that no longer suppresses
anything is reported as removable (``stale-suppression``).

Usage: ``python -m tools.analyze [--json] [PATH ...]`` (default
``pilosa_tpu``), or ``make analyze``.  Exit 1 on any unsuppressed
finding.  ``tests/test_analyze.py`` pins the committed tree at zero.
"""

from __future__ import annotations

from tools.analyze.core import (  # noqa: F401 — public API
    ALL_RULES,
    Finding,
    SourceFile,
    analyze_paths,
    analyze_sources,
    render_json,
    render_text,
)
