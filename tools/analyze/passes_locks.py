"""P1 lock-discipline and P3 blocking-under-lock.

Both passes reason about *held-lock regions*: the statements inside a
``with <recv>._lock:`` (or bare ``with <module_lock>:``) block, tracked
intra-procedurally.  P1 requires every touch of a registered guarded
attribute/global to sit inside its owner's region (the PR-6 class of
bug: ``row_ids()`` iterating ``_rows`` while the background compactor
flushed a delta — "dictionary changed size during iteration" on the
MinRow/MaxRow map path).  P3 inverts the check: calls that can block
(sleeps, joins, future results, RPC, device dispatch) are flagged
INSIDE any region — holding the fragment or registry lock across a
join is how the PR-6 compactor-shutdown review rounds were spent.

Approximations (by design, documented here and in the registry):

- Intra-procedural only.  A helper with a caller-holds-the-lock
  contract is declared in the registry (or carries the ``*_locked``
  suffix) and its body is not re-checked; a caller that invokes it
  without the lock is not caught by P1 — the dynamic lock-order
  checker (pilosa_tpu/lockcheck.py) and the race tests own that half.
- Nested function definitions reset the region state (a closure runs
  later, under whatever locks its caller holds).  Comprehensions
  execute inline and keep the current region.
"""

from __future__ import annotations

import ast

from tools.analyze import registry as reg
from tools.analyze.core import Finding, SourceFile


def _lock_tokens(ctx_expr) -> list[str]:
    """Lock tokens a ``with`` item establishes: ``recv:<unparse>`` for
    attribute locks, ``mod:<name>`` for bare module locks."""
    out = []
    if isinstance(ctx_expr, ast.Attribute) and \
            ctx_expr.attr in reg.LOCK_ATTR_NAMES:
        out.append("recv:" + ast.unparse(ctx_expr.value))
    elif isinstance(ctx_expr, ast.Name) and \
            ctx_expr.id.endswith("_lock"):
        out.append("mod:" + ctx_expr.id)
    return out


class _RegionWalker:
    """Walks one function body, invoking ``visit(node, active)`` for
    every expression-level node with the set of active lock tokens."""

    def __init__(self, visit):
        self._visit = visit

    def walk_function(self, fn) -> None:
        self._stmts(fn.body, frozenset())

    def _stmts(self, stmts, active) -> None:
        for st in stmts:
            self._stmt(st, active)

    def _stmt(self, st, active) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def runs later: fresh region state
            for deco in st.decorator_list:
                self._expr(deco, active)
            self._stmts(st.body, frozenset())
            return
        if isinstance(st, ast.ClassDef):
            self._stmts(st.body, frozenset())
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            inner = set(active)
            for item in st.items:
                self._expr(item.context_expr, active)
                inner.update(_lock_tokens(item.context_expr))
            self._stmts(st.body, frozenset(inner))
            return
        # generic: visit child expressions with current region, then
        # child statement blocks
        for fname, value in ast.iter_fields(st):
            if isinstance(value, list):
                if value and isinstance(value[0], ast.stmt):
                    self._stmts(value, active)
                else:
                    for v in value:
                        if isinstance(v, ast.expr):
                            self._expr(v, active)
                        elif isinstance(v, ast.excepthandler):
                            self._stmts(v.body, active)
            elif isinstance(value, ast.expr):
                self._expr(value, active)

    def _expr(self, e, active) -> None:
        if e is None:
            return
        for node in ast.walk(e):
            if isinstance(node, ast.Lambda):
                continue  # runs later; body nodes still walked —
                # acceptable: lambdas in this codebase close over
                # locals, not guarded attributes
            self._visit(node, active)


def _is_locked_helper(name: str, rule) -> bool:
    if name == "__init__" or name.endswith("_locked"):
        return True
    return rule is not None and name in rule.helpers


def _store_ctx(node) -> bool:
    return isinstance(node.ctx, (ast.Store, ast.Del))


class LockDisciplinePass:
    """P1: every registered guarded attribute/global touch inside its
    owning held-lock region."""

    rule = "lock-discipline"

    def run(self, sf: SourceFile) -> list[Finding]:
        out: list[Finding] = []
        class_rules = {cls: r for (suffix, cls), r in
                       reg.CLASS_LOCKS.items() if sf.suffix_is(suffix)}
        mod_rules = []
        for suffix, rules in reg.MODULE_LOCKS.items():
            if sf.suffix_is(suffix):
                mod_rules.extend(rules)
        mod_by_name = {r.name: r for r in mod_rules}

        def check_function(fn, cls_rule, out=out):
            def visit(node, active):
                if isinstance(node, ast.Attribute):
                    recv = node.value
                    recv_txt = (ast.unparse(recv)
                                if isinstance(recv, ast.Name) else None)
                    if recv_txt == "self" and cls_rule is not None:
                        if node.attr in cls_rule.attrs and \
                                "recv:self" not in active:
                            out.append(Finding(
                                self.rule, sf.path, node.lineno,
                                f"self.{node.attr} touched outside "
                                f"'with self.{cls_rule.lock}' (guarded "
                                "attribute; see tools/analyze/"
                                "registry.py CLASS_LOCKS)"))
                    elif recv_txt is not None and recv_txt != "self":
                        mode = reg.CROSS_OBJECT_ATTRS.get(node.attr)
                        grule = mod_by_name.get(recv_txt)
                        if grule is not None and grule.attrs and \
                                _store_ctx(node):
                            if "mod:" + grule.lock not in active:
                                out.append(Finding(
                                    self.rule, sf.path, node.lineno,
                                    f"write to {recv_txt}.{node.attr} "
                                    f"outside 'with {grule.lock}' "
                                    "(guarded module config)"))
                        elif mode is not None:
                            if mode == "w" and not _store_ctx(node):
                                return
                            if "recv:" + recv_txt not in active:
                                out.append(Finding(
                                    self.rule, sf.path, node.lineno,
                                    f"{recv_txt}.{node.attr} touched "
                                    f"outside 'with {recv_txt}._lock' "
                                    "(guarded attribute; see registry "
                                    "CROSS_OBJECT_ATTRS)"))
                elif isinstance(node, ast.Name):
                    grule = mod_by_name.get(node.id)
                    if grule is None:
                        return
                    # attrs=True ADDITIONALLY guards attribute writes
                    # (handled above); the name itself — in particular
                    # a rebind like `_cfg = IngestRuntimeConfig()` —
                    # still goes through the mode check here
                    if grule.mode == "w" and not _store_ctx(node):
                        return
                    if "mod:" + grule.lock not in active:
                        out.append(Finding(
                            self.rule, sf.path, node.lineno,
                            f"module global {node.id!r} touched "
                            f"outside 'with {grule.lock}' (guarded "
                            "global; see registry MODULE_LOCKS)"))

            _RegionWalker(visit).walk_function(fn)

        for node in sf.tree.body:
            if isinstance(node, ast.ClassDef):
                cls_rule = class_rules.get(node.name)
                for item in node.body:
                    if isinstance(item, ast.FunctionDef):
                        if cls_rule is not None and \
                                _is_locked_helper(item.name, cls_rule):
                            continue
                        check_function(item, cls_rule)
            elif isinstance(node, ast.FunctionDef):
                check_function(node, None)
        return out


def _call_suffix(func) -> str:
    """Dotted text of a call target (best effort)."""
    try:
        return ast.unparse(func)
    except Exception:  # pragma: no cover - unparse is total on exprs
        return ""


class BlockingUnderLockPass:
    """P3: blocking/device-dispatch calls inside held-lock regions."""

    rule = "blocking-under-lock"

    def run(self, sf: SourceFile) -> list[Finding]:
        out: list[Finding] = []

        def visit(node, active):
            if not active or not isinstance(node, ast.Call):
                return
            func = node.func
            label = None
            txt = _call_suffix(func)
            if isinstance(func, ast.Attribute):
                attr = func.attr
                if any(txt.endswith(s)
                       for s in reg.BLOCKING_CALL_SUFFIXES):
                    label = txt
                elif attr in reg.DEVICE_DISPATCH_NAMES:
                    label = f"device dispatch .{attr}()"
                elif attr in reg.BLOCKING_ATTRS:
                    if attr == "join" and (
                            isinstance(func.value, ast.Constant)
                            or txt.startswith("os.path.")):
                        return  # str.join / os.path.join
                    if attr == "wait":
                        recv = func.value
                        if isinstance(recv, ast.Attribute) and \
                                recv.attr in reg.CONDITION_ATTRS:
                            return  # Condition.wait releases the lock
                    label = f".{attr}()"
            elif isinstance(func, ast.Name):
                if func.id in reg.DEVICE_DISPATCH_NAMES:
                    label = f"device dispatch {func.id}()"
            if label is not None:
                locks = ", ".join(sorted(
                    a.split(":", 1)[1] for a in active))
                out.append(Finding(
                    self.rule, sf.path, node.lineno,
                    f"{label} called while holding lock(s) [{locks}]"
                    " — blocking under a lock convoys every waiter"))

        walker = _RegionWalker(visit)
        # top-level and method defs only: _RegionWalker recurses into
        # nested defs itself (with fresh region state), so walking
        # every FunctionDef in ast.walk would double-visit them
        for node in sf.tree.body:
            if isinstance(node, ast.FunctionDef):
                walker.walk_function(node)
            elif isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, ast.FunctionDef):
                        walker.walk_function(item)
        return out
