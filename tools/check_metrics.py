"""Strict Prometheus text-exposition checker.

A malformed /metrics line fails silently in-repo and loudly in a
production scraper — strict servers (Prometheus with honor-labels off,
the OpenMetrics ingest path) reject the whole scrape.  This module is
the CI tripwire: tests feed a live server's ``/metrics`` body through
``check_text`` so any future malformed line fails tier-1 instead of
failing a scraper.

Checked dialect: Prometheus text 0.0.4 plus the one OpenMetrics
extension this codebase emits — trace-id exemplars on histogram
``_bucket`` samples (``... # {trace_id="..."} value timestamp``).

Rules enforced:

- line grammar: ``# TYPE``/``# HELP``/comment/blank/sample only
- metric and label names match the Prometheus charset
- label values are double-quoted with only ``\\\\``/``\\"``/``\\n``
  escapes; label blocks are well-formed
- sample values parse as float (``+Inf``/``-Inf``/``NaN`` allowed)
- at most one ``# TYPE`` per metric name, and it precedes the metric's
  samples; TYPE values are the known set
- all samples of one metric form a single contiguous group
- no duplicate (name, labelset) sample
- histograms: ``le`` present on every ``_bucket``, cumulative bucket
  values non-decreasing per series, a ``+Inf`` bucket present and
  equal to ``_count``
- exemplars only on histogram ``_bucket`` samples

Usage: ``python -m tools.check_metrics URL`` (exit 1 on violation), or
``check_text(text)`` from tests.
"""

from __future__ import annotations

import re
import sys

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_TYPES = ("counter", "gauge", "histogram", "summary", "untyped")
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


class MetricsFormatError(ValueError):
    def __init__(self, lineno: int, line: str, reason: str):
        super().__init__(f"line {lineno}: {reason}: {line!r}")
        self.lineno = lineno
        self.reason = reason


def _parse_labels(lineno: int, line: str, raw: str) -> dict[str, str]:
    """Parse the inside of a ``{...}`` label block."""
    labels: dict[str, str] = {}
    i = 0
    n = len(raw)
    while i < n:
        m = re.match(r"[a-zA-Z_][a-zA-Z0-9_]*", raw[i:])
        if m is None:
            raise MetricsFormatError(lineno, line, "bad label name")
        name = m.group(0)
        i += len(name)
        if raw[i:i + 1] != "=":
            raise MetricsFormatError(lineno, line, "expected '=' in label")
        i += 1
        if raw[i:i + 1] != '"':
            raise MetricsFormatError(lineno, line,
                                     "label value must be quoted")
        i += 1
        val = []
        while True:
            if i >= n:
                raise MetricsFormatError(lineno, line,
                                         "unterminated label value")
            ch = raw[i]
            if ch == "\\":
                esc = raw[i + 1:i + 2]
                if esc not in ("\\", '"', "n"):
                    raise MetricsFormatError(lineno, line,
                                             f"bad escape \\{esc}")
                val.append({"\\": "\\", '"': '"', "n": "\n"}[esc])
                i += 2
                continue
            if ch == '"':
                i += 1
                break
            val.append(ch)
            i += 1
        if name in labels:
            raise MetricsFormatError(lineno, line,
                                     f"duplicate label {name}")
        labels[name] = "".join(val)
        if i < n:
            if raw[i] != ",":
                raise MetricsFormatError(lineno, line,
                                         "expected ',' between labels")
            i += 1
    return labels


def _parse_value(lineno: int, line: str, raw: str) -> float:
    if raw in ("+Inf", "Inf"):
        return float("inf")
    if raw == "-Inf":
        return float("-inf")
    if raw == "NaN":
        return float("nan")
    try:
        return float(raw)
    except ValueError:
        raise MetricsFormatError(lineno, line, f"bad value {raw!r}")


_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*?)\})?"
    r" (?P<value>\S+)"
    r"(?: (?P<ts>-?\d+))?"
    r"(?P<exemplar> # \{.*\} \S+(?: \S+)?)?$"
)


def check_text(text: str) -> dict:
    """Validate one exposition body.  Returns a summary dict
    ({"samples": n, "metrics": n}) or raises MetricsFormatError."""
    types: dict[str, str] = {}
    sampled: set[str] = set()      # base names with >=1 sample
    finished: set[str] = set()     # groups we've moved past
    current: str | None = None
    seen_series: set[tuple] = set()
    # histogram accounting: series key -> data
    buckets: dict[tuple, list[tuple[float, float]]] = {}
    counts: dict[tuple, float] = {}
    n_samples = 0

    def base_name(name: str) -> str:
        for suf in _HIST_SUFFIXES:
            if name.endswith(suf):
                stem = name[: -len(suf)]
                if types.get(stem) in ("histogram", "summary"):
                    return stem
        return name

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 2 and parts[1] == "TYPE":
                if len(parts) != 4:
                    raise MetricsFormatError(lineno, line,
                                             "malformed TYPE line")
                _, _, name, mtype = parts
                if not _NAME_RE.match(name):
                    raise MetricsFormatError(lineno, line,
                                             "bad metric name in TYPE")
                if mtype not in _TYPES:
                    raise MetricsFormatError(lineno, line,
                                             f"unknown type {mtype!r}")
                if name in types:
                    raise MetricsFormatError(lineno, line,
                                             f"duplicate TYPE for {name}")
                if name in sampled:
                    raise MetricsFormatError(
                        lineno, line, f"TYPE after samples of {name}")
                types[name] = mtype
            # HELP and plain comments pass
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise MetricsFormatError(lineno, line, "unparsable sample")
        name = m.group("name")
        labels = _parse_labels(lineno, line, m.group("labels") or "") \
            if m.group("labels") is not None else {}
        value = _parse_value(lineno, line, m.group("value"))
        stem = base_name(name)
        mtype = types.get(stem)
        if m.group("exemplar") is not None and not (
                mtype == "histogram" and name.endswith("_bucket")):
            raise MetricsFormatError(
                lineno, line, "exemplar outside a histogram _bucket")
        # contiguity: all of a metric's lines form one group
        if stem != current:
            if current is not None:
                finished.add(current)
            if stem in finished:
                raise MetricsFormatError(
                    lineno, line, f"interleaved samples for {stem}")
            current = stem
        sampled.add(stem)
        series = (name, tuple(sorted(
            (k, v) for k, v in labels.items() if k != "le")))
        if mtype == "histogram" and name.endswith("_bucket"):
            if "le" not in labels:
                raise MetricsFormatError(lineno, line,
                                         "_bucket without le label")
            le = _parse_value(lineno, line, labels["le"])
            key = (stem, series[1])
            prior = buckets.setdefault(key, [])
            if prior:
                ple, pval = prior[-1]
                if le <= ple:
                    raise MetricsFormatError(
                        lineno, line, "le not increasing")
                if value < pval:
                    raise MetricsFormatError(
                        lineno, line, "bucket counts not cumulative")
            prior.append((le, value))
            bseries = (name, tuple(sorted(labels.items())))
            if bseries in seen_series:
                raise MetricsFormatError(lineno, line, "duplicate series")
            seen_series.add(bseries)
        else:
            if series in seen_series:
                raise MetricsFormatError(lineno, line, "duplicate series")
            seen_series.add(series)
            if mtype == "histogram" and name.endswith("_count"):
                counts[(stem, series[1])] = value
        n_samples += 1

    for (stem, lbls), blist in buckets.items():
        if not blist or blist[-1][0] != float("inf"):
            raise MetricsFormatError(0, stem, "histogram missing +Inf bucket")
        cnt = counts.get((stem, lbls))
        if cnt is None:
            raise MetricsFormatError(0, stem, "histogram missing _count")
        if blist[-1][1] != cnt:
            raise MetricsFormatError(
                0, stem,
                f"+Inf bucket {blist[-1][1]} != _count {cnt}")
    return {"samples": n_samples, "metrics": len(sampled)}


# Family lists come from the one declarative registry
# (pilosa_tpu/metricfamilies.py) — a new family is declared exactly
# once there and both this live checker and the tools/analyze P6
# static drift pass consume it.  The per-subsystem constants below are
# the long-standing public names tests import.  The tool must stay
# runnable standalone (`python tools/check_metrics.py URL` from a
# scraper box, any cwd), so bootstrap the repo root when the package
# is not already importable.
try:
    from pilosa_tpu import metricfamilies as _mf
except ImportError:  # direct-script invocation from outside the repo
    import os as _os

    sys.path.insert(0, _os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))))
    from pilosa_tpu import metricfamilies as _mf

#: Device-runtime telemetry prefixes (devobs/residency/expr-compile).
DEVICE_FAMILIES = _mf.live_prefixes("device")

#: The query result cache's families (runtime/resultcache
#: publish_gauges), rendered as cache_*.
CACHE_FAMILIES = _mf.live_prefixes("cache")

#: Streaming-ingest families (ingest.compactor publish_gauges),
#: rendered as ingest_*.
INGEST_FAMILIES = _mf.live_prefixes("ingest")

#: Ragged-megabatch families (ops/tape.publish_gauges): tape_* plus
#: the coalescer heterogeneity accounting coalescer_shape_*.
TAPE_FAMILIES = _mf.live_prefixes("tape")

#: Compressed container-directory engine families
#: (ops/containers.publish_gauges), rendered as container_*.
CONTAINER_FAMILIES = _mf.live_prefixes("container")

#: Mesh-native execution families (parallel/meshexec.publish_gauges),
#: rendered as mesh_*.
MESH_FAMILIES = _mf.live_prefixes("mesh")

#: Tiered-residency prefetch families (runtime/prefetch.py via
#: devobs.publish_gauges), rendered as prefetch_*; the
#: residency_tier_* prefixes ride the "device" group with the rest of
#: the residency family.
TIER_FAMILIES = _mf.live_prefixes("tier")

#: Self-healing replication families (parallel/syncer.py anti-entropy
#: rounds, parallel/hints.py hinted handoff, models/fragment.py WAL
#: replay health), rendered as ae_* / hint_* / wal_*.
REPL_FAMILIES = _mf.live_prefixes("repl")

#: Online shard-migration families (parallel/rebalance.py
#: publish_gauges at scrape), rendered as rebalance_* — published
#: (zeros) even on a node that never ran a plan.
REBALANCE_FAMILIES = _mf.live_prefixes("rebalance")

#: Per-tenant isolation families (serve/tenant.publish_gauges),
#: rendered as tenant_* — published (zeros) even with [tenants] off.
TENANT_FAMILIES = _mf.live_prefixes("tenant")

#: Query-autopsy families (observe.publish_journal_gauges): the
#: cluster event journal event_* and the trace-assembly trace_* —
#: published (zeros) even before the first event or assembly.
TRACE_FAMILIES = _mf.live_prefixes("trace")

#: Everything the ``--families`` CLI mode requires of a live server.
ALL_FAMILIES = _mf.live_prefixes()


def check_families(text: str, prefixes=DEVICE_FAMILIES) -> dict[str, int]:
    """Strict-parse one exposition body AND require at least one
    sampled metric under every prefix in ``prefixes``.  Returns
    {prefix: n_metrics}; raises MetricsFormatError on a malformed body
    or ValueError naming the missing family — so a refactor that
    silently drops a whole telemetry family fails in CI, not in the
    operator's dashboard."""
    check_text(text)
    names = set()
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is not None:
            names.add(m.group("name"))
    out = {}
    for prefix in prefixes:
        n = sum(1 for name in names if name.startswith(prefix))
        if n == 0:
            raise ValueError(
                f"no metrics under family prefix {prefix!r}")
        out[prefix] = n
    return out


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    families = None
    if "--families" in argv:
        argv = [a for a in argv if a != "--families"]
        families = ALL_FAMILIES
    if len(argv) != 1:
        print("usage: python -m tools.check_metrics [--families] "
              "URL|FILE", file=sys.stderr)
        return 2
    src = argv[0]
    if src.startswith("http://") or src.startswith("https://"):
        import urllib.request

        with urllib.request.urlopen(src, timeout=10) as resp:
            text = resp.read().decode()
    else:
        with open(src) as f:
            text = f.read()
    try:
        summary = check_text(text)
        if families is not None:
            check_families(text, families)
    except (MetricsFormatError, ValueError) as e:
        print(f"INVALID: {e}", file=sys.stderr)
        return 1
    print(f"ok: {summary['samples']} samples, "
          f"{summary['metrics']} metrics")
    return 0


if __name__ == "__main__":
    sys.exit(main())
