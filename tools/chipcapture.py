"""Chip capture harness: the dated ``tools/tpu_captures/bench_*.json``
producer (and the ``BENCH_r*.json`` round-artifact body).

Runs ``bench.py`` in a subprocess, takes the last JSON object line of
its stdout (the bench artifact — the watcher-era captures carried
runtime-warning lines around it, so the parser here tolerates that),
and augments it with what earlier captures only held implicitly in the
log tail:

- ``device_topology`` — platform, device kind, device/host counts, and
  per-device coords/core when the backend exposes them (TPU), so a
  capture documents WHICH chip produced it;
- ``captured_at`` — the UTC timestamp that also names the capture file;
- ``target`` — the newest committed chip capture's headline (qps +
  bw_util), i.e. the number this run exists to beat.  The current
  committed slot is the XLA route's 1801 qps / 0.148 bw_util; the
  bitmap-VM round (``extras.vm``) is the retake attempt.

The capture lands in ``tools/tpu_captures/bench_<UTCSTAMP>Z.json``;
``--out`` additionally writes the same body to a named round artifact
(e.g. ``BENCH_r10.json``).  ``--from-json FILE`` skips the bench run
and re-wraps an existing bench stdout capture (for re-stamping a run
taken on a box without this harness).

The engine observatory (pilosa_tpu.perfobs) feeds two more slots:

- ``engine_bw_util`` — MEASURED per-engine achieved bandwidth /
  bw_util from the bench run's own launch samples
  (``extras.perfobs.engines`` in the bench artifact), lifted to the
  top level so a capture answers "which engine ran at what fraction
  of the roof" without digging;
- ``--profile`` — brackets the bench subprocess's run with a device
  trace (the bench process starts/stops it via
  ``PILOSA_TPU_BENCH_PROFILE``; the artifact dir rides the capture's
  ``profile`` slot);
- ``--compare PREV.json`` — per-extras qps / bw_util deltas against a
  previous capture, with >10%-drop regression flags stamped into the
  body and echoed on stderr.

Usage::

    python -m tools.chipcapture [--out BENCH_r10.json]
                                [--from-json FILE] [--timeout SEC]
                                [--profile] [--compare PREV.json]
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CAPTURE_DIR = os.path.join(REPO, "tools", "tpu_captures")


def device_topology() -> dict:
    """Platform + per-device identity from the live jax backend.
    Import is deferred and failure-tolerant: a capture taken while the
    accelerator relay is down still records the host side."""
    try:
        import jax

        devs = jax.devices()
    except Exception as e:  # noqa: BLE001 — record, don't crash
        return {"error": f"{type(e).__name__}: {e}"}
    out = {
        "platform": devs[0].platform if devs else None,
        "device_kind": devs[0].device_kind if devs else None,
        "n_devices": len(devs),
        "n_hosts": getattr(jax, "process_count", lambda: 1)(),
    }
    coords = []
    for d in devs:
        ent = {"id": d.id}
        for attr in ("coords", "core_on_chip"):
            v = getattr(d, attr, None)
            if v is not None:
                ent[attr] = list(v) if isinstance(v, tuple) else v
        coords.append(ent)
    out["devices"] = coords
    return out


def last_json_line(text: str) -> dict | None:
    """The last line that parses as a JSON object — bench stdout can
    carry warning lines around the artifact."""
    rec = None
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                rec = json.loads(line)
            except ValueError:
                continue
    return rec


def previous_chip_target() -> dict | None:
    """The newest committed on-chip capture's headline: the number the
    current run must beat (sourced the same way bench.py attaches its
    ``last_chip_capture`` slot)."""
    sys.path.insert(0, REPO)
    try:
        import bench

        prev = bench._last_chip_capture()
    finally:
        sys.path.pop(0)
    if prev is None:
        return None
    return {
        "captured": prev.get("captured"),
        "qps": prev.get("value"),
        "engine": prev.get("engine"),
        "bw_util": prev.get("bw_util"),
        "beat": "extras.vm must push qps past this capture's value "
                "and bw_util past its fraction of the HBM roof",
    }


#: A metric dropping by more than this fraction of the previous
#: capture flags a regression in ``--compare``.
REGRESSION_PCT = 10.0

#: Per-extras numeric fields worth comparing across captures: every
#: ``qps*`` variant plus the bandwidth figures.
_COMPARE_FIELDS = ("achieved_gbps_lower", "achieved_gbps", "bw_util")


def _delta(old, new) -> dict | None:
    if not (isinstance(old, (int, float)) and
            isinstance(new, (int, float)) and old):
        return None
    return {"prev": old, "cur": new,
            "delta_pct": round((new - old) / old * 100.0, 2)}


def compare_captures(prev: dict, cur: dict) -> dict:
    """Per-extras qps/bw_util deltas of ``cur`` against a previous
    capture body, with regression flags on qps drops past
    ``REGRESSION_PCT``."""
    out: dict = {"prev_captured_at": prev.get("captured_at"),
                 "regression_threshold_pct": REGRESSION_PCT,
                 "extras": {}, "regressions": []}
    for label, field in (("qps", "value"), ("bw_util", "bw_util")):
        d = _delta(prev.get(field), cur.get(field))
        if d is None:
            continue
        out[label] = d
        if label == "qps" and d["delta_pct"] < -REGRESSION_PCT:
            out["regressions"].append(
                f"headline qps {d['delta_pct']}%")
    for key in sorted(set(prev) & set(cur)):
        pv, cv = prev[key], cur[key]
        if not (isinstance(pv, dict) and isinstance(cv, dict)):
            continue
        ent = {}
        for sub in sorted(set(pv) & set(cv)):
            if not (sub.startswith("qps") or sub in _COMPARE_FIELDS):
                continue
            d = _delta(pv[sub], cv[sub])
            if d is None:
                continue
            ent[sub] = d
            if sub.startswith("qps") and \
                    d["delta_pct"] < -REGRESSION_PCT:
                out["regressions"].append(
                    f"{key}.{sub} {d['delta_pct']}%")
        if ent:
            out["extras"][key] = ent
    return out


def run(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None,
                    help="also write the body to this round artifact "
                         "(e.g. BENCH_r10.json, relative to the repo)")
    ap.add_argument("--from-json", default=None,
                    help="re-wrap an existing bench stdout capture "
                         "instead of running bench.py")
    ap.add_argument("--timeout", type=float, default=1800.0)
    ap.add_argument("--profile", action="store_true",
                    help="bracket the bench run with a device trace "
                         "(artifact dir in the capture's 'profile' "
                         "slot)")
    ap.add_argument("--compare", default=None, metavar="PREV.json",
                    help="stamp per-extras qps/bw_util deltas against "
                         "a previous capture, flagging regressions")
    args = ap.parse_args(argv)

    if args.from_json:
        with open(args.from_json, errors="replace") as fh:
            body = last_json_line(fh.read())
    else:
        env = dict(os.environ)
        if args.profile:
            # the bench process starts/stops the trace itself — a
            # trace opened in THIS process would capture nothing
            env["PILOSA_TPU_BENCH_PROFILE"] = CAPTURE_DIR
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True, text=True, timeout=args.timeout,
            cwd=REPO, env=env)
        if proc.returncode != 0:
            sys.stderr.write(proc.stderr[-4000:])
            return proc.returncode
        body = last_json_line(proc.stdout)
    if body is None:
        print("chipcapture: no JSON artifact found in bench output",
              file=sys.stderr)
        return 1

    stamp = datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y%m%dT%H%M%SZ")
    body["captured_at"] = stamp
    body["device_topology"] = device_topology()
    target = previous_chip_target()
    if target is not None:
        body["target"] = target
    # measured per-engine bw_util from the bench run's own launch
    # samples (perfobs) — analytic bytes / measured walls, not the
    # headline's modeled bytes-per-query
    po = body.get("perfobs")
    if isinstance(po, dict) and isinstance(po.get("engines"), dict):
        body["engine_bw_util"] = {
            eng: s.get("bwUtil")
            for eng, s in po["engines"].items()
            if isinstance(s, dict)}
    if args.compare:
        with open(os.path.join(REPO, args.compare),
                  errors="replace") as fh:
            prev = last_json_line(fh.read())
        if prev is None:
            print(f"chipcapture: no JSON body in {args.compare}",
                  file=sys.stderr)
            return 1
        cmp_out = compare_captures(prev, body)
        body["compare"] = cmp_out
        for r in cmp_out["regressions"]:
            print(f"chipcapture: REGRESSION {r}", file=sys.stderr)

    os.makedirs(CAPTURE_DIR, exist_ok=True)
    cap_path = os.path.join(CAPTURE_DIR, f"bench_{stamp}.json")
    text = json.dumps(body)
    with open(cap_path, "w") as fh:
        fh.write(text + "\n")
    print(cap_path)
    if args.out:
        out_path = os.path.join(REPO, args.out)
        with open(out_path, "w") as fh:
            fh.write(text + "\n")
        print(out_path)
    return 0


if __name__ == "__main__":
    sys.exit(run())
