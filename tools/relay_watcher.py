#!/usr/bin/env python
"""Relay watcher: capture TPU evidence during ANY relay up-window.

The axon relay (the only path to the v5e chip) has been down for whole
rounds at a time; VERDICT round-2 item 1 requires that a mid-round
ten-minute up-window is enough to produce chip artifacts.  This watcher
runs for the whole round:

  - polls for the relay process (``pgrep -f \.relay\.py``) every
    POLL_S seconds, logging every state transition and an hourly
    heartbeat to tools/relay_watcher.log (committed evidence that the
    relay never came up, if it never does);
  - on an up-transition runs the capture sequence serially:
      1. benchmarks/validate_tpu.py  -> PALLAS_TPU_VALIDATION.json
      2. bench.py                    -> tools/tpu_captures/bench_<ts>.json
      3. benchmarks/measure.py       -> tools/tpu_captures/measure_<ts>.jsonl
    each with a generous timeout (a jax-on-axon process killed mid-init
    wedges the tunnel for good, so the budgets err long and a timeout is
    logged as evidence of a wedged tunnel, not retried in a tight loop);
  - commits the artifacts with a path-scoped ``git commit --`` so a
    concurrently-staged index is never swept into the capture commit;
  - while the relay stays up, re-captures bench.py hourly (cheap) and
    the full sequence every 4 h.

Single-client tunnel: the capture steps run strictly serially, and the
watcher writes tools/relay_watcher.capturing while a capture is running
so an interactive operator knows not to start a second jax-on-axon
process.
"""

from __future__ import annotations

import datetime
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
LOG = os.path.join(REPO, "tools", "relay_watcher.log")
CAPTURE_DIR = os.path.join(REPO, "tools", "tpu_captures")
CAPTURING_FLAG = os.path.join(REPO, "tools", "relay_watcher.capturing")

POLL_S = 30
HEARTBEAT_S = 3600
BENCH_RECAPTURE_S = 3600
FULL_RECAPTURE_S = 4 * 3600

# Generous per-step budgets: first compile through the relay is 20-40 s,
# measure.py's 10B config ~2-3 min on-chip, but a wedged tunnel hangs
# forever — these bound the watcher without risking a mid-init kill of a
# healthy run.
VALIDATE_TIMEOUT = 1800
BENCH_TIMEOUT = 1800
MEASURE_TIMEOUT = 5400


def log(msg: str) -> None:
    stamp = datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ")
    line = f"{stamp} {msg}"
    with open(LOG, "a") as f:
        f.write(line + "\n")
    print(line, flush=True)


def relay_up() -> bool:
    try:
        out = subprocess.run(["pgrep", "-f", r"\.relay\.py"],
                             capture_output=True, timeout=5)
        return bool(out.stdout.strip())
    except Exception:
        return False


def tunnel_ok() -> bool:
    """End-to-end probe, run only when a capture is due.

    A live relay process is not a live tunnel: a wedged far end leaves
    the local mux healthy while every jax op hangs (observed round 3) —
    captures fired at a wedged tunnel each burn their full timeout, so
    a ~4-min killable-subprocess probe first is cheap insurance.  Probe
    successes are disk-cached (axon_guard), so a healthy steady state
    pays one real probe per TTL."""
    from pilosa_tpu.axon_guard import tunnel_responsive

    return tunnel_responsive()


def run_step(name: str, argv: list[str], timeout: int,
             out_path: str | None) -> bool:
    """Run one capture step; returns True on rc==0.  stdout+stderr go to
    out_path (or the log on failure)."""
    log(f"capture step {name}: {' '.join(argv)}")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + ":" + env.get("PYTHONPATH", "")
    # capture steps own the tunnel: their axon_guard must not wait on
    # our own relay_watcher.capturing flag
    env["PILOSA_TPU_AXON_CAPTURING"] = "1"
    t0 = time.monotonic()
    try:
        proc = subprocess.run(argv, capture_output=True, text=True,
                              timeout=timeout, cwd=REPO, env=env)
    except subprocess.TimeoutExpired:
        log(f"capture step {name}: TIMEOUT after {timeout}s — tunnel "
            f"likely wedged; will keep polling but captures may hang "
            f"until the harness restarts the relay")
        return False
    dt = time.monotonic() - t0
    if out_path:
        with open(out_path, "w") as f:
            f.write(proc.stdout)
            if proc.stderr:
                f.write("\n--- stderr ---\n" + proc.stderr)
    if proc.returncode != 0:
        log(f"capture step {name}: rc={proc.returncode} after {dt:.0f}s; "
            f"stderr tail: {proc.stderr[-500:]!r}")
        return False
    log(f"capture step {name}: ok in {dt:.0f}s")
    return True


def git_commit(paths: list[str], msg: str) -> None:
    try:
        subprocess.run(["git", "add", "--"] + paths, cwd=REPO,
                       capture_output=True, timeout=30)
        proc = subprocess.run(
            ["git", "commit", "-m", msg, "--"] + paths,
            cwd=REPO, capture_output=True, text=True, timeout=30)
        log(f"git commit rc={proc.returncode}: "
            f"{(proc.stdout or proc.stderr).strip().splitlines()[:1]}")
    except Exception as e:
        log(f"git commit failed: {e}")


def capture(full: bool) -> bool:
    """Run the capture sequence; returns True if bench succeeded."""
    os.makedirs(CAPTURE_DIR, exist_ok=True)
    ts = datetime.datetime.now(datetime.timezone.utc).strftime("%Y%m%dT%H%M%SZ")
    open(CAPTURING_FLAG, "w").write(ts)
    py = sys.executable
    paths = [os.path.relpath(LOG, REPO)]
    ok_bench = False
    try:
        if full:
            if run_step("validate_tpu", [py, "-u", "benchmarks/validate_tpu.py"],
                        VALIDATE_TIMEOUT,
                        os.path.join(CAPTURE_DIR, f"validate_{ts}.log")):
                paths += ["PALLAS_TPU_VALIDATION.json",
                          f"tools/tpu_captures/validate_{ts}.log"]
        bench_out = os.path.join(CAPTURE_DIR, f"bench_{ts}.json")
        if run_step("bench", [py, "-u", "bench.py"], BENCH_TIMEOUT, bench_out):
            paths.append(f"tools/tpu_captures/bench_{ts}.json")
            ok_bench = True
        if full:
            meas_out = os.path.join(CAPTURE_DIR, f"measure_{ts}.jsonl")
            if run_step("measure", [py, "-u", "benchmarks/measure.py"],
                        MEASURE_TIMEOUT, meas_out):
                paths.append(f"tools/tpu_captures/measure_{ts}.jsonl")
        git_commit(paths, f"TPU capture {ts} (relay up-window)")
    finally:
        try:
            os.remove(CAPTURING_FLAG)
        except OSError:
            pass
    return ok_bench


def main() -> None:
    log(f"relay_watcher start pid={os.getpid()} poll={POLL_S}s")
    was_up = False
    last_heartbeat = 0.0
    # time.monotonic() starts at machine boot: initializing these to
    # 0.0 would read as "captured moments ago" on a fresh boot and sit
    # out the first hours of an up-window — force both due at start
    last_bench = time.monotonic() - 2 * BENCH_RECAPTURE_S
    last_full = time.monotonic() - 2 * FULL_RECAPTURE_S
    while True:
        now = time.monotonic()
        up = relay_up()
        if up != was_up:
            log(f"relay state change: {'UP' if up else 'DOWN'}")
            was_up = up
        if now - last_heartbeat >= HEARTBEAT_S:
            log(f"heartbeat: relay {'UP' if up else 'DOWN'}")
            last_heartbeat = now
        if up:
            full_due = now - last_full >= FULL_RECAPTURE_S
            bench_due = now - last_bench >= BENCH_RECAPTURE_S
            if (full_due or bench_due) and not tunnel_ok():
                log("relay process up but tunnel unresponsive end-to-end "
                    "(probe timed out); deferring capture")
                # back off BOTH timers one bench interval — otherwise a
                # pending full_due re-triggers the 4-min probe every
                # 30 s poll
                now = time.monotonic()
                last_bench = now
                last_full = max(last_full,
                                now - FULL_RECAPTURE_S + BENCH_RECAPTURE_S)
            elif full_due or bench_due:
                if capture(full=full_due):
                    last_bench = time.monotonic()
                    if full_due:
                        last_full = time.monotonic()
                else:
                    # Failed capture: back off a full bench interval so a
                    # wedged tunnel doesn't spin the log.
                    last_bench = time.monotonic()
        time.sleep(POLL_S)


if __name__ == "__main__":
    main()
