"""Executable documentation checker.

Walks a markdown file for ` ```sh ` blocks (replaying their
`curl -XPOST localhost:10101/...` lines) and ` ```pql ` blocks
(executed against the current `<!-- doctest index: NAME -->` context);
a ` ```response ` block immediately following a pql block asserts the
exact JSON `results` payload.  Run by `tests/test_docs.py` against a
fresh in-process server per file, so every example in the docs is a
tested example (the VERDICT #8 contract; reference
docs/query-language.md:57-905 is the coverage bar).

`--fill` rewrites the response blocks in place with the actual
results — the authoring loop: write examples, fill, review the diff,
commit; the test then pins them forever.
"""

from __future__ import annotations

import json
import re
import sys

_MARKER = re.compile(r"<!--\s*doctest index:\s*(\S+)\s*-->")
_CURL = re.compile(r"curl\s+-XPOST\s+localhost:10101(/\S+)")
_BODY = re.compile(r"-d\s+'([^']*)'")


def parse(text: str):
    """-> list of events:
    ("post", path, body_or_None)           — replayed sh curl
    ("query", index, pql, expected_or_None, response_span) — pql block;
      response_span = (start_line, end_line) of the response BODY for
      --fill rewriting, or None when no response block follows."""
    lines = text.splitlines()
    events = []
    index = None
    i = 0
    while i < len(lines):
        stripped = lines[i].strip()
        m = _MARKER.match(stripped)
        if m:
            index = m.group(1)
            i += 1
            continue
        if stripped == "```sh":
            i += 1
            block = []
            while i < len(lines) and lines[i].strip() != "```":
                block.append(lines[i])
                i += 1
            joined, cur = [], ""
            for ln in block:
                if ln.rstrip().endswith("\\"):
                    cur += ln.rstrip()[:-1] + " "
                else:
                    joined.append(cur + ln)
                    cur = ""
            for cmd in joined:
                mc = _CURL.search(cmd)
                if mc:
                    mb = _BODY.search(cmd)
                    events.append(("post", mc.group(1),
                                   mb.group(1) if mb else None))
        elif stripped == "```pql":
            i += 1
            pql_lines = []
            while i < len(lines) and lines[i].strip() != "```":
                pql_lines.append(lines[i])
                i += 1
            i += 1  # closing fence
            # optional response block directly after (blank lines ok)
            j = i
            while j < len(lines) and not lines[j].strip():
                j += 1
            expected = None
            span = None
            if j < len(lines) and lines[j].strip() == "```response":
                start = j + 1
                j += 1
                resp_lines = []
                while j < len(lines) and lines[j].strip() != "```":
                    resp_lines.append(lines[j])
                    j += 1
                span = (start, j)  # body lines [start, j)
                expected = "\n".join(resp_lines)
                i = j
            if index is None:
                raise SystemExit(
                    "pql block before any doctest index marker")
            events.append(("query", index,
                           "\n".join(pql_lines).strip(), expected, span))
            if span is None:
                # i already points at the first line AFTER the pql
                # fence; the loop-bottom increment would skip it
                continue
        i += 1
    return events


def run(path: str, fill: bool = False) -> int:
    """Execute one doc's examples against a fresh in-process server.
    Returns the number of verified examples; raises on mismatch."""
    import contextlib
    import tempfile
    import urllib.request

    from pilosa_tpu.server.server import Server

    text = open(path).read()
    events = parse(text)
    stack = contextlib.ExitStack()
    data_dir = stack.enter_context(tempfile.TemporaryDirectory())
    srv = Server(data_dir, host="127.0.0.1", port=0)
    srv.open()
    rewrites: list[tuple[tuple[int, int], str]] = []
    checked = 0
    try:
        for ev in events:
            if ev[0] == "post":
                _, p, body = ev
                data = (body or "").encode() or None
                req = urllib.request.Request(srv.uri + p, data=data,
                                             method="POST")
                if body and body.lstrip().startswith("{"):
                    req.add_header("Content-Type", "application/json")
                with urllib.request.urlopen(req) as resp:
                    resp.read()
                continue
            _, index, pql, expected, span = ev
            req = urllib.request.Request(
                srv.uri + f"/index/{index}/query",
                data=pql.encode(), method="POST")
            with urllib.request.urlopen(req) as resp:
                got = json.loads(resp.read())["results"]
            if fill and span is not None:
                rewrites.append((span, json.dumps(got, sort_keys=True)))
                continue
            if expected is not None:
                want = json.loads(expected)
                if got != want:
                    raise AssertionError(
                        f"{path}: example {pql!r} returned\n  {got}\n"
                        f"expected\n  {want}")
                checked += 1
    finally:
        srv.close()
        stack.close()
    if fill and rewrites:
        lines = text.splitlines()
        for (start, end), payload in reversed(rewrites):
            lines[start:end] = [payload]
        open(path, "w").write("\n".join(lines) + "\n")
        print(f"{path}: filled {len(rewrites)} response blocks")
    return checked


def main(argv) -> int:
    fill = "--fill" in argv
    files = [a for a in argv if not a.startswith("--")]
    for f in files:
        n = run(f, fill=fill)
        if not fill:
            print(f"{f}: {n} examples verified")
    return 0


if __name__ == "__main__":
    from pilosa_tpu.axon_guard import guard_dead_relay

    guard_dead_relay()
    sys.exit(main(sys.argv[1:]))
