#!/usr/bin/env python
"""Randomized differential soak: hours-scale stress beyond the CI tier.

Drives a replicated in-process 3-node cluster with an interleaved
random workload — bulk imports, PQL Set/Clear, BSI writes, nested set
algebra, BSI ranges, TopN, GroupBy — checking EVERY read against
Python-set/dict oracles, while randomly dropping a node (reads must
fail over exactly), running anti-entropy repair cycles, and (round 3)
driving coordinator-led elastic RESIZE events: a fourth node joins
(fragments re-home by jump hash) and later leaves, with the oracle
exact across every ownership change — the reference's
internal/clustertests/ tier including its resize legs.

Round 5 adds bidirectional PAIR PARTITIONS to the fault schedule
(internal/clustertests/cluster_test.go:69-80's pumba netem scenario):
two live nodes stop hearing each other while both keep serving the
rest of the cluster — reads from either side must fail over to the
reachable replica, and anti-entropy passes RACE the partition (the
syncer must skip the unreachable peer, never half-apply.  Also GRAY
faults: a node answers every message LATE — no TransportError fires,
so nothing fails over; writes keep replicating through it
synchronously and every read must stay exact, just slower).  The
process-level counterpart with real SIGSTOP freezes is
tools/soak_proc.py.

    PYTHONPATH=/root/repo:$PYTHONPATH python tools/soak.py --seconds 600

Exit code 0 = no divergence.  Deterministic per --seed.  The CI-tier
equivalents are tests/test_fuzz_stress.py and tests/test_model_stress.py;
this harness exists to run 100x longer (the reference's long-running
clustertests tier, internal/clustertests/).
"""

from __future__ import annotations

import argparse
import os
import pathlib
import random
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("PILOSA_TPU_SHARD_WIDTH_EXP", "16")
os.environ.setdefault("PILOSA_TPU_PARANOIA", "1")  # sanitizer on


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seconds", type=float, default=300.0)
    ap.add_argument("--seed", type=int, default=12348)
    ap.add_argument("--progress-every", type=float, default=30.0)
    args = ap.parse_args()

    # pin jax before anything touches a backend
    import jax

    jax.config.update("jax_platforms", "cpu")

    from pilosa_tpu.api import API
    from pilosa_tpu.parallel.syncer import HolderSyncer
    from pilosa_tpu.shardwidth import SHARD_WIDTH
    from tests.test_cluster import make_cluster
    from tests.test_fuzz_stress import eval_set_algebra, gen_query

    rng = random.Random(args.seed)
    tmp = pathlib.Path(tempfile.mkdtemp(prefix="soak-"))
    transport, nodes = make_cluster(tmp, n=3, replica_n=2)
    coord = nodes[0]
    coord.create_index("i")
    api = API(coord)

    n_shards = 4
    fields = [f"f{i}" for i in range(3)]
    for f in fields:
        coord.create_field("i", f)
    from pilosa_tpu.models.field import FieldOptions
    from pilosa_tpu.models.index import IndexOptions

    coord.create_field("i", "v", options=FieldOptions.int_field(-1000, 1000))
    # keyed surface: translation (coordinator-allocated ids, replica
    # tailing, read-through) must stay exact under the same fault
    # schedule as everything else
    coord.create_index("k", options=IndexOptions(keys=True))
    coord.create_field("k", "kf", options=FieldOptions(keys=True))
    kbits: dict[str, set] = {f"r{j}": set() for j in range(4)}
    # time-quantum surface: every write lands in multiple views, AE
    # reconciles per view, and resize transfers must move ALL views
    coord.create_field("i", "t",
                       options=FieldOptions.time_field("YM"))
    # oracle: (row, month) -> cols; months 1..6 of 2024
    tbits: dict[tuple[int, int], set] = {
        (r, m): set() for r in range(3) for m in range(1, 7)}
    # Store/ClearRow target: a whole-row write forwarded to ALL nodes
    # (a different replication shape from per-shard owner fan-out)
    coord.create_field("i", "st")
    stbits: dict[int, set] = {j: set() for j in range(3)}

    bits: dict[tuple[str, int], set] = {
        (f, r): set() for f in fields for r in range(5)}
    vals: dict[int, int] = {}
    universe: set[int] = set()

    def col():
        return rng.randrange(n_shards * SHARD_WIDTH)

    from pilosa_tpu.pql import parse_python

    downed: str | None = None
    partition: tuple[str, str] | None = None
    slowed: str | None = None
    iters = 0
    checks = 0
    resizes = 0
    partitions = 0
    slow_events = 0
    extra: list = []  # nodes joined beyond the base 3, newest last
    next_extra_id = 3
    t_end = time.monotonic() + args.seconds
    t_report = time.monotonic() + args.progress_every
    ex = coord.executor

    def live_nodes():
        return [*nodes, *extra]

    capturing_flag = os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "relay_watcher.capturing")
    while time.monotonic() < t_end:
        # yield the single core while a relay capture is timing QPS on
        # the chip — a soak-loaded host would distort the committed
        # bench/measure artifacts (see BASELINE.md benchmark hygiene).
        # Staleness bound: a flag older than the watcher's longest
        # step budget (90 min) plus slack is an orphan from a killed
        # watcher, not a live capture — ignore it or pause forever.
        while os.path.exists(capturing_flag):
            try:
                if time.time() - os.path.getmtime(capturing_flag) > 7200:
                    break
            except OSError:
                break
            time.sleep(5)
        iters += 1
        action = rng.random()
        # writes and resizes need every replica reachable from the
        # coordinator; reads and AE deliberately RACE active faults
        quiesced = downed is None and partition is None

        if action < 0.18:  # bulk import
            f = rng.choice(fields)
            row = rng.randrange(5)
            cs = sorted({col() for _ in range(rng.randrange(1, 120))})
            if quiesced:  # writes only with all replicas up
                api.import_bits("i", f, [row] * len(cs), cs)
                bits[(f, row)].update(cs)
                universe.update(cs)
        elif action < 0.28:  # single Set / Clear via PQL
            f = rng.choice(fields)
            row = rng.randrange(5)
            c = col()
            if quiesced:
                if rng.random() < 0.7:
                    ex.execute("i", f"Set({c}, {f}={row})")
                    bits[(f, row)].add(c)
                    universe.add(c)
                else:
                    ex.execute("i", f"Clear({c}, {f}={row})")
                    bits[(f, row)].discard(c)
        elif action < 0.32:  # BSI write
            c = col()
            v = rng.randrange(-1000, 1001)
            if quiesced:
                ex.execute("i", f"Set({c}, v={v})")
                vals[c] = v
                universe.add(c)
        elif action < 0.345:  # time-field write (multi-view)
            if quiesced:
                r_, m = rng.randrange(3), rng.randrange(1, 7)
                c = col()
                ex.execute("i",
                           f"Set({c}, t={r_}, 2024-{m:02d}-15T00:00)")
                tbits[(r_, m)].add(c)
                universe.add(c)
        elif action < 0.36:  # time-window read vs oracle (any node,
            # races every fault: per-view failover + AE)
            r_ = rng.randrange(3)
            m0 = rng.randrange(1, 7)
            m1 = rng.randrange(m0, 7)
            node = rng.choice(live_nodes())
            if downed is not None and node.cluster.local_id == downed:
                node = coord
            got = node.executor.execute(
                "i", f"Count(Row(t={r_}, from='2024-{m0:02d}-01T00:00',"
                     f" to='2024-{m1 + 1:02d}-01T00:00'))")[0]
            want = len(set().union(*(tbits[(r_, m)]
                                     for m in range(m0, m1 + 1))))
            assert int(got) == want, \
                f"time divergence t={r_} [{m0},{m1}] on " \
                f"{node.cluster.local_id}"
            checks += 1
        elif action < 0.39:  # keyed write (translation allocates ids)
            if quiesced:
                rk = f"r{rng.randrange(4)}"
                ck = f"u{rng.randrange(3000)}"
                ex.execute("k", f'Set("{ck}", kf="{rk}")')
                kbits[rk].add(ck)
        elif action < 0.43:  # keyed read vs oracle — replicas serve
            # via tailed stores + read-through; during faults the
            # coordinator (never downed) answers, since a partitioned
            # replica legitimately cannot resolve keys created across
            # the cut (the reference's tailing replicas share that
            # staleness window)
            rk = f"r{rng.randrange(4)}"
            node = coord if not quiesced else rng.choice(live_nodes())
            got = node.executor.execute("k", f'Count(Row(kf="{rk}"))')[0]
            assert int(got) == len(kbits[rk]), \
                f"keyed divergence {rk} on {node.cluster.local_id}"
            ra, rb = rng.sample(list(kbits), 2)
            got = node.executor.execute(
                "k", f'Count(Intersect(Row(kf="{ra}"), '
                     f'Row(kf="{rb}")))')[0]
            assert int(got) == len(kbits[ra] & kbits[rb]), \
                f"keyed intersect divergence on {node.cluster.local_id}"
            checks += 2
        elif action < 0.445:  # Store / ClearRow: whole-row writes
            # forwarded to every node (executor.go:1739 / :1797 shape)
            if quiesced:
                sr = rng.randrange(3)
                if rng.random() < 0.75:
                    f = rng.choice(fields)
                    r1, r2 = rng.sample(range(5), 2)
                    ex.execute(
                        "i", f"Store(Union(Row({f}={r1}), "
                             f"Row({f}={r2})), st={sr})")
                    stbits[sr] = bits[(f, r1)] | bits[(f, r2)]
                else:
                    ex.execute("i", f"ClearRow(st={sr})")
                    stbits[sr] = set()
        elif action < 0.46:  # stored-row read vs oracle (races faults)
            sr = rng.randrange(3)
            node = rng.choice(live_nodes())
            if downed is not None and node.cluster.local_id == downed:
                node = coord
            got = node.executor.execute("i", f"Count(Row(st={sr}))")[0]
            assert int(got) == len(stbits[sr]), \
                f"Store divergence st={sr} on {node.cluster.local_id}"
            checks += 1
        elif action < 0.70:  # nested algebra vs oracle (any node)
            q = gen_query(rng)
            want = eval_set_algebra(parse_python(q).calls[0],
                                    bits, universe)
            node = rng.choice(live_nodes())
            if downed is not None and node.cluster.local_id == downed:
                node = coord
            res = node.executor.execute("i", q)[0]
            got = (set(int(x) for x in res.columns())
                   if hasattr(res, "columns") else None)
            if got is not None:
                assert got == want, f"divergence on {q}"
            else:
                assert int(res) == len(want), f"count divergence on {q}"
            checks += 1
        elif action < 0.80:  # BSI range vs oracle
            op = rng.choice(["<", "<=", ">", ">=", "==", "!="])
            p = rng.randrange(-1000, 1001)
            got = ex.execute("i", f"Count(Row(v {op} {p}))")[0]
            import operator as _op

            cmp = {"<": _op.lt, "<=": _op.le, ">": _op.gt,
                   ">=": _op.ge, "==": _op.eq, "!=": _op.ne}[op]
            want = sum(1 for v in vals.values() if cmp(v, p))
            assert int(got) == want, f"BSI divergence v {op} {p}"
            checks += 1
        elif action < 0.88:  # TopN vs oracle
            f = rng.choice(fields)
            pairs = ex.execute("i", f"TopN({f}, n=5)")[0]
            want = sorted((len(cs) for (fn, r), cs in bits.items()
                           if fn == f and cs), reverse=True)[:5]
            assert [p.count for p in pairs] == want, f"TopN divergence {f}"
            checks += 1
        elif action < 0.93:  # GroupBy vs oracle (both directions)
            fa, fb = rng.sample(fields, 2)
            gcs = ex.execute("i", f"GroupBy(Rows({fa}), Rows({fb}))")[0]
            got = {tuple((fr.field, fr.row_id) for fr in gc.group): gc.count
                   for gc in gcs}
            want = {}
            for ra in range(5):
                for rb in range(5):
                    n = len(bits[(fa, ra)] & bits[(fb, rb)])
                    if n:
                        want[((fa, ra), (fb, rb))] = n
            assert got == want, (
                f"GroupBy divergence {fa}x{fb}: "
                f"missing={set(want) - set(got)} "
                f"extra={set(got) - set(want)}")
            checks += 1
        elif action < 0.945:  # elastic resize: join or leave
            # ownership moves under live traffic; the oracle must stay
            # exact across every re-homing (reference clustertests
            # resize legs, cluster.go:1196-1561)
            if quiesced:
                from pilosa_tpu.models.holder import Holder
                from pilosa_tpu.parallel.cluster import Cluster, Node
                from pilosa_tpu.parallel.node import ClusterNode
                from pilosa_tpu.parallel.resize import Resizer

                if not extra:
                    # fixed node ID (placement + transport handle are
                    # overwritten on re-join, no per-cycle leak), fresh
                    # dir per cycle (a removed node keeps its detached
                    # data; rejoining on it would resurrect stale bits)
                    dirname = f"node3-epoch{next_extra_id}"
                    next_extra_id += 1
                    h = Holder(str(tmp / dirname))
                    cl = Cluster("node3", nodes=[Node(id="node3")],
                                 replica_n=2,
                                 transport=transport.bind("node3"))
                    jn = ClusterNode(h, cl)
                    resp = transport.send_message(
                        coord.cluster.local_node,
                        {"type": "node-join",
                         "node": {"id": "node3", "uri": ""}})
                    assert resp.get("ok"), f"join failed: {resp}"
                    extra.append(jn)
                else:
                    import shutil

                    jn = extra.pop()
                    Resizer(coord).run(remove_id=jn.cluster.local_id)
                    path = jn.holder.path
                    jn.holder.close()
                    shutil.rmtree(path, ignore_errors=True)
                resizes += 1
                for nd in live_nodes():
                    assert nd.cluster.state == "NORMAL", (
                        f"{nd.cluster.local_id} not NORMAL after resize")
        elif action < 0.975:  # fault injection: heal, or down /
            # partition / gray (slow) failure
            if downed is not None:
                transport.set_down(downed, False)
                downed = None
            elif partition is not None:
                transport.set_partition(*partition, False)
                partition = None
            elif slowed is not None:
                transport.set_slow(slowed, 0.0)
                slowed = None
            else:
                kind = rng.random()
                if kind < 0.4:
                    downed = rng.choice(["node1", "node2"])
                    transport.set_down(downed)
                elif kind < 0.8:
                    # bidirectional pair partition between two LIVE
                    # nodes: both keep serving everyone else; reads
                    # from either side must fail over to the
                    # reachable replica
                    ids = [nd.cluster.local_id for nd in live_nodes()]
                    a, b = rng.sample(ids, 2)
                    transport.set_partition(a, b)
                    partition = (a, b)
                    partitions += 1
                else:
                    # GRAY failure: the node answers, just late —
                    # no failover triggers, writes keep flowing, and
                    # every read must still be exact
                    slowed = rng.choice(["node1", "node2"])
                    transport.set_slow(slowed, rng.uniform(0.01, 0.06))
                    slow_events += 1
        else:  # anti-entropy repair pass — races any active partition
            if downed is None:
                for nd in live_nodes():
                    HolderSyncer(nd).sync_holder()

        if time.monotonic() >= t_report:
            t_report = time.monotonic() + args.progress_every
            print(f"soak: {iters} iters, {checks} oracle checks, "
                  f"{resizes} resizes, {partitions} partitions, "
                  f"{slow_events} gray, nodes={len(live_nodes())}, "
                  f"downed={downed}, partition={partition}, "
                  f"slowed={slowed}", flush=True)

    if downed is not None:
        transport.set_down(downed, False)
    if partition is not None:
        transport.set_partition(*partition, False)
    if slowed is not None:
        transport.set_slow(slowed, 0.0)
    for nd in live_nodes():
        HolderSyncer(nd).sync_holder()
    # final convergence: every node answers every row exactly
    for f in fields:
        for r in range(5):
            want = bits[(f, r)]
            for nd in live_nodes():
                res = nd.executor.execute("i", f"Row({f}={r})")[0]
                got = set(int(x) for x in res.columns())
                assert got == want, f"final divergence {f}={r} on " \
                    f"{nd.cluster.local_id}"
    print(f"soak PASSED: {iters} iters, {checks} oracle checks, "
          f"{resizes} resizes, {partitions} partitions, "
          f"{slow_events} gray faults")
    return 0


if __name__ == "__main__":
    sys.exit(main())
