#!/usr/bin/env python
"""Multi-process collective-plane soak: randomized writes + collective
reads across N full server processes, every answer checked.

The CI tier (tests/test_spmd.py multi-process leg) proves the protocol
once; this soak runs it for MINUTES with randomized workloads — the
long-haul evidence that the SPMD plane holds exactness and liveness
under churn (the single-process analog, tools/soak.py, caught a real
stale-cache bug; this is its distributed sibling).

Per round (all processes in lockstep, file barriers on the control
plane — never a jax collective, which would deadlock against serving):
  1. the coordinator applies K randomized writes (Set/Clear/BSI Set)
     through its HTTP API; EVERY process updates the identical Python
     oracle from the shared per-round rng;
  2. every process enters M randomized collective queries in the same
     order (Count trees, BSI conditions, Sum/Min/Max, TopN args,
     GroupBy 1-3 children); the coordinator asserts each against the
     oracle;
  3. every 5th round the coordinator re-asks a sample through the HTTP
     scatter plane (peers idle, serving) and asserts plane agreement.

Usage: python tools/soak_spmd.py [--seconds 600] [--procs 2]
Prints one JSON summary line; exit 0 = zero divergence, zero deadlock.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools import fleet_lib  # noqa: E402

WORKER = r'''
import json, os, random, sys, time
from tools import fleet_lib as _fl
os.environ["JAX_PLATFORMS"] = "cpu"
import re as _re
_fl2 = _re.sub(r"--xla_force_host_platform_device_count=\d+", "",
               os.environ.get("XLA_FLAGS", ""))
os.environ["XLA_FLAGS"] = (
    _fl2 + " --xla_force_host_platform_device_count=2").strip()
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:
    pass  # jax < 0.5: the XLA_FLAGS override above covers it

from pilosa_tpu.parallel import multihost, spmd
from pilosa_tpu.pql import parse
from pilosa_tpu.server.server import Server
from pilosa_tpu.server.client import InternalClient
from pilosa_tpu.shardwidth import SHARD_WIDTH

multihost.initialize()
pid = jax.process_index()
NPROC = int(os.environ["JAX_NUM_PROCESSES"])
ports = [int(os.environ[f"T_PORT{i}"]) for i in range(NPROC)]
data = os.environ["T_DATA"]
SOAK_S = float(os.environ["SOAK_SECONDS"])
SEED = int(os.environ["SOAK_SEED"])
N_SHARDS = 6
VMIN, VMAX = -1000, 100000

if pid == 0:
    srv = Server(data + "/n0", port=ports[0], name="n0", coordinator=True)
else:
    srv = Server(data + f"/n{pid}", port=ports[pid], name=f"n{pid}",
                 seeds=[f"http://127.0.0.1:{ports[0]}"])
srv.open()
c = InternalClient(timeout=60)

deadline = time.monotonic() + 60
while len(srv.cluster.sorted_nodes()) < NPROC:
    if time.monotonic() > deadline:
        raise SystemExit("join timeout")
    time.sleep(0.05)
spmd.verify_rank_convention(srv.cluster)


from tools.fleet_lib import file_barrier


def barrier(name, timeout=300):
    file_barrier(data, name, pid, NPROC, timeout)


# ---- deterministic base dataset (identical in every process) ----
rng = random.Random(SEED)
bits = {}     # (field, row) -> set of cols
exists = set()
for fi in range(3):
    for row in range(5):
        cols = {rng.randrange(N_SHARDS * SHARD_WIDTH) for _ in range(150)}
        bits[(f"f{fi}", row)] = cols
        exists |= cols
vcols = sorted({rng.randrange(N_SHARDS * SHARD_WIDTH) for _ in range(400)})
vals = {cc: rng.randrange(VMIN, VMAX) for cc in vcols}
exists |= set(vcols)

if pid == 0:
    post = lambda p, o: c.post_json(srv.uri + p, o)
    post("/index/i", {})
    for fi in range(3):
        post(f"/index/i/field/f{fi}", {})
        rows_l, cols_l = [], []
        for row in range(5):
            cs = bits[(f"f{fi}", row)]
            rows_l += [row] * len(cs)
            cols_l += sorted(cs)
        post(f"/index/i/field/f{fi}/import",
             {"rowIDs": rows_l, "columnIDs": cols_l})
    post("/index/i/field/v",
         {"options": {"type": "int", "min": VMIN, "max": VMAX}})
    post("/index/i/field/v/import-value",
         {"columnIDs": vcols, "values": [vals[cc] for cc in vcols]})

# visibility barrier: scatter plane sees the data
want0 = len(bits[("f0", 0)])
end = time.monotonic() + 120
while True:
    try:
        got = c.post_json(srv.uri + "/index/i/query",
                          {"query": "Count(Row(f0=0))"})["results"][0]
        if got == want0:
            break
    except Exception:
        pass
    if time.monotonic() > end:
        raise SystemExit("data visibility timeout")
    time.sleep(0.1)
barrier("loaded")

ce = spmd.CollectiveExecutor(srv.holder, srv.cluster, "i")


def gen_tree(r, depth):
    if depth == 0 or r.random() < 0.45:
        fi, row = r.randrange(3), r.randrange(5)
        return (f"Row(f{fi}={row})", bits[(f"f{fi}", row)])
    op = r.choice(["Union", "Intersect", "Difference", "Xor"])
    parts = [gen_tree(r, depth - 1) for _ in range(r.randrange(2, 4))]
    sets = [p[1] for p in parts]
    if op == "Union":
        acc = set().union(*sets)
    elif op == "Intersect":
        acc = sets[0]
        for s in sets[1:]:
            acc = acc & s
    elif op == "Difference":
        acc = sets[0]
        for s in sets[1:]:
            acc = acc - s
    else:
        acc = sets[0]
        for s in sets[1:]:
            acc = acc ^ s
    return (f"{op}({', '.join(p[0] for p in parts)})", acc)


import operator as _op
CMPS = {"<": _op.lt, "<=": _op.le, ">": _op.gt, ">=": _op.ge,
        "==": _op.eq, "!=": _op.ne}


def gen_query(r):
    """-> (pql, oracle_fn) — oracle_fn() computed lazily AFTER this
    round's writes land in the shared state."""
    kind = r.randrange(11)
    if kind == 10:
        # Options(shards=[...]) restricts the plan (late round 4):
        # oracle filters the column universe to the chosen shards
        text, acc = gen_tree(r, 2)
        ss = sorted(r.sample(range(N_SHARDS), r.randrange(1, N_SHARDS)))
        return (f"Options(Count({text}), shards={ss})",
                lambda a=acc, s=frozenset(ss): sum(
                    1 for c in a if c // SHARD_WIDTH in s),
                "count")
    if kind == 8:
        # bare bitmap tree: the global Row gathers replicated (round 4)
        text, acc = gen_tree(r, 2)
        return text, (lambda a=acc: sorted(a)), "row"
    if kind == 9:
        text, acc = gen_tree(r, 1)
        return (f"Not({text})",
                lambda a=acc: sorted(exists - a), "row")
    if kind == 7:
        # Not rides the existence field: oracle = every column ever
        # Set/imported minus the subtree (Clear never clears _exists,
        # matching the product semantics)
        text, acc = gen_tree(r, 1)
        return (f"Count(Not({text}))",
                lambda a=acc: len(exists - a), "count")
    if kind == 0:
        text, acc = gen_tree(r, 2)
        return f"Count({text})", (lambda a=acc: len(a)), "count"
    if kind == 1:
        o = r.choice(list(CMPS))
        p = r.randrange(VMIN - 500, VMAX + 500)
        return (f"Count(Row(v {o} {p}))",
                lambda o=o, p=p: sum(1 for x in vals.values()
                                     if CMPS[o](x, p)), "count")
    if kind == 2:
        text, acc = gen_tree(r, 1)
        return (f"Sum({text}, field=v)",
                lambda a=acc: ((sum(x for cc, x in vals.items()
                                    if cc in a)),
                               sum(1 for cc in vals if cc in a)), "sum")
    if kind == 3:
        name = r.choice(["Min", "Max"])
        text, acc = gen_tree(r, 1)
        def mm(a=acc, name=name):
            sel = [x for cc, x in vals.items() if cc in a]
            if not sel:
                return None
            best = min(sel) if name == "Min" else max(sel)
            return (best, sel.count(best))
        return f"{name}({text}, field=v)", mm, "valcount"
    if kind == 4:
        fi = r.randrange(3)
        n = r.randrange(0, 4)
        thr = r.randrange(0, 3) * 40
        args = [f"f{fi}"]
        if n:
            args.append(f"n={n}")
        if thr:
            args.append(f"threshold={thr}")
        def topn(fi=fi, n=n, thr=thr):
            t = sorted(((row, len(bits[(f"f{fi}", row)]))
                        for row in range(5)),
                       key=lambda rc: (-rc[1], rc[0]))
            t = [(row, cnt) for row, cnt in t if cnt > 0]
            if thr:
                t = [(row, cnt) for row, cnt in t if cnt >= thr]
            return t[:n] if n else t
        return f"TopN({', '.join(args)})", topn, "pairs"
    if kind == 5:
        # up to 4 children: the outer cartesian loop stays within
        # MAX_OUTER_DISPATCHES (5 rows/field -> <=25 combos)
        nch = r.randrange(1, 5)
        fis = [r.randrange(3) for _ in range(nch)]
        children = ", ".join(f"Rows(f{fi})" for fi in fis)
        def gb(fis=tuple(fis)):
            out = []
            def walk(prefix, sets, lvl):
                if lvl == len(fis):
                    inter = sets[0]
                    for s in sets[1:]:
                        inter = inter & s
                    n = len(inter)
                    if n:
                        out.append((prefix, n))
                    return
                for row in range(5):
                    cs = bits[(f"f{fis[lvl]}", row)]
                    walk(prefix + ((f"f{fis[lvl]}", row),),
                         sets + [cs], lvl + 1)
            walk((), [], 0)
            # sorted-group order == tuple sort of ((field,row),...)
            return sorted(out)
        return f"GroupBy({children})", gb, "groups"
    text, acc = gen_tree(r, 1)
    fi, row = r.randrange(3), r.randrange(5)
    return (f"Count(Intersect(Row(f{fi}={row}), {text}))",
            lambda a=acc, k=(f"f{fi}", row): len(bits[k] & a), "count")


checked = writes = rounds = xchecks = 0
t_start = time.monotonic()
R = 0
while True:
    # round gate: the coordinator decides stop vs go (wall clocks skew)
    if pid == 0:
        if time.monotonic() - t_start > SOAK_S:
            open(f"{data}/stop.ok", "w").write("1")
        else:
            open(f"{data}/round.{R}.go", "w").write("1")
    end = time.monotonic() + 300
    while not (os.path.exists(f"{data}/stop.ok")
               or os.path.exists(f"{data}/round.{R}.go")):
        if time.monotonic() > end:
            raise SystemExit(f"round {R} gate timeout")
        time.sleep(0.02)
    if os.path.exists(f"{data}/stop.ok"):
        break
    rr = random.Random((SEED << 20) ^ R)

    # ---- write phase (coordinator applies; everyone updates oracle)
    wlist = []
    for _ in range(rr.randrange(3, 9)):
        w = rr.random()
        fi, row = rr.randrange(3), rr.randrange(5)
        col = rr.randrange(N_SHARDS * SHARD_WIDTH)
        if w < 0.55:
            wlist.append((f"Set({col}, f{fi}={row})",))
            bits[(f"f{fi}", row)].add(col)
            exists.add(col)
        elif w < 0.8:
            wlist.append((f"Clear({col}, f{fi}={row})",))
            bits[(f"f{fi}", row)].discard(col)
        else:
            val = rr.randrange(VMIN, VMAX)
            wlist.append((f"Set({col}, v={val})",))
            vals[col] = val
            exists.add(col)
    if pid == 0:
        for (w,) in wlist:
            c.post_json(srv.uri + "/index/i/query", {"query": w})
        writes += len(wlist)
    barrier(f"w{R}")

    # ---- collective phase: identical query sequence, lockstep
    qlist = [gen_query(rr) for _ in range(rr.randrange(4, 10))]
    answers = []
    for q, oracle_fn, shape in qlist:
        if not ce.supported(parse(q).calls[0]):
            continue
        got = ce.execute(q)
        answers.append((q, got))
        if pid != 0:
            continue
        want = oracle_fn()
        if shape == "count":
            assert got == want, (R, q, got, want)
        elif shape == "sum":
            assert (got.val, got.count) == want, (R, q, got, want)
        elif shape == "valcount":
            if want is not None:
                assert (got.val, got.count) == want, (R, q, got, want)
            else:
                assert got.count == 0, (R, q, got)
        elif shape == "pairs":
            assert [(p.id, p.count) for p in got] == want, \
                (R, q, got, want)
        elif shape == "groups":
            g = [(tuple((fr.field, fr.row_id) for fr in gc.group),
                  gc.count) for gc in got]
            assert g == want, (R, q, g, want)
        elif shape == "row":
            assert sorted(int(x) for x in got.columns()) == want, \
                (R, q, len(got.columns()), len(want))
        checked += 1
    barrier(f"q{R}")

    # ---- every 5th round: plane cross-check (peers idle, serving).
    # The HTTP plane answers in JSON, so compare the integer-shaped
    # results (counts) — aggregate/pair shapes are already oracle-
    # checked above on every round
    if R % 5 == 0 and pid == 0:
        for q, coll in answers:
            # counts and bare Rows cross-check against the HTTP plane
            # (aggregate/pair shapes are oracle-checked every round);
            # normalization is SHARED with measure_spmd (fleet_lib) so
            # the two harnesses cannot drift
            if not (isinstance(coll, int) or hasattr(coll, "columns")):
                continue
            http = c.post_json(srv.uri + "/index/i/query",
                               {"query": q})["results"][0]
            assert _fl.norm_http_result(http) == _fl.norm_result(coll), \
                (R, q, http)
            xchecks += 1
    barrier(f"x{R}")
    rounds += 1
    R += 1

barrier("done")
c.close(); srv.close()
print("RESULT " + json.dumps({
    "rounds": rounds, "writes_applied": writes if pid == 0 else None,
    "collective_queries_checked": checked if pid == 0 else None,
    "plane_xchecks": xchecks if pid == 0 else None,
    "counters": spmd.counters()}))
'''


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seconds", type=float, default=600.0)
    ap.add_argument("--procs", type=int, default=2)
    ap.add_argument("--seed", type=int, default=918273)
    args = ap.parse_args()

    tmp = tempfile.mkdtemp(prefix="soak_spmd_")
    coord_port, *node_ports = fleet_lib.free_ports(1 + args.procs)

    worker = os.path.join(tmp, "worker.py")
    with open(worker, "w") as f:
        f.write(WORKER)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update(
        PALLAS_AXON_POOL_IPS="",
        JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{coord_port}",
        JAX_NUM_PROCESSES=str(args.procs),
        T_DATA=tmp,
        SOAK_SECONDS=str(args.seconds),
        SOAK_SEED=str(args.seed),
        PYTHONPATH=repo + os.pathsep + env.get("PYTHONPATH", ""),
        **{f"T_PORT{i}": str(p) for i, p in enumerate(node_ports)},
    )
    t0 = time.time()
    # a hung worker is exactly what this soak hunts — run_fleet kills
    # the whole fleet on timeout so reruns never fight orphaned
    # servers/ports
    ok, outs, timed_out = fleet_lib.run_fleet(
        [[sys.executable, worker] for _ in range(args.procs)],
        [dict(env, JAX_PROCESS_ID=str(pid))
         for pid in range(args.procs)],
        timeout=args.seconds + 900, label="soak_spmd")
    if timed_out:
        # a genuine hang — exactly what this soak hunts; crashes
        # (rc!=0 without a hang) fall through to the normal summary so
        # triage chases the right thing
        print(json.dumps({"ok": False, "reason": "worker hang/timeout",
                          "procs": args.procs, "seed": args.seed}))
        return 1
    results = [ln for out in outs for ln in out.splitlines()
               if ln.startswith("RESULT ")]
    summary = {"ok": ok, "procs": args.procs,
               "wall_s": round(time.time() - t0, 1),
               "seed": args.seed}
    if ok and results:
        parsed = [json.loads(r[7:]) for r in results]
        coord = next((p for p in parsed
                      if p["writes_applied"] is not None), None)
        if coord:
            summary.update({k: coord[k] for k in
                            ("rounds", "writes_applied",
                             "collective_queries_checked",
                             "plane_xchecks")})
            # counters summed ACROSS workers: "joined" only ever
            # increments on peers (the coordinator initiates), so the
            # coordinator's counters alone would always read joined=0
            # and make the evidence look like nothing ever joined
            summary["counters"] = {
                k: sum(p["counters"].get(k, 0) for p in parsed)
                for k in coord["counters"]}
    # run_fleet already wrote every worker's tail to stderr on failure
    print(json.dumps(summary))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
