"""Open-loop load generator for the admission-controlled serving path.

Open-loop means arrivals are scheduled by a fixed clock (target QPS),
NOT by completions — the generator keeps firing even while earlier
requests queue or shed, which is what real overload looks like (a
closed-loop generator self-throttles and can never push a server past
saturation, hiding exactly the regime admission control exists for).

Each request draws an admission class from the configured mix and a
deadline from the configured distribution (sent as the
``X-Pilosa-Deadline`` header).  The report carries goodput (completed
OK per second), shed/expired rates, and latency percentiles of the
*admitted* requests — the numbers the [admission] acceptance criteria
pin (p99 of admitted stays bounded under 2x-capacity overload while
overflow sheds with 429/503 + Retry-After).

CLI::

    python -m tools.loadgen --host http://127.0.0.1:10101 -i myindex \
        --qps 200 --seconds 5 --query 'Count(Row(f=1))' \
        --mix query=0.9,ingest=0.1 --ingest-bits 1000 --ingest-field f \
        --deadline-ms 50,500

Mixed read/write mode: ingest-class requests POST real import payloads
(``--ingest-bits`` random positions over ``--ingest-rows`` rows and
``--ingest-cols`` columns into ``--ingest-field``), and the report adds
read-only p50/p99, ingested bits/s, and the server's result-cache hit
rate over the run window — the streaming-ingest acceptance numbers.

Sparsity-mix mode (``--sparsity-mix dense=1,pct10=2,pct01=3``):
query-class requests rotate across rows whose fill ratios the operator
controlled at load time, and the report adds per-bucket read p50/p99 —
how compressed-container sparse-path wins (ops/containers.py) are
measured under serving traffic rather than in microbench.

Importable: ``run_load(...)`` returns the report dict (used by
tests/test_admission.py to drive a server at 2x capacity,
tests/test_ingest.py for the mixed-workload acceptance run, and
tests/test_containers.py for the sparsity-mix serving check).
"""

from __future__ import annotations

import argparse
import json
import random
import threading
import time
import urllib.error
import urllib.request

#: class -> request builder is fixed: queries POST PQL, ingest POSTs a
#: real import payload (``ingest_bits`` random positions — sized so a
#: modest request rate sustains >=100k bits/s, the streaming-ingest
#: acceptance floor).  ``internal`` posts a cluster control message (a
#: cheap attr-blocks probe) — enough to occupy an internal slot.
DEFAULT_MIX = {"query": 1.0}


class _Stats:
    """Thread-safe accumulation of per-request outcomes."""

    def __init__(self):
        self.lock = threading.Lock()
        self.ok_latencies: list[float] = []
        #: READ (query-class) completions only — the latencies the
        #: mixed-workload pins are about (read p50/p99 under ingest,
        #: not the blended number that an import's larger body and
        #: server-side bulk apply would skew)
        self.read_latencies: list[float] = []
        self.sent = 0
        self.ok = 0
        self.shed = 0
        self.expired = 0
        self.errors = 0
        self.retry_after_seen = 0
        self.ingest_ok = 0
        self.ingest_bits = 0
        #: sparsity-mix view: bucket name -> completed-read latencies
        self.bucket_latencies: dict[str, list[float]] = {}
        #: per-bucket outcome counts (the --chaos fault/clear split)
        self.bucket_outcomes: dict[str, dict[str, int]] = {}
        #: --tenant-mix view: tenant -> completed-read latencies and
        #: per-tenant outcome counts (goodput/shed per tenant is the
        #: isolation evidence the [tenants] acceptance run pins)
        self.tenant_latencies: dict[str, list[float]] = {}
        self.tenant_outcomes: dict[str, dict[str, int]] = {}

    def note(self, outcome: str, latency_s: float,
             retry_after: bool, klass: str = "query",
             bits: int = 0, bucket: str | None = None,
             tenant: str | None = None) -> None:
        with self.lock:
            self.sent += 1
            if retry_after:
                self.retry_after_seen += 1
            if bucket is not None:
                oc = self.bucket_outcomes.setdefault(
                    bucket, {"ok": 0, "shed": 0, "expired": 0,
                             "error": 0})
                oc["ok" if outcome == "ok"
                   else outcome if outcome in ("shed", "expired")
                   else "error"] += 1
            if tenant is not None:
                toc = self.tenant_outcomes.setdefault(
                    tenant, {"ok": 0, "shed": 0, "expired": 0,
                             "error": 0})
                toc["ok" if outcome == "ok"
                    else outcome if outcome in ("shed", "expired")
                    else "error"] += 1
                if outcome == "ok" and klass == "query":
                    self.tenant_latencies.setdefault(
                        tenant, []).append(latency_s)
            if outcome == "ok":
                self.ok += 1
                self.ok_latencies.append(latency_s)
                if klass == "query":
                    self.read_latencies.append(latency_s)
                    if bucket is not None:
                        self.bucket_latencies.setdefault(
                            bucket, []).append(latency_s)
                elif klass == "ingest":
                    self.ingest_ok += 1
                    self.ingest_bits += bits
            elif outcome == "shed":
                self.shed += 1
            elif outcome == "expired":
                self.expired += 1
            else:
                self.errors += 1


def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[i]


def _build_request(host: str, index: str, klass: str, query: str,
                   deadline_s: float | None,
                   ingest_field: str = "loadgen",
                   ingest_bits: int = 1, ingest_rows: int = 8,
                   ingest_cols: int = 1 << 20,
                   tenant: str | None = None):
    bits = 0
    if klass == "ingest":
        url = f"{host}/index/{index}/field/{ingest_field}/import"
        # a REAL import payload: ingest_bits random positions across a
        # small row set — the shape a bulk loader ships, and (with
        # [ingest] deltas on) exactly what lands in the delta plane
        rows = [random.randrange(ingest_rows)
                for _ in range(ingest_bits)]
        cols = [random.randrange(ingest_cols)
                for _ in range(ingest_bits)]
        body = json.dumps({"rowIDs": rows, "columnIDs": cols}).encode()
        bits = ingest_bits
    elif klass == "internal":
        url = f"{host}/internal/cluster/message"
        body = json.dumps({"type": "attr-blocks", "index": index,
                           "field": None}).encode()
    else:
        url = f"{host}/index/{index}/query"
        body = json.dumps({"query": query}).encode()
    req = urllib.request.Request(url, data=body, method="POST")
    req.add_header("Content-Type", "application/json")
    if deadline_s is not None:
        req.add_header("X-Pilosa-Deadline", f"{deadline_s:.3f}")
    if tenant is not None:
        req.add_header("X-Pilosa-Tenant", tenant)
    return req, klass, bits


def _fire(req, timeout: float, stats: _Stats, klass: str = "query",
          bits: int = 0, bucket: str | None = None,
          tenant: str | None = None) -> None:
    t0 = time.perf_counter()
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            resp.read()
        stats.note("ok", time.perf_counter() - t0, False, klass, bits,
                   bucket, tenant=tenant)
    except urllib.error.HTTPError as e:
        body = b""
        try:
            body = e.read()
        except OSError:
            pass
        retry_after = e.headers.get("Retry-After") is not None
        if e.code in (429, 503):
            outcome = "expired" if b"expired" in body else "shed"
        else:
            outcome = "error"
        stats.note(outcome, time.perf_counter() - t0, retry_after, klass,
                   bucket=bucket, tenant=tenant)
    except Exception:
        stats.note("error", time.perf_counter() - t0, False, klass,
                   bucket=bucket, tenant=tenant)


def _cache_counters(host: str) -> tuple[int, int] | None:
    """(hits, misses) from the server's result cache, or None when the
    debug surface is unreachable — the report's hit rate is the DELTA
    over the run window, so concurrent warmup traffic outside the run
    doesn't pollute the number."""
    try:
        with urllib.request.urlopen(f"{host}/debug/resultcache",
                                    timeout=5) as resp:
            d = json.loads(resp.read())
        return int(d.get("hits", 0)), int(d.get("misses", 0))
    except Exception:
        return None


def _vars_counter(host: str, name: str) -> float | None:
    """One counter from the server's /debug/vars snapshot, or None —
    like the cache counters, consumers report the DELTA over the run
    window."""
    try:
        with urllib.request.urlopen(f"{host}/debug/vars",
                                    timeout=5) as resp:
            d = json.loads(resp.read())
        # absent means "this server never ticked the counter" (e.g.
        # coalescer off) — report None like the other unavailable
        # metrics, NOT 0.0, which would read as perfect batching
        v = d.get(name)
        return float(v) if isinstance(v, (int, float)) else None
    except Exception:
        return None


def _journal_counters(host: str) -> dict | None:
    """The event journal's per-kind counters off /debug/events, or
    None — the report's ``events`` section is the DELTA over the run
    window, so a long-lived server's history doesn't pollute it."""
    try:
        with urllib.request.urlopen(f"{host}/debug/events?limit=0",
                                    timeout=5) as resp:
            d = json.loads(resp.read())
        return d.get("counters")
    except Exception:
        return None


def _slowest_trace(host: str) -> dict | None:
    """The assembled span tree for the slowest recent query: the
    report's worked autopsy example — /debug/queries picks the
    slowest completed record, /debug/trace/{id} fans its records in
    and assembles the causal tree (admission wait -> coalescer window
    -> stage/launch -> per-node remote -> reduce)."""
    try:
        with urllib.request.urlopen(f"{host}/debug/queries",
                                    timeout=5) as resp:
            d = json.loads(resp.read())
        recent = [r for r in (d.get("recent") or [])
                  if r.get("traceID") and not r.get("active")]
        if not recent:
            return None
        slowest = max(recent, key=lambda r: r.get("elapsedMs", 0.0))
        tid = slowest["traceID"]
        with urllib.request.urlopen(f"{host}/debug/trace/{tid}",
                                    timeout=10) as resp:
            tree = json.loads(resp.read())
        return {
            "traceId": tree.get("traceId"),
            "pql": slowest.get("pql"),
            "elapsedMs": slowest.get("elapsedMs"),
            "accounting": tree.get("accounting"),
            "root": tree.get("root"),
            "errors": tree.get("errors") or None,
        }
    except Exception:
        return None


def shape_mix_queries(n: int, field: str = "f", rows: int = 6,
                      seed: int = 7) -> list[str]:
    """``n`` structurally DISTINCT fused-eligible Count trees over
    ``field`` — the mixed-dashboard-traffic analog the ragged
    megabatch engine exists for.  Structures enumerate in increasing
    size (single row, binary ops, 3-wide folds, nested pairs, nested
    triples) so a realistic mix spans several tree depths; leaf row
    ids draw from ``rows`` deterministically per ``seed`` so repeat
    runs issue identical traffic."""
    rng = random.Random(seed)
    ops = ["Intersect", "Union", "Difference", "Xor"]

    def leaf() -> str:
        return f"Row({field}={rng.randrange(rows)})"

    structures: list = [("leaf",)]
    structures += [("op", o) for o in ops]            # op(l, l)
    structures += [("flat3", o) for o in ops]         # op(l, l, l)
    structures += [("nest", o, i) for o in ops for i in ops]
    structures += [("nest3", o, i) for o in ops for i in ops]
    out = []
    for kind in structures[:n]:
        if kind[0] == "leaf":
            tree = leaf()
        elif kind[0] == "op":
            tree = f"{kind[1]}({leaf()}, {leaf()})"
        elif kind[0] == "flat3":
            tree = f"{kind[1]}({leaf()}, {leaf()}, {leaf()})"
        elif kind[0] == "nest":
            tree = f"{kind[1]}({kind[2]}({leaf()}, {leaf()}), {leaf()})"
        else:
            tree = (f"{kind[1]}({kind[2]}({leaf()}, {leaf()}), "
                    f"{leaf()}, {leaf()})")
        out.append(f"Count({tree})")
    if len(out) < n:
        raise ValueError(
            f"shape-mix supports at most {len(structures)} distinct "
            f"shapes, asked for {n}")
    return out


def parse_tenant_mix(spec: str) -> list[tuple[str, float, str]]:
    """``tenant:weight[:class]`` comma list -> [(tenant, weight,
    class)] — e.g. ``gold:8:query,free:2:query,abuser:10:ingest``.
    Weights are the arrival-rate proportions (largest-remainder
    interleaved like the class mix); class defaults to ``query``."""
    out: list[tuple[str, float, str]] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        bits = part.split(":")
        if len(bits) < 2 or not bits[0]:
            raise ValueError(
                f"bad tenant-mix entry {part!r} "
                "(tenant:weight[:class])")
        klass = bits[2] if len(bits) > 2 else "query"
        if klass not in ("query", "ingest", "internal"):
            raise ValueError(f"bad tenant-mix class {klass!r}")
        out.append((bits[0], float(bits[1]), klass))
    if not out:
        raise ValueError("empty tenant mix")
    return out


def parse_sparsity_mix(spec: str) -> dict[str, int]:
    """``"dense=1,pct10=2,pct01=3"`` -> {bucket: row id}.  Bucket
    names are free-form labels for the report; the rows must already
    hold data at the intended fill ratios (loadgen generates traffic,
    not data — tests/benches load the controlled-fill rows first)."""
    out: dict[str, int] = {}
    for part in spec.split(","):
        k, _, v = part.partition("=")
        if not k.strip() or not v:
            raise ValueError(f"bad --sparsity-mix entry: {part!r}")
        out[k.strip()] = int(v)
    if not out:
        raise ValueError("--sparsity-mix needs at least one bucket")
    return out


class _ChaosDriver:
    """Arms/disarms failpoints on a schedule mid-run (the ``--chaos``
    mode): a background thread POSTs the spec to every target host's
    ``/debug/failpoints`` for ``duty * period`` seconds of each
    ``period``, then disarms for the remainder.  Requests are labeled
    ``fault``/``clear`` by their FIRE time, so the report separates
    goodput/error-rate/p99 during fault windows from the windows
    between them — the number that shows degradation is graceful, not
    just survivable."""

    def __init__(self, hosts: list[str], spec: str,
                 period_s: float = 2.0, duty: float = 0.5):
        self.hosts = hosts
        self.spec = spec
        self.period_s = max(0.2, period_s)
        self.duty = min(max(duty, 0.05), 0.95)
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._fault_now = False
        self.windows = 0
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _post(self, body: dict) -> None:
        for host in self.hosts:
            try:
                req = urllib.request.Request(
                    f"{host}/debug/failpoints",
                    data=json.dumps(body).encode(), method="POST")
                req.add_header("Content-Type", "application/json")
                with urllib.request.urlopen(req, timeout=5) as resp:
                    resp.read()
            except Exception:
                pass  # a dead host IS the chaos; keep driving

    def _run(self) -> None:
        while not self._stop.is_set():
            self._post({"arm": self.spec})
            with self._lock:
                self._fault_now = True
                self.windows += 1
            if self._stop.wait(self.period_s * self.duty):
                break
            self._post({"disarm": True})
            with self._lock:
                self._fault_now = False
            if self._stop.wait(self.period_s * (1.0 - self.duty)):
                break
        self._post({"disarm": True})
        with self._lock:
            self._fault_now = False

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10)

    def label(self) -> str:
        with self._lock:
            return "fault" if self._fault_now else "clear"


def parse_scale_schedule(spec: str) -> list[tuple[float, dict, str]]:
    """``t:action[;t:action...]`` -> [(offset_s, resize_body, label)].
    Actions: ``add=<id>=<uri>`` (join a running node) and
    ``remove=<id>`` — e.g.
    ``"2:add=n4=http://127.0.0.1:10104;8:remove=n4"``.  Entries are
    ``;``-separated because URIs carry ``,``-adjacent characters;
    offsets are seconds from run start and must be ascending."""
    out: list[tuple[float, dict, str]] = []
    last = -1.0
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        t_txt, _, action = part.partition(":")
        try:
            t = float(t_txt)
        except ValueError:
            raise ValueError(f"bad --scale-schedule offset in {part!r}")
        if t < last:
            raise ValueError("--scale-schedule offsets must ascend")
        last = t
        if action.startswith("add="):
            bits = action[len("add="):].split("=", 1)
            if len(bits) != 2 or not bits[0] or not bits[1]:
                raise ValueError(
                    f"bad add action {action!r} (add=<id>=<uri>)")
            out.append((t, {"add": {"id": bits[0], "uri": bits[1]}},
                        f"add:{bits[0]}"))
        elif action.startswith("remove="):
            nid = action[len("remove="):]
            if not nid:
                raise ValueError(
                    f"bad remove action {action!r} (remove=<id>)")
            out.append((t, {"removeId": nid}, f"remove:{nid}"))
        else:
            raise ValueError(f"unknown scale action {action!r}")
    if not out:
        raise ValueError("empty --scale-schedule")
    return out


#: rebalance.* gauges the scale-schedule report deltas over the run —
#: the migration-cost evidence next to the per-phase latency numbers.
_REBALANCE_VARS = (
    "rebalance.plans", "rebalance.cutovers", "rebalance.bytes_streamed",
    "rebalance.dual_writes", "rebalance.aborts", "rebalance.resumes",
    "rebalance.backoffs", "rebalance.transfer_failures",
)


class _ScaleDriver:
    """Timed node add/remove against the online-resize control route
    (``--scale-schedule``): a background thread POSTs each scheduled
    action to the coordinator's ``/cluster/resize``, then polls
    ``/debug/rebalance`` until the migration settles before relabeling
    traffic ``steady``.  Requests are labeled by FIRE time with the
    active phase (``steady`` / ``add:<id>`` / ``remove:<id>``) so the
    report separates goodput/p50/p99 during each migration window from
    steady state — the read-p99-under-rebalance acceptance number."""

    def __init__(self, host: str, schedule: list, poll_s: float = 0.2,
                 settle_timeout: float = 120.0):
        self.host = host
        self.schedule = schedule
        self.poll_s = poll_s
        self.settle_timeout = settle_timeout
        self.actions: list[dict] = []
        self.durations: dict[str, float] = {}
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._label = "steady"
        self._label_t0 = time.perf_counter()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _set_label(self, label: str) -> None:
        now = time.perf_counter()
        with self._lock:
            self.durations[self._label] = (
                self.durations.get(self._label, 0.0)
                + (now - self._label_t0))
            self._label = label
            self._label_t0 = now

    def _resize(self, body: dict) -> dict:
        req = urllib.request.Request(
            f"{self.host}/cluster/resize",
            data=json.dumps(body).encode(), method="POST")
        req.add_header("Content-Type", "application/json")
        with urllib.request.urlopen(req, timeout=30) as resp:
            return json.loads(resp.read())

    def _rebalance_active(self) -> bool:
        try:
            with urllib.request.urlopen(
                    f"{self.host}/debug/rebalance", timeout=5) as resp:
                return bool(json.loads(resp.read()).get("active"))
        except Exception:
            return False  # unreachable debug surface: don't spin

    def _wait_settled(self) -> bool:
        deadline = time.perf_counter() + self.settle_timeout
        while time.perf_counter() < deadline:
            if not self._rebalance_active():
                return True
            if self._stop.wait(self.poll_s):
                return False
        return False

    def _run(self) -> None:
        start = time.perf_counter()
        for offset, body, label in self.schedule:
            delay = start + offset - time.perf_counter()
            if delay > 0 and self._stop.wait(delay):
                break
            if self._stop.is_set():
                break
            self._set_label(label)
            entry = {"offset": offset, "label": label}
            try:
                entry["response"] = self._resize(body)
                entry["settled"] = self._wait_settled()
            except Exception as e:
                entry["error"] = repr(e)
            self.actions.append(entry)
            self._set_label("steady")

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=self.settle_timeout + 30)
        self._set_label(self.label())  # flush the final window

    def label(self) -> str:
        with self._lock:
            return self._label


def run_load(host: str, index: str, qps: float, seconds: float,
             query: str = "Count(Row(f=1))",
             mix: dict[str, float] | None = None,
             deadline_s: tuple[float, float] | None = None,
             timeout: float = 10.0, pool: int = 32,
             ingest_field: str = "loadgen", ingest_bits: int = 1,
             ingest_rows: int = 8, ingest_cols: int = 1 << 20,
             shape_mix: int = 0, shape_field: str | None = None,
             shape_rows: int = 6,
             sparsity_mix: dict[str, int] | None = None,
             sparsity_field: str = "f",
             chaos: "_ChaosDriver | None" = None,
             tenant_mix: list | None = None,
             scale: "_ScaleDriver | None" = None) -> dict:
    """Drive ``host`` open-loop at ``qps`` for ``seconds``; returns the
    report dict.  ``mix`` maps class -> weight; ``deadline_s`` is a
    (lo, hi) uniform range for the per-request deadline header (None =
    no deadline sent).  ``shape_mix=N`` rotates query-class requests
    through N structurally distinct Count shapes (``shape_mix_queries``
    over ``shape_field``, default field ``f``) and the
    report adds ``dispatches_per_query`` — the server-side coalescer
    launch count per completed read, the number the ragged megabatch
    engine drives toward the batch dispatch floor.

    A fixed pool of ``pool`` workers fires the scheduled arrivals —
    NOT a thread per request: hundreds of short-lived Python threads
    distort the latency measurement itself (threads get descheduled
    between their start and their send, inflating p99 with client-side
    GIL waits that have nothing to do with the server).  The pool stays
    open-loop as long as in-flight requests < pool — true under
    admission control, where overflow is refused in milliseconds; when
    the pool ever falls behind an arrival by >50ms the report's
    ``late`` counter says so instead of silently closing the loop.

    ``tenant_mix`` ([(tenant, weight, class)], from
    :func:`parse_tenant_mix`) replaces the class mix: each arrival is
    drawn from the tenant schedule, stamped with its
    ``X-Pilosa-Tenant`` header, and the report adds a per-tenant
    goodput/p50/p99/shed section — the isolation evidence the
    [tenants] acceptance run pins."""
    import queue as _queue

    if tenant_mix is not None:
        # the tenant schedule IS the class schedule: weight per
        # (tenant, class) pair, same largest-remainder interleave
        mix = {(t, k): w for t, w, k in tenant_mix}
    else:
        mix = mix or DEFAULT_MIX
    classes = list(mix)
    stats = _Stats()
    qlist = None
    if shape_mix:
        qlist = shape_mix_queries(shape_mix,
                                  field=shape_field or "f",
                                  rows=shape_rows)
    # sparsity-mix mode: rotate query-class requests across rows with
    # operator-controlled fill ratios (dense / 10% / 0.1% — whatever
    # the loaded buckets hold) and report per-bucket p50/p99, so
    # sparse-path wins (the compressed container engine,
    # ops/containers.py) are measurable under serving traffic, not
    # just in microbench
    buckets = None
    if sparsity_mix:
        buckets = [(name, f"Count(Row({sparsity_field}={row}))")
                   for name, row in sparsity_mix.items()]
    n = int(qps * seconds)
    # EXACT-proportion, evenly interleaved class schedule (largest-
    # remainder pacing).  A binomial draw would make the delivered
    # ingest bits/s wobble +/-30% run to run at small n, and a random
    # shuffle can cluster several heavy imports back to back — the
    # schedule itself manufacturing tail latency the server didn't
    # cause.  Deterministic interleave keeps the mix exact and the
    # inter-class spacing as even as the proportions allow.
    total_w = sum(mix.values()) or 1.0
    err = dict.fromkeys(classes, 0.0)
    sched = []
    for _ in range(n):
        for c in classes:
            err[c] += mix[c] / total_w
        pick = max(classes, key=lambda c: err[c])
        err[pick] -= 1.0
        sched.append(pick)
    jobs: _queue.Queue = _queue.Queue()
    late = [0]
    late_lock = threading.Lock()

    def worker():
        while True:
            item = jobs.get()
            if item is None:
                return
            due, req, klass, bits, bucket, tenant = item
            delay = due - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            elif delay < -0.05:
                with late_lock:
                    late[0] += 1
            if scale is not None and bucket is None:
                # label by FIRE time: which rebalance phase is running
                bucket = scale.label()
            elif chaos is not None and bucket is None:
                # label by FIRE time: is a fault window armed right now
                bucket = chaos.label()
            _fire(req, timeout, stats, klass, bits, bucket,
                  tenant=tenant)

    cache0 = _cache_counters(host)
    ev0 = _journal_counters(host)
    disp0 = _vars_counter(host, "coalescer.dispatches")
    hedge0 = _vars_counter(host, "hedge.issued")
    hrpcs0 = _vars_counter(host, "hedge.rpcs")
    # self-healing replication counters (hinted handoff + AE repair):
    # the chaos report carries their deltas so a degraded-write run
    # shows how many writes were hinted and whether they drained
    hint0 = {n_: _vars_counter(host, n_)
             for n_ in ("hint.queued", "hint.replayed", "hint.dropped",
                        "ae.reconciled")}
    reb0 = ({n_: _vars_counter(host, n_) for n_ in _REBALANCE_VARS}
            if scale is not None else None)
    if chaos is not None:
        chaos.start()
    if scale is not None:
        scale.start()
    workers = [threading.Thread(target=worker, daemon=True)
               for _ in range(pool)]
    for w in workers:
        w.start()
    start = time.perf_counter()
    for i in range(n):
        due = start + i / qps
        pick_i = sched[i]
        tenant = None
        if tenant_mix is not None:
            tenant, klass = pick_i
        else:
            klass = pick_i
        dl = (random.uniform(*deadline_s)
              if deadline_s is not None else None)
        bucket = None
        if buckets is not None and klass == "query":
            bucket, q = buckets[i % len(buckets)]
        else:
            q = qlist[i % len(qlist)] if qlist else query
        req, kl, bits = _build_request(host, index, klass, q, dl,
                                       ingest_field, ingest_bits,
                                       ingest_rows, ingest_cols,
                                       tenant=tenant)
        jobs.put((due, req, kl, bits, bucket, tenant))
    for _ in workers:
        jobs.put(None)
    for w in workers:
        w.join(seconds + n * timeout)
    elapsed = time.perf_counter() - start
    if chaos is not None:
        chaos.stop()
    if scale is not None:
        scale.stop()
    reb1 = ({n_: _vars_counter(host, n_) for n_ in _REBALANCE_VARS}
            if scale is not None else None)
    cache1 = _cache_counters(host)
    ev1 = _journal_counters(host)
    disp1 = _vars_counter(host, "coalescer.dispatches")
    hedge1 = _vars_counter(host, "hedge.issued")
    hrpcs1 = _vars_counter(host, "hedge.rpcs")
    hint1 = {n_: _vars_counter(host, n_) for n_ in hint0}
    hint_depth = _vars_counter(host, "hint.depth")
    hit_rate = None
    if cache0 is not None and cache1 is not None:
        dh = cache1[0] - cache0[0]
        dm = cache1[1] - cache0[1]
        if dh + dm > 0:
            hit_rate = round(dh / (dh + dm), 4)
    lat = sorted(stats.ok_latencies)
    rlat = sorted(stats.read_latencies)
    return {
        "target_qps": qps,
        "seconds": round(elapsed, 3),
        "sent": stats.sent,
        "ok": stats.ok,
        "shed": stats.shed,
        "expired": stats.expired,
        "errors": stats.errors,
        "late": late[0],
        "goodput_qps": round(stats.ok / elapsed, 2) if elapsed else 0.0,
        "shed_rate": round((stats.shed + stats.expired)
                           / max(1, stats.sent), 4),
        "retry_after_seen": stats.retry_after_seen,
        "p50_ms": round(_percentile(lat, 0.50) * 1e3, 2),
        "p99_ms": round(_percentile(lat, 0.99) * 1e3, 2),
        # mixed read/write view: READ latencies alone (query-class
        # completions), the ingest goodput in bits, and the server's
        # result-cache hit rate over the run window — the numbers the
        # streaming-ingest acceptance pins (read p99 within 2x of the
        # read-only baseline at >=100k bits/s with hit rate >50%)
        "read_ok": len(rlat),
        "read_p50_ms": round(_percentile(rlat, 0.50) * 1e3, 2),
        "read_p99_ms": round(_percentile(rlat, 0.99) * 1e3, 2),
        "ingest_ok": stats.ingest_ok,
        "ingest_bits": stats.ingest_bits,
        "ingest_bits_per_s": round(stats.ingest_bits / elapsed, 1)
        if elapsed else 0.0,
        "cache_hit_rate": hit_rate,
        # shape-mix view: distinct shapes in rotation and the server's
        # coalescer launches per completed read over the run window —
        # near 1.0 means per-query dispatch (the pre-ragged behavior
        # for mixed traffic); the ragged engine drives it toward
        # 1/batch (the homogeneous dispatch floor)
        "shape_mix": shape_mix or None,
        "dispatches_per_query": (
            # a missing baseline on a fresh server means zero prior
            # dispatches; a missing END sample means the coalescer
            # never dispatched at all -> None, not fake-perfect 0.0
            round((disp1 - (disp0 or 0.0)) / len(rlat), 4)
            if disp1 is not None and rlat else None),
        # chaos view (--chaos): goodput / error rate / p99 during
        # fault windows vs between them, the fault-window count, and
        # the server's hedge rate over the run — graceful degradation
        # as numbers, not vibes
        "chaos": (None if chaos is None else {
            "spec": chaos.spec,
            "windows": chaos.windows,
            # hinted-handoff / anti-entropy view over the run window:
            # writes that missed a replica and were queued, hints that
            # drained back, and the residual queue depth at run end
            # (nonzero = the replay worker is still catching up)
            "hints": {
                n_.replace(".", "_"): (
                    None if hint1[n_] is None
                    else hint1[n_] - (hint0[n_] or 0.0))
                for n_ in hint0
            },
            "hint_depth_end": hint_depth,
            "hedge_issued": (None if hedge1 is None
                             else hedge1 - (hedge0 or 0.0)),
            "hedge_rate": (
                round((hedge1 - (hedge0 or 0.0))
                      / max(1.0, hrpcs1 - (hrpcs0 or 0.0)), 4)
                if hedge1 is not None and hrpcs1 is not None
                else None),
            **{
                label: {
                    **stats.bucket_outcomes.get(
                        label, {"ok": 0, "shed": 0, "expired": 0,
                                "error": 0}),
                    "p50_ms": round(_percentile(sorted(
                        stats.bucket_latencies.get(label, [])),
                        0.50) * 1e3, 2),
                    "p99_ms": round(_percentile(sorted(
                        stats.bucket_latencies.get(label, [])),
                        0.99) * 1e3, 2),
                }
                for label in ("fault", "clear")
            },
        }),
        # --tenant-mix view: per-tenant goodput / latency / shed —
        # with [tenants] isolation on, an abusive tenant's flood shows
        # up in ITS shed column while the victims' p99 holds
        "tenants": (None if tenant_mix is None else {
            t: {
                **stats.tenant_outcomes.get(
                    t, {"ok": 0, "shed": 0, "expired": 0, "error": 0}),
                "goodput_qps": round(
                    stats.tenant_outcomes.get(t, {}).get("ok", 0)
                    / elapsed, 2) if elapsed else 0.0,
                "p50_ms": round(_percentile(sorted(
                    stats.tenant_latencies.get(t, [])), 0.50) * 1e3, 2),
                "p99_ms": round(_percentile(sorted(
                    stats.tenant_latencies.get(t, [])), 0.99) * 1e3, 2),
            }
            for t in sorted({t_ for t_, _, _ in tenant_mix})
        }),
        # --scale-schedule view: each control action's outcome, the
        # server's rebalance.* counter deltas over the run, and
        # per-phase goodput/p50/p99 — migration windows (add:<id> /
        # remove:<id>) vs steady state, the read-p99-under-rebalance
        # acceptance evidence
        "scale": (None if scale is None else {
            "actions": scale.actions,
            "rebalance": {
                n_.replace(".", "_"): (
                    None if reb1[n_] is None
                    else reb1[n_] - (reb0[n_] or 0.0))
                for n_ in _REBALANCE_VARS
            },
            "phases": {
                label: {
                    **stats.bucket_outcomes.get(
                        label, {"ok": 0, "shed": 0, "expired": 0,
                                "error": 0}),
                    "seconds": round(
                        scale.durations.get(label, 0.0), 3),
                    "goodput_qps": round(
                        stats.bucket_outcomes.get(label, {}).get(
                            "ok", 0)
                        / max(0.001, scale.durations.get(label, 0.0)),
                        2),
                    "p50_ms": round(_percentile(sorted(
                        stats.bucket_latencies.get(label, [])),
                        0.50) * 1e3, 2),
                    "p99_ms": round(_percentile(sorted(
                        stats.bucket_latencies.get(label, [])),
                        0.99) * 1e3, 2),
                }
                for label in sorted(
                    set(scale.durations)
                    | set(stats.bucket_outcomes) | {"steady"})
            },
            # every migration window POOLED: per-window percentiles
            # over a sub-second window are one-outlier-dominated, the
            # pooled view is the statistically usable latency evidence
            "migration": {
                "ok": sum(
                    oc.get("ok", 0)
                    for label, oc in stats.bucket_outcomes.items()
                    if label != "steady"),
                "seconds": round(sum(
                    s for label, s in scale.durations.items()
                    if label != "steady"), 3),
                "p50_ms": round(_percentile(sorted(
                    lat for label, ls in stats.bucket_latencies.items()
                    if label != "steady" for lat in ls),
                    0.50) * 1e3, 2),
                "p99_ms": round(_percentile(sorted(
                    lat for label, ls in stats.bucket_latencies.items()
                    if label != "steady" for lat in ls),
                    0.99) * 1e3, 2),
            },
        }),
        # event-journal view: per-kind journal deltas over the run
        # window (hedges fired, breakers opened, rebalance shard
        # transitions ...) — the cluster's state-transition story next
        # to the latency numbers it explains
        "events": (None if ev1 is None else {
            "total": int(ev1.get("total", 0)
                         - (ev0 or {}).get("total", 0)),
            "dropped": int(ev1.get("dropped", 0)
                           - (ev0 or {}).get("dropped", 0)),
            "by_kind": {
                k: int(v - (ev0 or {}).get("kinds", {}).get(k, 0))
                for k, v in sorted(ev1.get("kinds", {}).items())
                if v - (ev0 or {}).get("kinds", {}).get(k, 0)
            },
        }),
        # the slowest recent query's assembled causal span tree —
        # the worked /debug/trace/{id} autopsy for this run
        "slowest_trace": _slowest_trace(host),
        # sparsity-mix view: per-bucket read latency percentiles
        "sparsity": (None if buckets is None else {
            name: {
                "ok": len(lats),
                "p50_ms": round(_percentile(sorted(lats), 0.50) * 1e3,
                                2),
                "p99_ms": round(_percentile(sorted(lats), 0.99) * 1e3,
                                2),
            }
            for name, lats in sorted(
                stats.bucket_latencies.items())
        }),
    }


def zipf_rows(n_rows: int, count: int, alpha: float = 1.1,
              seed: int = 11) -> list[int]:
    """``count`` row ids drawn zipfian (exponent ``alpha``) over
    ``[0, n_rows)`` — the skewed access pattern the tiered-residency
    prefetcher exists for: a hot head that should stay HBM-resident
    and a long tail that lives in the host tier.  Deterministic per
    seed so repeat runs issue identical traffic."""
    rng = random.Random(seed)
    weights = [1.0 / (r + 1) ** alpha for r in range(n_rows)]
    return rng.choices(range(n_rows), weights=weights, k=count)


def _residency_budget(host: str) -> int | None:
    """The server's HBM residency budget (bytes) off /debug/devices."""
    try:
        with urllib.request.urlopen(f"{host}/debug/devices",
                                    timeout=10) as resp:
            d = json.loads(resp.read())
        return int(d["residency"]["budget"])
    except Exception:
        return None


def _residency_usage(host: str) -> int | None:
    try:
        with urllib.request.urlopen(f"{host}/debug/devices",
                                    timeout=10) as resp:
            d = json.loads(resp.read())
        return int(d["residency"]["total"])
    except Exception:
        return None


#: /debug/vars counters the working-set report deltas over the run.
_TIER_VARS = ("residency.tier.hits", "residency.tier.misses",
              "residency.tier.demotions", "residency.tier.promotions",
              "residency.tier.fallbacks", "residency.evictions",
              "prefetch.issued", "prefetch.completed",
              "prefetch.useful")


def run_working_set(host: str, index: str, factor: float,
                    qps: float = 50.0, seconds: float = 5.0,
                    field: str = "ws", shards: int = 4,
                    alpha: float = 1.1, pool: int = 16,
                    timeout: float = 10.0,
                    deadline_s: float | None = None) -> dict:
    """The working-set-over-HBM scenario (``--working-set-factor N``):
    size a row population at N× the server's residency budget, drive a
    zipfian read mix over it, and report the tier hit/stall split with
    per-tier read latencies.

    Setup is self-contained: one probe row is imported and queried
    (``nocache=1&nocontainers=1`` — the dense fused path, whose
    per-row device stack is the tier's unit) to measure the per-row
    resident bytes off /debug/devices, then enough rows are imported
    (one bit per shard each — row COUNT, not fill, is what multiplies
    resident stacks) that ``rows x row_bytes >= factor x budget``.
    Every measured request carries ``profile=1`` and buckets by the
    flight record's tier outcome: ``warm`` (every stack access hit
    HBM), ``promoted``, ``fallback``, ``cold``.  The report adds the
    server's ``residency_tier_*``/``prefetch_*`` counter deltas over
    the run window."""
    budget = _residency_budget(host)
    if budget is None:
        raise RuntimeError(f"no /debug/devices at {host}")
    # the SERVER's shard width, not an assumed one: against a
    # PILOSA_TPU_SHARD_WIDTH_EXP build the hardcoded 2^20 would land
    # every "shard" of a row inside shard 0 and the probe would size
    # the working set against the wrong stack footprint
    try:
        with urllib.request.urlopen(f"{host}/info", timeout=10) as r:
            shard_width = int(json.loads(r.read())["shardWidth"])
    except Exception:
        shard_width = 1 << 20

    def _import_rows(lo: int, hi: int) -> None:
        # one bit per shard per row, batched — enough to materialize
        # the row in every shard so its dense stack spans all of them
        rows_l, cols_l = [], []
        for r in range(lo, hi):
            for s in range(shards):
                rows_l.append(r)
                cols_l.append(s * shard_width + (r % 1024))
        body = json.dumps({"rowIDs": rows_l,
                           "columnIDs": cols_l}).encode()
        req = urllib.request.Request(
            f"{host}/index/{index}/field/{field}/import", data=body,
            method="POST")
        req.add_header("Content-Type", "application/json")
        with urllib.request.urlopen(req, timeout=60) as resp:
            resp.read()

    def _count(row: int, profile: bool = False) -> dict:
        params = "nocache=1&nocontainers=1"
        if profile:
            params += "&profile=1"
        req = urllib.request.Request(
            f"{host}/index/{index}/query?{params}",
            data=json.dumps(
                {"query": f"Count(Row({field}={row}))"}).encode(),
            method="POST")
        req.add_header("Content-Type", "application/json")
        if deadline_s is not None:
            req.add_header("X-Pilosa-Deadline", f"{deadline_s:.3f}")
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read())

    # probe: one row's resident stack bytes (usage delta of its first
    # cold staging)
    _import_rows(0, 1)
    u0 = _residency_usage(host)
    _count(0)
    u1 = _residency_usage(host)
    if u0 is None or u1 is None or u1 - u0 < 1024:
        # probe measured nothing (debug surface unreachable mid-probe,
        # or the stack was refused as uncacheable): abort loudly — a
        # row_bytes floor of 1 would size n_rows at ~factor x budget
        # ROWS and hang the client building import payloads
        raise RuntimeError(
            f"working-set probe measured no resident stack bytes "
            f"(usage {u0} -> {u1}); cannot size the working set")
    row_bytes = u1 - u0
    n_rows = min(1 << 20, max(8, int(factor * budget / row_bytes) + 1))
    _import_rows(1, n_rows)

    rows = zipf_rows(n_rows, int(qps * seconds), alpha=alpha)
    vars0 = {n: _vars_counter(host, n) for n in _TIER_VARS}
    stats = _Stats()
    import queue as _queue

    jobs: _queue.Queue = _queue.Queue()

    def worker():
        while True:
            item = jobs.get()
            if item is None:
                return
            due, row = item
            delay = due - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            t0 = time.perf_counter()
            try:
                resp = _count(row, profile=True)
            except urllib.error.HTTPError as e:
                outcome = ("shed" if e.code in (429, 503) else "error")
                stats.note(outcome, time.perf_counter() - t0, False)
                continue
            except Exception:
                stats.note("error", time.perf_counter() - t0, False)
                continue
            tier = (resp.get("profile") or {}).get("tier") or {}
            if tier.get("fallback"):
                bucket = "fallback"
            elif tier.get("cold"):
                bucket = "cold"
            elif tier.get("promoted"):
                bucket = "promoted"
            else:
                bucket = "warm"
            stats.note("ok", time.perf_counter() - t0, False,
                       bucket=bucket)

    workers = [threading.Thread(target=worker, daemon=True)
               for _ in range(pool)]
    for w in workers:
        w.start()
    start = time.perf_counter()
    for i, row in enumerate(rows):
        jobs.put((start + i / qps, row))
    for _ in workers:
        jobs.put(None)
    for w in workers:
        w.join(seconds + len(rows) * timeout)
    elapsed = time.perf_counter() - start
    vars1 = {n: _vars_counter(host, n) for n in _TIER_VARS}
    ok_total = stats.ok
    stall = sum(stats.bucket_outcomes.get(b, {}).get("ok", 0)
                for b in ("promoted", "fallback", "cold"))
    return {
        "factor": factor,
        "budget_bytes": budget,
        "row_bytes": row_bytes,
        "rows": n_rows,
        "working_set_bytes": n_rows * row_bytes,
        "sent": stats.sent,
        "ok": ok_total,
        "shed": stats.shed,
        "errors": stats.errors,
        "seconds": round(elapsed, 3),
        # the headline: what fraction of completed reads paid ANY
        # non-HBM stack access (promotion wait / fallback / rebuild)
        "stall_rate": round(stall / ok_total, 4) if ok_total else None,
        "tiers": {
            b: {
                "ok": len(lats),
                "p50_ms": round(_percentile(sorted(lats), 0.50) * 1e3,
                                2),
                "p99_ms": round(_percentile(sorted(lats), 0.99) * 1e3,
                                2),
            }
            for b, lats in sorted(stats.bucket_latencies.items())
        },
        "server": {
            n: (None if vars1.get(n) is None
                else round(vars1[n] - (vars0.get(n) or 0.0), 1))
            for n in _TIER_VARS
        },
    }


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description="open-loop load generator (admission control)")
    p.add_argument("--host", default="http://127.0.0.1:10101")
    p.add_argument("-i", "--index", required=True)
    p.add_argument("--qps", type=float, default=100.0)
    p.add_argument("--seconds", type=float, default=5.0)
    p.add_argument("--query", default="Count(Row(f=1))")
    p.add_argument("--mix", default="query=1.0",
                   help="class=weight[,class=weight...] over "
                        "query/ingest/internal")
    p.add_argument("--deadline-ms", default=None,
                   help="lo,hi uniform per-request deadline in ms "
                        "(default: none sent)")
    p.add_argument("--ingest-field", default="loadgen",
                   help="field ingest-class imports land in (point it "
                        "at the queried field to measure cache warmth "
                        "under same-field ingest)")
    p.add_argument("--ingest-bits", type=int, default=1,
                   help="bit positions per ingest import payload "
                        "(sized so the mix sustains the target "
                        "bits/s)")
    p.add_argument("--ingest-rows", type=int, default=8,
                   help="row-id range ingest positions draw from")
    p.add_argument("--ingest-cols", type=int, default=1 << 20,
                   help="column range ingest positions draw from "
                        "(span multiple shard widths to fan the write "
                        "load out)")
    p.add_argument("--shape-mix", type=int, default=0,
                   help="rotate query-class requests through N "
                        "structurally distinct Count shapes (0 = the "
                        "single --query); report adds "
                        "dispatches/query")
    p.add_argument("--shape-field", default=None,
                   help="field the shape-mix trees read (default: "
                        "'f')")
    p.add_argument("--shape-rows", type=int, default=6,
                   help="row-id range shape-mix leaves draw from")
    p.add_argument("--sparsity-mix", default=None,
                   help="bucket=row[,bucket=row...] — rotate "
                        "query-class requests across rows with "
                        "controlled fill ratios (e.g. "
                        "dense=1,pct10=2,pct01=3) and report "
                        "per-bucket p50/p99")
    p.add_argument("--sparsity-field", default="f",
                   help="field the sparsity-mix rows live in")
    p.add_argument("--working-set-factor", type=float, default=None,
                   help="drive a zipfian row mix over an index sized "
                        "N x the server's HBM residency budget "
                        "(self-importing; see run_working_set) and "
                        "report the tier hit/stall split with "
                        "per-tier read p50/p99")
    p.add_argument("--working-set-field", default="ws",
                   help="field the working-set rows are imported into")
    p.add_argument("--working-set-shards", type=int, default=4,
                   help="shards each working-set row spans")
    p.add_argument("--working-set-alpha", type=float, default=1.1,
                   help="zipf exponent of the working-set row mix")
    p.add_argument("--chaos", default=None,
                   help="failpoint spec armed/disarmed on a schedule "
                        "mid-run via POST /debug/failpoints (e.g. "
                        "'client.request.send=error(transport)@3'); "
                        "the report splits goodput/error-rate/p99 "
                        "into fault vs clear windows and adds the "
                        "server's hedge rate")
    p.add_argument("--chaos-period", type=float, default=2.0,
                   help="seconds per arm+disarm cycle")
    p.add_argument("--chaos-duty", type=float, default=0.5,
                   help="fraction of each cycle the spec stays armed")
    p.add_argument("--chaos-hosts", default=None,
                   help="comma-separated extra hosts to arm (default: "
                        "--host only)")
    p.add_argument("--scale-schedule", default=None,
                   help="timed node add/remove against the online "
                        "resize control route while traffic flows "
                        "(e.g. '2:add=n4=http://127.0.0.1:10104;"
                        "8:remove=n4'); the report adds per-phase "
                        "goodput/p50/p99 and the server's rebalance_* "
                        "counter deltas")
    p.add_argument("--scale-settle-timeout", type=float, default=120.0,
                   help="seconds to wait for each migration to settle "
                        "(/debug/rebalance active=false) before the "
                        "next phase")
    p.add_argument("--tenant-mix", default=None,
                   help="tenant:weight[:class][,tenant:weight...] — "
                        "draw each arrival from a weighted tenant "
                        "schedule, stamp its X-Pilosa-Tenant header, "
                        "and report per-tenant goodput/p50/p99/shed "
                        "(e.g. 'gold:8:query,abuser:40:query'); "
                        "replaces --mix")
    p.add_argument("--timeout", type=float, default=10.0)
    args = p.parse_args(argv)
    mix = {}
    for part in args.mix.split(","):
        k, _, w = part.partition("=")
        mix[k.strip()] = float(w or 1.0)
    deadline_s = None
    if args.deadline_ms:
        lo, _, hi = args.deadline_ms.partition(",")
        deadline_s = (float(lo) / 1e3, float(hi or lo) / 1e3)
    if args.working_set_factor is not None:
        dl = None
        if deadline_s is not None:
            dl = deadline_s[1]
        report = run_working_set(
            args.host.rstrip("/"), args.index,
            args.working_set_factor, qps=args.qps,
            seconds=args.seconds, field=args.working_set_field,
            shards=args.working_set_shards,
            alpha=args.working_set_alpha, timeout=args.timeout,
            deadline_s=dl)
        print(json.dumps(report, indent=2))
        return 0
    chaos = None
    if args.chaos:
        hosts = [args.host.rstrip("/")]
        if args.chaos_hosts:
            hosts += [h.rstrip("/")
                      for h in args.chaos_hosts.split(",") if h]
        chaos = _ChaosDriver(hosts, args.chaos,
                             period_s=args.chaos_period,
                             duty=args.chaos_duty)
    scale = None
    if args.scale_schedule:
        scale = _ScaleDriver(
            args.host.rstrip("/"),
            parse_scale_schedule(args.scale_schedule),
            settle_timeout=args.scale_settle_timeout)
    report = run_load(args.host.rstrip("/"), args.index, args.qps,
                      args.seconds, query=args.query, mix=mix,
                      chaos=chaos, scale=scale,
                      deadline_s=deadline_s, timeout=args.timeout,
                      ingest_field=args.ingest_field,
                      ingest_bits=args.ingest_bits,
                      ingest_rows=args.ingest_rows,
                      ingest_cols=args.ingest_cols,
                      shape_mix=args.shape_mix,
                      shape_field=args.shape_field,
                      shape_rows=args.shape_rows,
                      sparsity_mix=(parse_sparsity_mix(args.sparsity_mix)
                                    if args.sparsity_mix else None),
                      sparsity_field=args.sparsity_field,
                      tenant_mix=(parse_tenant_mix(args.tenant_mix)
                                  if args.tenant_mix else None))
    print(json.dumps(report, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
