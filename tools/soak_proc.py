#!/usr/bin/env python
"""Process-level randomized fault soak: real server processes, real
signals, hours-scale.

The deterministic CI tier (tests/test_proc_cluster.py) proves one
scripted SIGKILL and one scripted SIGSTOP scenario.  This harness
randomizes them for hours against a live 3-process cluster — the
reference's long-running docker-compose clustertests with pumba
pauses (internal/clustertests/cluster_test.go:69-80) — so a freeze
can land at ANY phase of an import, a scatter query, or the servers'
own 2 s anti-entropy cadence:

  - FREEZE cycle: SIGSTOP a victim mid-import (replication to its
    accepted-but-unserved socket blocks), query survivors WHILE frozen
    (replica failover must stay exact), SIGCONT after 2-8 s, then
    require full convergence on all three nodes (AE heals whatever the
    frozen window missed).
  - KILL cycle: SIGKILL the victim, require DEGRADED detection and
    exact reads from survivors, restart from the same data dir, and
    require NORMAL + exact reads everywhere (WAL/snapshot recovery).
  - RESIZE cycle: a REAL 4th server process joins (coordinator-led
    re-homing over live sockets) — half the time with a replica
    FROZEN mid-join, the zombie-rejoin-versus-resize race the
    in-process soak cannot produce — then leaves via
    /cluster/resize/remove-node; reads must be exact at every stage
    whether the contested join completed or aborted cleanly.
  - QUIET cycle: import + exact reads on every node (steady-state
    oracle pressure between faults).

Bidirectional pair partitions need sender-aware message drops, which
real sockets do not offer without netem privileges — that fault lives
in the in-process randomized soak (tools/soak.py, LocalTransport
pair partitions) with identical query/AE semantics.

    PYTHONPATH=/root/repo:$PYTHONPATH python tools/soak_proc.py --seconds 3600

Exit 0 = zero divergence.  Deterministic per --seed (modulo OS
scheduling).  PARANOIA is ON in every server: each fragment mutation
re-validates invariants in all three real processes.
"""

from __future__ import annotations

import argparse
import os
import pathlib
import random
import signal
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the harness MUST agree with the spawned servers on shard width: the
# roaring import path pre-encodes absolute positions (row*width+off)
# with THIS process's width (tests/test_proc_cluster._spawn pins the
# servers to 16)
os.environ.setdefault("PILOSA_TPU_SHARD_WIDTH_EXP", "16")

from tests.test_proc_cluster import (  # noqa: E402
    _free_port, _get, _post, _spawn, _wait_status)
from pilosa_tpu.shardwidth import SHARD_WIDTH  # noqa: E402

N_SHARDS = 9


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seconds", type=float, default=600.0)
    ap.add_argument("--seed", type=int, default=20260801)
    args = ap.parse_args()

    rng = random.Random(args.seed)
    tmp = pathlib.Path(tempfile.mkdtemp(prefix="soakproc-"))
    ports = [_free_port() for _ in range(3)]
    procs: list = [None, None, None]

    def spawn(i: int):
        procs[i] = _spawn(str(tmp / f"n{i}"), ports[i],
                          seeds=[ports[0]] if i else None,
                          paranoia=True)

    stats = {"cycles": 0, "freezes": 0, "kills": 0, "resizes": 0,
             "frozen_joins": 0, "checks": 0, "imports": 0}
    epoch = 0
    oracle: dict[int, set] = {r: set() for r in range(4)}

    def batch(n=250):
        rows, cols = [], []
        for r in oracle:
            for _ in range(n):
                c = rng.randrange(N_SHARDS * SHARD_WIDTH)
                oracle[r].add(c)
                rows.append(r)
                cols.append(c)
        return {"rowIDs": rows, "columnIDs": cols}

    def roaring_import(port, b, timeout=180.0):
        """Deliver a batch over the FASTEST wire: pre-encoded roaring
        per shard via /import-roaring/{shard} (owner fan-out + WAL
        roaring records — a different durability/replication path from
        /import's JSON arrays)."""
        import urllib.request

        import numpy as np

        from pilosa_tpu.storage import roaring as rcodec

        rows_a = np.asarray(b["rowIDs"], dtype=np.int64)
        cols_a = np.asarray(b["columnIDs"], dtype=np.int64)
        shard_a = cols_a // SHARD_WIDTH
        pos_a = (rows_a * SHARD_WIDTH
                 + (cols_a % SHARD_WIDTH)).astype(np.uint64)
        for s in np.unique(shard_a):
            u = np.unique(pos_a[shard_a == s])
            k_, w_ = rcodec.positions_to_containers(u)
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/index/i/field/f/"
                f"import-roaring/{int(s)}",
                data=rcodec.encode(k_, w_), method="POST")
            req.add_header("Content-Type", "application/octet-stream")
            urllib.request.urlopen(req, timeout=timeout).read()

    def any_import(port, b, timeout=180.0):
        if rng.random() < 0.5:
            roaring_import(port, b, timeout)
        else:
            _post(port, "/index/i/field/f/import", b, timeout=timeout)

    def check_exact(port, rows=(0, 1)):
        q = "Count(Union(%s))" % ", ".join(f"Row(f={r})" for r in rows)
        got = _post(port, "/index/i/query", {"query": q}, timeout=90.0)
        want = len(set().union(*(oracle[r] for r in rows)))
        assert got["results"][0] == want, \
            f":{port} {q} -> {got['results'][0]} != {want}"
        stats["checks"] += 1

    def converge(deadline_s=90.0):
        """Poll until all three nodes answer the union row exactly —
        the post-fault AE-heal barrier."""
        end = time.time() + deadline_s
        want = len(oracle[0] | oracle[1])
        last = None
        while time.time() < end:
            try:
                last = [_post(p, "/index/i/query",
                              {"query":
                               "Count(Union(Row(f=0), Row(f=1)))"},
                              timeout=30.0)["results"][0]
                        for p in ports]
                if last == [want] * 3:
                    stats["checks"] += 3
                    return
            except OSError:
                pass
            time.sleep(1.0)
        raise AssertionError(f"no convergence: {last} != {want}")

    try:
        spawn(0)
        _wait_status(ports[0], "NORMAL", 1)
        spawn(1)
        spawn(2)
        for p in ports:
            _wait_status(p, "NORMAL", 3)
        base_ids = {_get(p, "/status")["localID"] for p in ports}
        _post(ports[0], "/index/i", {})
        _post(ports[0], "/index/i/field/f", {})
        _post(ports[0], "/index/i/field/f/import", batch())
        stats["imports"] += 1
        for p in ports:
            check_exact(p)

        t_end = time.monotonic() + args.seconds
        capturing_flag = os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "relay_watcher.capturing")
        while time.monotonic() < t_end:
            # yield the single core while a relay capture is timing
            # QPS on the chip (same hygiene and staleness bound as
            # tools/soak.py — an orphaned flag must not pause forever)
            while os.path.exists(capturing_flag):
                try:
                    if time.time() - os.path.getmtime(
                            capturing_flag) > 7200:
                        break
                except OSError:
                    break
                time.sleep(5)
            stats["cycles"] += 1
            roll = rng.random()
            victim = rng.choice([1, 2])
            survivors = [p for i, p in enumerate(ports) if i != victim]

            if roll < 0.40:  # ---- FREEZE cycle
                stats["freezes"] += 1
                pre = {r: len(s) for r, s in oracle.items()}
                b = batch()
                procs[victim].send_signal(signal.SIGSTOP)
                time.sleep(rng.uniform(0.1, 1.0))
                err: list = []

                use_roaring = rng.random() < 0.5

                def do_import():
                    # the per-shard roaring sequence can observe the
                    # DEGRADED write-block mid-freeze (405) where the
                    # single JSON POST was already in flight — retry
                    # through the window like a real client; the merge
                    # is idempotent, so re-sending shards is exact
                    import urllib.error

                    deadline = time.time() + 180.0
                    while True:
                        try:
                            if use_roaring:
                                roaring_import(ports[0], b)
                            else:
                                _post(ports[0],
                                      "/index/i/field/f/import",
                                      b, timeout=180.0)
                            return
                        except urllib.error.HTTPError as e:
                            if e.code != 405 or time.time() > deadline:
                                err.append(e)
                                return
                            time.sleep(1.0)
                        except Exception as e:  # noqa: BLE001
                            err.append(e)
                            return

                t = threading.Thread(target=do_import, daemon=True)
                t.start()
                # survivors answer WHILE the victim is frozen; the
                # racing import bounds row counts, never breaks them
                for p in rng.sample(survivors, 2):
                    got = _post(p, "/index/i/query",
                                {"query": "Count(Row(f=3))"},
                                timeout=90.0)["results"][0]
                    assert pre[3] <= got <= len(oracle[3]), \
                        (got, pre[3], len(oracle[3]))
                    stats["checks"] += 1
                time.sleep(rng.uniform(2.0, 8.0))
                procs[victim].send_signal(signal.SIGCONT)
                t.join(timeout=180.0)
                assert not t.is_alive(), "import never finished post-thaw"
                assert not err, err
                stats["imports"] += 1
                for p in ports:
                    _wait_status(p, "NORMAL", 3, deadline=120.0)
                converge()

            elif roll < 0.60:  # ---- RESIZE cycle: real 4th process
                # joins (sometimes against a frozen replica) and leaves
                stats["resizes"] += 1
                epoch += 1
                p3 = _free_port()
                # fresh dir per epoch: a re-joining node must never
                # resurrect a removed epoch's detached fragments
                pr3 = _spawn(str(tmp / f"n3-e{epoch}"), p3,
                             seeds=[ports[0]], paranoia=True)
                frozen = rng.random() < 0.5
                if frozen:
                    stats["frozen_joins"] += 1
                    time.sleep(rng.uniform(0.0, 1.0))
                    procs[victim].send_signal(signal.SIGSTOP)
                    time.sleep(rng.uniform(2.0, 5.0))
                    procs[victim].send_signal(signal.SIGCONT)
                try:
                    # the join either completes (4 nodes) or aborts
                    # cleanly (3) — both legal under a frozen owner;
                    # reads must be exact either way once NORMAL
                    deadline = time.time() + 120.0
                    settled = False
                    while time.time() < deadline:
                        try:
                            st = _get(ports[0], "/status", timeout=5)
                            if st["state"] == "NORMAL" and (
                                    len(st["nodes"]) == 4
                                    or pr3.poll() is not None):
                                settled = True
                                break
                        except OSError:
                            pass
                        time.sleep(1.0)
                    if settled:
                        for p in ports:
                            check_exact(p)
                    # deadline expiry = the contested join neither
                    # completed nor aborted in time; exactness is
                    # enforced by the post-cleanup NORMAL wait +
                    # converge() below, after strays are removed
                finally:
                    # graceful leave for whatever actually joined
                    # (judged from the coordinator's member list, not
                    # our racy local view), then stop the process
                    end = time.time() + 120.0
                    while time.time() < end:
                        try:
                            st = _get(ports[0], "/status", timeout=10)
                            stray = [n["id"] for n in st["nodes"]
                                     if n["id"] not in base_ids]
                            if not stray and st["state"] == "NORMAL":
                                break
                            for nid in stray:
                                _post(ports[0],
                                      "/cluster/resize/remove-node",
                                      {"id": nid}, timeout=120.0)
                        except OSError:
                            pass  # coordinator mid-resize; retry
                        time.sleep(1.0)
                    if pr3.poll() is None:
                        pr3.terminate()
                        try:
                            pr3.wait(timeout=15)
                        except Exception:  # noqa: BLE001
                            pr3.kill()
                    import shutil

                    shutil.rmtree(tmp / f"n3-e{epoch}",
                                  ignore_errors=True)
                for p in ports:
                    _wait_status(p, "NORMAL", 3, deadline=120.0)
                converge()

            elif roll < 0.80:  # ---- KILL + restart cycle
                stats["kills"] += 1
                procs[victim].send_signal(signal.SIGKILL)
                procs[victim].wait(timeout=30)
                _wait_status(ports[0], "DEGRADED", deadline=60.0)
                for p in survivors:
                    check_exact(p)
                spawn(victim)
                for p in ports:
                    _wait_status(p, "NORMAL", 3, deadline=120.0)
                converge()

            else:  # ---- QUIET cycle: steady-state oracle pressure
                any_import(ports[0], batch(60))
                stats["imports"] += 1
                check_exact(rng.choice(ports), rows=(0, 1, 2))
                topn = _post(rng.choice(ports), "/index/i/query",
                             {"query": "TopN(f)"})["results"][0]
                want = sorted(((len(s), r) for r, s in oracle.items()),
                              key=lambda x: (-x[0], x[1]))
                assert [(p["count"], p["id"]) for p in topn] == want
                stats["checks"] += 1

            print(f"soak_proc: {stats}", flush=True)

        for p in ports:
            check_exact(p, rows=(0, 1, 2))
        print(f"soak_proc PASSED: {stats}", flush=True)
        return 0
    finally:
        for pr in procs:
            if pr is not None and pr.poll() is None:
                try:
                    pr.send_signal(signal.SIGCONT)  # never leave frozen
                except OSError:
                    pass
                pr.terminate()
        for pr in procs:
            if pr is not None:
                try:
                    pr.wait(timeout=15)
                except Exception:  # noqa: BLE001
                    pr.kill()


if __name__ == "__main__":
    sys.exit(main())
