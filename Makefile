# Developer surface, mirroring the reference's Makefile targets
# (test / bench / clustertests) and its CI matrix (-race runs and the
# SHARD_WIDTH build-tag job, .circleci/config.yml:52-64) adapted to
# this build: the paranoia gate is our sanitizer tier and the shard
# width is env-configurable rather than a build tag.

PY ?= python

.PHONY: test test-paranoia test-shard22 test-matrix analyze typecheck bench perfsnapshot measure measure-resize measure-spmd validate-tpu soak soak-spmd check doccheck doccheck-fill native clean

test:
	$(PY) -m pytest tests/ -x -q

# pilosa-lint: the six project-invariant analysis passes over the
# package (tools/analyze/) — exit 1 on any unsuppressed finding.
# tests/test_analyze.py pins the committed tree at zero.
analyze:
	$(PY) -m tools.analyze pilosa_tpu

# mypy over the strict scope (mypy.ini; ops/tape.py, ops/expr.py,
# runtime/resultcache.py).  Gates gracefully when mypy is absent.
typecheck:
	$(PY) tools/typecheck.py

native:  # pre-build all four C++ fast paths (they also self-build lazily)
	$(PY) -c "from pilosa_tpu.ops import hostkernels as hk; \
	from pilosa_tpu.storage import roaring; \
	from pilosa_tpu.pql import native as pqlnative; \
	from pilosa_tpu import csvload; \
	print('bitcount:', hk.native_available()); \
	print('roaring :', roaring.native_available()); \
	print('pql     :', pqlnative.available()); \
	print('csv     :', csvload.available())"

# sanitizer tier: every fragment mutation re-validates invariants
test-paranoia:
	PILOSA_TPU_PARANOIA=1 $(PY) -m pytest tests/ -x -q

# shard-width independence (reference SHARD_WIDTH=22 matrix job)
test-shard22:
	PILOSA_TPU_SHARD_WIDTH_EXP=22 $(PY) -m pytest tests/ -x -q

test-matrix: analyze typecheck test test-paranoia test-shard22

# executable documentation: verify every doc example against a live
# server; doccheck-fill rewrites the response blocks from actual
# results (the authoring loop)
doccheck:
	$(PY) tools/doccheck.py docs/query-language.md docs/getting-started.md

doccheck-fill:
	$(PY) tools/doccheck.py --fill docs/query-language.md docs/getting-started.md

# north-star benchmark: one JSON line (driver artifact)
bench:
	$(PY) bench.py

# dated chip capture with measured per-engine bw_util (perfobs), plus
# a full metric-family sweep against a throwaway live server (usage:
# make perfsnapshot CAPTURE_ARGS="--profile --compare BENCH_r10.json")
perfsnapshot:
	$(PY) -m tools.chipcapture $(CAPTURE_ARGS)
	$(PY) -c "import tempfile, urllib.request; \
	from pilosa_tpu.server.server import Server; \
	from tools import check_metrics as cm; \
	s = Server(tempfile.mkdtemp() + '/perfsnap'); s.open(); \
	t = urllib.request.urlopen(s.uri + '/metrics', timeout=10).read().decode(); \
	cm.check_families(t, cm.ALL_FAMILIES); s.close(); \
	print('metric families: ok')"

# all BASELINE.md configs, one JSON line each
measure:
	$(PY) benchmarks/measure.py

# elastic resize at 1.07B columns (join + leave, one JSON line each)
measure-resize:
	$(PY) benchmarks/measure_resize.py

# collective vs scatter plane latency over real processes (usage:
# make measure-spmd MEASURE_PROCS=2)
MEASURE_PROCS ?= 2
measure-spmd:
	$(PY) benchmarks/measure_spmd.py --procs $(MEASURE_PROCS)

# on-chip Pallas validation (no-op skip without a TPU)
validate-tpu:
	$(PY) benchmarks/validate_tpu.py

# long randomized differential soak (usage: make soak SOAK_SECONDS=1500)
SOAK_SECONDS ?= 300
soak:
	$(PY) tools/soak.py --seconds $(SOAK_SECONDS)

# multi-process collective-plane soak (usage: make soak-spmd
# SOAK_SECONDS=600 SOAK_PROCS=2)
SOAK_PROCS ?= 2
soak-spmd:
	$(PY) tools/soak_spmd.py --seconds $(SOAK_SECONDS) --procs $(SOAK_PROCS)

# offline data-dir integrity (usage: make check DIR=/path/to/data)
check:
	$(PY) -m pilosa_tpu check $(DIR)

clean:
	rm -rf pilosa_tpu/native/build
	find . -name __pycache__ -type d -prune -exec rm -rf {} +
