#!/usr/bin/env python
"""North-star benchmark: PQL Count(Intersect(Row, Row)) QPS.

Measures the fused AND+popcount+reduce kernel (the hot path of every
Count/Intersect PQL query, reference executor.go:1790 → roaring.go:595)
over a multi-shard packed-bitmap index on the available accelerator, and
compares against an in-process NumPy CPU baseline evaluating the same
query the way the reference's Go engine does (per-shard AND + popcount,
serial map-reduce).

The measured path is the PRODUCT kernel: ``bm.popcount_and`` — one fused
XLA program on TPU, the native C++ AVX popcount kernel
(ops/hostkernels.py) on a CPU host — exactly what the executor's fused
pipeline dispatches.  Since the op is memory-bound, the JSON line also
reports achieved memory bandwidth and, on TPU, utilization of the chip's
peak HBM bandwidth (the MFU-equivalent for set algebra).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline",
"platform", "engine", "achieved_gbps", "peak_gbps", "bw_util",
"engines"}.  On TPU, "engines" carries an XLA-vs-Pallas A/B of the
same exact count (per-engine QPS, or a loud skip/WRONG-COUNT marker),
and "engine"/"value" take the winner.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

from pilosa_tpu.axon_guard import guard_dead_relay

# Poll up to 30s for a briefly-restarting relay before accepting the
# CPU fallback: the driver's artifact should be a chip number whenever
# the chip is reachable at all.
guard_dead_relay(wait_s=30.0)

# Benchmark shape: 256 shards x 2^20 columns = 268M columns per operand.
# Each operand is a [shards, 2^15] uint32 tensor (32 MiB) resident in HBM.
N_SHARDS = 256
WORDS = (1 << 20) // 32
DENSITY = 0.08  # fraction of bits set; typical set-field fragment occupancy

#: platforms that count as a real chip for peak-bw lookup and capture
#: attachment (the axon relay registers the v5e as "tpu" in practice,
#: but accept the plugin name too)
_CHIP_PLATFORMS = ("tpu", "axon")

# Peak HBM bandwidth by TPU generation, GB/s (public figures; used only
# for the utilization ratio on real chips).
_PEAK_GBPS = {
    # order matters: first match wins, most specific first.  JAX reports
    # v5e as "TPU v5 lite" and v6e as "TPU v6 lite" (normalized below to
    # "tpuv5lite"/"tpuv6lite"), hence the *lite aliases.
    "v5lite": 819.0,
    "v6lite": 1640.0,
    "v5e": 819.0,
    "v6e": 1640.0,
    "v5p": 2765.0,
    "v5": 2765.0,   # bare "TPU v5" = v5p
    "v4": 1228.0,
}


def make_operands(seed: int):
    rng = np.random.default_rng(seed)
    # Bernoulli bits packed into uint32 words, identical data for both runs.
    bits_a = rng.random((N_SHARDS, WORDS, 32)) < DENSITY
    bits_b = rng.random((N_SHARDS, WORDS, 32)) < DENSITY
    weights = (1 << np.arange(32, dtype=np.uint64)).astype(np.uint32)
    a = (bits_a * weights).sum(axis=2, dtype=np.uint32)
    b = (bits_b * weights).sum(axis=2, dtype=np.uint32)
    return a, b


def bench_device(a_np: np.ndarray, b_np: np.ndarray):
    """Throughput of the product fused kernel — ``bm.popcount_and``, the
    exact computation the executor's fused all-shard path dispatches for
    `Count(Intersect(Row, Row))`.

    On an accelerator, queries pipeline (block once at the end), as a
    serving process overlaps independent queries; a sync-per-query loop
    would measure host<->device round-trip latency, not chip throughput.
    On a CPU host the kernel is the synchronous native C++ popcount —
    each call IS a full query.

    Returns (qps, count, platform, engine, qps_by_engine)."""
    import jax

    from pilosa_tpu.ops import bitmap as bm

    platform = jax.devices()[0].platform

    if bm.host_mode():
        from pilosa_tpu.ops import hostkernels as hk

        engine = "native-host" if hk.native_available() else "numpy-host"
        expect = int(bm.popcount_and(a_np, b_np))
        # run for >= 2s so one scheduler hiccup on the single core
        # cannot swing the figure
        iters = 0
        t0 = time.perf_counter()
        while iters < 100 or time.perf_counter() - t0 < 2.0:
            bm.popcount_and(a_np, b_np)
            iters += 1
        dt = time.perf_counter() - t0
        qps = iters / dt
        return qps, expect, platform, engine, {engine: qps}

    a = jax.device_put(a_np)
    b = jax.device_put(b_np)

    # Pre-stage N_VARIANTS distinct left operands (low bits XOR'd with the
    # variant id — same byte volume, near-identical density, different
    # count) and precompute each expected count on the host.  Timing with
    # DISTINCT inputs matters twice over: (1) a serving process never
    # re-answers one literal query back-to-back, and (2) the execution
    # path may memoize an identical (executable, args) dispatch — measured
    # on the axon relay, an identical-input loop reports >1 TB/s on an
    # 819 GB/s part, i.e. the work provably did not re-run.  Rotating
    # variants keeps every iteration a real HBM-streaming execution.
    N_VARIANTS = 16
    expects = [int(np.bitwise_count((a_np ^ np.uint32(i)) & b_np)
                   .sum(dtype=np.uint64))
               for i in range(N_VARIANTS)]
    # Derive the variants ON DEVICE from the one staged operand (a
    # jitted XOR each): the axon tunnel moves host->device bytes at
    # single-digit MB/s in degraded states, so staging 16x32 MiB from
    # the host could eat the whole capture budget, while deriving them
    # costs zero tunnel bytes on any backend.
    import jax.numpy as jnp

    xor_const = jax.jit(lambda x, c: x ^ c)
    a_vars = [a] + [xor_const(a, jnp.uint32(i))
                    for i in range(1, N_VARIANTS)]
    jax.block_until_ready(a_vars)

    check_rng = np.random.default_rng(7)

    def timed_qps(fn) -> float:
        # Closed-loop QPS over rotating distinct queries: dispatches
        # pipeline (block once at the end) as a serving process overlaps
        # independent queries.  Correctness is checked two ways — each
        # variant individually before timing, and a 32-query random
        # sample of the timed window after it (per-result fetches cost
        # ~10 ms each through the relay, so checking every one of
        # thousands would dwarf the measurement; any systematic
        # work-dropping still hits a sample of 32 with certainty) — so
        # a run that got fast by skipping work fails loudly instead of
        # recording a fantasy number.  Median of 3 repeats, >=200
        # queries and >=0.3 s each, damps relay congestion spikes.
        for i in range(N_VARIANTS):
            got = int(np.asarray(fn(a_vars[i], b)))
            if got != expects[i]:
                raise AssertionError(
                    f"variant {i} returned {got}, expected {expects[i]}")
        reps = []
        for _ in range(3):
            iters = 200
            while True:
                outs = []
                t0 = time.perf_counter()
                for i in range(iters):
                    outs.append(fn(a_vars[i % N_VARIANTS], b))
                jax.block_until_ready(outs)
                dt = time.perf_counter() - t0
                if dt >= 0.3 or iters >= 3200:
                    break
                iters *= 4
            for i in check_rng.choice(iters, size=32, replace=False):
                got = int(np.asarray(outs[i]))
                if got != expects[i % N_VARIANTS]:
                    raise AssertionError(
                        f"query {i} returned {got}, "
                        f"expected {expects[i % N_VARIANTS]}")
            reps.append(iters / dt)
        reps.sort()
        return reps[1]

    # Warm-up: compile + one execution.
    expect = int(np.asarray(bm.popcount_and(a, b)))
    qps_by_engine = {"xla": timed_qps(bm.popcount_and)}

    if platform in _CHIP_PLATFORMS:
        # A/B the Pallas single-pass kernel against XLA's fused
        # AND+popcount on the real chip — both are exact; the headline
        # takes the winner and the artifact records both so a relay
        # window always captures the comparison
        from pilosa_tpu.ops import pallas_kernels as pk

        try:
            got = int(np.asarray(pk.count_and(a, b)))
        except Exception as e:  # noqa: BLE001 — a Mosaic lowering bug
            # must not kill the bench; the xla number stands, and the
            # artifact records WHY the pallas leg is absent
            print(f"bench: pallas engine skipped: {e!r}", file=sys.stderr)
            qps_by_engine["pallas"] = f"error: {type(e).__name__}"
        else:
            if got != expect:
                # a wrong COUNT is a correctness bug, not a benign
                # skip — it must be loud in the artifact
                qps_by_engine["pallas"] = f"WRONG COUNT {got} != {expect}"
            else:
                qps_by_engine["pallas"] = timed_qps(pk.count_and)

    numeric = {k: v for k, v in qps_by_engine.items()
               if isinstance(v, float)}
    engine = max(numeric, key=numeric.get)
    return numeric[engine], expect, platform, engine, qps_by_engine


def verify_product_path(a_np: np.ndarray, b_np: np.ndarray,
                        expect: int) -> None:
    """Bit-exactness of the REAL path: the PQL string through the
    executor's fused pipeline must produce the identical count."""
    import tempfile

    from pilosa_tpu.models.holder import Holder
    from pilosa_tpu.ops import bitmap as bm
    from pilosa_tpu.parallel.executor import Executor
    from pilosa_tpu.shardwidth import SHARD_WIDTH

    if bm.n_words(SHARD_WIDTH) != WORDS:
        # benchmark rows are built for the default 2^20-column shards;
        # with a non-default PILOSA_TPU_SHARD_WIDTH_EXP the kernel
        # benchmark above is still valid, so just skip this check
        return

    holder = Holder(tempfile.mkdtemp() + "/bench")
    idx = holder.create_index("i")
    f = idx.create_field("f")
    view = f.create_view_if_not_exists("standard")
    for s in range(N_SHARDS):
        frag = view.create_fragment_if_not_exists(s)
        with frag._lock:
            frag._rows[1] = a_np[s].copy()
            frag._rows[2] = b_np[s].copy()
            frag._gen += 1
        f._note_shard(s)
    ex = Executor(holder)
    got = int(ex.execute("i", "Count(Intersect(Row(f=1), Row(f=2)))")[0])
    assert got == expect, f"product path mismatch: {got} != {expect}"


def bench_cpu_baseline(a: np.ndarray, b: np.ndarray) -> tuple[float, int]:
    """Serial per-shard AND+popcount, mirroring the reference's single-node
    map-reduce over shards (executor.go:2561 worker loop, one shard at a
    time per worker; we grant the baseline full vectorization per shard)."""
    def query() -> int:
        total = 0
        for s in range(a.shape[0]):
            total += int(np.bitwise_count(a[s] & b[s]).sum(dtype=np.uint64))
        return total

    expect = query()  # warm-up / page-in
    # Best-of-3 minimum-duration loops: the baseline is the denominator
    # of vs_baseline, so noise here swings the headline ratio harder
    # than device noise does.  Taking the BEST repeat is deliberately
    # conservative — it credits the CPU with its least-interrupted run.
    best = 0.0
    for _ in range(3):
        iters = 0
        t0 = time.perf_counter()
        while iters < 3 or time.perf_counter() - t0 < 1.0:
            query()
            iters += 1
        best = max(best, iters / (time.perf_counter() - t0))
    return best, expect


def _peak_gbps(platform: str) -> float | None:
    if platform not in _CHIP_PLATFORMS:
        return None
    import jax

    kind = (jax.devices()[0].device_kind or "").lower().replace(" ", "")
    for gen, peak in _PEAK_GBPS.items():
        if gen in kind:
            return peak
    return None


def _last_chip_capture():
    """The newest committed on-chip bench capture, or None.  Attached
    (clearly labeled) when THIS run had to fall back to the CPU host,
    so a round-end artifact taken during a relay outage still points
    at the repo's real chip evidence instead of reading as a
    regression.  Never substitutes for the current run's numbers."""
    import glob
    import os

    caps = sorted(glob.glob(os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "tools", "tpu_captures", "bench_*.json")))
    for path in reversed(caps):
        try:
            with open(path) as fh:
                rec = json.loads(fh.read().strip())
        except (OSError, ValueError):
            continue
        if rec.get("platform") in _CHIP_PLATFORMS:
            rec["captured"] = os.path.basename(path)[6:-5]
            return rec
    return None


def main():
    a, b = make_operands(seed=12348)
    cpu_qps, cpu_count = bench_cpu_baseline(a, b)
    dev_qps, dev_count, platform, engine, qps_by_engine = bench_device(a, b)
    assert dev_count == cpu_count, f"bit-exactness violated: {dev_count} != {cpu_count}"
    verify_product_path(a, b, cpu_count)
    bytes_per_query = a.nbytes + b.nbytes  # streamed once per query
    achieved_gbps = dev_qps * bytes_per_query / 1e9
    peak = _peak_gbps(platform)
    # Physics backstop: a memory-bound kernel cannot beat the HBM roof.
    # The relay memoizes identical dispatches (see timed_qps); variant
    # rotation defeats the observed back-to-back case, but a deeper
    # (executable, args) cache would inflate QPS while every sampled
    # count still verifies — so a >roof figure is flagged as a
    # measurement fault in the artifact itself, never recorded as a
    # clean number.
    suspect = peak is not None and achieved_gbps > peak
    if suspect:
        print(f"bench: MEASUREMENT FAULT: achieved {achieved_gbps:.0f} "
              f"GB/s exceeds the {peak:.0f} GB/s HBM roof — dispatches "
              "were memoized, not executed; number is NOT trustworthy",
              file=sys.stderr)
    chip = (None if platform in _CHIP_PLATFORMS
            else _last_chip_capture())
    print(json.dumps({
        "metric": "intersect_count_qps_268M_cols",
        "value": round(dev_qps, 2),
        "unit": "qps",
        "vs_baseline": round(dev_qps / cpu_qps, 2),
        "platform": platform,
        "engine": engine,
        "achieved_gbps": round(achieved_gbps, 1),
        "peak_gbps": peak,
        "bw_util": None if peak is None else round(achieved_gbps / peak, 3),
        "engines": {k: round(v, 2) if isinstance(v, float) else v
                    for k, v in qps_by_engine.items()},
        **({"suspect_memoized_dispatch": True} if suspect else {}),
        **({"last_chip_capture": chip} if chip else {}),
    }))


if __name__ == "__main__":
    main()


