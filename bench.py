#!/usr/bin/env python
"""North-star benchmark: PQL Count(Intersect(Row, Row)) QPS.

Measures the fused AND+popcount+reduce kernel (the hot path of every
Count/Intersect PQL query, reference executor.go:1790 → roaring.go:595)
over a multi-shard packed-bitmap index on the available accelerator, and
compares against an in-process NumPy CPU baseline evaluating the same
query the way the reference's Go engine does (per-shard AND + popcount,
serial map-reduce).

The measured path is the PRODUCT kernel: ``bm.popcount_and`` — one fused
XLA program on TPU, the native C++ AVX popcount kernel
(ops/hostkernels.py) on a CPU host — exactly what the executor's fused
pipeline dispatches.  Since the op is memory-bound, the JSON line also
reports achieved memory bandwidth and, on TPU, utilization of the chip's
peak HBM bandwidth (the MFU-equivalent for set algebra).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline",
"platform", "engine", "achieved_gbps", "peak_gbps", "bw_util",
"engines"}.  On TPU, "engines" carries an XLA-vs-Pallas A/B of the
same exact count (per-engine QPS, or a loud skip/WRONG-COUNT marker),
"engine"/"value" take the winner, and two context keys are added:
"dispatch_floor_us" (per-dispatch overhead of a trivial kernel — when
it approaches the per-query time, the run was relay-dispatch-bound)
and "batch32" (B=32 queries per executable launch, the product's
fused-dispatch shape; see _bench_batched_and_floor).
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

from pilosa_tpu.axon_guard import guard_dead_relay

# Poll up to 30s for a briefly-restarting relay before accepting the
# CPU fallback: the driver's artifact should be a chip number whenever
# the chip is reachable at all.
guard_dead_relay(wait_s=30.0)

# Benchmark shape: 256 shards x 2^20 columns = 268M columns per operand.
# Each operand is a [shards, 2^15] uint32 tensor (32 MiB) resident in HBM.
N_SHARDS = 256
WORDS = (1 << 20) // 32
DENSITY = 0.08  # fraction of bits set; typical set-field fragment occupancy

#: platforms that count as a real chip for peak-bw lookup and capture
#: attachment (the axon relay registers the v5e as "tpu" in practice,
#: but accept the plugin name too)
_CHIP_PLATFORMS = ("tpu", "axon")

# Peak HBM bandwidth by TPU generation, GB/s (public figures; used only
# for the utilization ratio on real chips).
_PEAK_GBPS = {
    # order matters: first match wins, most specific first.  JAX reports
    # v5e as "TPU v5 lite" and v6e as "TPU v6 lite" (normalized below to
    # "tpuv5lite"/"tpuv6lite"), hence the *lite aliases.
    "v5lite": 819.0,
    "v6lite": 1640.0,
    "v5e": 819.0,
    "v6e": 1640.0,
    "v5p": 2765.0,
    "v5": 2765.0,   # bare "TPU v5" = v5p
    "v4": 1228.0,
}


def make_operands(seed: int):
    rng = np.random.default_rng(seed)
    # Bernoulli bits packed into uint32 words, identical data for both runs.
    bits_a = rng.random((N_SHARDS, WORDS, 32)) < DENSITY
    bits_b = rng.random((N_SHARDS, WORDS, 32)) < DENSITY
    weights = (1 << np.arange(32, dtype=np.uint64)).astype(np.uint32)
    a = (bits_a * weights).sum(axis=2, dtype=np.uint32)
    b = (bits_b * weights).sum(axis=2, dtype=np.uint32)
    return a, b


def _timed_median(dispatch, verify_sample, start_iters: int,
                  max_iters: int, rng) -> float:
    """Median-of-3 pipelined dispatch rate.  Each repeat grows the
    pipelined batch until it spans >=0.3 s (one scheduler hiccup can't
    swing a shorter window), blocks once, then verifies a random
    sample of the window's results via ``verify_sample(i, out)``.
    Shared by the single-dispatch engines and the batched engine so
    the memoization-defeat/verification logic cannot drift between
    them.  Returns dispatches/second (callers scale by queries per
    dispatch)."""
    import jax

    reps = []
    for _ in range(3):
        iters = start_iters
        while True:
            outs = []
            t0 = time.perf_counter()
            for i in range(iters):
                outs.append(dispatch(i))
            jax.block_until_ready(outs)
            dt = time.perf_counter() - t0
            if dt >= 0.3 or iters >= max_iters:
                break
            iters *= 4
        for i in rng.choice(iters, size=min(32, iters), replace=False):
            verify_sample(int(i), outs[int(i)])
        reps.append(iters / dt)
    reps.sort()
    return reps[1]


def bench_device(a_np: np.ndarray, b_np: np.ndarray):
    """Throughput of the product fused kernel — ``bm.popcount_and``, the
    exact computation the executor's fused all-shard path dispatches for
    `Count(Intersect(Row, Row))`.

    On an accelerator, queries pipeline (block once at the end), as a
    serving process overlaps independent queries; a sync-per-query loop
    would measure host<->device round-trip latency, not chip throughput.
    On a CPU host the kernel is the synchronous native C++ popcount —
    each call IS a full query.

    Returns (qps, count, platform, engine, qps_by_engine, extras)
    where extras carries the chip-only context measurements
    (dispatch_floor_us, batch32) or is empty."""
    import jax

    from pilosa_tpu.ops import bitmap as bm

    platform = jax.devices()[0].platform

    if bm.host_mode():
        from pilosa_tpu.ops import hostkernels as hk

        engine = "native-host" if hk.native_available() else "numpy-host"
        expect = int(bm.popcount_and(a_np, b_np))
        # run for >= 2s so one scheduler hiccup on the single core
        # cannot swing the figure
        iters = 0
        t0 = time.perf_counter()
        while iters < 100 or time.perf_counter() - t0 < 2.0:
            bm.popcount_and(a_np, b_np)
            iters += 1
        dt = time.perf_counter() - t0
        qps = iters / dt
        # context fields on the CPU fallback too (VERDICT #1: the
        # committed artifact must not drop the fields the capture
        # instrumentation computes just because the chip was away)
        extras = _bench_batched_and_floor_host(a_np, b_np)
        return qps, expect, platform, engine, {engine: qps}, extras

    a = jax.device_put(a_np)
    b = jax.device_put(b_np)

    # Pre-stage N_VARIANTS distinct left operands (low bits XOR'd with the
    # variant id — same byte volume, near-identical density, different
    # count) and precompute each expected count on the host.  Timing with
    # DISTINCT inputs matters twice over: (1) a serving process never
    # re-answers one literal query back-to-back, and (2) the execution
    # path may memoize an identical (executable, args) dispatch — measured
    # on the axon relay, an identical-input loop reports >1 TB/s on an
    # 819 GB/s part, i.e. the work provably did not re-run.  Rotating
    # variants keeps every iteration a real HBM-streaming execution.
    N_VARIANTS = 16
    expects = [int(np.bitwise_count((a_np ^ np.uint32(i)) & b_np)
                   .sum(dtype=np.uint64))
               for i in range(N_VARIANTS)]
    # Derive the variants ON DEVICE from the one staged operand (a
    # jitted XOR each): the axon tunnel moves host->device bytes at
    # single-digit MB/s in degraded states, so staging 16x32 MiB from
    # the host could eat the whole capture budget, while deriving them
    # costs zero tunnel bytes on any backend.
    import jax.numpy as jnp

    xor_const = jax.jit(lambda x, c: x ^ c)
    a_vars = [a] + [xor_const(a, jnp.uint32(i))
                    for i in range(1, N_VARIANTS)]
    jax.block_until_ready(a_vars)

    check_rng = np.random.default_rng(7)

    def timed_qps(fn) -> float:
        # Closed-loop QPS over rotating distinct queries: dispatches
        # pipeline (block once at the end) as a serving process overlaps
        # independent queries.  Correctness is checked two ways — each
        # variant individually before timing, and a random sample of
        # the timed window after it (per-result fetches cost ~10 ms
        # each through the relay, so checking every one of thousands
        # would dwarf the measurement; any systematic work-dropping
        # still hits the sample with certainty) — so a run that got
        # fast by skipping work fails loudly instead of recording a
        # fantasy number.
        for i in range(N_VARIANTS):
            got = int(np.asarray(fn(a_vars[i], b)))
            if got != expects[i]:
                raise AssertionError(
                    f"variant {i} returned {got}, expected {expects[i]}")

        def verify(i, out):
            got = int(np.asarray(out))
            if got != expects[i % N_VARIANTS]:
                raise AssertionError(
                    f"query {i} returned {got}, "
                    f"expected {expects[i % N_VARIANTS]}")

        return _timed_median(
            lambda i: fn(a_vars[i % N_VARIANTS], b), verify,
            start_iters=200, max_iters=3200, rng=check_rng)

    # Warm-up: compile + one execution.
    expect = int(np.asarray(bm.popcount_and(a, b)))
    qps_by_engine = {"xla": timed_qps(bm.popcount_and)}

    if platform in _CHIP_PLATFORMS:
        # A/B the Pallas single-pass kernel against XLA's fused
        # AND+popcount on the real chip — both are exact; the headline
        # takes the winner and the artifact records both so a relay
        # window always captures the comparison.  The PRIVATE kernel
        # entry point, deliberately: the public wrapper routes by the
        # committed per-kernel winners, so going through it would time
        # XLA against itself once evidence says XLA wins.
        from pilosa_tpu.ops import pallas_kernels as pk

        pallas_count = pk._count_and_pallas

        try:
            got = int(np.asarray(pallas_count(a, b)))
        except Exception as e:  # noqa: BLE001 — a Mosaic lowering bug
            # must not kill the bench; the xla number stands, and the
            # artifact records WHY the pallas leg is absent
            print(f"bench: pallas engine skipped: {e!r}", file=sys.stderr)
            qps_by_engine["pallas"] = f"error: {type(e).__name__}"
        else:
            if got != expect:
                # a wrong COUNT is a correctness bug, not a benign
                # skip — it must be loud in the artifact
                qps_by_engine["pallas"] = f"WRONG COUNT {got} != {expect}"
            else:
                qps_by_engine["pallas"] = timed_qps(pallas_count)

    extras: dict = {}
    if platform in _CHIP_PLATFORMS:
        extras = _bench_batched_and_floor(a, b, a_np, b_np)

    numeric = {k: v for k, v in qps_by_engine.items()
               if isinstance(v, float)}
    engine = max(numeric, key=numeric.get)
    return numeric[engine], expect, platform, engine, qps_by_engine, extras


def _bench_batched_and_floor(a, b, a_np: np.ndarray,
                             b_np: np.ndarray) -> dict:
    """Two context measurements for chip captures:

    ``dispatch_floor_us`` — per-dispatch overhead of a trivial kernel
    through the same pipelined loop shape.  When this approaches the
    measured per-query time, the single-dispatch QPS figures above are
    relay-dispatch-bound and the kernel time is hidden under tunnel
    overhead — the artifact then proves WHERE the bottleneck was
    instead of leaving a low bw_util unexplained.

    ``batch32`` — B=32 intersect-counts per executable launch: 32
    DISTINCT device-resident row variants against one filter, the
    dispatch shape of the product's fused all-shard paths
    (`masked_matrix_counts`, TopN/GroupBy row scans) and of any server
    batching concurrent queries.  The row stack is MATERIALIZED in HBM
    so every dispatch must stream all B rows (no cross-query read
    fusion can fake throughput), and a rotating scalar salt makes each
    dispatch's args distinct (the relay memoizes identical dispatches,
    see timed_qps).  Bandwidth accounting uses the row-stack bytes
    only (the shared filter's re-reads are not credited), so the
    figure is a LOWER bound and the >roof memoization flag stays
    valid."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from pilosa_tpu.ops import hostkernels as hk

    extras: dict = {}

    # ---- per-dispatch floor: trivial kernel, same loop shape
    tiny = jax.device_put(np.arange(8, dtype=np.uint32))
    tiny_fn = jax.jit(lambda x, c: jnp.sum(x ^ c, dtype=jnp.uint32))
    consts = [jnp.uint32(i) for i in range(16)]
    jax.block_until_ready([tiny_fn(tiny, c) for c in consts])
    t0 = time.perf_counter()
    iters = 2048
    outs = [tiny_fn(tiny, consts[i % 16]) for i in range(iters)]
    jax.block_until_ready(outs)
    extras["dispatch_floor_us"] = round(
        (time.perf_counter() - t0) / iters * 1e6, 1)

    # ---- batched engine
    B = 32
    N_ROT = 8
    row_salts = (np.arange(1, B + 1, dtype=np.uint64)
                 * np.uint64(0x9E3779B9)).astype(np.uint32)
    rot_salts = (np.arange(N_ROT, dtype=np.uint64)
                 * np.uint64(0x85EBCA6B)).astype(np.uint32)
    # 32 distinct rows derived ON DEVICE (the tunnel cannot stage
    # 1 GB from the host), then materialized: [B, shards, words]
    stack = jax.jit(jax.vmap(lambda r: a ^ r))(
        jax.device_put(row_salts))
    jax.block_until_ready(stack)

    if hk.native_available():
        def host_count(x):
            return int(hk.count_and(x, b_np))
    else:
        def host_count(x):
            return int(np.bitwise_count(x & b_np).sum(dtype=np.uint64))

    expects = [[host_count(a_np ^ np.uint32(int(r) ^ int(s)))
                for r in row_salts] for s in rot_salts]

    @jax.jit
    def batched(stack, b, s):
        return jax.vmap(
            lambda ai: jnp.sum(lax.population_count((ai ^ s) & b),
                               dtype=jnp.uint32))(stack)

    dev_salts = [jnp.uint32(int(s)) for s in rot_salts]
    for j in range(N_ROT):  # warm + verify every rotation
        got = np.asarray(batched(stack, b, dev_salts[j]))
        if got.tolist() != expects[j]:
            extras["batch32"] = "WRONG COUNTS"
            return extras

    def verify(i, out):
        if np.asarray(out).tolist() != expects[i % N_ROT]:
            raise AssertionError(
                f"batched dispatch {i} returned wrong counts")

    try:
        qps_b = _timed_median(
            lambda i: batched(stack, b, dev_salts[i % N_ROT]), verify,
            start_iters=64, max_iters=1024,
            rng=np.random.default_rng(11)) * B
    except AssertionError as e:
        # a wrong batched count must not kill the single-dispatch
        # artifact — record it loudly instead
        extras["batch32"] = f"WRONG COUNTS (timed window): {e}"
        return extras
    extras["batch32"] = {
        "qps": round(qps_b, 2),
        "queries_per_dispatch": B,
        # row-stack bytes only — lower bound, see docstring
        "achieved_gbps_lower": round(
            qps_b * (stack.nbytes / B) / 1e9, 1),
    }
    return extras


def _bench_batched_and_floor_host(a_np: np.ndarray,
                                  b_np: np.ndarray) -> dict:
    """CPU-fallback analogs of the chip context measurements, same
    field names and shapes so artifact consumers never branch:

    ``dispatch_floor_us`` — per-call floor of the host kernel entry
    point (a trivial 8-word count through the same native/numpy path
    every query pays; the host's analog of launch overhead).

    ``batch32`` — B=32 distinct intersect-counts back-to-back; the
    host has no executable-launch batching to amortize, so this is the
    honest per-query cost at the batched shape, bandwidth-credited
    like the chip version (each query's own operand bytes only)."""
    from pilosa_tpu.ops import hostkernels as hk

    extras: dict = {}
    tiny = np.arange(8, dtype=np.uint32)
    if hk.native_available():
        def tiny_fn():
            return hk.count_and(tiny, tiny)

        def count(x):
            return int(hk.count_and(x, b_np))
    else:
        def tiny_fn():
            return int(np.bitwise_count(tiny).sum())

        def count(x):
            return int(np.bitwise_count(x & b_np).sum(dtype=np.uint64))

    for _ in range(256):
        tiny_fn()
    iters = 20000
    t0 = time.perf_counter()
    for _ in range(iters):
        tiny_fn()
    extras["dispatch_floor_us"] = round(
        (time.perf_counter() - t0) / iters * 1e6, 1)

    B = 32
    salts = (np.arange(1, B + 1, dtype=np.uint64)
             * np.uint64(0x9E3779B9)).astype(np.uint32)
    expects = [count(a_np ^ s) for s in salts]
    reps = []
    for _ in range(3):
        t0 = time.perf_counter()
        got = [count(a_np ^ s) for s in salts]
        dt = time.perf_counter() - t0
        if got != expects:
            extras["batch32"] = "WRONG COUNTS"
            return extras
        reps.append(B / dt)
    reps.sort()
    qps_b = reps[1]
    extras["batch32"] = {
        "qps": round(qps_b, 2),
        "queries_per_dispatch": B,
        # each query's own operand bytes only — lower bound, matching
        # the chip accounting
        "achieved_gbps_lower": round(qps_b * a_np.nbytes / 1e9, 1),
    }
    return extras


def bench_coalescer(a_np: np.ndarray,
                    b_np: np.ndarray) -> tuple[dict, dict, dict] | None:
    """Serving-path benchmark of the PRODUCT batching layer: concurrent
    `Count(Intersect(Row, Row))` PQL queries through the executor with
    the cross-query coalescer (parallel/coalescer.py) enabled — the
    `batch32` context measurement made product code.  Row-id variants
    rotate across queries (distinct leaf stacks per query, one compiled
    shape), so no dispatch can be satisfied by relay memoization, and
    every result is verified against a host-computed expected count.

    Bandwidth accounting credits only each query's own row stack (the
    shared filter's re-reads are not credited), so ``achieved_gbps_lower``
    is a LOWER bound and the >roof memoization flag stays valid.

    The load runs TWICE — query flight recorder enabled (the product
    default) and disabled — so the artifact carries the recorder's
    overhead on this exact coalesced Count path (the <1% budget of the
    observe layer).  The headline coalescer numbers come from the
    recorder-ENABLED run, the shipping configuration.

    Returns (coalescer_extras, observe_extras, devobs_extras,
    perfobs_extras), or None under a non-default shard width (the
    index rows are built for 2^20-column shards)."""
    import tempfile
    import threading

    from pilosa_tpu import stats as _stats
    from pilosa_tpu.models.holder import Holder
    from pilosa_tpu.ops import bitmap as bm
    from pilosa_tpu.parallel.coalescer import Coalescer
    from pilosa_tpu.parallel.executor import Executor
    from pilosa_tpu.shardwidth import SHARD_WIDTH

    if bm.n_words(SHARD_WIDTH) != WORDS:
        return None

    N_VAR = 8
    salts = (np.arange(1, N_VAR + 1, dtype=np.uint64)
             * np.uint64(0x9E3779B9)).astype(np.uint32)
    holder = Holder(tempfile.mkdtemp() + "/bench-co")
    idx = holder.create_index("i")
    f = idx.create_field("f")
    view = f.create_view_if_not_exists("standard")
    for s in range(N_SHARDS):
        frag = view.create_fragment_if_not_exists(s)
        with frag._lock:
            frag._rows[2] = b_np[s].copy()
            for v in range(N_VAR):
                frag._rows[100 + v] = a_np[s] ^ salts[v]
            frag._gen += 1
        f._note_shard(s)
    expects = [int(np.bitwise_count((a_np ^ salts[v]) & b_np)
                   .sum(dtype=np.uint64)) for v in range(N_VAR)]

    ex = Executor(holder)
    stats = _stats.MemStatsClient()
    ex.coalescer = Coalescer(window_s=0.002, max_batch=32,
                             enabled=True, stats=stats)
    # this benchmark measures the coalesced DISPATCH path; with the
    # result cache on, the 8-variant rotation would turn into pure
    # cache hits after one window (bench_resultcache measures that
    # side separately)
    from pilosa_tpu.runtime import resultcache as _resultcache

    _resultcache.cache().enabled = False
    qs = [f"Count(Intersect(Row(f={100 + v}), Row(f=2)))"
          for v in range(N_VAR)]
    for v, q in enumerate(qs):  # warm (stacks + jit) and verify each
        got = int(ex.execute("i", q)[0])
        if got != expects[v]:
            raise AssertionError(
                f"coalescer variant {v} returned {got}, "
                f"expected {expects[v]}")

    THREADS = 16

    def run_load(seconds: float) -> float:
        done = [0] * THREADS
        errs: list = []
        t0 = time.perf_counter()
        stop = t0 + seconds

        def worker(t: int) -> None:
            i = t
            try:
                while time.perf_counter() < stop:
                    v = i % N_VAR
                    got = int(ex.execute("i", qs[v])[0])
                    if got != expects[v]:
                        raise AssertionError(
                            f"coalesced query returned {got}, "
                            f"expected {expects[v]}")
                    i += THREADS
                    done[t] += 1
            except BaseException as e:  # noqa: BLE001 — fail loudly
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(THREADS)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        elapsed = time.perf_counter() - t0
        if errs:
            raise errs[0]
        return sum(done) / elapsed

    # Recorder on/off A/B as INTERLEAVED median windows: a sequential
    # off-then-on pair confounds the delta with load drift on a busy
    # host (observed swings of tens of percent between identical runs,
    # far above any real recorder cost), while medians of alternating
    # short windows see the same ambient load on both sides.  Both
    # phases are PRE-WARMED by a throwaway window first and the side
    # that goes first alternates per iteration — before this, the
    # off-phase always ran first and ate the serving-path warmup
    # (thread ramp, allocator), which made recorder-ON measure FASTER
    # than off (overhead_pct -26.2 in BENCH_r06, a nonsense number).
    ex.recorder.stats = stats
    run_load(0.6)  # warmup window: not recorded on either side
    offs, ons = [], []
    for i in range(3):
        order = ((False, True) if i % 2 == 0 else (True, False))
        for rec_on in order:
            ex.recorder.enabled = rec_on
            (ons if rec_on else offs).append(run_load(0.6))
    ex.recorder.enabled = True
    qps_off = sorted(offs)[1]
    qps_on = sorted(ons)[1]
    # The noise-free overhead figure: the recorder's own begin+publish
    # cost per query (histogram observation included), measured
    # directly — the note_* calls on the hot path are list appends and
    # perf_counter reads, dwarfed by this pair.
    from pilosa_tpu import observe as _observe

    r = _observe.FlightRecorder(stats=_stats.MemStatsClient())
    n_rec = 20000
    t0 = time.perf_counter()
    for _ in range(n_rec):
        r.publish(r.begin("i", "Count(Row(f=1))"))
    record_cost_us = (time.perf_counter() - t0) / n_rec * 1e6

    # Device-runtime telemetry A/B on the same coalesced path (the
    # [observe] devobs budget): interleaved median windows with the
    # observer on (shipping default) vs off, plus the noise-free
    # per-dispatch probe cost measured directly — two _cache_size C
    # calls and a perf_counter pair around a cached jit dispatch.
    from pilosa_tpu import devobs as _devobs

    dv_obs = _devobs.observer()
    dv_offs, dv_ons = [], []
    for _ in range(3):
        dv_obs.enabled = False
        dv_offs.append(run_load(0.6))
        dv_obs.enabled = True
        dv_ons.append(run_load(0.6))
    dv_qps_off = sorted(dv_offs)[1]
    dv_qps_on = sorted(dv_ons)[1]
    import jax.numpy as jnp

    probe_a = jnp.zeros(256, dtype=jnp.uint32)
    wrapped = bm._jit_popcount_and      # devobs-instrumented
    raw = getattr(wrapped, "fn", wrapped)  # the underlying jit
    n_probe = 20000
    wrapped(probe_a, probe_a)  # warm
    t0 = time.perf_counter()
    for _ in range(n_probe):
        wrapped(probe_a, probe_a)
    t_wrapped = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(n_probe):
        raw(probe_a, probe_a)
    t_raw = time.perf_counter() - t0
    probe_cost_us = max(0.0, (t_wrapped - t_raw) / n_probe * 1e6)

    # Engine-observatory A/B on the same coalesced path (the perfobs
    # <1% budget): interleaved median windows with the observatory on
    # (shipping default) vs off, plus the noise-free per-launch cost
    # measured directly — a t0()+sample() bracket over an
    # already-materialized host array (block_until_ready is a no-op,
    # isolating the observatory's own bookkeeping).
    from pilosa_tpu import perfobs as _perfobs

    po_offs, po_ons = [], []
    for _ in range(3):
        _perfobs.configure(enabled_=False)
        po_offs.append(run_load(0.6))
        _perfobs.configure(enabled_=True)
        po_ons.append(run_load(0.6))
    po_qps_off = sorted(po_offs)[1]
    po_qps_on = sorted(po_ons)[1]
    probe_out = np.zeros(64, dtype=np.uint32)
    n_s = 20000
    t0 = time.perf_counter()
    for _ in range(n_s):
        s0 = _perfobs.t0()
        _perfobs.sample("dense", probe_out, s0, nbytes=256)
    sample_cost_us = (time.perf_counter() - t0) / n_s * 1e6
    # drop the probe's synthetic samples so the headline window below
    # owns the measured per-engine summary
    _perfobs.reset_counters()

    # headline run, shipping configuration (recorder on); occupancy
    # must describe the SAME window as the headline qps, so delta the
    # histogram across this run only
    occ0 = dict(stats.snapshot().get("coalescer.batch_occupancy") or {})
    qps = run_load(1.5)
    occ = stats.snapshot().get("coalescer.batch_occupancy") or {}
    occ_sum = occ.get("sum", 0) - occ0.get("sum", 0)
    occ_n = occ.get("count", 0) - occ0.get("count", 0)
    out = {
        "qps": round(qps, 2),
        "threads": THREADS,
        "window_ms": 2.0,
        "queries_per_dispatch_mean": round(occ_sum / max(1, occ_n), 2),
        # each query's own 32 MiB row stack only — lower bound
        "achieved_gbps_lower": round(qps * a_np.nbytes / 1e9, 1),
    }
    obs = {
        "qps_recorder_on": round(qps_on, 2),
        "qps_recorder_off": round(qps_off, 2),
        # the qps A/B is EVIDENCE, not the budget pin: even
        # order-alternated median windows swing by double digits on a
        # busy host (23.58% in BENCH_r10 with a 9us direct cost —
        # three orders of magnitude apart), so the delta mostly
        # measures ambient load, and it reports unclamped under a
        # name that says so
        "ab_overhead_pct_noisy": round(
            (qps_off - qps_on) / qps_off * 100.0, 2),
        # per-query recorder cost measured directly (begin+publish
        # bracket), as a share of the measured per-query service time
        # — THE number the <1% budget is judged on
        "record_cost_us": round(record_cost_us, 2),
        "record_cost_pct_of_query": round(
            record_cost_us / (THREADS / qps * 1e6) * 100.0, 3),
        "budget_pct": 1.0,
        "within_budget": bool(
            record_cost_us / (THREADS / qps * 1e6) * 100.0 < 1.0),
    }
    dv = {
        "qps_devobs_on": round(dv_qps_on, 2),
        "qps_devobs_off": round(dv_qps_off, 2),
        # medians of interleaved windows; negative = within noise
        "overhead_pct": round(
            (dv_qps_off - dv_qps_on) / dv_qps_off * 100.0, 2),
        # per-dispatch probe cost as a share of the measured per-query
        # service time — the number the <1% budget is judged on (one
        # coalesced dispatch serves a whole batch, so the per-QUERY
        # share is smaller still)
        "probe_cost_us": round(probe_cost_us, 3),
        "probe_cost_pct_of_query": round(
            probe_cost_us / (THREADS / qps * 1e6) * 100.0, 3),
        "budget_pct": 1.0,
    }
    po = {
        "qps_perfobs_on": round(po_qps_on, 2),
        "qps_perfobs_off": round(po_qps_off, 2),
        # medians of interleaved windows; negative = within noise
        "overhead_pct": round(
            (po_qps_off - po_qps_on) / po_qps_off * 100.0, 2),
        # per-launch bracket cost as a share of the measured per-query
        # service time — the number the <1% budget is judged on (one
        # coalesced launch serves a whole batch, so the per-QUERY
        # share is smaller still)
        "sample_cost_us": round(sample_cost_us, 3),
        "sample_cost_pct_of_query": round(
            sample_cost_us / (THREADS / qps * 1e6) * 100.0, 3),
        "budget_pct": 1.0,
        # MEASURED per-engine achieved bandwidth over the headline
        # window — the bw_util slice tools/chipcapture.py stamps
        "engines": _perfobs.engine_summary(),
    }
    holder.close()
    _resultcache.cache().enabled = True
    return out, obs, dv, po


def bench_ragged(a_np: np.ndarray, b_np: np.ndarray) -> dict | None:
    """Homogeneous-vs-heterogeneous A/B on the coalesced serving path
    (the ragged-megabatch round): closed-loop concurrent Count
    traffic through the executor, first 8 same-shape variants (the
    pre-ragged best case — every query merges into one fused-program
    launch), then 16 structurally DISTINCT shapes (realistic mixed
    dashboard traffic — pre-ragged this coalesced almost never and
    paid per-query dispatch; with the op-tape interpreter the whole
    mix shares size-class buckets).

    Every completed query is verified against a host-computed expected
    count, and each phase reports p50 latency plus
    ``dispatches_per_query`` (coalescer launches over completed
    queries — the number the engine exists to push toward the batch
    dispatch floor).  Artifact pins: ``pin_2x_ok`` — the mixed-shape
    open-loop p50 stays within 2x of the homogeneous p50 — and
    ``pin_dpq_ok`` — mixed dispatches/query <= 0.25 (>= 4 queries per
    launch on heterogeneous traffic)."""
    import statistics
    import tempfile
    import threading

    from pilosa_tpu import stats as _stats
    from pilosa_tpu.models.holder import Holder
    from pilosa_tpu.ops import bitmap as bm
    from pilosa_tpu.parallel.coalescer import Coalescer
    from pilosa_tpu.parallel.executor import Executor
    from pilosa_tpu.runtime import resultcache as _resultcache
    from pilosa_tpu.shardwidth import SHARD_WIDTH
    from tools.loadgen import shape_mix_queries

    if bm.n_words(SHARD_WIDTH) != WORDS:
        return None

    SH = 64  # shards: real fan-out, bounded host A/B time
    N_VAR = 8
    salts = (np.arange(1, N_VAR + 1, dtype=np.uint64)
             * np.uint64(0x9E3779B9)).astype(np.uint32)
    holder = Holder(tempfile.mkdtemp() + "/bench-rg")
    idx = holder.create_index("i")
    f = idx.create_field("f")
    view = f.create_view_if_not_exists("standard")
    for s in range(SH):
        frag = view.create_fragment_if_not_exists(s)
        with frag._lock:
            # rows 0..5 feed the shape-mix trees; row 2 doubles as the
            # homogeneous filter; 100+v are the same-shape variants
            for r in range(6):
                frag._rows[r] = (
                    a_np[s] ^ np.uint32((r * 0x85EBCA6B) & 0xFFFFFFFF)
                    if r != 2 else b_np[s].copy())
            for v in range(N_VAR):
                frag._rows[100 + v] = a_np[s] ^ salts[v]
            frag._gen += 1
        f._note_shard(s)

    ex = Executor(holder)
    stats = _stats.MemStatsClient()
    # 10ms window (vs the 2ms serving default): the host A/B runs
    # closed-loop with ~100ms flushes, and a 2ms window lets the
    # post-flush re-convergence straggle into under-filled buckets —
    # the wider window costs ~10% of one flush and makes the measured
    # dispatches/query describe batching, not thread wake-up jitter
    ex.coalescer = Coalescer(window_s=0.010, max_batch=32,
                             enabled=True, stats=stats)
    _resultcache.cache().enabled = False

    homo_qs = [f"Count(Intersect(Row(f={100 + v}), Row(f=2)))"
               for v in range(N_VAR)]
    mixed_qs = shape_mix_queries(16, field="f", rows=6)

    def ground_truth(qs):
        ex.fuse_shards = False
        try:
            return [int(ex.execute("i", q)[0]) for q in qs]
        finally:
            ex.fuse_shards = True

    homo_expect = ground_truth(homo_qs)
    mixed_expect = ground_truth(mixed_qs)
    for qs, expects in ((homo_qs, homo_expect),
                        (mixed_qs, mixed_expect)):
        for q, want in zip(qs, expects):  # warm stacks + programs
            got = int(ex.execute("i", q)[0])
            if got != want:
                raise AssertionError(
                    f"ragged bench warm-up mismatch: {q} -> {got}, "
                    f"expected {want}")

    THREADS = 16

    def phase(qs, expects, seconds: float) -> dict:
        lats: list[list[int]] = [[] for _ in range(THREADS)]
        errs: list = []
        d0 = stats.snapshot().get("coalescer.dispatches", 0)
        t0 = time.perf_counter()
        stop = t0 + seconds

        def worker(t: int) -> None:
            i = t
            try:
                while time.perf_counter() < stop:
                    v = i % len(qs)
                    tq = time.perf_counter_ns()
                    got = int(ex.execute("i", qs[v])[0])
                    lats[t].append(time.perf_counter_ns() - tq)
                    if got != expects[v]:
                        raise AssertionError(
                            f"ragged bench returned {got}, expected "
                            f"{expects[v]} for {qs[v]}")
                    i += THREADS
            except BaseException as e:  # noqa: BLE001 — fail loudly
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(THREADS)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        if errs:
            raise errs[0]
        flat = [x for per in lats for x in per]
        done = len(flat)
        dn = stats.snapshot().get("coalescer.dispatches", 0) - d0
        return {
            "p50_us": round(statistics.median(flat) / 1e3, 1),
            "queries": done,
            "qps": round(done / seconds, 1),
            "dispatches_per_query": round(dn / max(1, done), 4),
        }

    try:
        homo = phase(homo_qs, homo_expect, 1.5)
        mixed = phase(mixed_qs, mixed_expect, 1.5)
    finally:
        _resultcache.cache().enabled = True
        holder.close()
    from pilosa_tpu.ops import tape as _tape

    out = {
        "homogeneous_batch32": homo,
        "mixed_16_shapes": mixed,
        "shape_mix": 16,
        "mixed_vs_homogeneous_p50": round(
            mixed["p50_us"] / homo["p50_us"], 2),
        "tape_counters": {k: v for k, v in _tape.counters().items()
                          if v},
        "pin_2x_ok": mixed["p50_us"] <= 2.0 * homo["p50_us"],
        "pin_dpq_ok": mixed["dispatches_per_query"] <= 0.25,
    }
    if not out["pin_2x_ok"]:
        print(f"bench: ragged mixed-shape p50 {mixed['p50_us']:.0f}us "
              f"is NOT within 2x of the homogeneous p50 "
              f"{homo['p50_us']:.0f}us", file=sys.stderr)
    if not out["pin_dpq_ok"]:
        print(f"bench: ragged mixed dispatches/query "
              f"{mixed['dispatches_per_query']} exceeds the 0.25 "
              f"acceptance bound", file=sys.stderr)
    return out


def bench_resultcache(a_np: np.ndarray,
                      b_np: np.ndarray) -> dict | None:
    """Cold/warm A/B of the generation-stamped result cache on the
    coalesced Count path (the acceptance pin of the resultcache
    round): per-query p50 of the UNCACHED fused-dispatch path
    (?nocache semantics — every query stages leaves and launches) vs
    the warm-hit p50 (parse + translate + generation probe, zero
    device work), plus the cache's per-query cost on a 0%-hit-rate
    workload measured directly (probe -> miss -> fill, the exact work
    a never-repeating query stream adds).

    Artifact pins: ``speedup_p50`` must be >= 10 (``pin_10x_ok``), and
    ``miss_overhead_pct_of_query`` must stay under the 1% budget."""
    import statistics
    import tempfile

    from pilosa_tpu.models.holder import Holder
    from pilosa_tpu.ops import bitmap as bm
    from pilosa_tpu.parallel.coalescer import Coalescer
    from pilosa_tpu.parallel.executor import ExecOptions, Executor
    from pilosa_tpu.pql import parse
    from pilosa_tpu.runtime import resultcache
    from pilosa_tpu.shardwidth import SHARD_WIDTH

    if bm.n_words(SHARD_WIDTH) != WORDS:
        return None

    N_VAR = 4
    salts = (np.arange(1, N_VAR + 1, dtype=np.uint64)
             * np.uint64(0x9E3779B9)).astype(np.uint32)
    holder = Holder(tempfile.mkdtemp() + "/bench-rc")
    idx = holder.create_index("i")
    f = idx.create_field("f")
    view = f.create_view_if_not_exists("standard")
    for s in range(N_SHARDS):
        frag = view.create_fragment_if_not_exists(s)
        with frag._lock:
            frag._rows[2] = b_np[s].copy()
            for v in range(N_VAR):
                frag._rows[100 + v] = a_np[s] ^ salts[v]
            frag._gen += 1
        f._note_shard(s)
    expects = [int(np.bitwise_count((a_np ^ salts[v]) & b_np)
                   .sum(dtype=np.uint64)) for v in range(N_VAR)]
    ex = Executor(holder)
    ex.coalescer = Coalescer(window_s=0.002, max_batch=32,
                             enabled="auto")
    resultcache.reset()
    qs = [f"Count(Intersect(Row(f={100 + v}), Row(f=2)))"
          for v in range(N_VAR)]
    nocache = ExecOptions(cache=False)
    for v, q in enumerate(qs):  # warm stacks + jit, verify, fill cache
        for opt in (nocache, None):
            got = int(ex.execute("i", q, opt=opt)[0])
            if got != expects[v]:
                raise AssertionError(
                    f"resultcache variant {v} returned {got}, "
                    f"expected {expects[v]}")

    def p50_us(n: int, run) -> float:
        lats = []
        for i in range(n):
            t0 = time.perf_counter_ns()
            run(i)
            lats.append(time.perf_counter_ns() - t0)
        return statistics.median(lats) / 1e3

    uncached_p50 = p50_us(
        40, lambda i: ex.execute("i", qs[i % N_VAR], opt=nocache))
    warm_p50 = p50_us(2000, lambda i: ex.execute("i", qs[i % N_VAR]))

    # 0%-hit-rate added cost, measured directly: canonical signature +
    # generation capture + key digest + miss lookup + fill — what a
    # never-repeating query stream pays per query on top of execution
    call = parse(qs[0]).calls[0]
    shards_t = tuple(range(N_SHARDS))
    scratch = resultcache.ResultCache()
    n_probe = 2000
    reps = []
    for _ in range(5):  # median-of-5: host timing jitter dominates
        t0 = time.perf_counter()
        for i in range(n_probe):
            rc, key, gens = ex._rc_probe(idx, "count", shards_t, None,
                                         tree=call.children[0])
            # distinct keys, like a never-repeating stream: every get
            # is a genuine miss and every put a genuine fill
            scratch.get((key, i), gens)
            scratch.put((key, i), gens, 1, 32)
        reps.append((time.perf_counter() - t0) / n_probe * 1e6)
        scratch.invalidate_all()
    miss_cost_us = statistics.median(reps)

    out = {
        "uncached_p50_us": round(uncached_p50, 1),
        "warm_hit_p50_us": round(warm_p50, 1),
        "speedup_p50": round(uncached_p50 / warm_p50, 1),
        "pin_10x_ok": uncached_p50 >= 10 * warm_p50,
        "miss_overhead_us": round(miss_cost_us, 2),
        "miss_overhead_pct_of_query": round(
            miss_cost_us / uncached_p50 * 100.0, 3),
        "budget_pct": 1.0,
    }
    if not out["pin_10x_ok"]:
        print(f"bench: resultcache warm-hit p50 {warm_p50:.0f}us is "
              f"NOT >=10x under the uncached path "
              f"{uncached_p50:.0f}us", file=sys.stderr)
    holder.close()
    return out


def bench_ingest(a_np: np.ndarray, b_np: np.ndarray) -> dict | None:
    """Read-under-ingest A/B on the coalesced Count path (the
    streaming-ingest round): p50 of a repeated
    ``Count(Intersect(Row, Row))`` measured in three phases —
    read-only baseline, reads while a writer thread sustains batched
    same-field imports with DELTA PLANES ON (writes land beside the
    base; only compaction bumps the generation, so the queried rows'
    device stacks stay resident), and the same write load with deltas
    OFF (every import bumps the generation: per-read stack rebuild +
    re-upload, the pre-ingest-subsystem behavior).

    Every sampled read is verified bit-exact (the write load touches
    rows the query never reads, so the count is invariant), background
    compactions run mid-phase to exercise the merge-vs-read race, and
    each phase reports the result-cache hit rate over its window.
    Artifact pin: ``pin_2x_ok`` — the delta-path p50 under ingest
    stays within 2x of the read-only baseline (the bench-local analog
    of the loadgen acceptance run's read-p99 bound)."""
    import statistics
    import tempfile
    import threading

    from pilosa_tpu import ingest as _ingest
    from pilosa_tpu.ingest import compactor as _compactor
    from pilosa_tpu.models.holder import Holder
    from pilosa_tpu.ops import bitmap as bm
    from pilosa_tpu.parallel.coalescer import Coalescer
    from pilosa_tpu.parallel.executor import Executor
    from pilosa_tpu.runtime import resultcache
    from pilosa_tpu.shardwidth import SHARD_WIDTH

    if bm.n_words(SHARD_WIDTH) != WORDS:
        return None

    SH = 32  # shards: enough for a real fan-out, small enough to A/B
    holder = Holder(tempfile.mkdtemp() + "/bench-ing")
    idx = holder.create_index("i")
    f = idx.create_field("f")
    view = f.create_view_if_not_exists("standard")
    for s in range(SH):
        frag = view.create_fragment_if_not_exists(s)
        with frag._lock:
            frag._rows[1] = a_np[s].copy()
            frag._rows[2] = b_np[s].copy()
            frag._gen += 1
        f._note_shard(s)
    expect = int(np.bitwise_count(a_np[:SH] & b_np[:SH])
                 .sum(dtype=np.uint64))
    ex = Executor(holder)
    ex.coalescer = Coalescer(window_s=0.002, max_batch=32,
                             enabled="auto")
    q = "Count(Intersect(Row(f=1), Row(f=2)))"
    rng = np.random.default_rng(4242)

    def phase(delta_on: bool, write: bool, seconds: float) -> dict:
        # short compact interval: at the default 2.0s age bound (and
        # 128k-bit threshold vs ~16 bits/fragment/batch here) nothing
        # would be _due() inside a 2s phase and run_once() below would
        # be a no-op — the merge-vs-read race this phase exists to
        # exercise needs age-due fragments mid-phase (reset() in the
        # outer finally restores the defaults)
        _ingest.configure(delta_enabled=delta_on,
                          compact_interval=0.2)
        _compactor.reset()
        resultcache.reset()
        rc0 = resultcache.cache().stats_dict()
        stop = threading.Event()
        bits = [0]

        def writer():
            batch = 0
            while not stop.is_set():
                rows = rng.integers(10, 18, size=512).tolist()
                cols = rng.integers(0, SH * SHARD_WIDTH,
                                    size=512).tolist()
                f.import_bits(rows, cols)
                bits[0] += 512
                batch += 1
                if delta_on and batch % 50 == 0:
                    # background merge racing the reads (what the
                    # compactor thread does in production)
                    _compactor.compactor().run_once()
                time.sleep(0.001)

        t = threading.Thread(target=writer, daemon=True)
        if write:
            t.start()
        lats = []
        end = time.perf_counter() + seconds
        while time.perf_counter() < end:
            t0 = time.perf_counter_ns()
            got = int(ex.execute("i", q)[0])
            lats.append(time.perf_counter_ns() - t0)
            if got != expect:
                stop.set()
                raise AssertionError(
                    f"ingest A/B bit-exactness violated: {got} != "
                    f"{expect} (delta_on={delta_on})")
        stop.set()
        if write:
            t.join(timeout=10)
        merged = f.flush_deltas()
        if int(ex.execute("i", q)[0]) != expect:
            raise AssertionError("post-flush count diverged")
        rc1 = resultcache.cache().stats_dict()
        dh = rc1["hits"] - rc0["hits"]
        dm = rc1["misses"] - rc0["misses"]
        elapsed_bits = bits[0] / seconds
        return {
            "p50_us": round(statistics.median(lats) / 1e3, 1),
            "reads": len(lats),
            "ingest_bits_per_s": round(elapsed_bits, 0),
            "cache_hit_rate": round(dh / (dh + dm), 3)
            if dh + dm else None,
            "flushed_bits": merged,
            # proof the merge-vs-read race actually ran mid-phase
            "compactions": _compactor.compactor().compactions,
        }

    try:
        read_only = phase(True, write=False, seconds=1.0)
        under_delta = phase(True, write=True, seconds=2.0)
        under_base = phase(False, write=True, seconds=2.0)
    finally:
        _ingest.reset()
        _compactor.reset()
        holder.close()
    out = {
        "read_only": read_only,
        "under_ingest_delta": under_delta,
        "under_ingest_base": under_base,
        "delta_vs_readonly": round(
            under_delta["p50_us"] / read_only["p50_us"], 2),
        "base_vs_readonly": round(
            under_base["p50_us"] / read_only["p50_us"], 2),
        "pin_2x_ok": under_delta["p50_us"]
        <= 2.0 * read_only["p50_us"],
    }
    if not out["pin_2x_ok"]:
        print(f"bench: ingest read-under-write p50 "
              f"{under_delta['p50_us']:.0f}us is NOT within 2x of the "
              f"read-only baseline {read_only['p50_us']:.0f}us",
              file=sys.stderr)
    return out


def bench_containers() -> dict | None:
    """Sparse/dense A/B of the compressed container-directory engine
    (ops/containers.py — the roaring-on-TPU representation change):

    - builds a ≤1%-fill CLUSTERED synthetic index (each row's bits
      confined to 2 of the 16 containers per shard — the shape real
      sparse bitmap rows take, and exactly what roaring's container
      specialization exists for) plus a dense ~50%-fill control,
    - measures the same Count(Intersect(...)) workload with the
      engine enabled vs disabled (``[containers] enabled`` — disabled
      IS the pre-container dense fused path, byte-identical),
    - reports resident device bytes both ways (dense stacks vs pooled
      container blocks, from the residency manager's kind split) and
      the achieved streaming rates, every sample verified against a
      host-computed expected count,
    - adds an ultra-sparse (~0.1% fill) leg A/Bing the per-kind pools
      (``[containers] kinds``) against the dense-kind compressed path
      — the array-kind capacity pin (>=5x lower resident bytes) plus
      a kinds-dispatch no-regression qps pin on the 1%-fill leg.

    Returns None under a non-default shard width (the container
    geometry assumes 2^20-column shards here).  CPU-fallback numbers
    are acceptable for the artifact; the chip capture slot rides the
    main JSON line like every other extras phase."""
    import tempfile

    from pilosa_tpu.models.holder import Holder
    from pilosa_tpu.ops import bitmap as bm
    from pilosa_tpu.ops import containers as ct
    from pilosa_tpu.parallel.executor import Executor
    from pilosa_tpu.runtime import residency
    from pilosa_tpu.shardwidth import SHARD_WIDTH

    if bm.n_words(SHARD_WIDTH) != WORDS:
        return None
    CT_SHARDS = 32
    FILL = 0.01
    bits_per_row = int(FILL * SHARD_WIDTH)      # ~10.5k bits/shard-row
    rng = np.random.default_rng(12348)
    holder = Holder(tempfile.mkdtemp() + "/bench-ct")
    idx = holder.create_index("i")
    f = idx.create_field("f")
    view = f.create_view_if_not_exists("standard")
    # ~0.09% fill, sized so per-container cardinality (~460) sits
    # under the 512 pow2 size class — device array-pool rows pad to
    # powers of two, and a card just past a boundary doubles the row
    us_bits = 920
    FILL_US = us_bits / SHARD_WIDTH
    truth: dict[int, set] = {10: set(), 11: set(),
                             20: set(), 21: set()}
    for s in range(CT_SHARDS):
        frag = view.create_fragment_if_not_exists(s)
        # clustered: all bits inside containers 0-1 (128Ki bits); the
        # shared half is drawn ONCE per shard so rows 10 and 11 really
        # intersect in ~bits_per_row/2 positions (drawing it inside
        # the row loop made the sets independent and the measured
        # intersection mostly random overlap)
        shared = rng.choice(1 << 17, size=bits_per_row // 2,
                            replace=False)
        for r in (10, 11):
            own = rng.choice(1 << 17, size=bits_per_row // 2,
                             replace=False)
            pos = np.unique(np.concatenate([shared, own]))
            frag.import_positions((r * SHARD_WIDTH + pos)
                                  .astype(np.uint64))
            truth[r].update((s * SHARD_WIDTH + pos).tolist())
        # ultra-sparse rows (~0.1% fill, same clustering): each
        # non-empty container holds a few hundred bits — exactly the
        # array-kind regime the per-kind pools exist for
        us_shared = rng.choice(1 << 17, size=us_bits // 2,
                               replace=False)
        for r in (20, 21):
            own = rng.choice(1 << 17, size=us_bits // 2,
                             replace=False)
            pos = np.unique(np.concatenate([us_shared, own]))
            frag.import_positions((r * SHARD_WIDTH + pos)
                                  .astype(np.uint64))
            truth[r].update((s * SHARD_WIDTH + pos).tolist())
        f._note_shard(s)
    ex = Executor(holder)
    from pilosa_tpu.runtime import resultcache as _resultcache

    rc_was = _resultcache.cache().enabled
    ct.retain()  # baseline-snapshot the [containers] config we flip
    _resultcache.cache().enabled = False  # measure the dispatch path
    q = "Count(Intersect(Row(f=10), Row(f=11)))"
    expect = len(truth[10] & truth[11])
    q_us = "Count(Intersect(Row(f=20), Row(f=21)))"
    expect_us = len(truth[20] & truth[21])

    def timed(seconds: float, query: str = q,
              want: int | None = None) -> float:
        want = expect if want is None else want
        got = int(ex.execute("i", query)[0])  # warm + verify
        if got != want:
            raise AssertionError(f"containers bench: {got} != {want}")
        n = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < seconds:
            if int(ex.execute("i", query)[0]) != want:
                raise AssertionError("containers bench: drift mid-run")
            n += 1
        return n / (time.perf_counter() - t0)

    try:
        ct.configure(enabled=True, kinds=True)
        ct.reset_counters()
        qps_compressed = timed(1.0)
        gathered = ct.counters()["container.containers_gathered"]
        queries = max(1, ct.counters()["container.queries"])
        # THIS workload's pooled leaves, not the process-wide kind
        # split (earlier bench phases leave their own residency
        # behind); the /debug/devices residency.kinds gauge carries
        # the live total
        compressed_bytes = sum(
            f.device_container_leaf(r, tuple(range(CT_SHARDS))).nbytes
            for r in (10, 11))
        assert (residency.manager().stats().get("kinds") or {}).get(
            "compressed", 0) >= compressed_bytes
        # ultra-sparse leg (~0.1% fill): per-kind pools vs the
        # dense-kind compressed path (kinds=false — every non-empty
        # container a full 2048-word block).  The bytes ratio is the
        # array-kind capacity story; the 1%-leg qps pin below guards
        # against the kinds dispatch costing throughput
        qps_us_kinds = timed(1.0, q_us, expect_us)
        us_kinds_bytes = sum(
            f.device_container_leaf(r, tuple(range(CT_SHARDS))).nbytes
            for r in (20, 21))
        ct.configure(kinds=False)
        qps_nokinds = timed(1.0)           # 1%-fill leg, kinds off
        qps_us_nokinds = timed(1.0, q_us, expect_us)
        us_nokinds_bytes = sum(
            f.device_container_leaf(r, tuple(range(CT_SHARDS))).nbytes
            for r in (20, 21))
        ct.configure(kinds=True)
        ct.configure(enabled=False)
        qps_dense = timed(1.0)
    finally:
        # restore the pre-bench [containers] baseline and the result
        # cache, and close the holder, no matter which phase raised
        ct.release()
        _resultcache.cache().enabled = rc_was
        holder.close()
    # dense layout residency for the same two leaves: 2 row stacks of
    # [shards, words] uint32
    dense_bytes = 2 * CT_SHARDS * WORDS * 4
    per_query_compressed = gathered / queries * ct.CWORDS * 4
    out = {
        "fill": FILL,
        "shards": CT_SHARDS,
        "qps_compressed": round(qps_compressed, 2),
        "qps_dense": round(qps_dense, 2),
        "speedup": round(qps_compressed / qps_dense, 2),
        "resident_bytes_dense": dense_bytes,
        "resident_bytes_compressed": compressed_bytes,
        "bytes_ratio": round(dense_bytes / max(1, compressed_bytes), 1),
        # bytes the compressed launch actually streams per query vs
        # the dense layout's full-stack read
        "achieved_gbps_compressed": round(
            qps_compressed * per_query_compressed / 1e9, 2),
        "achieved_gbps_dense": round(
            qps_dense * dense_bytes / 1e9, 2),
        # acceptance pins: >=4x lower resident bytes at <=1% fill, and
        # the sparse workload at least matching the dense path
        "pin_bytes_ok": dense_bytes >= 4 * max(1, compressed_bytes),
        "pin_qps_ok": qps_compressed >= 0.95 * qps_dense,
        # ---- per-kind pools (ultra-sparse ~0.1% fill leg) ----
        "ultra_sparse": {
            "fill": FILL_US,
            "qps_kinds": round(qps_us_kinds, 2),
            "qps_nokinds": round(qps_us_nokinds, 2),
            "resident_bytes_kinds": us_kinds_bytes,
            "resident_bytes_nokinds": us_nokinds_bytes,
            "bytes_ratio": round(
                us_nokinds_bytes / max(1, us_kinds_bytes), 1),
            # acceptance pins: array/run pools >=5x smaller than the
            # dense-kind compressed path at ~0.1% fill, and kinds
            # dispatch not costing throughput on the 1%-fill leg
            "pin_bytes_ok": us_nokinds_bytes >= 5 * max(
                1, us_kinds_bytes),
            "pin_qps_ok": qps_compressed >= 0.95 * qps_nokinds,
            "qps_1pct_kinds": round(qps_compressed, 2),
            "qps_1pct_nokinds": round(qps_nokinds, 2),
        },
    }
    return out


def bench_vm() -> dict | None:
    """Bitmap-VM A/B (ops/pallas_kernels.vm_counts + the coalescer's
    "vm" buckets): the SAME heterogeneous 16-distinct-shape sparse
    Count mix served closed-loop through the coalescer twice — once
    with the VM routing eligible buckets through the one
    scalar-prefetch kernel over compressed container pools, once with
    ``?novm`` semantics (the pre-VM engines: dense gather + the XLA
    tape interpreter).  Every completed query is verified against a
    host-computed expected count.

    The reported pin is the no-regression floor ``pin_vm_qps_ok``
    (vm qps >= 0.9x the pre-VM path on this host); the chip target —
    beat the XLA route's committed 1801 qps / 0.148 bw_util capture —
    rides the chip-capture slot (tools/chipcapture.py)."""
    import statistics
    import tempfile
    import threading

    from pilosa_tpu import stats as _stats
    from pilosa_tpu.models.holder import Holder
    from pilosa_tpu.ops import bitmap as bm
    from pilosa_tpu.ops import containers as ct
    from pilosa_tpu.ops import tape as _tape
    from pilosa_tpu.parallel.coalescer import Coalescer
    from pilosa_tpu.parallel.executor import ExecOptions, Executor
    from pilosa_tpu.runtime import resultcache as _resultcache
    from pilosa_tpu.shardwidth import SHARD_WIDTH
    from tools.loadgen import shape_mix_queries

    if bm.n_words(SHARD_WIDTH) != WORDS:
        return None
    VM_SHARDS = 32
    FILL = 0.01
    bits_per_row = int(FILL * SHARD_WIDTH)
    rng = np.random.default_rng(12350)
    holder = Holder(tempfile.mkdtemp() + "/bench-vm")
    idx = holder.create_index("i")
    f = idx.create_field("f")
    view = f.create_view_if_not_exists("standard")
    exist: dict[int, set] = {}
    for s in range(VM_SHARDS):
        frag = view.create_fragment_if_not_exists(s)
        # clustered sparsity: each row's bits confined to the first
        # two containers (the roaring-shaped rows the VM gathers)
        for r in range(6):
            pos = np.unique(rng.choice(
                1 << 17, size=bits_per_row, replace=False))
            frag.import_positions(
                (r * SHARD_WIDTH + pos).astype(np.uint64))
            exist.setdefault(s, set()).update(pos.tolist())
        f._note_shard(s)
    for s, cols in exist.items():
        arr = np.fromiter(cols, dtype=np.int64) + s * SHARD_WIDTH
        idx.import_existence(arr)
    ex = Executor(holder)
    stats = _stats.MemStatsClient()
    ex.coalescer = Coalescer(window_s=0.010, max_batch=32,
                             enabled=True, stats=stats)
    rc_was = _resultcache.cache().enabled
    _resultcache.cache().enabled = False
    qs = shape_mix_queries(16, field="f", rows=6)
    # mesh off in both legs: the VM is a single-device kernel, and the
    # A/B must differ only in the ?novm bit
    vm_on = ExecOptions(mesh=False)
    vm_off = ExecOptions(mesh=False, vm=False)

    def ground_truth(q):
        ex.fuse_shards = False
        try:
            return int(ex.execute("i", q)[0])
        finally:
            ex.fuse_shards = True

    expects = [ground_truth(q) for q in qs]
    THREADS = 16

    def phase(opt, seconds: float) -> dict:
        for q, want in zip(qs, expects):  # warm + verify
            got = int(ex.execute("i", q, opt=opt)[0])
            if got != want:
                raise AssertionError(
                    f"vm bench warm-up mismatch: {q} -> {got}, "
                    f"expected {want}")
        lats: list[list[int]] = [[] for _ in range(THREADS)]
        errs: list = []
        t0 = time.perf_counter()
        stop = t0 + seconds

        def worker(t: int) -> None:
            i = t
            try:
                while time.perf_counter() < stop:
                    v = i % len(qs)
                    tq = time.perf_counter_ns()
                    got = int(ex.execute("i", qs[v], opt=opt)[0])
                    lats[t].append(time.perf_counter_ns() - tq)
                    if got != expects[v]:
                        raise AssertionError(
                            f"vm bench returned {got}, expected "
                            f"{expects[v]} for {qs[v]}")
                    i += THREADS
            except BaseException as e:  # noqa: BLE001 — fail loudly
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(THREADS)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        if errs:
            raise errs[0]
        flat = [x for per in lats for x in per]
        return {
            "p50_us": round(statistics.median(flat) / 1e3, 1),
            "queries": len(flat),
            "qps": round(len(flat) / seconds, 1),
        }

    try:
        c0 = dict(_tape.counters())
        with_vm = phase(vm_on, 1.2)
        c1 = dict(_tape.counters())
        without = phase(vm_off, 1.2)
        c2 = dict(_tape.counters())
    finally:
        _resultcache.cache().enabled = rc_was
        holder.close()
    vm_q = c1["vm.queries"] - c0["vm.queries"]
    vm_x = c1["vm.executions"] - c0["vm.executions"]
    out = {
        "shape_mix": 16,
        "fill": FILL,
        "shards": VM_SHARDS,
        "vm": with_vm,
        "novm": without,
        "speedup": round(with_vm["qps"] / max(1.0, without["qps"]), 2),
        "vm_queries": vm_q,
        "vm_executions": vm_x,
        "vm_queries_per_launch": round(vm_q / max(1, vm_x), 2),
        # the pre-VM engines must stay off the VM leg's counters and
        # vice versa: the off leg's executions delta is the evidence
        "novm_leaked_vm_launches": c2["vm.executions"]
        - c1["vm.executions"],
        "pin_vm_qps_ok": with_vm["qps"] >= 0.9 * without["qps"],
    }
    if not out["pin_vm_qps_ok"]:
        print(f"bench: bitmap-VM qps {with_vm['qps']:.0f} fell below "
              f"0.9x the pre-VM path {without['qps']:.0f}",
              file=sys.stderr)
    return out


def bench_residency() -> dict | None:
    """Tiered-residency A/B (runtime/residency.py): the same zipfian
    Count mix measured (a) fully resident — HBM budget far above the
    working set, (b) at a 4x-over-budget working set with the
    predictive prefetcher OFF, and (c) 4x over budget with it ON.

    The pinned number is the STALL RATE: the fraction of queries whose
    flight record shows any non-HBM stack access (an async-promotion
    wait, a host-compute fallback, or a cold rebuild).  Fully resident
    it is ~0 after warmup by construction; at 4x the tier machinery
    absorbs the overflow, and the prefetcher must strictly reduce it
    on the zipfian mix (the hot head gets promoted ahead of demand) —
    ``pin_prefetch_ok``.  Every sample is verified against the
    imported truth (one bit per shard per row -> count == shards)."""
    from pilosa_tpu import observe
    from pilosa_tpu.models.holder import Holder
    from pilosa_tpu.ops import bitmap as bm
    from pilosa_tpu.parallel.executor import ExecOptions, Executor
    from pilosa_tpu.runtime import residency
    from pilosa_tpu.runtime.prefetch import Prefetcher
    from pilosa_tpu.shardwidth import SHARD_WIDTH

    SHARDS = 8
    stack_bytes = SHARDS * bm.n_words(SHARD_WIDTH) * 4
    budget = 8 * stack_bytes + (64 << 10)   # ~8 resident row stacks
    n_rows = 32                              # 4x the budget
    rng = np.random.default_rng(12349)
    holder = Holder(None)
    idx = holder.create_index("i")
    f = idx.create_field("f")
    for row in range(n_rows):
        cols = np.arange(SHARDS, dtype=np.int64) * SHARD_WIDTH + row
        f.import_bits(np.full(SHARDS, row), cols)
    ex = Executor(holder)
    # zipfian row schedule, fixed across all three legs
    weights = [1.0 / (r + 1) ** 1.2 for r in range(n_rows)]
    zrng = np.random.default_rng(4242)
    schedule = zrng.choice(n_rows, size=4096,
                           p=np.array(weights) / sum(weights))

    def leg(seconds: float) -> dict:
        n = 0
        stalled = 0
        stall_ms = 0.0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < seconds:
            row = int(schedule[n % len(schedule)])
            got = ex.execute(
                "i", f"Count(Row(f={row}))",
                opt=ExecOptions(cache=False, containers=False))[0]
            if got != SHARDS:
                raise AssertionError(
                    f"residency bench: row {row}: {got} != {SHARDS}")
            rec = observe.take_last()
            tier = (rec.to_dict().get("tier") or {}) if rec else {}
            if (tier.get("promoted", 0) or tier.get("fallback", 0)
                    or tier.get("cold", 0)):
                stalled += 1
                stall_ms += tier.get("stallMs", 0.0)
            n += 1
        dt = time.perf_counter() - t0
        return {"qps": round(n / dt, 2), "queries": n,
                "stall_rate": round(stalled / max(1, n), 4),
                "stall_ms_total": round(stall_ms, 1)}

    def fresh_manager(hbm: int) -> None:
        # a reset ORPHANS entries still sitting in the field's stack
        # cache (they keep hitting, untracked — hiding the budget);
        # clear the owner dicts so every leg restages under its own
        # budget from a cold start
        residency.reset(hbm)
        residency.configure(host_budget_bytes=1 << 30, prefetch=False)
        with f._lock:
            f._row_stack_cache.clear()
            f._matrix_stack_cache.clear()

    try:
        # (a) fully resident
        fresh_manager(64 * stack_bytes)
        leg(0.5)  # warm
        resident = leg(1.0)
        # (b) 4x working set, prefetch off
        fresh_manager(budget)
        leg(1.0)  # populate + demote into steady churn
        off = leg(2.0)
        # (c) 4x working set, prefetch on (same demoted steady state)
        residency.configure(prefetch=True, prefetch_interval=0.005)
        pf = Prefetcher()
        pf.start()
        try:
            leg(1.0)
            on = leg(2.0)
        finally:
            pf.stop()
    finally:
        residency.reset()
        holder.close()
    return {
        "shards": SHARDS,
        "rows": n_rows,
        "budget_bytes": budget,
        "working_set_bytes": n_rows * stack_bytes,
        "working_set_factor": round(n_rows * stack_bytes / budget, 2),
        "resident": resident,
        "overbudget_prefetch_off": off,
        "overbudget_prefetch_on": on,
        # acceptance pins: the prefetcher strictly reduces the stall
        # rate on the zipfian mix, and the fully-resident control is
        # (near-)stall-free after warmup
        "pin_prefetch_ok": on["stall_rate"] < off["stall_rate"],
        "pin_resident_ok": resident["stall_rate"] <= 0.01,
    }


def bench_admission(coalescer_extras: dict | None) -> dict:
    """Admission-layer overhead on the uncontended serving path: the
    gate's acquire+release pair is what every admitted request pays on
    top of execution, so its cost must stay under 1% of the coalesced
    Count path's per-query service time (the [admission] budget).
    Measured directly (one thread, free slots — the uncontended case);
    ``pct_of_query`` is computed against the coalescer benchmark's
    measured per-query time when that ran."""
    from pilosa_tpu import stats as _stats
    from pilosa_tpu.serve.admission import AdmissionController

    ctrl = AdmissionController(stats=_stats.MemStatsClient())
    n = 20000
    ctrl.acquire("query").release()  # warm (lock, stats path)
    t0 = time.perf_counter()
    for _ in range(n):
        ctrl.acquire("query").release()
    cost_us = (time.perf_counter() - t0) / n * 1e6
    out = {"acquire_release_us": round(cost_us, 3), "budget_pct": 1.0}
    if coalescer_extras and coalescer_extras.get("qps"):
        per_query_us = (coalescer_extras.get("threads", 16)
                        / coalescer_extras["qps"] * 1e6)
        out["pct_of_query"] = round(cost_us / per_query_us * 100.0, 3)
    return out


def bench_tenants(coalescer_extras: dict | None) -> dict:
    """[tenants] isolation cost + effect.

    Two measurements: (1) the UNCONTENDED acquire+release pair with
    isolation off vs on — the per-request tax every admitted request
    pays, held to the same <1% budget as the admission/observe gates;
    (2) an abusive-mix A/B at the controller — one tenant flooding
    from 12 threads against a 2-thread victim on a 4-slot class, with
    isolation off vs on — reporting the victim's queue-wait p99 both
    ways (the isolation contract: the victim's wait must not degrade
    with isolation ON vs OFF while the abuser floods)."""
    import threading

    from pilosa_tpu import stats as _stats
    from pilosa_tpu.serve import tenant as _tenant
    from pilosa_tpu.serve.admission import AdmissionController

    out: dict = {"budget_pct": 1.0}
    try:
        n = 20000
        _tenant.reset()
        ctrl = AdmissionController(stats=_stats.MemStatsClient())
        ctrl.acquire("query", tenant="t0").release()  # warm
        t0 = time.perf_counter()
        for _ in range(n):
            ctrl.acquire("query", tenant="t0").release()
        off_us = (time.perf_counter() - t0) / n * 1e6
        _tenant.configure(enabled=True,
                          quotas={"t0": {"share": 8, "queue": 32}})
        ctrl.acquire("query", tenant="t0").release()  # warm tenant path
        t0 = time.perf_counter()
        for _ in range(n):
            ctrl.acquire("query", tenant="t0").release()
        on_us = (time.perf_counter() - t0) / n * 1e6
        out["acquire_release_us_off"] = round(off_us, 3)
        out["acquire_release_us_on"] = round(on_us, 3)
        out["added_us"] = round(on_us - off_us, 3)
        if coalescer_extras and coalescer_extras.get("qps"):
            per_query_us = (coalescer_extras.get("threads", 16)
                            / coalescer_extras["qps"] * 1e6)
            out["pct_of_query"] = round(
                max(0.0, on_us - off_us) / per_query_us * 100.0, 3)

        def abusive(iso: bool) -> dict:
            _tenant.reset()
            if iso:
                _tenant.configure(
                    enabled=True, default_share=1, default_queue=8,
                    quotas={"victim": {"share": 3, "queue": 32},
                            "abuser": {"share": 1, "queue": 64}})
            c = AdmissionController(query_cap=4, query_queue=128,
                                    stats=_stats.MemStatsClient())
            waits: dict = {"victim": [], "abuser": []}
            shed = {"victim": 0, "abuser": 0}
            lock = threading.Lock()
            stop = time.perf_counter() + 0.75

            def client(name: str):
                from pilosa_tpu.serve.admission import ShedError

                while time.perf_counter() < stop:
                    try:
                        tk = c.acquire("query", tenant=name)
                    except ShedError:
                        with lock:
                            shed[name] += 1
                        time.sleep(0.001)
                        continue
                    with lock:
                        waits[name].append(tk.queue_wait_ns / 1e6)
                    time.sleep(0.002)  # simulated service time
                    tk.release()

            threads = ([threading.Thread(target=client,
                                         args=("abuser",))
                        for _ in range(12)]
                       + [threading.Thread(target=client,
                                           args=("victim",))
                          for _ in range(2)])
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            vw = sorted(waits["victim"])
            return {
                "victim_ok": len(vw),
                "victim_wait_p99_ms": round(
                    vw[int(0.99 * (len(vw) - 1))] if vw else 0.0, 3),
                "victim_shed": shed["victim"],
                "abuser_ok": len(waits["abuser"]),
                "abuser_shed": shed["abuser"],
            }

        iso_on = abusive(True)
        iso_off = abusive(False)
        out["abusive"] = {
            "isolation_on": iso_on,
            "isolation_off": iso_off,
            # the isolation contract (with margin for scheduler noise)
            "pin_isolation_ok": (
                iso_on["victim_wait_p99_ms"]
                <= max(1.0, 1.5 * iso_off["victim_wait_p99_ms"])),
        }
    finally:
        from pilosa_tpu.serve import tenant as _tenant2

        _tenant2.reset()
    return out


def verify_product_path(a_np: np.ndarray, b_np: np.ndarray,
                        expect: int) -> None:
    """Bit-exactness of the REAL path: the PQL string through the
    executor's fused pipeline must produce the identical count."""
    import tempfile

    from pilosa_tpu.models.holder import Holder
    from pilosa_tpu.ops import bitmap as bm
    from pilosa_tpu.parallel.executor import Executor
    from pilosa_tpu.shardwidth import SHARD_WIDTH

    if bm.n_words(SHARD_WIDTH) != WORDS:
        # benchmark rows are built for the default 2^20-column shards;
        # with a non-default PILOSA_TPU_SHARD_WIDTH_EXP the kernel
        # benchmark above is still valid, so just skip this check
        return

    holder = Holder(tempfile.mkdtemp() + "/bench")
    idx = holder.create_index("i")
    f = idx.create_field("f")
    view = f.create_view_if_not_exists("standard")
    for s in range(N_SHARDS):
        frag = view.create_fragment_if_not_exists(s)
        with frag._lock:
            frag._rows[1] = a_np[s].copy()
            frag._rows[2] = b_np[s].copy()
            frag._gen += 1
        f._note_shard(s)
    ex = Executor(holder)
    got = int(ex.execute("i", "Count(Intersect(Row(f=1), Row(f=2)))")[0])
    assert got == expect, f"product path mismatch: {got} != {expect}"


def bench_cpu_baseline(a: np.ndarray, b: np.ndarray) -> tuple[float, int]:
    """Serial per-shard AND+popcount, mirroring the reference's single-node
    map-reduce over shards (executor.go:2561 worker loop, one shard at a
    time per worker; we grant the baseline full vectorization per shard)."""
    def query() -> int:
        total = 0
        for s in range(a.shape[0]):
            total += int(np.bitwise_count(a[s] & b[s]).sum(dtype=np.uint64))
        return total

    expect = query()  # warm-up / page-in
    # Best-of-3 minimum-duration loops: the baseline is the denominator
    # of vs_baseline, so noise here swings the headline ratio harder
    # than device noise does.  Taking the BEST repeat is deliberately
    # conservative — it credits the CPU with its least-interrupted run.
    best = 0.0
    for _ in range(3):
        iters = 0
        t0 = time.perf_counter()
        while iters < 3 or time.perf_counter() - t0 < 1.0:
            query()
            iters += 1
        best = max(best, iters / (time.perf_counter() - t0))
    return best, expect


def _peak_gbps(platform: str) -> float | None:
    if platform not in _CHIP_PLATFORMS:
        return None
    import jax

    kind = (jax.devices()[0].device_kind or "").lower().replace(" ", "")
    for gen, peak in _PEAK_GBPS.items():
        if gen in kind:
            return peak
    return None


def _last_chip_capture():
    """The newest committed on-chip bench capture, or None.  Attached
    (clearly labeled) when THIS run had to fall back to the CPU host,
    so a round-end artifact taken during a relay outage still points
    at the repo's real chip evidence instead of reading as a
    regression.  Never substitutes for the current run's numbers."""
    import glob
    import os

    caps = sorted(glob.glob(os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "tools", "tpu_captures", "bench_*.json")))
    for path in reversed(caps):
        rec = None
        try:
            with open(path, errors="replace") as fh:
                # capture files can carry runtime-warning lines around
                # the JSON (the watcher records stdout verbatim) — take
                # the last line that parses as a JSON object
                for line in fh:
                    line = line.strip()
                    if line.startswith("{"):
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            continue
        except OSError:
            continue
        if rec and rec.get("platform") in _CHIP_PLATFORMS:
            rec["captured"] = os.path.basename(path)[6:-5]
            return rec
    return None


def bench_mesh() -> dict | None:
    """Mesh-native execution A/B (parallel/meshexec.py): the batch32
    coalesced-path workload on a 4-device CPU mesh, shard_map program
    vs the identical single-device program, every sampled batch
    host-verified.  Runs in a SUBPROCESS with its own virtual 4-device
    CPU backend — the device count is fixed at backend init, and this
    process's backend is whatever the chip probe chose — via
    tools/multichip.py, so the bench capture and the MULTICHIP_r*
    capture share one measurement path.  The pin: mesh qps >= the
    single-device path on the same workload (no-regression floor)."""
    import os
    import subprocess

    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the child sets its own device count
    env["JAX_PLATFORMS"] = "cpu"
    try:
        out = subprocess.run(
            [sys.executable, "-m", "tools.multichip", "--devices", "4",
             "--skip-dryrun", "--seconds", "2.0"],
            capture_output=True, text=True, timeout=900, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)))
    except Exception as e:  # noqa: BLE001 — bench keeps going
        print(f"bench: mesh A/B skipped: {e}", file=sys.stderr)
        return None
    if out.returncode != 0:
        print(f"bench: mesh A/B failed rc={out.returncode}: "
              f"{out.stderr[-400:]!r}", file=sys.stderr)
        return None
    try:
        body = json.loads(out.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError) as e:
        print(f"bench: mesh A/B unparseable: {e}", file=sys.stderr)
        return None
    m = body["mesh"]
    m["pin_no_regression_ok"] = (
        m["scaling_vs_single"] is not None
        and m["scaling_vs_single"] >= 1.0)
    return m


def bench_faultinject() -> dict:
    """Disarmed-failpoint A/B (the chaos round's <1% budget, same
    discipline as extras.observe/devobs): the per-site disarmed cost
    is one module-bool read — measured directly against an empty-body
    baseline loop, and expressed against the ~20 us dispatch floor the
    serving path is built around.  Armed-pass cost is also reported
    (registry lock + dict probe) for context; it is off the shipping
    path by definition."""
    import time

    from pilosa_tpu import faultinject as fi

    n = 200000

    def loop(body) -> float:
        t0 = time.perf_counter()
        for _ in range(n):
            body()
        return (time.perf_counter() - t0) / n * 1e9  # ns/op

    def disarmed():
        if fi.armed:
            fi.hit("device.dispatch")

    fi.disarm()
    base_ns = loop(lambda: None)
    off_ns = loop(disarmed)
    fi.arm("device.dispatch=delay(0)@1000000000")  # armed, never fires
    try:
        on_ns = loop(disarmed)
    finally:
        fi.disarm()
    gate_ns = max(0.0, off_ns - base_ns)
    return {
        "disarmed_gate_ns": round(gate_ns, 2),
        "armed_pass_ns": round(max(0.0, on_ns - base_ns), 2),
        # share of the 20 us trivial-dispatch floor (VERDICT round 5)
        # — the budget the acceptance criterion pins
        "disarmed_pct_of_dispatch_floor": round(
            gate_ns / 20_000 * 100.0, 4),
        "budget_pct": 1.0,
    }


def bench_traceasm() -> dict:
    """Disarmed event-journal A/B (the autopsy round's <1% budget,
    same discipline as extras.faultinject): the per-site disarmed
    cost is one module-bool read, measured against an empty-body
    baseline loop and expressed against the ~20 us dispatch floor.
    The armed-emit cost (lock + ring append) is reported for context
    — it is paid only at state transitions (breaker flips, hedge
    fires), never per query on the coalesced Count path."""
    import time

    from pilosa_tpu import observe as obs

    n = 200000

    def loop(body) -> float:
        t0 = time.perf_counter()
        for _ in range(n):
            body()
        return (time.perf_counter() - t0) / n * 1e9  # ns/op

    def gated():
        if obs.journal_on:
            pass

    obs.retain()
    try:
        obs.configure(enabled=False)
        base_ns = loop(lambda: None)
        off_ns = loop(gated)
        obs.configure(enabled=True)
        emit_ns = loop(lambda: obs.emit("bench.tick"))
    finally:
        obs.release()  # restores the pre-bench journal baseline
        obs.reset_journal()
    gate_ns = max(0.0, off_ns - base_ns)
    return {
        "disarmed_gate_ns": round(gate_ns, 2),
        "armed_emit_ns": round(max(0.0, emit_ns - base_ns), 2),
        # share of the 20 us trivial-dispatch floor — the budget the
        # acceptance criterion pins (<1% on the coalesced Count path)
        "disarmed_pct_of_dispatch_floor": round(
            gate_ns / 20_000 * 100.0, 4),
        "budget_pct": 1.0,
    }


def main():
    import os

    # tools/chipcapture.py --profile: bracket the whole bench with a
    # device trace (the capture must come from THIS process — the
    # harness wrapping the subprocess would trace nothing)
    prof_dir = os.environ.get("PILOSA_TPU_BENCH_PROFILE")
    prof_info = None
    if prof_dir:
        from pilosa_tpu import perfobs as _perfobs

        try:
            prof_info = _perfobs.profiler_start(prof_dir, max_seconds=0)
        except Exception as e:  # noqa: BLE001 — bench over trace
            prof_info = {"error": f"{type(e).__name__}: {e}"}
    a, b = make_operands(seed=12348)
    cpu_qps, cpu_count = bench_cpu_baseline(a, b)
    (dev_qps, dev_count, platform, engine, qps_by_engine,
     extras) = bench_device(a, b)
    assert dev_count == cpu_count, f"bit-exactness violated: {dev_count} != {cpu_count}"
    verify_product_path(a, b, cpu_count)
    co_obs = bench_coalescer(a, b)
    co = None
    if co_obs is not None:
        co, obs, dv, po = co_obs
        extras["coalescer"] = co
        extras["observe"] = obs
        extras["devobs"] = dv
        extras["perfobs"] = po
    extras["admission"] = bench_admission(co)
    rg = bench_ragged(a, b)
    if rg is not None:
        extras["ragged"] = rg
    rc = bench_resultcache(a, b)
    if rc is not None:
        extras["resultcache"] = rc
    ing = bench_ingest(a, b)
    if ing is not None:
        extras["ingest"] = ing
    ctn = bench_containers()
    if ctn is not None:
        extras["containers"] = ctn
    vmab = bench_vm()
    if vmab is not None:
        extras["vm"] = vmab
    extras["faultinject"] = bench_faultinject()
    extras["traceasm"] = bench_traceasm()
    extras["tenants"] = bench_tenants(co)
    msh = bench_mesh()
    if msh is not None:
        extras["mesh"] = msh
    rsd = bench_residency()
    if rsd is not None:
        extras["residency"] = rsd
    bytes_per_query = a.nbytes + b.nbytes  # streamed once per query
    achieved_gbps = dev_qps * bytes_per_query / 1e9
    peak = _peak_gbps(platform)
    # Physics backstop: a memory-bound kernel cannot beat the HBM roof.
    # The relay memoizes identical dispatches (see timed_qps); variant
    # rotation defeats the observed back-to-back case, but a deeper
    # (executable, args) cache would inflate QPS while every sampled
    # count still verifies — so a >roof figure is flagged as a
    # measurement fault in the artifact itself, never recorded as a
    # clean number.
    b32 = extras.get("batch32")
    over_roof = []
    if peak is not None:
        if achieved_gbps > peak:
            over_roof.append(f"single-dispatch {achieved_gbps:.0f} GB/s")
        if isinstance(b32, dict) and b32["achieved_gbps_lower"] > peak:
            over_roof.append(
                f"batch32 {b32['achieved_gbps_lower']:.0f} GB/s")
        if (co is not None
                and co["achieved_gbps_lower"] > peak):
            over_roof.append(
                f"coalescer {co['achieved_gbps_lower']:.0f} GB/s")
    suspect = bool(over_roof)
    if suspect:
        print(f"bench: MEASUREMENT FAULT: {' and '.join(over_roof)} "
              f"exceeds the {peak:.0f} GB/s HBM roof — dispatches "
              "were memoized, not executed; number is NOT trustworthy",
              file=sys.stderr)
    chip = (None if platform in _CHIP_PLATFORMS
            else _last_chip_capture())
    if prof_dir and prof_info is not None and "error" not in prof_info:
        from pilosa_tpu import perfobs as _perfobs

        try:
            prof_info = _perfobs.profiler_stop()
        except Exception as e:  # noqa: BLE001
            prof_info = {"error": f"{type(e).__name__}: {e}"}
    print(json.dumps({
        "metric": "intersect_count_qps_268M_cols",
        "value": round(dev_qps, 2),
        "unit": "qps",
        "vs_baseline": round(dev_qps / cpu_qps, 2),
        "platform": platform,
        "engine": engine,
        "achieved_gbps": round(achieved_gbps, 1),
        "peak_gbps": peak,
        "bw_util": None if peak is None else round(achieved_gbps / peak, 3),
        "engines": {k: round(v, 2) if isinstance(v, float) else v
                    for k, v in qps_by_engine.items()},
        **extras,
        **({"suspect_memoized_dispatch": True} if suspect else {}),
        **({"last_chip_capture": chip} if chip else {}),
        **({"profile": prof_info} if prof_info is not None else {}),
    }))


if __name__ == "__main__":
    main()


