"""Stats: counters/gauges/histograms with tag scoping and pluggable
backends.

Parity target: the reference's stats package (stats/stats.go:31
StatsClient interface; :84 expvar impl; :164 multi fan-out) and the
prometheus adapter (prometheus/prometheus.go:40) — collapsed here into
one in-process registry that can render both the /debug/vars JSON
snapshot and the /metrics Prometheus text exposition
(http/handler.go:280-282).

Timings and histograms record into FIXED-BUCKET latency histograms
(a 1/2.5/5-per-decade ladder wide enough for both nanosecond timings
and small occupancy counts), rendered as the native Prometheus
``histogram`` type — cumulative ``_bucket`` lines with optional
OpenMetrics-style trace-id exemplars — and summarized with
p50/p95/p99 estimates in the /debug/vars snapshot.  The strict
exposition checker (tools/check_metrics.py) validates the rendering
in CI."""

from __future__ import annotations

import bisect
import threading
import time
from collections import defaultdict


class StatsClient:
    """Interface (stats/stats.go:31).  Tag scoping via with_tags returns
    a child client that stamps every metric."""

    def count(self, name: str, value: int = 1, rate: float = 1.0) -> None:
        pass

    def count_with_tags(self, name: str, value: int, rate: float,
                        tags: list[str]) -> None:
        pass

    def gauge(self, name: str, value: float, rate: float = 1.0) -> None:
        pass

    def histogram(self, name: str, value: float, rate: float = 1.0,
                  exemplar: str | None = None) -> None:
        pass

    def set(self, name: str, value: str, rate: float = 1.0) -> None:
        pass

    def timing(self, name: str, value_ns: float, rate: float = 1.0,
               exemplar: str | None = None) -> None:
        pass

    def with_tags(self, *tags: str) -> "StatsClient":
        return self

    def tags(self) -> list[str]:
        return []


#: Shared no-op (reference NopStatsClient)
NOP = StatsClient()


class MemStatsClient(StatsClient):
    """In-memory registry backend — the expvar + prometheus roles in one
    (stats/stats.go:84, prometheus/prometheus.go:40)."""

    def __init__(self, registry: "_Registry | None" = None,
                 _tags: tuple[str, ...] = ()):
        self._registry = registry or _Registry()
        self._tags = tuple(sorted(_tags))

    # ------------------------------------------------------------ metrics

    def count(self, name, value=1, rate=1.0):
        self._registry.add_counter(name, self._tags, value)

    def count_with_tags(self, name, value, rate, tags):
        all_tags = tuple(sorted({*self._tags, *tags}))
        self._registry.add_counter(name, all_tags, value)

    def gauge(self, name, value, rate=1.0):
        self._registry.set_gauge(name, self._tags, value)

    def histogram(self, name, value, rate=1.0, exemplar=None):
        self._registry.observe(name, self._tags, value, exemplar)

    def set(self, name, value, rate=1.0):
        self._registry.set_gauge(f"{name}.{value}", self._tags, 1)

    def timing(self, name, value_ns, rate=1.0, exemplar=None):
        self._registry.observe(name, self._tags, value_ns, exemplar)

    def with_tags(self, *tags):
        return MemStatsClient(self._registry, (*self._tags, *tags))

    def tags(self):
        return list(self._tags)

    # ----------------------------------------------------------- exports

    def snapshot(self) -> dict:
        return self._registry.snapshot()

    def prometheus_text(self, exemplars: bool = False) -> str:
        return self._registry.prometheus_text(exemplars)


class MultiStatsClient(StatsClient):
    """Fan-out to several backends (stats/stats.go:164)."""

    def __init__(self, clients: list[StatsClient]):
        self.clients = list(clients)

    def count(self, name, value=1, rate=1.0):
        for c in self.clients:
            c.count(name, value, rate)

    def count_with_tags(self, name, value, rate, tags):
        for c in self.clients:
            c.count_with_tags(name, value, rate, tags)

    def gauge(self, name, value, rate=1.0):
        for c in self.clients:
            c.gauge(name, value, rate)

    def histogram(self, name, value, rate=1.0, exemplar=None):
        for c in self.clients:
            c.histogram(name, value, rate, exemplar=exemplar)

    def set(self, name, value, rate=1.0):
        for c in self.clients:
            c.set(name, value, rate)

    def timing(self, name, value_ns, rate=1.0, exemplar=None):
        for c in self.clients:
            c.timing(name, value_ns, rate, exemplar=exemplar)

    def with_tags(self, *tags):
        return MultiStatsClient([c.with_tags(*tags) for c in self.clients])

    def snapshot(self) -> dict:
        """Merged view across EVERY snapshot-capable backend, so a
        fan-out with two registries surfaces both key spaces (the old
        behavior returned only the first capable backend).  Like
        prometheus_text(), this assumes disjoint metric names per
        registry; on a collision the first backend's value wins."""
        out: dict = {}
        for c in self.clients:
            if hasattr(c, "snapshot"):
                for k, v in c.snapshot().items():
                    out.setdefault(k, v)
        return out

    def prometheus_text(self, exemplars: bool = False) -> str:
        """Concatenated exposition across every capable backend, with
        repeated ``# TYPE`` lines dropped so two registries sharing a
        metric name cannot produce the duplicate-TYPE exposition strict
        scrapers reject.  (Samples themselves are not merged: fan-out
        deployments keep disjoint metric names per registry; the server
        assembly wires exactly one MemStatsClient.)"""
        lines: list[str] = []
        seen_types: set[str] = set()
        for c in self.clients:
            if not hasattr(c, "prometheus_text"):
                continue
            for line in c.prometheus_text(exemplars).splitlines():
                if line.startswith("# TYPE "):
                    if line in seen_types:
                        continue
                    seen_types.add(line)
                lines.append(line)
        return "\n".join(lines) + ("\n" if lines else "")


#: Histogram bucket upper bounds: 1 / 2.5 / 5 per decade from 1e-6 to
#: 5e9 — one fixed ladder wide enough for second-scale latencies
#: (pilosa_query_latency), nanosecond timings (Timer feeds ns), and
#: small value histograms (coalescer batch occupancy 1..32).  Fixed
#: buckets keep observe() O(log B) with no per-metric configuration.
BUCKETS: tuple[float, ...] = tuple(
    m * (10.0 ** e) for e in range(-6, 10) for m in (1.0, 2.5, 5.0))

#: Quantiles reported in the /debug/vars snapshot.
_QUANTILES = (("p50", 0.50), ("p95", 0.95), ("p99", 0.99))


class _Hist:
    """One (name, tagset) histogram: count/sum/min/max plus per-bucket
    counts and the last exemplar seen per bucket (trace id, value,
    unix seconds) — the hot-bucket -> trace linkage."""

    __slots__ = ("n", "total", "mn", "mx", "counts", "exemplars")

    def __init__(self):
        self.n = 0
        self.total = 0.0
        self.mn = float("inf")
        self.mx = float("-inf")
        # one slot per bound + the +Inf overflow slot
        self.counts = [0] * (len(BUCKETS) + 1)
        self.exemplars: dict[int, tuple[str, float, float]] = {}

    def observe(self, value: float, exemplar: str | None) -> None:
        self.n += 1
        self.total += value
        self.mn = min(self.mn, value)
        self.mx = max(self.mx, value)
        i = bisect.bisect_left(BUCKETS, value)
        self.counts[i] += 1
        if exemplar is not None:
            self.exemplars[i] = (exemplar, value, time.time())

    def quantile(self, q: float) -> float:
        """Estimate by linear interpolation inside the bucket holding
        rank q*n, clamped to the observed [min, max] — the pinned math
        of tests/test_observe.py."""
        if self.n == 0:
            return 0.0
        target = q * self.n
        cum = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if cum + c >= target:
                lo = BUCKETS[i - 1] if i > 0 else 0.0
                hi = BUCKETS[i] if i < len(BUCKETS) else self.mx
                v = lo + (hi - lo) * ((target - cum) / c)
                return min(max(v, self.mn), self.mx)
            cum += c
        return self.mx


class _Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[tuple, float] = defaultdict(float)
        self._gauges: dict[tuple, float] = {}
        self._hists: dict[tuple, _Hist] = {}

    def add_counter(self, name, tags, value):
        with self._lock:
            self._counters[(name, tags)] += value

    def set_gauge(self, name, tags, value):
        with self._lock:
            self._gauges[(name, tags)] = value

    def observe(self, name, tags, value, exemplar=None):
        with self._lock:
            h = self._hists.get((name, tags))
            if h is None:
                h = self._hists[(name, tags)] = _Hist()
            h.observe(value, exemplar)

    def snapshot(self) -> dict:
        with self._lock:
            out = {}
            for (name, tags), v in self._counters.items():
                out[_flat(name, tags)] = v
            for (name, tags), v in self._gauges.items():
                out[_flat(name, tags)] = v
            for (name, tags), h in self._hists.items():
                entry = {"count": h.n, "sum": h.total,
                         "min": h.mn, "max": h.mx}
                for label, q in _QUANTILES:
                    entry[label] = h.quantile(q)
                out[_flat(name, tags)] = entry
            return out

    def prometheus_text(self, exemplars: bool = False) -> str:
        """Prometheus 0.0.4 text exposition; tag "k:v" -> label k="v"
        (the reference's tag translation, prometheus/prometheus.go:120).
        Each ``# TYPE`` is emitted ONCE per metric name (a second
        tagset must not repeat it — strict scrapers reject duplicate
        TYPE lines).  Histograms render natively: sparse cumulative
        ``_bucket`` lines (buckets a value landed in, plus ``+Inf``),
        ``_sum``/``_count``.

        ``exemplars=True`` appends OpenMetrics-style trace-id
        exemplars to the buckets that have one.  OFF by default: the
        legacy 0.0.4 parser (a stock Prometheus scrape) rejects the
        trailing ``# {...}``, so the handler only enables it when the
        client negotiates OpenMetrics (or asks with ``?exemplars=1``)."""
        lines = []
        with self._lock:
            last = None
            for (name, tags), v in sorted(self._counters.items()):
                m = _prom_name(name)
                if m != last:
                    lines.append(f"# TYPE {m} counter")
                    last = m
                lines.append(f"{m}{_prom_labels(tags)} {v}")
            last = None
            for (name, tags), v in sorted(self._gauges.items()):
                m = _prom_name(name)
                if m != last:
                    lines.append(f"# TYPE {m} gauge")
                    last = m
                lines.append(f"{m}{_prom_labels(tags)} {v}")
            last = None
            for (name, tags), h in sorted(self._hists.items()):
                m = _prom_name(name)
                if m != last:
                    lines.append(f"# TYPE {m} histogram")
                    last = m
                cum = 0
                for i, c in enumerate(h.counts):
                    inf = i == len(BUCKETS)
                    if c == 0 and not inf:
                        continue  # sparse: unchanged cumulative buckets
                    cum += c
                    le = "+Inf" if inf else f"{BUCKETS[i]:g}"
                    line = (f"{m}_bucket"
                            f"{_prom_labels(tags, ('le', le))} {cum}")
                    ex = h.exemplars.get(i) if exemplars else None
                    if ex is not None:
                        tid, val, ts = ex
                        line += (f' # {{trace_id="{tid}"}} '
                                 f"{val:g} {ts:.3f}")
                    lines.append(line)
                lines.append(f"{m}_sum{_prom_labels(tags)} {h.total}")
                lines.append(f"{m}_count{_prom_labels(tags)} {h.n}")
        return "\n".join(lines) + ("\n" if lines else "")


def _flat(name: str, tags: tuple) -> str:
    return name if not tags else f"{name}[{','.join(tags)}]"


def _prom_name(name: str) -> str:
    out = "".join(ch if ch.isalnum() or ch == "_" else "_" for ch in name)
    return out if not out[:1].isdigit() else "_" + out


def _prom_labels(tags: tuple, extra: tuple[str, str] | None = None) -> str:
    if not tags and extra is None:
        return ""
    pairs = []
    for t in tags:
        k, _, v = t.partition(":")
        v = v.replace("\\", "\\\\").replace('"', '\\"')
        pairs.append(f'{_prom_name(k)}="{v}"')
    if extra is not None:
        pairs.append(f'{extra[0]}="{extra[1]}"')
    return "{" + ",".join(pairs) + "}"


class Timer:
    """Context manager feeding StatsClient.timing."""

    def __init__(self, stats: StatsClient, name: str):
        self.stats = stats
        self.name = name

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        self.stats.timing(self.name, time.perf_counter_ns() - self._t0)
        return False
