"""Stats: counters/gauges/timings with tag scoping and pluggable
backends.

Parity target: the reference's stats package (stats/stats.go:31
StatsClient interface; :84 expvar impl; :164 multi fan-out) and the
prometheus adapter (prometheus/prometheus.go:40) — collapsed here into
one in-process registry that can render both the /debug/vars JSON
snapshot and the /metrics Prometheus text exposition
(http/handler.go:280-282)."""

from __future__ import annotations

import threading
import time
from collections import defaultdict


class StatsClient:
    """Interface (stats/stats.go:31).  Tag scoping via with_tags returns
    a child client that stamps every metric."""

    def count(self, name: str, value: int = 1, rate: float = 1.0) -> None:
        pass

    def count_with_tags(self, name: str, value: int, rate: float,
                        tags: list[str]) -> None:
        pass

    def gauge(self, name: str, value: float, rate: float = 1.0) -> None:
        pass

    def histogram(self, name: str, value: float, rate: float = 1.0) -> None:
        pass

    def set(self, name: str, value: str, rate: float = 1.0) -> None:
        pass

    def timing(self, name: str, value_ns: float, rate: float = 1.0) -> None:
        pass

    def with_tags(self, *tags: str) -> "StatsClient":
        return self

    def tags(self) -> list[str]:
        return []


#: Shared no-op (reference NopStatsClient)
NOP = StatsClient()


class MemStatsClient(StatsClient):
    """In-memory registry backend — the expvar + prometheus roles in one
    (stats/stats.go:84, prometheus/prometheus.go:40)."""

    def __init__(self, registry: "_Registry | None" = None,
                 _tags: tuple[str, ...] = ()):
        self._registry = registry or _Registry()
        self._tags = tuple(sorted(_tags))

    # ------------------------------------------------------------ metrics

    def count(self, name, value=1, rate=1.0):
        self._registry.add_counter(name, self._tags, value)

    def count_with_tags(self, name, value, rate, tags):
        all_tags = tuple(sorted({*self._tags, *tags}))
        self._registry.add_counter(name, all_tags, value)

    def gauge(self, name, value, rate=1.0):
        self._registry.set_gauge(name, self._tags, value)

    def histogram(self, name, value, rate=1.0):
        self._registry.observe(name, self._tags, value)

    def set(self, name, value, rate=1.0):
        self._registry.set_gauge(f"{name}.{value}", self._tags, 1)

    def timing(self, name, value_ns, rate=1.0):
        self._registry.observe(name, self._tags, value_ns)

    def with_tags(self, *tags):
        return MemStatsClient(self._registry, (*self._tags, *tags))

    def tags(self):
        return list(self._tags)

    # ----------------------------------------------------------- exports

    def snapshot(self) -> dict:
        return self._registry.snapshot()

    def prometheus_text(self) -> str:
        return self._registry.prometheus_text()


class MultiStatsClient(StatsClient):
    """Fan-out to several backends (stats/stats.go:164)."""

    def __init__(self, clients: list[StatsClient]):
        self.clients = list(clients)

    def count(self, name, value=1, rate=1.0):
        for c in self.clients:
            c.count(name, value, rate)

    def count_with_tags(self, name, value, rate, tags):
        for c in self.clients:
            c.count_with_tags(name, value, rate, tags)

    def gauge(self, name, value, rate=1.0):
        for c in self.clients:
            c.gauge(name, value, rate)

    def histogram(self, name, value, rate=1.0):
        for c in self.clients:
            c.histogram(name, value, rate)

    def set(self, name, value, rate=1.0):
        for c in self.clients:
            c.set(name, value, rate)

    def timing(self, name, value_ns, rate=1.0):
        for c in self.clients:
            c.timing(name, value_ns, rate)

    def with_tags(self, *tags):
        return MultiStatsClient([c.with_tags(*tags) for c in self.clients])

    def snapshot(self) -> dict:
        for c in self.clients:
            if hasattr(c, "snapshot"):
                return c.snapshot()
        return {}

    def prometheus_text(self) -> str:
        for c in self.clients:
            if hasattr(c, "prometheus_text"):
                return c.prometheus_text()
        return ""


class _Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[tuple, float] = defaultdict(float)
        self._gauges: dict[tuple, float] = {}
        self._summaries: dict[tuple, list] = defaultdict(
            lambda: [0, 0.0, float("inf"), float("-inf")])  # n, sum, min, max

    def add_counter(self, name, tags, value):
        with self._lock:
            self._counters[(name, tags)] += value

    def set_gauge(self, name, tags, value):
        with self._lock:
            self._gauges[(name, tags)] = value

    def observe(self, name, tags, value):
        with self._lock:
            s = self._summaries[(name, tags)]
            s[0] += 1
            s[1] += value
            s[2] = min(s[2], value)
            s[3] = max(s[3], value)

    def snapshot(self) -> dict:
        with self._lock:
            out = {}
            for (name, tags), v in self._counters.items():
                out[_flat(name, tags)] = v
            for (name, tags), v in self._gauges.items():
                out[_flat(name, tags)] = v
            for (name, tags), (n, total, mn, mx) in self._summaries.items():
                out[_flat(name, tags)] = {
                    "count": n, "sum": total, "min": mn, "max": mx}
            return out

    def prometheus_text(self) -> str:
        """Prometheus 0.0.4 text exposition; tag "k:v" -> label k="v"
        (the reference's tag translation, prometheus/prometheus.go:120)."""
        lines = []
        with self._lock:
            for (name, tags), v in sorted(self._counters.items()):
                m = _prom_name(name)
                lines.append(f"# TYPE {m} counter")
                lines.append(f"{m}{_prom_labels(tags)} {v}")
            for (name, tags), v in sorted(self._gauges.items()):
                m = _prom_name(name)
                lines.append(f"# TYPE {m} gauge")
                lines.append(f"{m}{_prom_labels(tags)} {v}")
            for (name, tags), (n, total, _, _) in sorted(
                    self._summaries.items()):
                m = _prom_name(name)
                lines.append(f"# TYPE {m} summary")
                lines.append(f"{m}_count{_prom_labels(tags)} {n}")
                lines.append(f"{m}_sum{_prom_labels(tags)} {total}")
        return "\n".join(lines) + ("\n" if lines else "")


def _flat(name: str, tags: tuple) -> str:
    return name if not tags else f"{name}[{','.join(tags)}]"


def _prom_name(name: str) -> str:
    out = "".join(ch if ch.isalnum() or ch == "_" else "_" for ch in name)
    return out if not out[:1].isdigit() else "_" + out


def _prom_labels(tags: tuple) -> str:
    if not tags:
        return ""
    pairs = []
    for t in tags:
        k, _, v = t.partition(":")
        v = v.replace("\\", "\\\\").replace('"', '\\"')
        pairs.append(f'{_prom_name(k)}="{v}"')
    return "{" + ",".join(pairs) + "}"


class Timer:
    """Context manager feeding StatsClient.timing."""

    def __init__(self, stats: StatsClient, name: str):
        self.stats = stats
        self.name = name

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        self.stats.timing(self.name, time.perf_counter_ns() - self._t0)
        return False
