"""StatsD backend: ships metrics to a statsd/DataDog agent over UDP.

Parity target: the reference's statsd package (statsd/statsd.go:41 —
DataDog client adapter with 1s aggregation).  Implemented on a plain
UDP socket (dogstatsd line protocol, which plain statsd servers accept
minus the |#tags suffix) — no third-party dependency.  Sends are
best-effort and never block or raise into the caller."""

from __future__ import annotations

import random
import socket
import threading
import time

from pilosa_tpu.stats import StatsClient


class StatsdClient(StatsClient):
    """Tag-scoped statsd emitter (statsd/statsd.go:41 NewStatsClient)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8125,
                 prefix: str = "pilosa_tpu", flush_interval: float = 1.0,
                 _shared=None, _tags: tuple[str, ...] = ()):
        self.prefix = prefix
        self._tags = tuple(sorted(_tags))
        if _shared is not None:
            self._shared = _shared
        else:
            self._shared = _Conn(host, port, flush_interval)

    # ------------------------------------------------------------- metrics

    def _send(self, name: str, value, kind: str, rate: float,
              tags: tuple[str, ...]) -> None:
        if rate < 1.0 and random.random() >= rate:
            return  # actually sample — the |@rate suffix tells the
            # agent to scale the events we DO send back up
        line = f"{self.prefix}.{name}:{value}|{kind}"
        if rate < 1.0:
            line += f"|@{rate}"
        if tags:
            line += "|#" + ",".join(tags)
        self._shared.enqueue(line)

    def count(self, name, value=1, rate=1.0):
        self._send(name, value, "c", rate, self._tags)

    def count_with_tags(self, name, value, rate, tags):
        self._send(name, value, "c", rate,
                   tuple(sorted({*self._tags, *tags})))

    def gauge(self, name, value, rate=1.0):
        self._send(name, value, "g", rate, self._tags)

    def histogram(self, name, value, rate=1.0, exemplar=None):
        # statsd's wire format has no exemplar slot; dropped here, kept
        # by the registry backend in a MultiStatsClient fan-out
        self._send(name, value, "h", rate, self._tags)

    def set(self, name, value, rate=1.0):
        self._send(name, value, "s", rate, self._tags)

    def timing(self, name, value_ns, rate=1.0, exemplar=None):
        self._send(name, value_ns / 1e6, "ms", rate, self._tags)

    def with_tags(self, *tags):
        return StatsdClient(prefix=self.prefix, _shared=self._shared,
                            _tags=(*self._tags, *tags))

    def tags(self):
        return list(self._tags)

    def close(self) -> None:
        self._shared.close()


class _Conn:
    """Shared UDP socket with a 1s-aggregated send buffer (the
    reference's DataDog client buffers similarly)."""

    MAX_PACKET = 1432  # typical safe UDP payload

    def __init__(self, host: str, port: int, flush_interval: float):
        self.addr = (host, port)
        self.flush_interval = flush_interval
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._buf: list[str] = []
        self._buf_len = 0
        self._lock = threading.Lock()
        self._last_flush = time.monotonic()
        self._stop = threading.Event()
        # background flusher: a quiet server must still drain its tail
        # (the DataDog client the reference wraps flushes on a timer)
        if flush_interval > 0:
            self._flusher = threading.Thread(target=self._flush_loop,
                                             daemon=True)
            self._flusher.start()

    def _flush_loop(self) -> None:
        while not self._stop.wait(self.flush_interval):
            self.flush()

    def enqueue(self, line: str) -> None:
        with self._lock:
            if self._buf_len + len(line) + 1 > self.MAX_PACKET:
                self._flush_locked()
            self._buf.append(line)
            self._buf_len += len(line) + 1
            if time.monotonic() - self._last_flush >= self.flush_interval:
                self._flush_locked()

    def _flush_locked(self) -> None:
        if self._buf:
            try:
                self.sock.sendto("\n".join(self._buf).encode(), self.addr)
            except OSError:
                pass  # best-effort
            self._buf = []
            self._buf_len = 0
        self._last_flush = time.monotonic()

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def close(self) -> None:
        self._stop.set()
        self.flush()
        try:
            self.sock.close()
        except OSError:
            pass
