"""API façade: every externally triggerable action, validated against the
cluster state machine.

Parity target: the reference's ``*pilosa.API`` (api.go:42).  Each public
method checks the cluster state against a per-method validation table
(api.go:119 ``validate`` / api.go:1343 ``methodsNormal`` etc.) before
touching the holder/executor, so callers — the HTTP handler, the CLI,
tests — share one enforcement point.
"""

from __future__ import annotations

import base64 as _b64mod
import io

import numpy as np


def _ts_iso(ts):
    return None if ts is None else ts.isoformat()

from pilosa_tpu.models.field import FieldOptions
from pilosa_tpu.models.index import IndexOptions
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.version import VERSION


class ApiError(Exception):
    """Base API error; http layer maps subclasses to status codes."""


class NotFoundError(ApiError):
    pass


class ConflictError(ApiError):
    pass


class ApiMethodNotAllowedError(ApiError):
    """Method not valid for the current cluster state (api.go:114)."""


# Per-method allowed cluster states (reference api.go:1343-end).  Methods
# absent from this table are allowed in any state.
_NORMAL = frozenset({"NORMAL"})
_QUERY = frozenset({"NORMAL", "DEGRADED"})
_RESIZE_OK = frozenset({"NORMAL", "STARTING", "RESIZING", "DEGRADED"})

_METHOD_STATES = {
    "query": _QUERY,
    "create_index": _NORMAL,
    "delete_index": _NORMAL,
    "create_field": _NORMAL,
    "delete_field": _NORMAL,
    "delete_view": _NORMAL,
    "import_bits": _NORMAL,
    "import_values": _NORMAL,
    "import_roaring": _NORMAL,
    "export_csv": _NORMAL,
    "apply_schema": _NORMAL,
    "set_coordinator": _RESIZE_OK,
    "remove_node": _NORMAL,
    "resize_abort": frozenset({"RESIZING"}),
    "recalculate_caches": _QUERY,
}


class API:
    """Façade over one node's holder + cluster + executor (api.go:42)."""

    def __init__(self, node):
        """`node` is a pilosa_tpu.parallel.node.ClusterNode."""
        self.node = node
        self.holder = node.holder
        self.cluster = node.cluster
        self.executor = node.executor
        self.max_writes_per_request = 0  # 0 = unlimited (config wired by server)

    # ----------------------------------------------------------- validate

    def _validate(self, method: str) -> None:
        allowed = _METHOD_STATES.get(method)
        if allowed is None:
            return
        state = self.cluster.state
        if state not in allowed:
            raise ApiMethodNotAllowedError(
                f"api method {method} not allowed in cluster state {state}"
            )

    # -------------------------------------------------------------- query

    def query(self, index: str, pql, shards=None, remote: bool = False,
              column_attrs: bool = False, exclude_row_attrs: bool = False,
              exclude_columns: bool = False, coalesce: bool = True,
              cache: bool = True, delta: bool = True,
              containers: bool = True, mesh: bool = True,
              tiers: bool = True, vm: bool = True,
              partial: bool = False,
              partial_meta: dict | None = None,
              tenant: str | None = None):
        """Execute PQL -> list of results (api.go:135 API.Query).

        ``partial=True`` (the HTTP layer's ?partial=1 /
        X-Pilosa-Partial) degrades instead of erroring when shards
        exhaust every replica: results come back with the reachable
        shards only, and ``partial_meta`` (when given) is filled with
        ``missingShards`` (the exact unavailable set) and
        ``missingFraction``.  The default keeps all-or-error
        semantics on an identical code path.

        ``tenant`` is the request's tenant id (the HTTP layer's
        X-Pilosa-Tenant / ?tenant=): it rides ExecOptions into the
        executor, where admission quotas, result-cache soft budgets
        and residency tier quotas charge it ([tenants] isolation;
        inert while disabled)."""
        from pilosa_tpu.parallel.executor import ExecOptions
        from pilosa_tpu.serve import deadline as _deadline

        self._validate("query")
        # end-to-end deadline: the handler installed the request's
        # X-Pilosa-Deadline scope on this thread; expired budgets shed
        # here, before translate/collective work touches anything
        dl = _deadline.current()
        _deadline.check(dl, "query execution")
        if (not remote and shards is None and not partial
                and isinstance(pql, str)):
            # multi-process runtime: the coordinator upgrades supported
            # reads to one collective SPMD program over the global mesh
            # (parallel/spmd.py); None falls through to scatter-gather.
            # This check runs BEFORE the write-limit branch below, which
            # rebinds pql to a parsed Query and would otherwise make the
            # upgrade unreachable on config-launched servers.
            from pilosa_tpu import observe as _observe
            from pilosa_tpu import tracing as _tracing
            from pilosa_tpu.parallel import spmd

            # the collective upgrade bypasses the executor, so its
            # flight record is opened (and, when the upgrade declines,
            # discarded) here — but only when a collective runtime
            # exists at all: on the default single-node path the
            # executor opens the one record, and a begin/discard pair
            # here would double the recorder cost per query
            recorder = getattr(self.executor, "recorder", None)
            rec = None
            if (recorder is not None and recorder.enabled
                    and spmd.collective_available()):
                rec = recorder.begin(index, pql,
                                     trace_id=_tracing.active_trace_id())
                rec.tenant = tenant
            try:
                # the collective upgrade bypasses the executor, so the
                # tenant scope the executor would install goes here —
                # without it, cache fills and residency admissions on
                # this path charge the default tier, escaping the
                # requesting tenant's quotas
                from pilosa_tpu.serve import tenant as _tenantmod

                with _observe.attach(rec), _tenantmod.scope(tenant):
                    res = spmd.try_collective(
                        self.node, index, pql,
                        exclude_row_attrs=exclude_row_attrs)
            except BaseException as e:
                if rec is not None:
                    recorder.publish(rec,
                                     error=f"{type(e).__name__}: {e}")
                raise
            if res is not None:
                if rec is not None:
                    rec.note_path("collective")
                    rec.note_engine("collective")
                    rec.result_sizes = [_observe.result_size(r)
                                        for r in res]
                    recorder.publish(rec)
                return res
            if rec is not None:
                recorder.discard(rec)
        if self.max_writes_per_request > 0:
            from pilosa_tpu.pql import Query, parse as _parse

            # the parsed Query skips the executor's re-parse, so the
            # sentinel gate must apply here too (remote-only spellings)
            q = (_parse(pql, allow_internal=remote)
                 if isinstance(pql, str) else pql)
            if isinstance(q, Query) and (
                    q.write_call_n() > self.max_writes_per_request):
                raise ApiError(
                    f"too many writes in one request "
                    f"({q.write_call_n()} > {self.max_writes_per_request})")
            pql = q
        opt = ExecOptions(
            remote=remote,
            column_attrs=column_attrs,
            exclude_row_attrs=exclude_row_attrs,
            exclude_columns=exclude_columns,
            shards=None if shards is None else list(shards),
            coalesce=coalesce,
            cache=cache,
            delta=delta,
            containers=containers,
            mesh=mesh,
            tiers=tiers,
            vm=vm,
            deadline=dl,
            partial=partial,
            missing=set() if partial else None,
            tenant=tenant,
        )
        results = self.executor.execute(index, pql, opt=opt)
        if partial_meta is not None:
            miss = sorted(opt.missing or ())
            partial_meta["missingShards"] = miss
            partial_meta["missingFraction"] = (
                round(len(miss) / opt.targeted, 4) if opt.targeted
                else 0.0)
        return results

    # ------------------------------------------------------------- schema

    def schema(self) -> list[dict]:
        return self.holder.schema()

    def apply_schema(self, schema: list[dict]) -> None:
        """Idempotent schema merge (api.go ApplySchema)."""
        self._validate("apply_schema")
        self.holder.apply_schema(schema)

    def index(self, name: str):
        idx = self.holder.index(name)
        if idx is None:
            raise NotFoundError(f"index not found: {name}")
        return idx

    def create_index(self, name: str, options: IndexOptions | None = None):
        self._validate("create_index")
        if self.holder.index(name) is not None:
            raise ConflictError(f"index already exists: {name}")
        return self.node.create_index(name, options)

    def delete_index(self, name: str) -> None:
        self._validate("delete_index")
        if self.holder.index(name) is None:
            raise NotFoundError(f"index not found: {name}")
        self.node.delete_index(name)

    def field(self, index: str, name: str):
        idx = self.index(index)
        f = idx.field(name)
        if f is None:
            raise NotFoundError(f"field not found: {name}")
        return f

    def create_field(self, index: str, name: str,
                     options: FieldOptions | None = None):
        self._validate("create_field")
        idx = self.index(index)
        if idx.field(name) is not None:
            raise ConflictError(f"field already exists: {name}")
        return self.node.create_field(index, name, options)

    def delete_field(self, index: str, name: str) -> None:
        self._validate("delete_field")
        self.field(index, name)
        self.node.delete_field(index, name)

    # ------------------------------------------------------------- import

    def import_bits(self, index: str, field: str, rows, cols,
                    timestamps=None, row_keys=None, col_keys=None,
                    clear: bool = False, remote: bool = False) -> None:
        """Bulk bit import: translate keys, group bits by shard, and
        forward each group to every owner replica — local owners import
        directly (api.go:920 API.Import; client-side shard routing
        http/client.go:1164 GroupByShard + per-owner POST)."""
        self._validate("import_bits")
        idx = self.index(index)
        f = self.field(index, field)
        if col_keys:
            cols = self._translate_keys(index, None, col_keys)
        if row_keys:
            cols_n = len(cols)
            rows = self._translate_keys(index, field, row_keys)
            if len(rows) != cols_n:
                raise ApiError("row keys and columns length mismatch")
        # ndarrays (the protobuf bulk path) pass through untouched —
        # field.import_bits groups them vectorized; anything else
        # becomes a list once here
        if not isinstance(rows, np.ndarray):
            rows = list(rows)
        if not isinstance(cols, np.ndarray):
            cols = list(cols)
        if remote or not self._clustered():
            f.import_bits(rows, cols, timestamps, clear=clear)
            if not clear:
                idx.import_existence(cols)
            return
        known_shards = f.available_shards()
        for shard, sel in self._group_by_shard(cols).items():
            # the bus payload is JSON — ndarray selections convert via
            # fancy-index + tolist (C speed), list inputs via comp
            def pick(seq, to_list: bool, sel=sel):
                # sel bound at definition: local_fn runs inside
                # _send_to_owners, but never risk the loop variable
                if isinstance(seq, np.ndarray):
                    out = seq[sel]
                    return out.tolist() if to_list else out
                return [seq[i] for i in sel]

            payload = {
                "type": "import",
                "index": index,
                "field": field,
                "rows": pick(rows, True),
                "cols": pick(cols, True),
                "timestamps": None if timestamps is None else
                    [_ts_iso(timestamps[i]) for i in sel],
                "clear": clear,
            }
            self._send_to_owners(
                index, shard, payload,
                # pick=pick: pick rebinds every iteration, so the
                # lambda must be self-contained even if delivery is
                # ever deferred past this loop step
                local_fn=lambda sel=sel, pick=pick: (
                    f.import_bits(
                        pick(rows, False), pick(cols, False),
                        None if timestamps is None
                        else [timestamps[i] for i in sel],
                        clear=clear,
                    ),
                    None if clear else idx.import_existence(
                        pick(cols, False)),
                ),
            )
            self._note_shard_everywhere(f, index, field, shard,
                                        known=shard in known_shards)

    def import_values(self, index: str, field: str, cols, values,
                      col_keys=None, remote: bool = False) -> None:
        """Bulk BSI import with shard routing (api.go:1000
        API.ImportValue)."""
        self._validate("import_values")
        idx = self.index(index)
        f = self.field(index, field)
        if col_keys:
            cols = self._translate_keys(index, None, col_keys)
        cols, values = list(cols), list(values)
        if remote or not self._clustered():
            f.import_values(cols, values)
            idx.import_existence(cols)
            return
        known_shards = f.available_shards()
        for shard, sel in self._group_by_shard(cols).items():
            payload = {
                "type": "import-value",
                "index": index,
                "field": field,
                "cols": [cols[i] for i in sel],
                "values": [values[i] for i in sel],
            }
            self._send_to_owners(
                index, shard, payload,
                local_fn=lambda sel=sel: (
                    f.import_values([cols[i] for i in sel],
                                    [values[i] for i in sel]),
                    idx.import_existence([cols[i] for i in sel]),
                ),
            )
            self._note_shard_everywhere(f, index, field, shard,
                                        known=shard in known_shards)

    def _translate_keys(self, index: str, field: str | None, keys):
        """Key creation with single-writer routing (api.go:920 import
        key translation; holder.go:690 primary-only writes).  All
        routing lives in node.translate_keys_cluster."""
        return self.node.translate_keys_cluster(index, field, keys,
                                                create=True)

    def _clustered(self) -> bool:
        return (self.cluster.transport is not None
                and len(self.cluster.sorted_nodes()) > 1)

    @staticmethod
    def _group_by_shard(cols) -> dict:
        """shard -> selection of indices into ``cols`` (list of ints
        for list input, ndarray for ndarray input — both index back
        into the parallel rows/cols sequences)."""
        if isinstance(cols, np.ndarray):
            from pilosa_tpu.ops.bitmap import group_indices

            return group_indices(cols // SHARD_WIDTH)
        by_shard: dict[int, list[int]] = {}
        for i, c in enumerate(cols):
            by_shard.setdefault(c // SHARD_WIDTH, []).append(i)
        return by_shard

    def _note_shard_everywhere(self, f, index: str, field: str,
                               shard: int, known: bool) -> None:
        """Record shard existence locally and broadcast it so every
        node's available-shard bitmap includes it (reference
        CreateShardMessage, view.go:263-305)."""
        f._note_shard(shard)
        if not known:
            self.node.note_shard_created(index, field, shard)

    def _send_to_owners(self, index: str, shard: int, payload: dict,
                        local_fn) -> None:
        """Deliver one shard's import to all owner replicas;
        unreachable peers are skipped (anti-entropy reconciles, like
        the reference's best-effort replication).

        A peer REFUSING as non-owner (reference api.go
        ErrClusterDoesNotOwnShard) means its membership view is
        fresher than ours — a resize just re-homed the shard.  The
        fan-out then waits for the status broadcast to land,
        re-resolves the owner set, and retries the refused deliveries;
        if the views never converge it raises instead of silently
        dropping a write on an ex-owner (whose fragments the
        post-resize sweep deletes)."""
        from pilosa_tpu.parallel.cluster import converge_owner_deliveries
        from pilosa_tpu.serve.admission import rpc_class

        applied: set[str] = set()

        def on_timeout() -> None:
            raise ApiError(
                f"shard {shard} owners refused the import as "
                "non-owners and the membership view did not "
                "converge; retry")

        # replica deliveries carry the ingest class on the wire so the
        # receiving node admits them against its ingest gate, not the
        # internal one anti-entropy competes in
        with rpc_class("ingest"):
            converge_owner_deliveries(
                lambda: self._owner_pass(index, shard, payload, local_fn,
                                         applied),
                on_timeout)

    def _owner_pass(self, index: str, shard: int, payload: dict,
                    local_fn, applied: set) -> bool:
        """One delivery sweep over the CURRENT owner set, skipping
        nodes already applied.  Returns True if any owner refused as
        non-owner (caller retries after the view converges)."""
        from pilosa_tpu.parallel.cluster import TransportError

        refused = False
        # write_nodes = serving owners + PENDING owners mid-rebalance:
        # imports dual-write during a migration so the new owner's
        # copy converges bit-exact without waiting for anti-entropy
        for n in self.cluster.write_nodes(index, shard):
            if n.id in applied:
                continue
            if n.id == self.cluster.local_id:
                local_fn()
                applied.add(n.id)
                continue
            try:
                resp = self.cluster.transport.send_message(n, payload)
            except TransportError:
                applied.add(n.id)  # unreachable: AE reconciles later
                continue
            if isinstance(resp, dict) and resp.get("unowned"):
                refused = True
                continue
            applied.add(n.id)
        return refused

    def import_roaring(self, index: str, field: str, shard: int,
                       views: dict[str, bytes], clear: bool = False,
                       remote: bool = False) -> None:
        """Merge serialized roaring bitmaps per view into one shard's
        fragments, replicated to every shard owner (api.go:368
        API.ImportRoaring: the origin forwards to all owners with
        remote=true; remote receivers apply locally only)."""
        self._validate("import_roaring")
        from pilosa_tpu.models.field import FieldType
        from pilosa_tpu.models.view import VIEW_STANDARD

        f = self.field(index, field)
        if f.options.type not in (FieldType.SET, FieldType.TIME):
            raise ApiError("roaring import is only supported for set "
                           "and time fields")

        def apply_local() -> None:
            for vname, data in views.items():
                view = f.create_view_if_not_exists(vname or VIEW_STANDARD)
                frag = view.create_fragment_if_not_exists(shard)
                frag.import_roaring(data, clear=clear)
                f._note_shard(shard)

        if remote or not self._clustered():
            apply_local()
            return
        known_shards = f.available_shards()
        payload = {
            "type": "import-roaring",
            "index": index,
            "field": field,
            "shard": shard,
            "views": {vname: _b64mod.b64encode(data).decode()
                      for vname, data in views.items()},
            "clear": clear,
        }
        self._send_to_owners(index, shard, payload, local_fn=apply_local)
        self._note_shard_everywhere(f, index, field, shard,
                                    known=shard in known_shards)

    def export_csv(self, index: str, field: str, shard: int, w: io.TextIOBase) -> None:
        """Write `row,col` (or translated keys) CSV for one shard
        (api.go:500 API.ExportCSV)."""
        self._validate("export_csv")
        from pilosa_tpu.models.view import VIEW_STANDARD

        idx = self.index(index)
        f = self.field(index, field)
        view = f.view(VIEW_STANDARD)
        if view is None:
            return
        frag = view.fragment(shard)
        if frag is None:
            return
        base = shard * SHARD_WIDTH
        for row_id in frag.row_ids():
            words = frag.row(row_id)
            offs = _word_bits(words)
            row_label = row_id
            if f.options.keys:
                row_label = f.translate_store.translate_id(row_id) or row_id
            for off in offs:
                col = base + int(off)
                col_label = col
                if idx.options.keys:
                    col_label = idx.translate_store.translate_id(col) or col
                w.write(f"{row_label},{col_label}\n")

    # ------------------------------------------------------------ cluster

    def hosts(self) -> list[dict]:
        return [n.to_dict() for n in self.cluster.sorted_nodes()]

    def recalculate_caches(self, remote: bool = False) -> None:
        """Force every node's TopN caches up to date (reference
        API.RecalculateCaches, api.go:1139: local recalc + broadcast;
        used by clients that need fresh ranks immediately)."""
        self._validate("recalculate_caches")
        self.node.recalculate_caches()
        if not remote:
            self.node.broadcast({"type": "recalculate-caches"})

    def node_info(self) -> dict:
        return self.cluster.local_node.to_dict()

    def state(self) -> str:
        return self.cluster.state

    def info(self) -> dict:
        return {
            "shardWidth": SHARD_WIDTH,
            "memory": None,
            "cpuType": "tpu+host",
            "cpuPhysicalCores": None,
            "cpuLogicalCores": None,
        }

    def version(self) -> str:
        return VERSION

    def shards_max(self) -> dict[str, int]:
        """index -> max shard (handler /internal/shards/max)."""
        out = {}
        for d in self.holder.schema():
            idx = self.holder.index(d["name"])
            shards = idx.available_shards()
            if shards:
                out[d["name"]] = max(shards)
        return out

    def shard_nodes(self, index: str, shard: int) -> list[dict]:
        return [n.to_dict() for n in self.cluster.shard_nodes(index, shard)]

    def set_coordinator(self, node_id: str) -> None:
        self._validate("set_coordinator")
        if self.cluster.node(node_id) is None:
            raise NotFoundError(f"node not found: {node_id}")
        self.node.set_coordinator(node_id)

    def remove_node(self, node_id: str) -> dict:
        self._validate("remove_node")
        n = self.cluster.node(node_id)
        if n is None:
            raise NotFoundError(f"node not found: {node_id}")
        removed = n.to_dict()
        self.node.remove_node(node_id)
        return removed

    def resize_abort(self) -> None:
        driver = getattr(self.node, "rebalance", None)
        if driver is not None and driver.active():
            # an ONLINE rebalance runs with the cluster state NORMAL
            # (that is the whole point), so the legacy RESIZING-only
            # state gate must not block its abort
            self.node.resize_abort()
            return
        self._validate("resize_abort")
        self.node.resize_abort()

    def cluster_resize(self, body: dict) -> dict:
        """POST /cluster/resize: node add/remove as a control-plane
        operation.  ``mode: "online"`` (the default) drives the live
        per-shard migration (parallel/rebalance.py) — the cluster
        keeps serving throughout; ``mode: "offline"`` is the legacy
        stop-the-world resize (byte-identical behavior: the whole
        cluster goes RESIZING and refuses queries for the duration),
        kept as an explicit escape hatch.

        Body: ``{"mode": "online"|"offline", "add": {node dict}}`` or
        ``{"mode": ..., "removeId": "node-id"}`` (exactly one of
        add/removeId); online accepts ``"background": false`` for
        synchronous runs (tests)."""
        mode = (body.get("mode") or "online").lower()
        if mode not in ("online", "offline"):
            raise ApiError(
                f"unknown resize mode {mode!r} (online|offline)")
        add = body.get("add")
        remove_id = body.get("removeId") or body.get("remove_id")
        if (add is None) == (remove_id is None):
            raise ApiError(
                "exactly one of 'add' or 'removeId' is required")
        if mode == "offline":
            if add is not None:
                resp = self.node.receive_message(
                    {"type": "node-join", "node": add})
                return {"mode": "offline", "applied": True,
                        "response": resp}
            self._validate("remove_node")
            if self.cluster.node(remove_id) is None:
                raise NotFoundError(f"node not found: {remove_id}")
            self.node.remove_node(remove_id)
            return {"mode": "offline", "applied": True}
        driver = getattr(self.node, "rebalance", None)
        if driver is None:
            raise ApiError(
                "no rebalance driver attached to this node; use "
                'mode "offline" or target a server-assembled node')
        from pilosa_tpu.parallel.cluster import Node as _Node
        from pilosa_tpu.parallel.rebalance import RebalanceError

        try:
            out = driver.start(
                add=None if add is None else _Node.from_dict(add),
                remove_id=remove_id,
                background=bool(body.get("background", True)))
        except RebalanceError as e:
            raise ConflictError(str(e))
        out["mode"] = "online"
        return out

    def rebalance_status(self) -> dict:
        """The /debug/rebalance document (driver status + counters);
        a bare node without an attached driver reports inactive."""
        driver = getattr(self.node, "rebalance", None)
        if driver is None:
            from pilosa_tpu.parallel import rebalance as _rebalance

            return {"active": False, "attached": False,
                    "counters": _rebalance.counters()}
        out = driver.status()
        out["attached"] = True
        return out

    # ------------------------------------------------------ anti-entropy

    def fragment_blocks(self, index: str, field: str, view: str, shard: int):
        f = self.field(index, field)
        v = f.view(view)
        if v is None:
            raise NotFoundError(f"view not found: {view}")
        frag = v.fragment(shard)
        if frag is None:
            raise NotFoundError(f"fragment not found: shard {shard}")
        return frag.blocks()

    def fragment_block_data(self, index: str, field: str, view: str,
                            shard: int, block: int):
        f = self.field(index, field)
        v = f.view(view)
        frag = None if v is None else v.fragment(shard)
        if frag is None:
            raise NotFoundError(f"fragment not found: shard {shard}")
        return frag.block_data(block)

    def fragment_data(self, index: str, field: str, view: str, shard: int) -> bytes:
        """Serialized fragment (roaring) for resize transfer
        (api.go FragmentData / fragment.go:2436 WriteTo)."""
        f = self.field(index, field)
        v = f.view(view)
        frag = None if v is None else v.fragment(shard)
        if frag is None:
            raise NotFoundError(f"fragment not found: shard {shard}")
        return frag.to_roaring()

    # ---------------------------------------------------------- translate

    def translate_data(self, index: str, field: str | None, after: int,
                       limit: int = 10000):
        """Tail the primary's translate entry stream
        (api.go TranslateData / http/translator.go:30)."""
        if field:
            store = self.field(index, field).translate_store
        else:
            store = self.index(index).translate_store
        return store.entries(after, limit)


def _word_bits(words: np.ndarray) -> np.ndarray:
    """Bit offsets set in a packed little-endian word array."""
    if words is None or len(words) == 0:
        return np.empty(0, dtype=np.int64)
    bits = np.unpackbits(
        np.asarray(words).view(np.uint8), bitorder="little"
    )
    return np.nonzero(bits)[0]
