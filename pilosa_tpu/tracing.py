"""Tracing: Tracer/Span facade, context propagation, OTLP export.

Parity target: the reference's tracing package (tracing/tracing.go:27-76
Tracer/Span interfaces + GlobalTracer; opentracing/jaeger adapter
tracing/opentracing/opentracing.go:36).  Spans wrap executor ops and API
methods; the HTTP layer extracts/injects W3C ``traceparent`` headers the
way the reference's middleware does (http/handler.go:321), so a trace
follows a query across the scatter-gather fan-out to remote nodes.

Span parentage is implicit via a per-thread active-span stack (the
moral equivalent of context.Context threading in Go): ``start_span``
parents to the innermost active span unless an explicit parent is
given; cross-thread and cross-process boundaries re-attach via
``current_span()`` capture and ``inject_headers``/``extract_headers``.

Export: ``MemTracer`` records in-process (tests, /debug); ``OtlpExporter``
ships finished spans as OTLP/HTTP JSON to a collector endpoint from a
background thread.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
import uuid

_active = threading.local()  # .stack: list of active spans (innermost last)


def new_trace_id() -> str:
    """A fresh 32-hex W3C trace id — for work that originates inside
    the cluster (anti-entropy rounds, hint replay, rebalance plans)
    rather than behind an instrumented client."""
    return uuid.uuid4().hex


def normalize_trace_id(tid) -> str:
    """Canonical 32-hex lowercase form.  Flight records may carry a
    short self-generated id (observe.QueryRecord's 20-hex fallback)
    while traceparent headers zero-pad to 32 — every cross-node match
    on trace id must compare normalized forms."""
    return f"{tid:0>32}".lower()


def current_span() -> "Span | None":
    stack = getattr(_active, "stack", None)
    return stack[-1] if stack else None


def _push(span) -> None:
    if not hasattr(_active, "stack"):
        _active.stack = []
    _active.stack.append(span)


def _pop(span) -> None:
    stack = getattr(_active, "stack", None)
    if stack and stack[-1] is span:
        stack.pop()


class Span:
    """No-op span; also the base for recorded spans.  Entering a span
    makes it the thread's active span (the default parent)."""

    trace_id: str | None = None
    span_id: str | None = None

    def set_tag(self, key: str, value) -> None:
        pass

    def finish(self) -> None:
        pass

    def __enter__(self):
        _push(self)
        return self

    def __exit__(self, *exc):
        _pop(self)
        self.finish()
        return False


class RemoteParent(Span):
    """A span handle reconstructed from a traceparent header — parent
    for server-side spans of a propagated trace."""

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id
        self.name = "remote"


class ContextSpan(Span):
    """Trace identity WITHOUT recording: the nop tracer's propagation
    vehicle.  Before this class the default ``Tracer`` returned a bare
    ``Span()`` for every start_span call, which silently DROPPED an
    inbound RemoteParent — a remote node under the nop tracer
    self-generated a fresh record id and cross-node trace assembly had
    nothing to join on.  A ContextSpan inherits the ids (so
    ``inject_headers``/``active_trace_id`` keep working downstream)
    and records nothing; when no trace is in scope the nop tracer
    still returns the zero-cost bare ``Span()``."""

    def __init__(self, trace_id: str, span_id: str | None = None):
        self.trace_id = trace_id
        self.span_id = span_id or uuid.uuid4().hex[:16]


def inject_headers(span: Span | None = None) -> dict[str, str]:
    """W3C trace-context header for an outgoing request (reference
    middleware inject, http/handler.go:321).  Empty when no recorded
    span is active (nop tracer: nothing to propagate)."""
    span = span or current_span()
    if span is None or not span.trace_id:
        return {}
    return {"traceparent":
            f"00-{span.trace_id:0>32}-{span.span_id:0>16}-01"}


def extract_headers(headers) -> RemoteParent | None:
    """Parse a traceparent header (mapping or http.client-style
    getter) into a RemoteParent, or None."""
    get = headers.get if hasattr(headers, "get") else None
    raw = get("traceparent") if get else None
    if not raw:
        return None
    parts = raw.strip().split("-")
    if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    trace_id, span_id = parts[1], parts[2]
    hexdigits = set("0123456789abcdef")
    if (len(parts[0]) != 2 or not set(parts[0]) <= hexdigits
            or parts[0] == "ff"):
        return None  # W3C: malformed or explicitly-invalid version
    if not (set(trace_id) <= hexdigits and set(span_id) <= hexdigits):
        return None  # W3C: non-hex ids are invalid
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None  # W3C: all-zero ids mean "absent"
    return RemoteParent(trace_id, span_id)


class Tracer:
    def start_span(self, name: str, parent: "Span | None" = None) -> Span:
        if parent is None:
            parent = current_span()
        if parent is not None and parent.trace_id:
            # keep a propagated trace alive through the nop tracer:
            # server-side spans of a traced query must carry the ids
            # forward (records, downstream RPC headers) even when
            # nothing is being recorded locally
            return ContextSpan(parent.trace_id)
        return Span()


class RecordedSpan(Span):
    def __init__(self, tracer: "MemTracer", name: str,
                 parent: "Span | None"):
        self.tracer = tracer
        self.name = name
        self.trace_id = (parent.trace_id if parent is not None
                         and parent.trace_id else uuid.uuid4().hex)
        self.span_id = uuid.uuid4().hex[:16]
        self.parent_span_id = (parent.span_id if parent is not None
                               else None)
        self.parent_name = getattr(parent, "name", None)
        self.tags: dict = {}
        self.start_unix_ns = time.time_ns()
        self.start_ns = time.perf_counter_ns()
        self.duration_ns: int | None = None

    def set_tag(self, key, value):
        self.tags[key] = value

    def finish(self):
        if self.duration_ns is None:
            self.duration_ns = time.perf_counter_ns() - self.start_ns
            self.tracer._record(self)


class MemTracer(Tracer):
    """In-memory recording tracer — the test/debug backend; exporters
    subclass and ship finished spans instead."""

    def __init__(self, max_spans: int = 10000):
        self.max_spans = max_spans
        self._lock = threading.Lock()
        self.spans: list[RecordedSpan] = []

    def start_span(self, name, parent=None):
        if parent is None:
            parent = current_span()
        return RecordedSpan(self, name, parent)

    def _record(self, span: RecordedSpan) -> None:
        with self._lock:
            if len(self.spans) < self.max_spans:
                self.spans.append(span)

    def finished(self, name: str | None = None) -> list[RecordedSpan]:
        with self._lock:
            return [s for s in self.spans if name is None or s.name == name]


def _otlp_json(spans, service: str) -> bytes:
    def attrs(d):
        return [{"key": str(k), "value": {"stringValue": str(v)}}
                for k, v in d.items()]

    out = []
    for s in spans:
        rec = {
            "traceId": f"{s.trace_id:0>32}",
            "spanId": f"{s.span_id:0>16}",
            "name": s.name,
            "kind": 1,
            "startTimeUnixNano": str(s.start_unix_ns),
            "endTimeUnixNano": str(s.start_unix_ns + (s.duration_ns or 0)),
            "attributes": attrs(s.tags),
        }
        if s.parent_span_id:
            rec["parentSpanId"] = f"{s.parent_span_id:0>16}"
        out.append(rec)
    return json.dumps({"resourceSpans": [{
        "resource": {"attributes": attrs({"service.name": service})},
        "scopeSpans": [{"scope": {"name": "pilosa_tpu"}, "spans": out}],
    }]}).encode()


class OtlpExporter(MemTracer):
    """Ships finished spans to an OTLP/HTTP collector (`/v1/traces`)
    in batches from a daemon thread — the jaeger-adapter slot of the
    reference (tracing/opentracing/opentracing.go:36), speaking the
    open standard instead."""

    def __init__(self, endpoint: str, service: str = "pilosa-tpu",
                 flush_interval: float = 2.0, max_batch: int = 512):
        super().__init__(max_spans=1 << 30)
        self.endpoint = endpoint.rstrip("/")
        self.service = service
        self.flush_interval = flush_interval
        self.max_batch = max_batch
        self._buf: list[RecordedSpan] = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="otlp-exporter")
        self._thread.start()

    MAX_BUFFER = 16384  # spans; beyond this the oldest drop (outage cap)

    def _record(self, span: RecordedSpan) -> None:
        with self._lock:
            self._buf.append(span)
            if len(self._buf) > self.MAX_BUFFER:
                del self._buf[: len(self._buf) - self.MAX_BUFFER]

    def _loop(self) -> None:
        while not self._stop.wait(self.flush_interval):
            self.flush()
        self.flush()

    def flush(self) -> None:
        while True:
            with self._lock:
                batch = self._buf[:self.max_batch]
                self._buf = self._buf[self.max_batch:]
            if not batch:
                return
            import urllib.request

            body = _otlp_json(batch, self.service)
            req = urllib.request.Request(
                self.endpoint + "/v1/traces", data=body,
                headers={"Content-Type": "application/json"})
            try:
                urllib.request.urlopen(req, timeout=5).read()
            except Exception:
                # collector outage never affects serving — but a
                # transient error must not LOSE the popped batch: put
                # it back for the next tick (MAX_BUFFER still caps
                # memory during a long outage)
                with self._lock:
                    self._buf[:0] = batch
                    if len(self._buf) > self.MAX_BUFFER:
                        del self._buf[: len(self._buf) - self.MAX_BUFFER]
                return

    def close(self) -> None:
        """Stop the exporter thread and ship the final span batch.
        Idempotent; wired into the server's shutdown closers
        (cmd.run_server) — without the explicit final flush the batch
        recorded since the last 2 s tick would die with the daemon
        thread.  The post-join flush also covers a thread that died or
        missed the join window, and the global tracer is reset so
        spans finished after shutdown stop buffering into a dead
        exporter."""
        self._stop.set()
        self._thread.join(timeout=10)
        self.flush()
        if global_tracer() is self:
            set_global_tracer(Tracer())


_global = Tracer()
_global_lock = threading.Lock()


def global_tracer() -> Tracer:
    return _global


def set_global_tracer(t: Tracer) -> None:
    global _global
    with _global_lock:
        _global = t


def start_span(name: str, parent: Span | None = None) -> Span:
    """(reference tracing.StartSpanFromContext, tracing/tracing.go:60)"""
    return _global.start_span(name, parent)


@contextlib.contextmanager
def propagate(trace_id):
    """Make ``trace_id`` this thread's active trace for the scope —
    the cross-thread/cross-subsystem re-attach primitive.  Worker
    threads (hedge IO, hint replay, AE rounds, rebalance transfers,
    debug fan-in) run outside the request thread's span stack; wrapping
    their work in ``propagate(tid)`` makes every RPC they issue carry
    ``traceparent`` and every record they produce link the trace.

    No-ops (zero allocation) for a falsy id, and defers to an already-
    active traced span — an explicit propagate never clobbers real
    span parentage established by a recording tracer."""
    if not trace_id:
        yield None
        return
    span = current_span()
    if span is not None and span.trace_id:
        yield span
        return
    cs = ContextSpan(normalize_trace_id(trace_id))
    _push(cs)
    try:
        yield cs
    finally:
        _pop(cs)


def active_trace_id() -> str | None:
    """Trace id of this thread's innermost active span, or None under
    the nop tracer.  The query flight recorder (pilosa_tpu.observe)
    stamps it on each QueryRecord so a /debug/queries entry, a slow-
    query log line, and a histogram exemplar all share the id of the
    exported span tree — the span -> record linkage."""
    span = current_span()
    return span.trace_id if span is not None else None
