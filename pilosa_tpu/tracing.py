"""Tracing: minimal Tracer/Span facade with a global tracer.

Parity target: the reference's tracing package (tracing/tracing.go:27-76
Tracer/Span interfaces + GlobalTracer; opentracing/jaeger adapter
tracing/opentracing/opentracing.go:36).  Spans wrap executor ops and API
methods; the HTTP layer propagates a trace id header the way the
reference's middleware does (http/handler.go:321)."""

from __future__ import annotations

import threading
import time
import uuid


class Span:
    def set_tag(self, key: str, value) -> None:
        pass

    def finish(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.finish()
        return False


class Tracer:
    def start_span(self, name: str, parent: "Span | None" = None) -> Span:
        return Span()


class RecordedSpan(Span):
    def __init__(self, tracer: "MemTracer", name: str,
                 parent: "RecordedSpan | None"):
        self.tracer = tracer
        self.name = name
        self.trace_id = parent.trace_id if parent else uuid.uuid4().hex[:16]
        self.parent_name = parent.name if parent else None
        self.tags: dict = {}
        self.start_ns = time.perf_counter_ns()
        self.duration_ns: int | None = None

    def set_tag(self, key, value):
        self.tags[key] = value

    def finish(self):
        if self.duration_ns is None:
            self.duration_ns = time.perf_counter_ns() - self.start_ns
            self.tracer._record(self)


class MemTracer(Tracer):
    """In-memory recording tracer — the test/debug backend; a jaeger
    exporter would subclass and ship finished spans instead."""

    def __init__(self, max_spans: int = 10000):
        self.max_spans = max_spans
        self._lock = threading.Lock()
        self.spans: list[RecordedSpan] = []

    def start_span(self, name, parent=None):
        return RecordedSpan(self, name, parent)

    def _record(self, span: RecordedSpan) -> None:
        with self._lock:
            if len(self.spans) < self.max_spans:
                self.spans.append(span)

    def finished(self, name: str | None = None) -> list[RecordedSpan]:
        with self._lock:
            return [s for s in self.spans if name is None or s.name == name]


_global = Tracer()
_global_lock = threading.Lock()


def global_tracer() -> Tracer:
    return _global


def set_global_tracer(t: Tracer) -> None:
    global _global
    with _global_lock:
        _global = t


def start_span(name: str, parent: Span | None = None) -> Span:
    """(reference tracing.StartSpanFromContext, tracing/tracing.go:60)"""
    return _global.start_span(name, parent)
