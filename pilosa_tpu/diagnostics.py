"""Diagnostics + runtime monitoring.

Parity target: the reference's diagnostics collector (diagnostics.go:42-263
— version, schema shape, host info) and Server.monitorRuntime
(server.go:813-876 — goroutine/heap/FD gauges every metricInterval).
Deviation, by design: the reference phones home to diagnostics.pilosa.com
hourly; this build never sends anything anywhere — the same payload is
served locally at GET /diagnostics instead (this environment has zero
egress, and phone-home is an anti-feature for an embedded framework)."""

from __future__ import annotations

import gc
import os
import platform
import threading
import time

from pilosa_tpu.version import VERSION


def payload(node) -> dict:
    """The diagnostics document (diagnostics.go CheckVersion/Flush set)."""
    holder = node.holder
    n_fields = 0
    n_indexes = 0
    field_types: dict[str, int] = {}
    for d in holder.schema():
        n_indexes += 1
        for f in d.get("fields", []):
            n_fields += 1
            t = f.get("options", {}).get("type", "set")
            field_types[t] = field_types.get(t, 0) + 1
    return {
        "version": VERSION,
        "numIndexes": n_indexes,
        "numFields": n_fields,
        "fieldTypes": field_types,
        "numNodes": len(node.cluster.sorted_nodes()),
        "clusterState": node.cluster.state,
        "os": platform.system(),
        "arch": platform.machine(),
        "pythonVersion": platform.python_version(),
        "uptime": time.time() - _START_TIME,
    }


_START_TIME = time.time()


def compare_versions(current: str, latest: str) -> bool:
    """True when ``latest`` is strictly newer than ``current`` —
    numeric dotted compare with a lenient tail (the reference's
    VersionSegments compare, diagnostics.go:230 compareVersions)."""
    def segs(v: str) -> list[int]:
        v = v.lstrip("v").split("-")[0].split("+")[0]
        out = []
        for part in v.split("."):
            digits = "".join(ch for ch in part if ch.isdigit())
            out.append(int(digits) if digits else 0)
        return out
    a, b = segs(current), segs(latest)
    n = max(len(a), len(b))
    a += [0] * (n - len(a))
    b += [0] * (n - len(b))
    return b > a


def check_version(fetch=None) -> dict:
    """Update-check surface (reference diagnostics.go CheckVersion,
    which polls the install server hourly).  This build NEVER phones
    home (the documented local-only deviation): with no ``fetch`` the
    check reports itself disabled; an operator can wire ``fetch`` — a
    zero-arg callable returning the latest version string from their
    own mirror — and gets the reference's compare/report behavior."""
    out: dict = {"version": VERSION}
    if fetch is None:
        out["updateCheck"] = "disabled (local-only diagnostics; " \
                             "wire a fetcher to enable)"
        return out
    try:
        latest = str(fetch())
    except Exception as e:  # noqa: BLE001 — a broken mirror must not 500 /version
        out["updateCheck"] = f"error: {e!r}"
        return out
    out["latest"] = latest
    out["updateAvailable"] = compare_versions(VERSION, latest)
    return out


def runtime_gauges(stats) -> None:
    """One sweep of process gauges (server.go:813 monitorRuntime:
    goroutines -> threads, heap -> RSS, open FDs, GC collections)."""
    stats.gauge("threads", threading.active_count())
    try:
        with open("/proc/self/statm") as f:
            rss_pages = int(f.read().split()[1])
        stats.gauge("memory.rss_bytes", rss_pages * os.sysconf("SC_PAGE_SIZE"))
    except (OSError, ValueError, IndexError):
        pass
    try:
        stats.gauge("open_files", len(os.listdir("/proc/self/fd")))
    except OSError:
        pass
    try:
        from pilosa_tpu.runtime import residency

        r = residency.manager().stats()
        stats.gauge("device.cache_bytes", r["total"])
        stats.gauge("device.cache_budget_bytes", r["budget"])
        stats.gauge("device.cache_entries", r["entries"])
        stats.gauge("device.cache_evictions", r["evictions"])
    except Exception:
        pass  # gauges must never take the monitor loop down
    counts = gc.get_count()
    for i, c in enumerate(counts):
        stats.gauge(f"gc.gen{i}_count", c)
    totals = gc.get_stats()
    if totals:
        stats.gauge("gc.collections",
                    sum(s.get("collections", 0) for s in totals))


class RuntimeMonitor:
    """Background gauge loop (the reference's monitorRuntime goroutine +
    GCNotifier gauge, gc.go:21)."""

    def __init__(self, stats, interval: float):
        self.stats = stats
        self.interval = interval
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        if self.interval <= 0:
            return
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                runtime_gauges(self.stats)
            except Exception:
                pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
