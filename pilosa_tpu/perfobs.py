"""Engine observatory: per-launch wall time + bytes-touched accounting,
achieved bandwidth per engine, the shadow cost model, and on-demand
device profiler capture.

The flight recorder (pilosa_tpu.observe) explains where a QUERY spent
its time and devobs explains compile/transfer/memory events — but
neither measures what ROADMAP items 1 and 4 need: steady-state
per-LAUNCH device time and the bytes each engine actually touched, per
engine and per workload shape.  Roaring itself picks container
representations by measured cost (PAPERS.md 1709.07821) and TPU kernel
tuning of exactly our shape — ragged gathers over pooled blocks — is
driven by achieved-bandwidth accounting (PAPERS.md 2604.15464).  This
module is that measurement substrate:

- **Per-launch samples** — every engine dispatch site (dense fused
  ``ops/expr``, container-gather ``expr.evaluate_gathered``, ragged
  tape ``tape.execute``, Pallas VM ``tape.execute_vm``, the mesh
  shard_map variants, and the per-shard host path) brackets its launch
  with :func:`t0` / :func:`sample`.  ``sample`` blocks on the result
  (``jax.block_until_ready`` — compile time is already split out by
  devobs, so steady-state walls are clean after the first call) and
  pairs the wall time with an ANALYTIC bytes-touched estimate from the
  operand shapes: stack words for the dense engines, pooled container
  words gathered plus directory scalars for the compressed ones,
  register files for the interpreters.  bytes/wall yields achieved
  GB/s; against the configured roof (``[observe] device-peak-gbps``,
  defaulted per device kind) that is the ``bw_util`` the chip captures
  report.
- **Cost table** — samples feed a process-wide EWMA + deviation table
  keyed (engine, work size-class, sparsity bucket), rendered at
  ``GET /debug/cost`` and summarized per engine for
  ``tools/chipcapture.py``.
- **Shadow cost model** — with ``[cost] shadow=true`` (the default)
  the executor/coalescer consult :func:`would_choose` AFTER routing:
  the table's verdict lands on the flight record (``wouldChoose`` /
  ``costDisagree``) and ticks ``cost.disagreements``, while the launch
  itself is byte-identical to a consult-free build — the stepping
  stone to ROADMAP item 4's cost-based planner, never the planner
  itself.  ``shadow=false`` disables the consult entirely (samples
  still collect).
- **Profiler capture** — ``POST /debug/profiler/start|stop`` wraps
  ``jax.profiler.start_trace``/``stop_trace`` into a dated artifact
  dir, try-lock 409 on concurrent capture (the /debug/pprof/profile
  discipline) and auto-stop after ``[observe] profiler-max-seconds``.

Lock discipline: the disarmed fast path is ONE module-bool read
(:func:`t0` returns 0 and every sample call gates on it); blocking
(``block_until_ready``) always happens OUTSIDE the module lock, which
only covers the table/counter writes.  Budget: < 1% of the coalesced
Count path (bench.py extras.perfobs).
"""

from __future__ import annotations

import math
import os
import threading
import time
from typing import Any

from pilosa_tpu import observe as _observe

#: The canonical engine taxonomy — the one ``engine`` enum the flight
#: record, /debug/cost, and the chip captures all share.
ENGINES = ("dense", "gather", "tape", "vm", "mesh", "host",
           "collective", "gather_aa", "gather_ab", "gather_kinds",
           "vm_kinds")

#: Shadow consult requires this many samples in BOTH cells before it
#: is willing to disagree — a single noisy wall must not tick a
#: disagreement.
MIN_SAMPLES = 3

#: EWMA smoothing for wall/bytes/bandwidth per cell.
ALPHA = 0.2

#: Injectable monotonic clock (tests drive the cost-table math under a
#: fake clock by monkeypatching this).
_clock = time.perf_counter_ns

#: HBM roof (GB/s) per jax ``device_kind`` substring, checked in
#: order — datasheet ballparks, good enough for a utilization ratio
#: (an operator with exact numbers sets ``[observe] device-peak-gbps``).
#: The CPU entry is a host-DDR ballpark so the CPU twin's bw_util stays
#: a meaningful fraction instead of a lie against an HBM roof.
KIND_PEAKS: tuple[tuple[str, float], ...] = (
    ("v5e", 819.0), ("v5 lite", 819.0), ("v5p", 2765.0),
    ("v6", 1640.0), ("v5", 2765.0), ("v4", 1228.0), ("v3", 900.0),
    ("v2", 700.0), ("cpu", 100.0),
)
DEFAULT_PEAK_GBPS = 819.0  # the committed capture's roof (ROADMAP 1)


# ---------------------------------------------------------------- runtime cfg


class PerfobsRuntimeConfig:
    """Process-wide observatory knobs (``[observe]`` + ``[cost]``)."""

    __slots__ = ("enabled", "peak_gbps", "shadow",
                 "profiler_max_seconds")

    def __init__(self, enabled: bool = True, peak_gbps: float = 0.0,
                 shadow: bool = True,
                 profiler_max_seconds: float = 30.0):
        self.enabled = enabled
        self.peak_gbps = peak_gbps  # 0 = default per device kind
        self.shadow = shadow
        self.profiler_max_seconds = profiler_max_seconds


_cfg = PerfobsRuntimeConfig()
_cfg_lock = threading.Lock()
_baseline: PerfobsRuntimeConfig | None = None
_refs = 0
#: Module-bool fast gate mirroring ``config().enabled`` — the per-call
#: cost of a disabled observatory is one attribute read (the
#: faultinject.armed discipline).
enabled = True


def config() -> PerfobsRuntimeConfig:
    with _cfg_lock:
        return _cfg


def configure(enabled_: bool | None = None,
              peak_gbps: float | None = None,
              shadow: bool | None = None,
              profiler_max_seconds: float | None = None) -> None:
    """Apply explicit values only (the containers.configure rule: an
    absent kwarg leaves the knob untouched)."""
    global enabled, _peak_cached
    with _cfg_lock:
        if enabled_ is not None:
            _cfg.enabled = enabled_
        if peak_gbps is not None:
            _cfg.peak_gbps = peak_gbps
        if shadow is not None:
            _cfg.shadow = shadow
        if profiler_max_seconds is not None:
            _cfg.profiler_max_seconds = profiler_max_seconds
        enabled = _cfg.enabled
        _peak_cached = None


def retain() -> None:
    """First retain snapshots the baseline config (server open)."""
    global _refs, _baseline
    with _cfg_lock:
        if _refs == 0:
            _baseline = PerfobsRuntimeConfig(
                _cfg.enabled, _cfg.peak_gbps, _cfg.shadow,
                _cfg.profiler_max_seconds)
        _refs += 1


def release() -> None:
    """Last release restores the baseline (server close) — paired with
    :func:`retain`."""
    global _refs, _baseline, enabled, _peak_cached
    with _cfg_lock:
        if _refs == 0:
            return
        _refs -= 1
        if _refs == 0 and _baseline is not None:
            _cfg.enabled = _baseline.enabled
            _cfg.peak_gbps = _baseline.peak_gbps
            _cfg.shadow = _baseline.shadow
            _cfg.profiler_max_seconds = _baseline.profiler_max_seconds
            _baseline = None
            enabled = _cfg.enabled
            _peak_cached = None


def reset() -> None:
    """Restore defaults and drop all samples/counters (tests)."""
    global _cfg, _baseline, _refs, enabled, _peak_cached
    with _cfg_lock:
        _cfg = PerfobsRuntimeConfig()
        _baseline = None
        _refs = 0
        enabled = True
        _peak_cached = None
    with _lock:
        _table.clear()
        for k in _counters:
            _counters[k] = 0


_peak_cached: float | None = None


def device_peak_gbps() -> float:
    """The configured bandwidth roof, or the per-device-kind default —
    cached until the next configure/reset (jax device lookup is not
    free and this is read per sample)."""
    global _peak_cached
    p = _peak_cached
    if p is not None:
        return p
    with _cfg_lock:
        explicit = _cfg.peak_gbps
    if explicit > 0:
        _peak_cached = explicit
        return explicit
    kind = ""
    try:
        import jax

        devs = jax.devices()
        if devs:
            kind = (devs[0].device_kind or devs[0].platform or "")
    except Exception:  # noqa: BLE001 — no backend ≠ no observatory
        pass
    kind = kind.lower()
    peak = DEFAULT_PEAK_GBPS
    for sub, gbps in KIND_PEAKS:
        if sub in kind:
            peak = gbps
            break
    _peak_cached = peak
    return peak


# ------------------------------------------------------------------- counters

_lock = threading.Lock()
_counters = {
    "engine.launches": 0,       # sampled steady-state launches
    "engine.bytes": 0,          # analytic bytes across sampled launches
    "cost.samples": 0,          # cost-table sample insertions
    "cost.consults": 0,         # shadow-mode comparisons performed
    "cost.disagreements": 0,    # consults where the table preferred
                                # a different engine than routing chose
    "cost.profiles": 0,         # completed profiler captures
}


def bump(name: str, value: int = 1) -> None:
    with _lock:
        _counters[name] += value


def counters() -> dict[str, int]:
    with _lock:
        return dict(_counters)


def reset_counters() -> None:
    """Zero counters and the cost table (tests)."""
    with _lock:
        for k in _counters:
            _counters[k] = 0
        _table.clear()


def publish_gauges(stats: Any) -> None:
    """Push the engine.*/cost.* families into a stats registry at
    scrape time — cumulative totals as GAUGES (the tape/devobs rule:
    re-publishing a cumulative value through a counter double-counts).
    Per-engine achieved bandwidth rides engine tags."""
    with _lock:
        snap = dict(_counters)
        cells = len(_table)
    for name, value in snap.items():
        stats.gauge(name, value)
    stats.gauge("cost.cells", cells)
    stats.gauge("cost.shadow", 1 if config().shadow else 0)
    stats.gauge("engine.peak_gbps", device_peak_gbps())
    for eng, s in engine_summary().items():
        tagged = stats.with_tags(f"engine:{eng}")
        tagged.gauge("engine.wall_us", s["wallUs"])
        tagged.gauge("engine.gbps", s["gbps"])
        tagged.gauge("engine.bw_util", s["bwUtil"])


# ----------------------------------------------------------------- cost table


class _Cell:
    """One (engine, size-class, sparsity-bucket) cost cell: EWMA wall
    time with an EWMA absolute deviation (the hedging estimator's
    shape, parallel/executor.py), plus bytes and achieved GB/s."""

    __slots__ = ("count", "ewma_us", "dev_us", "ewma_bytes",
                 "ewma_gbps", "last_us")

    def __init__(self):
        self.count = 0
        self.ewma_us = 0.0
        self.dev_us = 0.0
        self.ewma_bytes = 0.0
        self.ewma_gbps = 0.0
        self.last_us = 0.0

    def add(self, wall_us: float, nbytes: int, gbps: float) -> None:
        if self.count == 0:
            self.ewma_us = wall_us
            self.ewma_bytes = float(nbytes)
            self.ewma_gbps = gbps
        else:
            self.dev_us += ALPHA * (abs(wall_us - self.ewma_us)
                                    - self.dev_us)
            self.ewma_us += ALPHA * (wall_us - self.ewma_us)
            self.ewma_bytes += ALPHA * (nbytes - self.ewma_bytes)
            self.ewma_gbps += ALPHA * (gbps - self.ewma_gbps)
        self.count += 1
        self.last_us = wall_us


_table: dict[tuple[str, str, str], _Cell] = {}


def size_class(work: int) -> str:
    """Pow2 size-class label for a launch's work (uint32 words read by
    a dense-equivalent evaluation) — "2^14" etc., so similar workloads
    share a cell instead of every exact shape owning one."""
    if work <= 1:
        return "2^0"
    return f"2^{int(math.ceil(math.log2(work)))}"


def sparsity_bucket(sparsity: float) -> str:
    """Coarse bucket of bytes-touched / dense-equivalent-bytes: the
    compressed engines win exactly as this falls, so it is the second
    cost-table axis."""
    if sparsity <= 0.0:
        return "0"
    if sparsity < 0.01:
        return "<1%"
    if sparsity < 0.1:
        return "<10%"
    if sparsity < 0.5:
        return "<50%"
    return ">=50%"


# ------------------------------------------------------- launch-scope context


_tls = threading.local()


class context:
    """Attribute launches sampled on this thread: the orchestration
    layer (executor per-shard map, coalescer flush) knows the engine
    taxonomy slot, the data sparsity, and the dense-equivalent work;
    the ops layer only knows its own operands.  Scopes nest (inner
    shadows)."""

    __slots__ = ("engine", "sparsity", "work", "_prev")

    def __init__(self, engine: str | None = None,
                 sparsity: float | None = None,
                 work: int | None = None):
        self.engine = engine
        self.sparsity = sparsity
        self.work = work

    def __enter__(self) -> "context":
        self._prev = getattr(_tls, "ctx", None)
        _tls.ctx = self
        return self

    def __exit__(self, *exc) -> bool:
        _tls.ctx = self._prev
        return False


def _ctx() -> "context | None":
    return getattr(_tls, "ctx", None)


# ------------------------------------------------------------------- sampling


def t0() -> int:
    """Launch-bracket start: the clock when the observatory is on,
    0 when off — call sites gate the sample on the returned value, so
    a disabled observatory costs one module-bool read per launch."""
    return _clock() if enabled else 0


def sample(engine: str, out: Any, t0_ns: int, nbytes: int,
           work: int = 0, sparsity: float = 1.0) -> None:
    """Complete one launch sample: block on ``out`` (OUTSIDE any lock
    — the P3 rule), then fold wall/bytes/bandwidth into the cost table
    and stamp the engine onto the active flight record.

    ``nbytes`` — analytic bytes the launch touched (operand reads +
    result writes); ``work`` — dense-equivalent uint32 words for the
    size-class key (defaults to nbytes/4); ``sparsity`` — bytes
    touched / dense-equivalent bytes (1.0 for the dense engines).  A
    thread-local :class:`context` overrides engine/sparsity when the
    orchestration layer knows better than the ops layer."""
    if not t0_ns:
        return
    ctx = _ctx()
    if ctx is not None:
        if ctx.engine is not None:
            engine = ctx.engine
        if ctx.sparsity is not None:
            sparsity = ctx.sparsity
        if ctx.work is not None:
            work = ctx.work
    try:
        import jax

        jax.block_until_ready(out)
    except Exception:  # noqa: BLE001 — telemetry never fails a query
        pass
    record_sample(engine, _clock() - t0_ns, nbytes, work, sparsity)
    rec = _observe.current()
    if rec is not None:
        rec.note_engine(engine)


def record_sample(engine: str, wall_ns: int, nbytes: int,
                  work: int = 0, sparsity: float = 1.0) -> None:
    """Fold one measured launch into the cost table (the pure math
    under :func:`sample` — tests drive it directly with a fake
    clock)."""
    wall_us = wall_ns / 1e3
    gbps = ((nbytes / (wall_ns / 1e9)) / 1e9) if wall_ns > 0 else 0.0
    key = (engine, size_class(work if work > 0 else max(1, nbytes // 4)),
           sparsity_bucket(sparsity))
    with _lock:
        cell = _table.get(key)
        if cell is None:
            cell = _table[key] = _Cell()
        cell.add(wall_us, nbytes, gbps)
        _counters["engine.launches"] += 1
        _counters["engine.bytes"] += nbytes
        _counters["cost.samples"] += 1


# --------------------------------------------------------------- shadow model


def would_choose(chosen: str,
                 candidates: dict[str, tuple[int, float]]) -> str | None:
    """SHADOW-mode cost consult: given the engine routing chose and
    each candidate engine's (work, sparsity) coordinates for THIS
    batch, return the engine the cost table would have picked instead,
    or None when it agrees / lacks confident data.  Ticks
    ``cost.consults`` always and ``cost.disagreements`` on a disagree.
    Never changes routing — callers only stamp the verdict onto the
    flight record (``[cost] shadow=false`` turns the consult off
    entirely)."""
    if not enabled or not config().shadow:
        return None
    with _lock:
        _counters["cost.consults"] += 1
        chosen_cell = None
        best = None
        best_us = float("inf")
        for eng, (work, sparsity) in candidates.items():
            cell = _table.get((eng, size_class(work),
                               sparsity_bucket(sparsity)))
            if cell is None or cell.count < MIN_SAMPLES:
                if eng == chosen:
                    return None  # no confident baseline to disagree with
                continue
            if eng == chosen:
                chosen_cell = cell
            if cell.ewma_us < best_us:
                best, best_us = eng, cell.ewma_us
        if (best is None or best == chosen or chosen_cell is None
                or best_us >= chosen_cell.ewma_us):
            return None
        _counters["cost.disagreements"] += 1
        return best


# ------------------------------------------------------------------- exports


def engine_summary() -> dict[str, dict]:
    """Per-engine rollup of the cost table (sample-count-weighted):
    the measured bw_util slice chip captures stamp
    (tools/chipcapture.py) and the tagged engine.* gauges."""
    peak = device_peak_gbps()
    out: dict[str, dict] = {}
    with _lock:
        for (eng, _s, _sp), cell in _table.items():
            agg = out.setdefault(eng, {"launches": 0, "_us": 0.0,
                                       "_bytes": 0.0, "_gbps": 0.0})
            agg["launches"] += cell.count
            agg["_us"] += cell.ewma_us * cell.count
            agg["_bytes"] += cell.ewma_bytes * cell.count
            agg["_gbps"] += cell.ewma_gbps * cell.count
    for eng, agg in out.items():
        n = max(1, agg["launches"])
        gbps = agg.pop("_gbps") / n
        agg["wallUs"] = round(agg.pop("_us") / n, 3)
        agg["bytes"] = int(agg.pop("_bytes") / n)
        agg["gbps"] = round(gbps, 3)
        agg["bwUtil"] = round(gbps / peak, 4) if peak > 0 else 0.0
    return out


def cost_debug() -> dict:
    """The GET /debug/cost document: config, counters, the per-cell
    cost table, and the per-engine rollup."""
    peak = device_peak_gbps()
    cfg = config()
    with _lock:
        rows = [
            {"engine": eng, "size": size, "sparsity": sp,
             "samples": c.count, "wallUs": round(c.ewma_us, 3),
             "devUs": round(c.dev_us, 3),
             "bytes": int(c.ewma_bytes), "gbps": round(c.ewma_gbps, 3),
             "bwUtil": (round(c.ewma_gbps / peak, 4)
                        if peak > 0 else 0.0),
             "lastUs": round(c.last_us, 3)}
            for (eng, size, sp), c in sorted(_table.items())
        ]
        snap = dict(_counters)
    return {
        "enabled": cfg.enabled,
        "shadow": cfg.shadow,
        "peakGbps": peak,
        "counters": snap,
        "engines": engine_summary(),
        "table": rows,
        "profiler": profiler_status(),
    }


def debug() -> dict:
    """Alias kept symmetric with the other observability modules."""
    return cost_debug()


# ----------------------------------------------------------- profiler capture


class ProfilerBusy(RuntimeError):
    """A device-profiler capture is already active (handler -> 409)."""


class ProfilerIdle(RuntimeError):
    """Stop requested with no active capture (handler -> 409)."""


#: Held (non-blocking acquire) for the whole start..stop window — the
#: /debug/pprof/profile discipline: a concurrent start is a 409, never
#: a queued second capture.  A plain Lock deliberately: stop may run on
#: a different HTTP thread (or the auto-stop timer) than start.
_prof_lock = threading.Lock()
#: Tiny mutex over the capture bookkeeping (dir/since/timer) so status
#: reads and the manual-stop/auto-stop race stay consistent.
_prof_state_lock = threading.Lock()
_prof: dict[str, Any] = {"active": False, "dir": None, "since": 0.0,
                         "timer": None, "auto_stopped": False}


def profiler_start(base_dir: str,
                   max_seconds: float | None = None) -> dict:
    """Begin a device trace into a dated artifact dir under
    ``base_dir`` (``profiles/trace_<UTCSTAMP>``).  Raises
    :class:`ProfilerBusy` when a capture is already active; arms an
    auto-stop timer after ``max_seconds`` (default ``[observe]
    profiler-max-seconds``; 0 disables) so a forgotten capture cannot
    trace forever."""
    if not _prof_lock.acquire(blocking=False):
        raise ProfilerBusy("a profiler capture is already active")
    try:
        stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        out_dir = os.path.join(base_dir, "profiles", f"trace_{stamp}")
        os.makedirs(out_dir, exist_ok=True)
        import jax

        jax.profiler.start_trace(out_dir)
    except BaseException:
        _prof_lock.release()
        raise
    limit = (max_seconds if max_seconds is not None
             else config().profiler_max_seconds)
    timer = None
    if limit and limit > 0:
        timer = threading.Timer(limit, _profiler_auto_stop)
        timer.daemon = True
    with _prof_state_lock:
        _prof["active"] = True
        _prof["dir"] = out_dir
        _prof["since"] = time.time()
        _prof["timer"] = timer
        _prof["auto_stopped"] = False
    if timer is not None:
        timer.start()
    return {"dir": out_dir, "maxSeconds": limit}


def profiler_stop() -> dict:
    """End the active capture: stop the jax trace, cancel the
    auto-stop timer, release the capture lock, and return the artifact
    dir + duration.  Raises :class:`ProfilerIdle` when nothing is
    active (the manual-stop/auto-stop race resolves here: whoever
    flips ``active`` first wins, the loser is told idle)."""
    with _prof_state_lock:
        if not _prof["active"]:
            raise ProfilerIdle("no active profiler capture")
        _prof["active"] = False
        out_dir = _prof["dir"]
        since = _prof["since"]
        timer = _prof["timer"]
        _prof["timer"] = None
    if timer is not None:
        timer.cancel()
    try:
        import jax

        jax.profiler.stop_trace()
    except Exception:  # noqa: BLE001 — the lock must release regardless
        pass
    finally:
        _prof_lock.release()
    bump("cost.profiles")
    return {"dir": out_dir,
            "seconds": round(time.time() - since, 3)}


def _profiler_auto_stop() -> None:
    """Timer body: stop an over-deadline capture; losing the race to a
    manual stop is fine (ProfilerIdle swallowed)."""
    try:
        profiler_stop()
        with _prof_state_lock:
            _prof["auto_stopped"] = True
    except ProfilerIdle:
        pass
    except Exception:  # noqa: BLE001 — a timer thread must not die loud
        pass


def profiler_status() -> dict:
    """Live capture state for /debug/cost and the profiler routes."""
    with _prof_state_lock:
        if not _prof["active"]:
            return {"active": False,
                    "autoStopped": _prof["auto_stopped"],
                    "lastDir": _prof["dir"]}
        return {"active": True, "dir": _prof["dir"],
                "seconds": round(time.time() - _prof["since"], 3)}
