"""Fused expression programs: a whole bitmap call tree as ONE dispatch.

The executor's fused all-shard path (`Executor._fused_eval`) used to emit
one jitted dispatch per AST node — `b_and`, then `row_counts_and`, … —
which is exactly wrong when device dispatch has real latency (VERDICT
round 5: a 20 us trivial-dispatch floor under a 0.555 ms/query capture;
the Count/Intersect hot path is dispatch-bound, not HBM-bound).  This
module compiles the SHAPE of a supported call tree into a single jitted
program over its leaf operand stacks, so the whole tree costs one launch
regardless of depth, and XLA fuses the chain (no materialized
intermediates for AND+popcount roots).

Shape grammar — hashable nested tuples; leaves are slot indices into the
operand tuple, so distinct row ids share one compiled program:

    ("leaf", i)                       operand slot i
    ("and"|"or"|"xor"|"andnot", c, ...)   left-fold over children
    ("not", ("leaf", i_exist), child)     exist & ~child
    ("shift", n, child)                   static shift by n words/bits
    ("dfuse", child, set_c, clear_c)      (child & ~clear) | set

``dfuse`` is the streaming-ingest delta fusion (pilosa_tpu.ingest): the
child is a base row stack resident since its last compaction, the
set/clear leaves are the fragment delta planes — the whole overlay
evaluates inside the same single launch, so sustained writes never
force the base stack off the device.

``evaluate(shape, leaves)`` returns the uint32 bitmap stack;
``evaluate(shape, leaves, counts=True)`` returns int32 per-row popcounts
(the Count root, reduced over the last axis inside the same program).

Every op is elementwise over the last axis (shift pads it, counts reduce
it), so ONE compiled program serves both the unbatched [S, W] stack and
the coalescer's cross-query [B, S, W] batch — jit re-specializes per
rank, the cached Python closure is shared.

Host stacks (single-CPU-device mode, where bm ops route to numpy + the
native popcount kernels) evaluate eagerly — dispatch is free there, so
the whole tree still ticks ONE `note_dispatch` to keep the launch-count
accounting meaningful across engines.
"""

from __future__ import annotations

import threading
from collections import namedtuple
from collections.abc import Callable
from typing import Any

import numpy as np

from pilosa_tpu import perfobs as _perfobs
from pilosa_tpu.ops import bitmap as bm

_FOLD_NAMES = ("and", "or", "xor", "andnot")


def _touched_bytes(*arrs) -> int:
    """Analytic bytes one launch touches: operand reads + result
    writes (perfobs bandwidth accounting) — ``.nbytes`` on every numpy
    / jax operand, 0 for anything shapeless."""
    return sum(getattr(a, "nbytes", 0) for a in arrs)


def _validate(shape: tuple, n_leaves: int) -> None:
    kind = shape[0]
    if kind == "leaf":
        if not 0 <= shape[1] < n_leaves:
            raise ValueError(f"leaf slot {shape[1]} out of range")
        return
    if kind in _FOLD_NAMES:
        if len(shape) < 2:
            raise ValueError(f"{kind} needs at least one child")
        for c in shape[1:]:
            _validate(c, n_leaves)
        return
    if kind == "not":
        _validate(shape[1], n_leaves)
        _validate(shape[2], n_leaves)
        return
    if kind == "dfuse":
        if len(shape) != 4:
            raise ValueError("dfuse needs (child, set, clear)")
        for c in shape[1:]:
            _validate(c, n_leaves)
        return
    if kind == "shift":
        if shape[1] < 0:
            raise ValueError("shift distance must be non-negative")
        _validate(shape[2], n_leaves)
        return
    raise ValueError(f"unknown expression node: {kind!r}")


# ------------------------------------------------------------ jit engine


def _build_jnp(shape: tuple) -> Callable[[tuple], Any]:
    """shape -> closure(leaves_tuple) -> jnp array, traced under jit."""
    import jax.numpy as jnp

    kind = shape[0]
    if kind == "leaf":
        i = shape[1]
        return lambda leaves: leaves[i]
    if kind in _FOLD_NAMES:
        kids = [_build_jnp(c) for c in shape[1:]]
        fold = {
            "and": jnp.bitwise_and,
            "or": jnp.bitwise_or,
            "xor": jnp.bitwise_xor,
            "andnot": lambda a, b: jnp.bitwise_and(a, jnp.bitwise_not(b)),
        }[kind]

        def ev(leaves: tuple) -> Any:
            out = kids[0](leaves)
            for k in kids[1:]:
                out = fold(out, k(leaves))
            return out

        return ev
    if kind == "not":
        exist = _build_jnp(shape[1])
        kid = _build_jnp(shape[2])
        return lambda leaves: jnp.bitwise_and(
            exist(leaves), jnp.bitwise_not(kid(leaves)))
    if kind == "dfuse":
        kid = _build_jnp(shape[1])
        dset = _build_jnp(shape[2])
        dclear = _build_jnp(shape[3])
        return lambda leaves: jnp.bitwise_or(
            jnp.bitwise_and(kid(leaves),
                            jnp.bitwise_not(dclear(leaves))),
            dset(leaves))
    # shift: the ONE shared body (bm.shift_words), traced into the
    # fused program with static n — cannot drift from the unfused path
    n = shape[1]
    kid = _build_jnp(shape[2])
    return lambda leaves: bm.shift_words(jnp, kid(leaves), n)


#: Compiled-program cache capacity.  Tests shrink it via
#: ``set_program_cache_size``; eviction past it means live tree shapes
#: outnumber retained programs and EVERY evicted shape re-traces +
#: re-lowers on its next query — tens of ms of invisible recompile per
#: hit, which is why evictions surface through devobs
#: (``compile.program_evictions``) instead of staying silent.
DEFAULT_PROGRAM_CACHE_SIZE = 512


_CacheInfo = namedtuple("_CacheInfo",
                        ("hits", "misses", "maxsize", "currsize"))


def _build_program(shape: tuple, counts: bool) -> Callable[..., Any]:
    """One jitted program per (canonical shape, root kind).  The
    cache is what makes tree fusion pay: distinct row ids (distinct
    leaf VALUES) reuse the program; only a new tree SHAPE traces."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    ev = _build_jnp(shape)
    if counts:
        def run(*leaves: Any) -> Any:
            return jnp.sum(lax.population_count(ev(leaves)),
                           axis=-1, dtype=jnp.int32)
    else:
        def run(*leaves: Any) -> Any:
            return ev(leaves)
    # compile telemetry (pilosa_tpu.devobs): fused-program first
    # lowerings are the ones a fresh tree SHAPE pays — exactly the
    # per-canonical-shape compile events the /debug/devices surface
    # exists to attribute
    from pilosa_tpu import devobs as _devobs

    name = "expr.fused_counts" if counts else "expr.fused"
    return _devobs.instrument(name, jax.jit(run))


def _build_gather_program(shape: tuple, counts: bool) -> Callable[..., Any]:
    """The container-engine variant of ``_build_program``: leaves are
    (pool, gather-index) pairs and each leaf materializes as
    ``take(pool, idx, axis=0)`` INSIDE the jitted program, so the
    directory-driven gather, the fused tree body, and the optional
    popcount Count root all cost one launch (ops/containers.py stages
    the pools and pow2-padded indices; see its module docstring for
    the layout).  Argument convention: ``run(*pools, *idxs)``."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    ev = _build_jnp(shape)

    def run(*args: Any) -> Any:
        n = len(args) // 2
        pools, idxs = args[:n], args[n:]
        leaves = tuple(jnp.take(p, ix, axis=0, mode="clip")
                       for p, ix in zip(pools, idxs))
        out = ev(leaves)
        if counts:
            return jnp.sum(lax.population_count(out),
                           axis=-1, dtype=jnp.int32)
        return out

    from pilosa_tpu import devobs as _devobs

    name = "expr.fused_gather_counts" if counts else "expr.fused_gather"
    return _devobs.instrument(name, jax.jit(run))


def _build_gather_kinds_program(key: tuple,
                                counts: bool) -> Callable[..., Any]:
    """The kind-dispatched variant of ``_build_gather_program``
    (roaring array/run parity, ops/kindpools.py): each leaf gathers
    compact rows from its per-kind pools and DECODES them to dense
    2048-word blocks inside the same launch — a lane's three gathers
    hit its own kind's row and the other kinds' canonical zero rows,
    so an OR reconstructs the block exactly and resident/transferred
    bytes stay compact.  ``key`` is ``(shape, spec)`` where ``spec``
    tags each leaf ``"b"`` (plain bitmap pool + index) or ``"k"``
    (bpool, apool, acard, rpool, ib, ia, ir); arguments flatten in
    leaf order."""
    shape, spec = key
    import jax
    import jax.numpy as jnp
    from jax import lax

    from pilosa_tpu.ops import kindpools as kp

    ev = _build_jnp(shape)

    def run(*args: Any) -> Any:
        leaves = []
        i = 0
        for tag in spec:
            if tag == "b":
                pool, ib = args[i:i + 2]
                i += 2
                leaves.append(jnp.take(pool, ib, axis=0, mode="clip"))
                continue
            bpool, apool, acard, rpool, ib, ia, ir = args[i:i + 7]
            i += 7
            dense = jnp.take(bpool, ib, axis=0, mode="clip")
            av = jnp.take(apool, ia, axis=0, mode="clip")
            ac = jnp.take(acard, ia, axis=0, mode="clip")
            rv = jnp.take(rpool, ir, axis=0, mode="clip")
            leaves.append(dense | kp.decode_array_jnp(av, ac)
                          | kp.decode_runs_jnp(rv))
        out = ev(tuple(leaves))
        if counts:
            return jnp.sum(lax.population_count(out),
                           axis=-1, dtype=jnp.int32)
        return out

    from pilosa_tpu import devobs as _devobs

    name = ("expr.fused_gather_kinds_counts" if counts
            else "expr.fused_gather_kinds")
    return _devobs.instrument(name, jax.jit(run))


def _build_mesh_program(meshkey: tuple, counts: bool) -> Callable[..., Any]:
    """The mesh-native variant of ``_build_program``: the same tree
    body runs per-device on shard-axis blocks under ``shard_map``
    (parallel/meshexec.py), so ONE launch evaluates the query across
    every mesh device.  A Count root popcounts its local shards and
    returns the full per-shard vector through a tiled
    ``lax.all_gather`` on the shard axis — the collective replacement
    for the host-side per-shard gather, keeping the output
    bit-identical to the single-device program (int32 per-shard
    counts; callers still sum in Python ints).  A bitmap root stays
    sharded in place (out_specs on the shard axis) — set algebra is
    embarrassingly shard-parallel and the host assembles segments
    from the sharded result.  ``meshkey`` is ``(shape, n_leaves,
    ndim, mesh)``: the in_specs tuple length and the shard-axis
    position are static per program."""
    shape, n_leaves, ndim, mesh = meshkey
    import jax
    import jax.numpy as jnp
    from jax import lax

    from pilosa_tpu.parallel import meshexec
    from pilosa_tpu.parallel.mesh import shard_map

    ev = _build_jnp(shape)
    leaf_spec = meshexec.shard_spec(ndim, ndim - 2)
    if counts:
        from jax.sharding import PartitionSpec as P

        out_spec = P()  # replicated full per-shard counts (all_gather)
    else:
        out_spec = leaf_spec

    def body(*blks: Any) -> Any:
        out = ev(blks)
        if counts:
            local = jnp.sum(lax.population_count(out),
                            axis=-1, dtype=jnp.int32)
            return lax.all_gather(local, meshexec.SHARD_AXIS,
                                  axis=ndim - 2, tiled=True)
        return out

    sm = shard_map(body, mesh=mesh, in_specs=(leaf_spec,) * n_leaves,
                   out_specs=out_spec, check_rep=False)

    def run(*leaves: Any) -> Any:
        return sm(*leaves)

    from pilosa_tpu import devobs as _devobs

    name = "expr.mesh_counts" if counts else "expr.mesh"
    return _devobs.instrument(name, jax.jit(run))


def _build_mesh_gather_program(meshkey: tuple,
                               counts: bool) -> Callable[..., Any]:
    """Mesh variant of ``_build_gather_program``: container word POOLS
    replicate across the mesh (gather indices address arbitrary pool
    rows — ops/containers.py's domain algebra crosses shard
    boundaries by construction) while the gather DOMAIN axis shards,
    so each device gathers and evaluates its block of the query's
    container domain.  Count roots all_gather the per-container
    popcounts back (replicated, same int32 vector as the
    single-device program); bitmap roots stay domain-sharded.
    Argument convention matches ``_build_gather_program``:
    ``run(*pools, *idxs)``."""
    shape, n_leaves, mesh = meshkey
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from pilosa_tpu.parallel import meshexec
    from pilosa_tpu.parallel.mesh import shard_map

    ev = _build_jnp(shape)
    pool_spec = P(None, None)
    idx_spec = P(meshexec.SHARD_AXIS)
    out_spec = P() if counts else P(meshexec.SHARD_AXIS, None)

    def body(*args: Any) -> Any:
        n = len(args) // 2
        pools, idxs = args[:n], args[n:]
        leaves = tuple(jnp.take(p, ix, axis=0, mode="clip")
                       for p, ix in zip(pools, idxs))
        out = ev(leaves)
        if counts:
            local = jnp.sum(lax.population_count(out),
                            axis=-1, dtype=jnp.int32)
            return lax.all_gather(local, meshexec.SHARD_AXIS,
                                  axis=0, tiled=True)
        return out

    sm = shard_map(body, mesh=mesh,
                   in_specs=(pool_spec,) * n_leaves
                   + (idx_spec,) * n_leaves,
                   out_specs=out_spec, check_rep=False)

    def run(*args: Any) -> Any:
        return sm(*args)

    from pilosa_tpu import devobs as _devobs

    name = ("expr.mesh_gather_counts" if counts
            else "expr.mesh_gather")
    return _devobs.instrument(name, jax.jit(run))


def _make_compiled(maxsize: int,
                   build: Callable[[tuple, bool],
                                   Callable[..., Any]] | None = None) -> Any:
    """An explicit LRU over compiled programs with an EXACT eviction
    count.  ``functools.lru_cache`` was abandoned here because its
    counters can't express evictions: ``misses - currsize`` over-counts
    whenever two threads race the same fresh shape (both count a miss,
    one entry lands) or a build raises — which made the one-line
    overflow warning and the ``compile.program_evictions`` gauge fire
    spuriously.  Here an eviction increments exactly when a resident
    program is popped for capacity, nothing else."""
    lock = threading.Lock()
    builder = build if build is not None else _build_program
    # insertion order == LRU order (move-to-end on hit)
    cache: dict[tuple, Callable[..., Any]] = {}
    counters = {"hits": 0, "misses": 0, "evictions": 0}

    def _compiled(shape: tuple, counts: bool) -> Callable[..., Any]:
        key = (shape, counts)
        with lock:
            prog = cache.get(key)
            if prog is not None:
                cache[key] = cache.pop(key)
                counters["hits"] += 1
                return prog
            counters["misses"] += 1
        # trace/lower outside the lock — tens of ms for a fresh shape;
        # a concurrent duplicate build is wasted work, never a wrong
        # count: only the first insert lands and no eviction is charged
        prog = builder(shape, counts)
        with lock:
            if key in cache:
                return cache[key]
            cache[key] = prog
            while len(cache) > maxsize:
                cache.pop(next(iter(cache)))
                counters["evictions"] += 1
        return prog

    def cache_info() -> _CacheInfo:
        with lock:
            return _CacheInfo(counters["hits"], counters["misses"],
                              maxsize, len(cache))

    def cache_clear() -> None:
        with lock:
            cache.clear()
            counters["hits"] = counters["misses"] = 0
            counters["evictions"] = 0

    def cache_evictions() -> int:
        with lock:
            return counters["evictions"]

    _compiled.cache_info = cache_info
    _compiled.cache_clear = cache_clear
    _compiled.cache_evictions = cache_evictions
    return _compiled


_compiled = _make_compiled(DEFAULT_PROGRAM_CACHE_SIZE)
#: gather-program cache (the container engine's fused programs): its
#: keys are the same canonical tree shapes, so the dense and gathered
#: variants of one shape are two entries — sized accordingly
_compiled_gather = _make_compiled(DEFAULT_PROGRAM_CACHE_SIZE,
                                  build=_build_gather_program)
#: kind-dispatched gather programs (array/run container parity): keyed
#: on (shape, per-leaf kind spec) composites
_compiled_gather_kinds = _make_compiled(DEFAULT_PROGRAM_CACHE_SIZE,
                                        build=_build_gather_kinds_program)
#: mesh-program caches (parallel/meshexec.py): keyed on the composite
#: (shape, n_leaves, ndim, mesh) — the Mesh is a cached singleton, so
#: one config's programs stay warm across queries and an axis resize
#: simply addresses fresh entries
_compiled_mesh = _make_compiled(DEFAULT_PROGRAM_CACHE_SIZE,
                                build=_build_mesh_program)
_compiled_mesh_gather = _make_compiled(DEFAULT_PROGRAM_CACHE_SIZE,
                                       build=_build_mesh_gather_program)
_eviction_warned: bool = False


def program_evictions() -> int:
    """Capacity evictions from the compiled-program caches so far —
    counted exactly at the point a resident program is popped (see
    ``_make_compiled``), so concurrent same-shape builds and failed
    builds never inflate it."""
    return (_compiled.cache_evictions()
            + _compiled_gather.cache_evictions()
            + _compiled_gather_kinds.cache_evictions()
            + _compiled_mesh.cache_evictions()
            + _compiled_mesh_gather.cache_evictions())


def set_program_cache_size(maxsize: int) -> None:
    """Swap in a fresh program cache of the given capacity (tests —
    forcing 512 distinct shapes to exercise eviction would dominate a
    test run with tracing)."""
    global _compiled, _compiled_gather, _eviction_warned
    global _compiled_mesh, _compiled_mesh_gather
    global _compiled_gather_kinds
    _compiled = _make_compiled(maxsize)
    _compiled_gather = _make_compiled(maxsize,
                                      build=_build_gather_program)
    _compiled_gather_kinds = _make_compiled(
        maxsize, build=_build_gather_kinds_program)
    _compiled_mesh = _make_compiled(maxsize,
                                    build=_build_mesh_program)
    _compiled_mesh_gather = _make_compiled(
        maxsize, build=_build_mesh_gather_program)
    _eviction_warned = False


def _note_program_cache_pressure() -> None:
    """One-line warning the FIRST time a compiled program is evicted:
    shape thrash otherwise shows up only as inexplicable recompile
    latency (the devobs gauge carries the running count)."""
    global _eviction_warned
    if _eviction_warned:
        return
    if program_evictions() > 0:
        _eviction_warned = True
        import logging

        ci = _compiled.cache_info()
        logging.getLogger("pilosa_tpu.ops.expr").warning(
            "fused-program cache overflowed (maxsize=%d): tree shapes "
            "now evict each other and re-trace on reuse; see "
            "compile.program_evictions on /metrics", ci.maxsize)


# ----------------------------------------------------------- host engine


def _host_tree(shape: tuple, leaves: tuple) -> np.ndarray:
    kind = shape[0]
    if kind == "leaf":
        return leaves[shape[1]]
    if kind in _FOLD_NAMES:
        fold = {
            "and": np.bitwise_and,
            "or": np.bitwise_or,
            "xor": np.bitwise_xor,
            "andnot": lambda a, b: np.bitwise_and(a, np.bitwise_not(b)),
        }[kind]
        out = _host_tree(shape[1], leaves)
        for c in shape[2:]:
            out = fold(out, _host_tree(c, leaves))
        return out
    if kind == "not":
        return np.bitwise_and(_host_tree(shape[1], leaves),
                              np.bitwise_not(_host_tree(shape[2], leaves)))
    if kind == "dfuse":
        return np.bitwise_or(
            np.bitwise_and(_host_tree(shape[1], leaves),
                           np.bitwise_not(_host_tree(shape[3], leaves))),
            _host_tree(shape[2], leaves))
    # shift — the shared body, numpy namespace
    return bm.shift_words(np, _host_tree(shape[2], leaves), shape[1])


def _host_counts(shape: tuple, leaves: tuple) -> np.ndarray:
    from pilosa_tpu.ops import hostkernels as hk

    if (shape[0] == "and" and len(shape) == 3
            and shape[1][0] == "leaf" and shape[2][0] == "leaf"):
        # pairwise fast path: native |a & b| per row without
        # materializing the intersection (at 10B columns that
        # intermediate alone is ~1.25 GB per query)
        a, b = leaves[shape[1][1]], leaves[shape[2][1]]
        lead = a.shape[:-1]
        flat = (a.reshape(-1, a.shape[-1]), b.reshape(-1, b.shape[-1]))
        return hk.row_counts_and(*flat).reshape(lead)
    return hk.row_counts(_host_tree(shape, leaves))


# -------------------------------------------------------------- frontend


def evaluate(shape: tuple, leaves: tuple, counts: bool = False,
             mesh: Any = None, mesh_queries: int | None = None) -> Any:
    """Evaluate one compiled tree over its leaf stacks in ONE launch.

    ``leaves`` — tuple of uint32 stacks, all the same shape ([S, W], or
    [B, S, W] for a coalesced cross-query batch).  Returns the result
    bitmap stack, or int32 per-row counts with ``counts=True``.

    ``mesh`` — an active device mesh (meshexec.query_mesh) routes the
    shard_map program: the same tree body per device over shard-axis
    blocks, one launch across every mesh chip, results bit-identical.
    None (the default, and the ?nomesh=1 escape) runs the exact
    single-device program.  ``mesh_queries`` — how many LIVE queries
    this launch serves for the mesh.queries counter (the coalescer
    passes its live occupancy; a [B, S, W] batch otherwise counts its
    batch rows, which include pow2 padding).
    """
    _validate(shape, len(leaves))
    if shape[0] == "leaf" and not counts:
        return leaves[shape[1]]  # passthrough: no launch at all
    bm.note_dispatch("fused_expr")
    t0 = _perfobs.t0()
    if bm._host(*leaves):
        out = (_host_counts(shape, leaves) if counts
               else _host_tree(shape, leaves))
        # host fused is still the DENSE engine (same operands, numpy
        # body); the executor's per-shard map re-attributes via
        # perfobs.context(engine="host")
        _perfobs.sample("dense", out, t0,
                        nbytes=_touched_bytes(*leaves, out))
        return out
    ndim = leaves[0].ndim
    if mesh is not None:
        from pilosa_tpu.parallel import meshexec

        if meshexec.shardable(mesh, leaves[0].shape[ndim - 2]):
            # jit refuses committed inputs on foreign device sets, so
            # every leaf commits to the program's sharding here — a
            # no-op when placement already matches (the warm path)
            placed = tuple(meshexec.ensure_placed(lv, mesh, ndim - 2)
                           for lv in leaves)
            fn = _compiled_mesh((shape, len(leaves), ndim, mesh),
                                counts)
            _note_program_cache_pressure()
            meshexec.note_launch(
                mesh_queries if mesh_queries is not None
                else (leaves[0].shape[0] if ndim == 3 else 1))
            # dispatch under the process-wide mesh launch lock:
            # concurrent collective dispatches from different threads
            # can interleave per-device enqueues and deadlock the
            # backend (meshexec.launch_lock); execution pipelines —
            # the lock covers the enqueue, not the compute (and the
            # perfobs block_until_ready waits OUTSIDE the lock)
            with meshexec.launch_lock():
                out = fn(*placed)
            _perfobs.sample("mesh", out, t0,
                            nbytes=_touched_bytes(*placed, out))
            return out
    fn = _compiled(shape, counts)
    _note_program_cache_pressure()
    out = fn(*leaves)
    _perfobs.sample("dense", out, t0,
                    nbytes=_touched_bytes(*leaves, out))
    return out


def evaluate_gathered(shape: tuple, pools: tuple, idxs: tuple,
                      counts: bool = False, mesh: Any = None) -> Any:
    """Evaluate one compiled tree over POOLED container operands in
    ONE launch (the compressed-fragment read path, ops/containers.py).

    ``pools[i]`` — leaf i's uint32[P_i, CWORDS] container block pool
    (host numpy or device array), rows past the directory's count all
    zeros; ``idxs[i]`` — int32[D] gather indices mapping the query's
    container domain into that pool (absent containers point at a zero
    row).  The caller pads D and each P_i to powers of two
    (``containers._pow2``) so the jit re-specializations stay O(log).
    Returns the uint32[D, CWORDS] result blocks, or int32[D]
    per-container popcounts with ``counts=True``.

    ``mesh`` (meshexec.query_mesh) shards the DOMAIN axis across the
    mesh and replicates the pools — one launch gathers and evaluates
    every device's domain block; None keeps the single-device gather
    program."""
    _validate(shape, len(pools))
    bm.note_dispatch("fused_gather")
    t0 = _perfobs.t0()
    if bm._host(*pools):
        leaves = tuple(p[np.asarray(ix)] for p, ix in zip(pools, idxs))
        out = (_host_counts(shape, leaves) if counts
               else _host_tree(shape, leaves))
        _perfobs.sample("gather", out, t0,
                        nbytes=_touched_bytes(*leaves, *idxs, out))
        return out
    import jax.numpy as jnp

    if mesh is not None:
        from pilosa_tpu.parallel import meshexec

        if meshexec.shardable(mesh, len(idxs[0])):
            placed_pools = tuple(meshexec.ensure_replicated(p, mesh)
                                 for p in pools)
            placed_idxs = tuple(meshexec.ensure_placed(
                jnp.asarray(ix), mesh, 0) for ix in idxs)
            fn = _compiled_mesh_gather((shape, len(pools), mesh),
                                       counts)
            _note_program_cache_pressure()
            meshexec.note_launch()
            with meshexec.launch_lock():  # see evaluate's mesh route
                out = fn(*placed_pools, *placed_idxs)
            _perfobs.sample(
                "mesh", out, t0,
                nbytes=_touched_bytes(*placed_pools, *placed_idxs,
                                      out))
            return out
    fn = _compiled_gather(shape, counts)
    _note_program_cache_pressure()
    out = fn(*pools, *(jnp.asarray(ix) for ix in idxs))
    # the gathered pool rows are what the launch actually reads — the
    # whole point of the compressed engine is touching D gathered
    # container blocks instead of the dense stacks
    gathered = sum(len(ix) for ix in idxs) * (
        pools[0].shape[-1] * 4 if pools else 0)
    _perfobs.sample("gather", out, t0,
                    nbytes=gathered + _touched_bytes(*idxs, out))
    return out


def evaluate_gathered_kinds(shape: tuple, leafops: tuple,
                            counts: bool = False) -> Any:
    """Evaluate one compiled tree over KIND-SPLIT container operands in
    ONE launch (roaring array/run parity; ops/kindpools.py holds the
    layouts, ops/containers.py stages the indices).

    ``leafops[i]`` is either ``("b", pool, ib)`` — a legacy all-bitmap
    leaf, gathered exactly like ``evaluate_gathered`` — or ``("k",
    bpool, apool, acard, rpool, ib, ia, ir)`` — a kind-split leaf whose
    three index vectors each point at the lane's own row in its kind's
    pool and at the OTHER pools' canonical zero rows, so gather +
    decode + OR reconstructs the lane's dense block inside the launch.
    Mesh execution never reaches here (ops/containers.py builds legacy
    leaves while a mesh is active)."""
    _validate(shape, len(leafops))
    bm.note_dispatch("fused_gather")
    t0 = _perfobs.t0()
    from pilosa_tpu.ops import kindpools as kp

    if bm._host(*(op[1] for op in leafops)):
        leaves = []
        for op in leafops:
            if op[0] == "b":
                _, pool, ib = op
                leaves.append(pool[np.asarray(ib)])
                continue
            _, bpool, apool, acard, rpool, ib, ia, ir = op
            ib, ia, ir = (np.asarray(v) for v in (ib, ia, ir))
            leaves.append(bpool[ib]
                          | kp.decode_array_np(apool[ia], acard[ia])
                          | kp.decode_runs_np(rpool[ir]))
        leaves = tuple(leaves)
        out = (_host_counts(shape, leaves) if counts
               else _host_tree(shape, leaves))
        _perfobs.sample("gather_kinds", out, t0,
                        nbytes=_touched_bytes(*leaves, out))
        return out
    import jax.numpy as jnp

    spec = tuple(op[0] for op in leafops)
    args: list[Any] = []
    gathered = 0
    for op in leafops:
        if op[0] == "b":
            _, pool, ib = op
            args.extend((pool, jnp.asarray(ib)))
            gathered += len(ib) * pool.shape[-1] * 4
            continue
        _, bpool, apool, acard, rpool, ib, ia, ir = op
        args.extend((bpool, apool, acard, rpool,
                     jnp.asarray(ib), jnp.asarray(ia), jnp.asarray(ir)))
        # the launch reads one compact row per lane per pool — the
        # whole point of the kind split is that those rows are small
        gathered += len(ib) * (bpool.shape[-1] * 4
                               + apool.shape[-1] * 2 + 4
                               + rpool.shape[-1] * 2)
    fn = _compiled_gather_kinds((shape, spec), counts)
    _note_program_cache_pressure()
    out = fn(*args)
    _perfobs.sample("gather_kinds", out, t0,
                    nbytes=gathered + _touched_bytes(out))
    return out
