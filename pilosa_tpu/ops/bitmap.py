"""Packed-bitmap kernel: the TPU-native core set-algebra engine.

Replaces the reference's hand-written roaring container algebra
(roaring/roaring.go:595-1023 Intersect/Union/Difference/Xor/Shift/Flip and
the per-container-type fast paths at roaring/roaring.go:2069-2749) with
dense bitwise ops the XLA compiler fuses and tiles onto TPU vector units.

Layout
------
A bitmap covering ``nbits`` columns is a ``uint32[nbits // 32]`` tensor.
Bit for column ``c`` lives in word ``c // 32`` at bit position ``c % 32``
(LSB-first).  Because the byte order is little-endian, viewing a host copy
as uint64 reproduces the reference's 64-bit word layout bit-for-bit
(roaring containers hold 1024 x uint64 = 2^16 bits), which keeps the
roaring file codec (storage/roaring.py) a pure reinterpret-cast away.

Counts are returned as int32: a single shard holds at most 2^20 bits per
row, far below 2^31, and cross-shard / cross-row totals are accumulated in
Python ints by the executor — exact arithmetic without enabling jax x64.

uint32 (not uint64) words are used on device because JAX's default dtype
regime is 32-bit and TPU has no native 64-bit integer path.
"""

from __future__ import annotations

import functools
import threading

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from pilosa_tpu import faultinject as _fi
from pilosa_tpu import observe as _observe

WORD_BITS = 32
_WORD_DTYPE = np.uint32


# ---------------------------------------------------------------------------
# Dispatch accounting — every public op below ticks once per kernel launch
# (jit dispatch on device, native/numpy kernel pass on host), so tests can
# assert how many launches a query actually cost.  The fused expression
# compiler (ops/expr.py) ticks ONCE for a whole tree, which is the point:
# the Count/Intersect hot path is dispatch-bound behind an RPC relay
# (VERDICT round 5: 20 us trivial-dispatch floor vs 0.555 ms/query), so
# launch count IS the perf model, and it must be regression-testable.
# ---------------------------------------------------------------------------

_dispatch = threading.local()  # .log: list[str] while a counter is active


def note_dispatch(name: str) -> None:
    """Record one kernel launch on this thread (no-op unless a
    dispatch_counter — or a query flight record, pilosa_tpu.observe —
    is active on it).  The flight recorder reuses THIS hook so a
    query's profiled device-launch count is the dispatch-count the
    regression tests pin, by construction."""
    if _fi.armed:
        # failpoint: every device kernel launch funnels through here —
        # error(oom) exercises the executor's RESOURCE_EXHAUSTED
        # evict-and-retry without a real allocation failure.  Gated on
        # the module bool so the disarmed hot path pays one attribute
        # read (bench.py extras.faultinject).
        _fi.hit("device.dispatch")
    log = getattr(_dispatch, "log", None)
    if log is not None:
        log.append(name)
    rec = _observe.current()
    if rec is not None:
        rec.note_launch(name)


class dispatch_counter:
    """Context manager counting kernel launches on the CURRENT thread.
    Nested counters stack (the inner one shadows).  Thread-local by
    design: the executor's fused paths run on the calling thread, which
    is exactly the scope a dispatch-count regression test needs."""

    def __enter__(self):
        self._prev = getattr(_dispatch, "log", None)
        self.launches: list[str] = []
        _dispatch.log = self.launches
        return self

    def __exit__(self, *exc):
        _dispatch.log = self._prev
        return False

    @property
    def n(self) -> int:
        return len(self.launches)


def n_words(nbits: int) -> int:
    """Number of uint32 words for a bitmap of ``nbits`` columns."""
    if nbits % WORD_BITS != 0:
        raise ValueError(f"nbits must be a multiple of {WORD_BITS}, got {nbits}")
    return nbits // WORD_BITS


def host_mode() -> bool:
    """True when compute should stay host-resident: a single CPU device
    means XLA buys no parallelism here, and the native popcount kernels
    (ops/hostkernels.py) beat XLA:CPU codegen by ~8x at query shapes.
    Placement (Field._place_on_devices, Fragment.device_*) consults this
    once per stack build; every op below then dispatches on operand
    type, so host stacks flow through numpy + native C++ and device
    stacks through the jit kernels."""
    import jax

    devs = jax.devices()
    return len(devs) == 1 and devs[0].platform == "cpu"


def _host(*xs) -> bool:
    """Dispatch predicate: all array operands are host numpy arrays."""
    return all(isinstance(x, np.ndarray) for x in xs)


# ---------------------------------------------------------------------------
# Host-side packing (numpy) — the boundary between sparse positions arriving
# over the wire and dense device tensors.
# ---------------------------------------------------------------------------


def pack_positions(positions, nbits: int) -> np.ndarray:
    """Pack sorted-or-not bit positions into a uint32 word array (host)."""
    words = np.zeros(n_words(nbits), dtype=_WORD_DTYPE)
    if len(positions) == 0:
        return words
    pos = np.asarray(positions, dtype=np.int64)
    if pos.size and (pos.min() < 0 or pos.max() >= nbits):
        raise ValueError(f"position out of range [0, {nbits})")
    np.bitwise_or.at(
        words,
        pos // WORD_BITS,
        (np.uint32(1) << (pos % WORD_BITS).astype(np.uint32)),
    )
    return words


def group_indices(keys: np.ndarray) -> dict:
    """Group index positions 0..n-1 by ``keys[i]`` -> {int(key):
    ndarray of indices}, via one stable argsort + split.  The shared
    host-side bulk-import grouping primitive (field.import_bits and
    api._group_by_shard) — a per-element Python loop costs ~1 us/key
    at millions of keys; this is ~30x faster and must exist exactly
    once."""
    if not len(keys):
        return {}
    order = np.argsort(keys, kind="stable")
    ks = keys[order]
    bounds = np.flatnonzero(np.diff(ks)) + 1
    firsts = ks[np.concatenate(([0], bounds))]
    return {int(k): chunk
            for k, chunk in zip(firsts, np.split(order, bounds))}


def unpack_positions(words: np.ndarray) -> np.ndarray:
    """Inverse of pack_positions: word array -> sorted int64 positions (host)."""
    bits = np.unpackbits(np.ascontiguousarray(words).view(np.uint8), bitorder="little")
    return np.nonzero(bits)[0].astype(np.int64)


def chunked_device_put(stack: np.ndarray, device=None,
                       label: str = "other"):
    """device_put in bounded pieces (axis 0), concatenated ON device.
    A single multi-GB transfer can wedge a constrained transport
    end-to-end (the axon relay tunnel died mid-2.5 GB prewarm and took
    the whole session's device access with it, BASELINE.md round 3);
    chunking caps any one transfer at ``PILOSA_TPU_STAGE_CHUNK_MB``
    and the per-piece block_until_ready doubles as a progress
    keepalive.  DISABLED by default (0): on a real host a single DMA
    put is pipelined and needs no extra HBM, while chunk+concatenate
    holds pieces and result alive together (~2x peak) — constrained
    transports opt in at their entry points (measure.py pins 16 MB
    when staging rides the relay tunnel)."""
    import os as _os

    chunk_bytes = int(float(_os.environ.get(
        "PILOSA_TPU_STAGE_CHUNK_MB", "0")) * 1e6)
    put = (lambda a: jax.device_put(a, device)) if device is not None \
        else jax.device_put
    from pilosa_tpu import devobs as _devobs

    if (not chunk_bytes or stack.nbytes <= chunk_bytes
            or stack.ndim < 2):
        _devobs.note_transfer(stack.nbytes, 1, label)
        return put(stack)
    row_bytes = max(1, stack.nbytes // max(1, stack.shape[0]))
    rows_per = max(1, chunk_bytes // row_bytes)
    parts = []
    for i in range(0, stack.shape[0], rows_per):
        d = put(np.ascontiguousarray(stack[i:i + rows_per]))
        d.block_until_ready()
        parts.append(d)
    _devobs.note_transfer(stack.nbytes, len(parts), label)
    return jnp.concatenate(parts, axis=0)


def pack_positions_matrix(rows_cols, row_ids, nbits: int) -> np.ndarray:
    """Pack (row, col) pairs into a dense [len(row_ids), nbits/32] matrix.

    ``row_ids`` maps matrix slots to logical row ids; pairs whose row is not
    present raise.  Host-side bulk-import helper (analog of the sorted-run
    import at fragment.go:2053).
    """
    slot = {r: i for i, r in enumerate(row_ids)}
    mat = np.zeros((len(row_ids), n_words(nbits)), dtype=_WORD_DTYPE)
    for r, c in rows_cols:
        if c < 0 or c >= nbits:
            raise ValueError(f"column {c} out of range [0, {nbits})")
        mat[slot[r], c // WORD_BITS] |= _WORD_DTYPE(1) << _WORD_DTYPE(c % WORD_BITS)
    return mat


# ---------------------------------------------------------------------------
# Elementwise set algebra — jitted; XLA fuses chains of these into one kernel.
# ---------------------------------------------------------------------------


@jax.jit
def _jit_and(a, b):
    return jnp.bitwise_and(a, b)


def b_and(a, b):
    """Intersect (roaring.Intersect, roaring/roaring.go:595)."""
    note_dispatch("b_and")
    if _host(a, b):
        return np.bitwise_and(a, b)
    return _jit_and(a, b)


@jax.jit
def _jit_or(a, b):
    return jnp.bitwise_or(a, b)


def b_or(a, b):
    """Union (roaring.Union, roaring/roaring.go:620)."""
    note_dispatch("b_or")
    if _host(a, b):
        return np.bitwise_or(a, b)
    return _jit_or(a, b)


@jax.jit
def _jit_xor(a, b):
    return jnp.bitwise_xor(a, b)


def b_xor(a, b):
    """Symmetric difference (roaring.Xor, roaring/roaring.go:918)."""
    note_dispatch("b_xor")
    if _host(a, b):
        return np.bitwise_xor(a, b)
    return _jit_xor(a, b)


@jax.jit
def _jit_andnot(a, b):
    return jnp.bitwise_and(a, jnp.bitwise_not(b))


def b_andnot(a, b):
    """Difference a \\ b (roaring.Difference, roaring/roaring.go:891)."""
    note_dispatch("b_andnot")
    if _host(a, b):
        return np.bitwise_and(a, np.bitwise_not(b))
    return _jit_andnot(a, b)


@jax.jit
def _jit_not(a, existence):
    return jnp.bitwise_and(jnp.bitwise_not(a), existence)


def b_not(a, existence):
    """Complement within an existence mask (executor Not uses the index's
    existence row as the universe, executor.go:1708)."""
    note_dispatch("b_not")
    if _host(a, existence):
        return np.bitwise_and(np.bitwise_not(a), existence)
    return _jit_not(a, existence)


@functools.lru_cache(maxsize=256)
def _range_mask_np(nwords: int, start: int, end: int) -> np.ndarray:
    """Host-built mask with bits [start, end) set, cached per (shape, range)."""
    mask = np.zeros(nwords, dtype=_WORD_DTYPE)
    if end > start:
        first, last = start // WORD_BITS, (end - 1) // WORD_BITS
        mask[first : last + 1] = np.uint32(0xFFFFFFFF)
        mask[first] &= np.uint32(0xFFFFFFFF) << np.uint32(start % WORD_BITS)
        keep = (end - 1) % WORD_BITS
        mask[last] &= np.uint32(0xFFFFFFFF) >> np.uint32(WORD_BITS - 1 - keep)
    return mask


def b_flip_range(a, start: int, end: int):
    """Flip bits in [start, end) (roaring.Flip, roaring/roaring.go:1683)."""
    mask = _range_mask_np(a.shape[-1], start, end)
    if _host(a):
        note_dispatch("b_flip_range")
        return np.bitwise_xor(a, mask)
    return b_xor(a, jnp.asarray(mask))  # b_xor ticks the dispatch


def shift_words(xp, a, n: int):
    """The ONE shift body, over either array namespace (``xp`` = numpy
    or jax.numpy; jax-traceable with static ``n``): bits move toward
    higher columns and drop at the shard edge (roaring.Shift semantics
    per shard, executor.go:1730).  Shared by the host/jit wrappers here
    and the fused expression compiler (ops/expr.py) so the four shift
    call sites cannot drift bit-for-bit."""
    if n == 0:
        return a
    w, s = n // WORD_BITS, n % WORD_BITS
    nw = a.shape[-1]
    if w >= nw:
        # every bit shifts past the shard edge; computing it would pad
        # an O(n)-word intermediate and compile per distinct n
        return xp.zeros_like(a)
    pad = [(0, 0)] * (a.ndim - 1)
    # words move up by w: out_word[i] = a[i - w]
    shifted = xp.pad(a, pad + [(w, 0)])[..., :nw]
    if s == 0:
        return shifted
    prev = xp.pad(shifted, pad + [(1, 0)])[..., :nw]
    return (shifted << np.uint32(s)) | (prev >> np.uint32(WORD_BITS - s))


def b_shift(a, n: int = 1):
    """Shift all bits toward higher columns by ``n`` (roaring.Shift,
    roaring/roaring.go:946).  Bits shifted past the shard width are dropped,
    matching per-shard Shift execution (executor.go:1730)."""
    if n < 0:
        raise ValueError("shift distance must be non-negative")
    note_dispatch("b_shift")
    if _host(a):
        return shift_words(np, a, n)
    return _jit_shift(a, n)


@functools.partial(jax.jit, static_argnums=(1,))
def _jit_shift(a, n: int = 1):
    if n < 0:
        # a clean error instead of a cryptic negative-pad failure from
        # inside jit tracing; surfaces as a 400 at the query layer
        raise ValueError("shift distance must be non-negative")
    return shift_words(jnp, a, n)


# ---------------------------------------------------------------------------
# Counting — popcount is the workhorse of Count/TopN/Sum.
# ---------------------------------------------------------------------------


@jax.jit
def _jit_popcount(a):
    return jnp.sum(lax.population_count(a), dtype=jnp.int32)


def popcount(a):
    """Total set bits (roaring.Count, roaring/roaring.go:478) — int32
    scalar on device, Python int on host stacks (native kernel)."""
    note_dispatch("popcount")
    if _host(a):
        from pilosa_tpu.ops import hostkernels as hk

        return hk.count(a)
    return _jit_popcount(a)


@jax.jit
def _jit_popcount_and(a, b):
    return jnp.sum(lax.population_count(jnp.bitwise_and(a, b)), dtype=jnp.int32)


def popcount_and(a, b):
    """Fused |a & b| — the north-star IntersectionCount fast path
    (roaring.IntersectionCount, roaring/roaring.go:570): one XLA kernel
    on device (AND + popcount + reduce, no intermediate materialized),
    one C++ pass on host stacks."""
    note_dispatch("popcount_and")
    if _host(a, b):
        from pilosa_tpu.ops import hostkernels as hk

        return hk.count_and(a, b)
    return _jit_popcount_and(a, b)


@jax.jit
def _jit_row_counts(mat):
    return jnp.sum(lax.population_count(mat), axis=-1, dtype=jnp.int32)


def row_counts(mat):
    """Per-row popcounts of a [rows, words] matrix -> int32[rows].

    The batched scan under TopN (fragment.top, fragment.go:1570) — one
    device-wide reduction instead of a per-row heap walk."""
    note_dispatch("row_counts")
    if _host(mat):
        from pilosa_tpu.ops import hostkernels as hk

        return hk.row_counts(mat)
    return _jit_row_counts(mat)


@jax.jit
def _jit_row_counts_and(a, b):
    return jnp.sum(lax.population_count(jnp.bitwise_and(a, b)),
                   axis=-1, dtype=jnp.int32)


def row_counts_and(a, b):
    """Per-row |a[r] & b[r]| -> int32[rows], no materialized
    intersection: one fused XLA kernel on device, one C++ pass on host
    stacks — the Count(Intersect(x, y)) fast path over stacked shard
    operands (vs b_and + row_counts, which allocates the full
    intersection stack first)."""
    note_dispatch("row_counts_and")
    if _host(a, b):
        from pilosa_tpu.ops import hostkernels as hk

        return hk.row_counts_and(a, b)
    return _jit_row_counts_and(a, b)


@jax.jit
def _jit_row_counts_masked(mat, filt):
    return jnp.sum(
        lax.population_count(jnp.bitwise_and(mat, filt[None, :])),
        axis=-1,
        dtype=jnp.int32,
    )


def row_counts_masked(mat, filt):
    """Per-row |row & filter| -> int32[rows]; TopN-with-filter / GroupBy
    inner loop (fragment.go:1600, groupByIterator executor.go:3058)."""
    note_dispatch("row_counts_masked")
    if _host(mat, filt):
        from pilosa_tpu.ops import hostkernels as hk

        return hk.row_counts_masked(mat, filt)
    return _jit_row_counts_masked(mat, filt)


def row_counts_gathered(mat, filt_stack, shard_pos):
    """Per-row |mat[r] & filt_stack[shard_pos[r]]| -> int32[rows]; see
    _jit_row_counts_gathered for the device story."""
    note_dispatch("row_counts_gathered")
    if _host(mat, filt_stack):
        from pilosa_tpu.ops import hostkernels as hk

        return hk.row_counts_gathered(mat, filt_stack, np.asarray(shard_pos))
    return _jit_row_counts_gathered(mat, filt_stack, shard_pos)


@jax.jit
def _jit_row_counts_gathered(mat, filt_stack, shard_pos):
    """Per-row |mat[r] & filt_stack[shard_pos[r]]| -> int32[rows].

    The fused cross-shard TopN scan: row matrices from many fragments
    concatenate along axis 0 (each row tagged with its shard's position
    in the query's shard tuple) and the whole filtered scan runs as one
    dispatch instead of one per shard (fragment.top over shards,
    fragment.go:1570 × executor.go:2561)."""
    filt = jnp.take(filt_stack, shard_pos, axis=0)
    return jnp.sum(
        lax.population_count(jnp.bitwise_and(mat, filt)),
        axis=-1,
        dtype=jnp.int32,
    )


def gathered_pair_counts(a_pool, ai, b_pool, bi):
    """Per-pair |a_pool[ai[p]] & b_pool[bi[p]]| -> int32[P] — the
    compressed-container IntersectionCount core (ops/containers.py):
    both gathers, the AND, the popcount and the per-container reduce
    fuse into one kernel, and only directory-matched container blocks
    are ever read (the dense layout's zero words are never streamed).
    Pool rows past the directory's count are zeros, so an absent-
    container index contributes 0 — the roaring co-present-container
    walk (roaring/roaring.go:570) as a gather."""
    note_dispatch("gathered_pair_counts")
    if _host(a_pool, b_pool):
        from pilosa_tpu.ops import hostkernels as hk

        return hk.row_counts_and(a_pool[np.asarray(ai)],
                                 b_pool[np.asarray(bi)])
    return _jit_gathered_pair_counts(a_pool, ai, b_pool, bi)


@jax.jit
def _jit_gathered_pair_counts(a_pool, ai, b_pool, bi):
    a = jnp.take(a_pool, ai, axis=0, mode="clip")
    b = jnp.take(b_pool, bi, axis=0, mode="clip")
    return jnp.sum(lax.population_count(jnp.bitwise_and(a, b)),
                   axis=-1, dtype=jnp.int32)


def masked_matrix_counts(mat, masks):
    """counts[g, r] = |mat[r] & masks[g]| -> int32[G, rows]; see
    _jit_masked_matrix_counts for the device story."""
    note_dispatch("masked_matrix_counts")
    if _host(mat, masks):
        from pilosa_tpu.ops import hostkernels as hk

        return hk.masked_matrix_counts(mat, masks)
    return _jit_masked_matrix_counts(mat, masks)


@jax.jit
def _jit_masked_matrix_counts(mat, masks):
    """counts[g, r] = |mat[r] & masks[g]| -> int32[G, rows].

    The GroupBy inner product (groupByIterator, executor.go:3058): every
    group mask against every child row in ONE dispatch.  lax.map keeps
    the [G, rows, words] intermediate out of memory — each step is a
    fused row_counts_masked."""
    return lax.map(lambda m: _jit_row_counts_masked(mat, m), masks)


def and_pairs(mat, masks, slots, group_idx):
    """out[p] = mat[slots[p]] & masks[group_idx[p]]; see _jit_and_pairs."""
    note_dispatch("and_pairs")
    if _host(mat, masks):
        return np.bitwise_and(np.take(mat, np.asarray(slots), axis=0),
                              np.take(masks, np.asarray(group_idx), axis=0))
    return _jit_and_pairs(mat, masks, slots, group_idx)


@jax.jit
def _jit_and_pairs(mat, masks, slots, group_idx):
    """out[p] = mat[slots[p]] & masks[group_idx[p]] -> uint32[P, words].

    Builds the next GroupBy level's group masks for every surviving
    (group, row) pair in one dispatch."""
    return jnp.bitwise_and(
        jnp.take(mat, slots, axis=0), jnp.take(masks, group_idx, axis=0))


# ---------------------------------------------------------------------------
# Point mutations — delta application from the host write path.  The host
# pre-ORs colliding bits into unique (word index, value) pairs; on device
# this is gather -> combine -> scatter with a donated buffer.
# ---------------------------------------------------------------------------


@jax.jit
def _jit_set_bits(words, idx, or_vals):
    return words.at[idx].set(words[idx] | or_vals)


def set_bits(words, idx, or_vals):
    """OR ``or_vals`` into ``words`` at unique ``idx`` (fragment setBit batch
    apply; mirrors the opN batch design of fragment.go:84,2296)."""
    note_dispatch("set_bits")
    if _host(words):
        out = words.copy()
        out[np.asarray(idx)] |= np.asarray(or_vals)
        return out
    return _jit_set_bits(words, idx, or_vals)


@jax.jit
def _jit_clear_bits(words, idx, andnot_vals):
    return words.at[idx].set(words[idx] & ~andnot_vals)


def clear_bits(words, idx, andnot_vals):
    """Clear bits given per-word masks of bits to remove."""
    note_dispatch("clear_bits")
    if _host(words):
        out = words.copy()
        out[np.asarray(idx)] &= ~np.asarray(andnot_vals)
        return out
    return _jit_clear_bits(words, idx, andnot_vals)


@jax.jit
def _jit_get_bits(words, positions):
    w = words[positions // WORD_BITS]
    return ((w >> (positions % WORD_BITS).astype(jnp.uint32)) & 1).astype(jnp.int32)


def get_bits(words, positions):
    """Read individual bits -> int32[len(positions)] of 0/1."""
    note_dispatch("get_bits")
    if _host(words):
        pos = np.asarray(positions)
        w = words[pos // WORD_BITS]
        return ((w >> (pos % WORD_BITS).astype(np.uint32)) & 1).astype(np.int32)
    return _jit_get_bits(words, positions)


# ---------------------------------------------------------------------------
# Row-axis reductions — union/intersection of many rows in one call
# (executor Union/Intersect over >2 children collapse to these).
# ---------------------------------------------------------------------------


@jax.jit
def _jit_reduce_or_rows(mat):
    return lax.reduce(mat, np.uint32(0), lax.bitwise_or, (0,))


def reduce_or_rows(mat):
    """OR-reduce a [rows, words] matrix -> [words]."""
    note_dispatch("reduce_or_rows")
    if _host(mat):
        return np.bitwise_or.reduce(mat, axis=0)
    return _jit_reduce_or_rows(mat)


@jax.jit
def _jit_reduce_and_rows(mat):
    return lax.reduce(mat, np.uint32(0xFFFFFFFF), lax.bitwise_and, (0,))


def reduce_and_rows(mat):
    """AND-reduce a [rows, words] matrix -> [words]."""
    note_dispatch("reduce_and_rows")
    if _host(mat):
        return np.bitwise_and.reduce(mat, axis=0)
    return _jit_reduce_and_rows(mat)


# ---------------------------------------------------------------------------
# Compile telemetry — every _jit_* kernel above routes through the
# device-runtime observer (pilosa_tpu.devobs), which detects and times
# jit cache-miss first lowerings per canonical operand shape.  One loop,
# so a new kernel added above is instrumented by adding its name here.
# ---------------------------------------------------------------------------

from pilosa_tpu import devobs as _devobs  # noqa: E402

for _n in ("_jit_and", "_jit_or", "_jit_xor", "_jit_andnot", "_jit_not",
           "_jit_shift", "_jit_popcount", "_jit_popcount_and",
           "_jit_row_counts", "_jit_row_counts_and",
           "_jit_row_counts_masked", "_jit_row_counts_gathered",
           "_jit_masked_matrix_counts", "_jit_and_pairs",
           "_jit_gathered_pair_counts",
           "_jit_set_bits", "_jit_clear_bits", "_jit_get_bits",
           "_jit_reduce_or_rows", "_jit_reduce_and_rows"):
    globals()[_n] = _devobs.instrument(f"bitmap.{_n[5:]}", globals()[_n])
del _n
