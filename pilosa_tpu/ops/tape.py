"""Op-tape interpreter: one device launch for a batch of
heterogeneous-shape expression trees.

The fused compiler (ops/expr.py) erases leaf VALUES from a tree, so
concurrent queries with the same STRUCTURE share one compiled program
and one launch (parallel/coalescer.py).  Real mixed dashboard traffic
is structurally diverse, though — many users, many distinct
Count/Row trees — and BENCH_r05 shows the read path is
dispatch-bound (1801 qps XLA against a ~20 us trivial-dispatch floor,
bw_util 0.148), so each distinct shape paying its own launch is the
single biggest qps-per-chip loss on diverse traffic (ROADMAP item 1).

This module erases the STRUCTURE too.  Each tree compiles to a flat
op-tape — an opcode stream over a register file, leaves pre-loaded
into the low registers — and a *batch* of tapes pads to a small set of
pow2 size buckets (tape length x leaf-slot count, mirroring the
coalescer's pow2 batch padding).  One jitted program per bucket then
executes the whole batch: ``lax.scan`` over tape steps, ``lax.switch``
on the per-query opcode (under ``vmap`` the switch lowers to a select
over the five bitwise ops — all cheap next to the register-file
reads), each step writing its result register with
``dynamic_update_slice``.  A Count root folds its popcount+reduce into
the same program, exactly like the fused path.  This is the
ragged-rows-in-one-kernel design of Ragged Paged Attention and
DrJAX's batched map primitives (PAPERS.md), applied to expression
trees instead of attention rows: each query's variable-depth tree is
one ragged row of a single batched launch.

Tape grammar (compiled from the ops/expr shape grammar):

    opcodes   AND OR XOR ANDNOT COPY
    operands  i >= 0  -> leaf slot i
              i <  0  -> instruction ~i's output register
    ``not``   -> ANDNOT(exist, child)
    ``dfuse`` -> OR(ANDNOT(child, clear), set)   (two instructions)
    ``shift`` is NOT tape-eligible (its distance is baked into the
    compiled program, not an operand) — shift-carrying shapes fall
    back to the per-shape fused path.

Instruction ``t`` writes register ``n_slots + t``; buckets pad short
tapes with COPYs of the final real register, so the LAST register
always holds the result after the scan.  Pad leaf slots are zero
stacks and pad batch rows are all-COPY tapes over them — never read
by a real query's operands, never scattered back.

Host stacks (single-CPU-device mode) interpret the tapes eagerly in
numpy — dispatch is free there — and still tick ONE ``note_dispatch``
for the whole batch, keeping launch accounting meaningful across
engines.  Bit-exactness against ``ops/expr._host_tree`` /
``_host_counts`` is pinned by tests/test_tape.py.
"""

from __future__ import annotations

import threading
from collections import namedtuple
from collections.abc import Callable, Sequence
from typing import Any

import numpy as np

from pilosa_tpu import perfobs as _perfobs
from pilosa_tpu.ops import bitmap as bm

OP_AND, OP_OR, OP_XOR, OP_ANDNOT, OP_COPY = range(5)

_FOLD_OPS = {"and": OP_AND, "or": OP_OR, "xor": OP_XOR,
             "andnot": OP_ANDNOT}

#: Smallest bucket edge for both axes: rounding tiny tapes up to 4
#: wastes a few no-op COPY steps but collapses the (1, 2, 4) size
#: classes into one — fewer lowered program variants AND better batch
#: occupancy for shallow-tree traffic (a Count(Row) and a
#: Count(Intersect(Row, Row)) share a launch).
MIN_BUCKET = 4

#: Default per-query caps (the ``[ragged]`` config): a tape longer
#: than ``max-tape`` — or a tree with more leaves than ``max-leaves``
#: — falls back to the per-shape fused path for that query alone.
DEFAULT_MAX_TAPE = 32
DEFAULT_MAX_LEAVES = 16


class TapeError(ValueError):
    """The shape cannot compile to a tape (unsupported node, bad leaf
    ref, or over the configured length cap)."""


#: One compiled tape: ``instrs`` is a tuple of (opcode, a, b) with the
#: symbolic operand encoding above; ``n_leaves`` the number of leaf
#: slots the operands reference.
Tape = namedtuple("Tape", ("instrs", "n_leaves"))


# ------------------------------------------------------------- compiler


def compile_shape(shape, n_leaves: int, max_len: int | None = None) -> Tape:
    """Compile one ops/expr shape into a Tape (post-order emission).
    Raises TapeError on shift nodes (structurally ineligible), unknown
    nodes, out-of-range leaf slots, or a tape longer than ``max_len``.
    """
    instrs: list[tuple[int, int, int]] = []

    def emit(op: int, a: int, b: int) -> int:
        instrs.append((op, a, b))
        return ~(len(instrs) - 1)

    def go(node: tuple) -> int:
        kind = node[0]
        if kind == "leaf":
            slot = node[1]
            if not 0 <= slot < n_leaves:
                raise TapeError(f"leaf slot {slot} out of range")
            return slot
        if kind in _FOLD_OPS:
            if len(node) < 2:
                raise TapeError(f"{kind} needs at least one child")
            op = _FOLD_OPS[kind]
            ref = go(node[1])
            for child in node[2:]:
                ref = emit(op, ref, go(child))
            return ref
        if kind == "not":
            # exist & ~child — one ANDNOT, same algebra as the fused
            # engine (expr._build_jnp)
            return emit(OP_ANDNOT, go(node[1]), go(node[2]))
        if kind == "dfuse":
            # (child & ~clear) | set — the streaming-ingest overlay
            if len(node) != 4:
                raise TapeError("dfuse needs (child, set, clear)")
            child = go(node[1])
            dset = go(node[2])
            dclear = go(node[3])
            return emit(OP_OR, emit(OP_ANDNOT, child, dclear), dset)
        if kind == "shift":
            raise TapeError("shift is not tape-eligible")
        raise TapeError(f"unknown expression node: {kind!r}")

    root = go(shape)
    if root >= 0:
        # pure-leaf (or single-child fold) root: materialize it into a
        # register so the result always lives in the last one
        root = emit(OP_COPY, root, 0)
    if max_len is not None and len(instrs) > max_len:
        raise TapeError(
            f"tape length {len(instrs)} exceeds cap {max_len}")
    return Tape(tuple(instrs), n_leaves)


def try_compile(shape, n_leaves: int,
                max_len: int | None = None) -> Tape | None:
    """``compile_shape`` that reports ineligibility via counters
    instead of raising — the coalescer's per-query fallback gate."""
    try:
        return compile_shape(shape, n_leaves, max_len)
    except TapeError as e:
        bump("tape.oversize_fallbacks" if "exceeds cap" in str(e)
             else "tape.unsupported")
        return None


def _pow2(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return b


def size_class(n_instrs: int, n_leaves: int) -> tuple[int, int]:
    """The (tape_len, leaf_slots) bucket a tape pads into: pow2 on
    both axes with a MIN_BUCKET floor.  Lowered-variant count stays
    O(log(max_tape) * log(max_leaves)) while heterogeneous shapes of
    similar size share one launch."""
    return (max(MIN_BUCKET, _pow2(max(1, n_instrs))),
            max(MIN_BUCKET, _pow2(max(1, n_leaves))))


# ------------------------------------------------------------- counters

_lock = threading.Lock()
_counters = {
    "tape.executions": 0,         # interpreter launches (device or host)
    "tape.queries": 0,            # queries served through those launches
    "tape.oversize_fallbacks": 0,  # per-query cap fallbacks to fused path
    "tape.unsupported": 0,        # structurally ineligible (shift) shapes
    "tape.prewarmed": 0,          # bucket programs lowered at server start
    "coalescer.shape_misses": 0,  # eligible queries with no same-shape
                                  # partner in their flushed batch
    "coalescer.shape_flushes": 0,  # flushes carrying >1 distinct shape
    "vm.executions": 0,           # bitmap-VM launches (pallas/jnp/host)
    "vm.queries": 0,              # queries served through those launches
    "vm.fallbacks": 0,            # VM-gated queries routed to the dense
                                  # ragged/fused engines instead
    # per-reason breakout of WHY a VM-gated query fell back (the
    # central vm.fallbacks stays the authoritative total; mesh_active
    # is informational only — a mesh route is not a degradation)
    "vm.fallbacks.disabled": 0,       # containers runtime disabled
    "vm.fallbacks.ineligible_leaf": 0,  # non-container-eligible leaf /
                                        # dense-slot directory
    "vm.fallbacks.kind_unsupported": 0,  # directory carries a kind
                                         # byte with no VM decode arm
    "vm.fallbacks.oversize": 0,       # tape/leaf caps exceeded
    "vm.fallbacks.max_prefetch": 0,   # single query blows the scalar
                                      # prefetch budget
    "vm.fallbacks.min_domain": 0,     # ...and only because of the
                                      # configured min-domain floor
    "vm.fallbacks.mesh_active": 0,    # mesh routing took the query
}
#: (counts, B, tape_len, slots, *stack_shape) combos the interpreter
#: has lowered — the /debug/ragged program inventory.
_lowered: set[tuple] = set()
#: (B, tape_len, slots, domain) combos the bitmap VM has lowered —
#: the /debug/ragged "vm" program inventory.
_vm_lowered: set[tuple] = set()


def bump(name: str, value: int = 1) -> None:
    with _lock:
        _counters[name] += value


def counters() -> dict[str, int]:
    with _lock:
        return dict(_counters)


def reset_counters() -> None:
    """Zero the module counters and the lowered-program inventory
    (tests)."""
    with _lock:
        for k in _counters:
            _counters[k] = 0
        _lowered.clear()
        _vm_lowered.clear()


def publish_gauges(stats: Any) -> None:
    """Push the tape.* / coalescer.shape_* families into a stats
    registry at scrape time — cumulative values as gauges, same rule
    as resultcache/devobs publish_gauges (re-publishing a cumulative
    total through a counter would double-count)."""
    for name, value in counters().items():
        stats.gauge(name, value)


def debug() -> dict[str, Any]:
    """The /debug/ragged document body: counters plus the interpreter
    program inventory (which bucket variants this process has
    lowered)."""
    with _lock:
        progs = [{"counts": c, "batch": b, "tapeLen": t, "slots": s,
                  "stack": list(shape)}
                 for (c, b, t, s, *shape) in sorted(_lowered)]
        vm_progs = [{"batch": b, "tapeLen": t, "slots": s, "domain": d}
                    for (b, t, s, d) in sorted(_vm_lowered)]
        reasons = {k.split(".", 2)[2]: v for k, v in _counters.items()
                   if k.startswith("vm.fallbacks.")}
        return {"counters": dict(_counters), "programs": progs,
                "vm": {"programs": vm_progs,
                       "fallbackReasons": reasons}}


# ------------------------------------------------------------ interpreter


def _abs_operand(ref: int, n_slots: int) -> int:
    """Symbolic operand -> absolute register index in a bucket with
    ``n_slots`` leaf registers."""
    return ref if ref >= 0 else n_slots + ~ref


_programs: dict = {}


def _one_query(counts: bool) -> Callable[..., Any]:
    """The per-query scan/switch interpreter body, shared verbatim by
    the single-device program and the shard_map mesh variant — the
    two routes cannot drift because they trace the same closure."""
    import jax.numpy as jnp
    from jax import lax

    def one(tape_q: Any, leaves_q: Any) -> Any:
        n_slots = leaves_q.shape[0]
        tape_len = tape_q.shape[0]
        regs0 = jnp.concatenate(
            [leaves_q,
             jnp.zeros((tape_len,) + leaves_q.shape[1:],
                       leaves_q.dtype)])

        def step(regs: Any, xs: Any) -> tuple[Any, None]:
            instr, t = xs
            xa = regs[instr[1]]
            xb = regs[instr[2]]
            out = lax.switch(instr[0], (
                lambda a, b: jnp.bitwise_and(a, b),
                lambda a, b: jnp.bitwise_or(a, b),
                lambda a, b: jnp.bitwise_xor(a, b),
                lambda a, b: jnp.bitwise_and(a, jnp.bitwise_not(b)),
                lambda a, b: a,
            ), xa, xb)
            regs = lax.dynamic_update_slice(
                regs, out[None], (n_slots + t,) + (0,) * out.ndim)
            return regs, None

        regs, _ = lax.scan(step, regs0,
                           (tape_q, jnp.arange(tape_len)))
        res = regs[-1]
        if counts:
            return jnp.sum(lax.population_count(res), axis=-1,
                           dtype=jnp.int32)
        return res

    return one


def _program(counts: bool) -> Callable[..., Any]:
    """The ONE vmapped scan/switch interpreter per root kind, jitted —
    jax re-lowers it per (batch, tape_len, slots, stack) input shape,
    which is exactly the bucket structure; the Python closure is
    shared.  devobs-instrumented so first lowerings surface on
    /debug/devices and ride the paying query's flight record."""
    prog = _programs.get(counts)
    if prog is not None:
        return prog
    import jax

    from pilosa_tpu import devobs

    one = _one_query(counts)
    name = "tape.interpret_counts" if counts else "tape.interpret"
    prog = devobs.instrument(name, jax.jit(jax.vmap(one)))
    _programs[counts] = prog
    return prog


def _mesh_program(counts: bool, mesh: Any) -> Callable[..., Any]:
    """The mesh-native interpreter (parallel/meshexec.py): the SAME
    vmapped scan/switch body runs per device on shard-axis blocks of
    the batched register file under ``shard_map`` — tapes replicate
    (they are tiny int32 control words), leaf stacks shard on the
    shard axis (dim 2 of the [B, slots, S, W] batch), and a Count
    root all_gathers the per-shard popcounts back so the output is
    bit-identical to the single-device interpreter.  One launch then
    executes the whole heterogeneous megabatch across every mesh
    chip.  Cached per (root kind, mesh) — the Mesh is a meshexec
    singleton."""
    key = (counts, mesh)
    prog = _programs.get(key)
    if prog is not None:
        return prog
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from pilosa_tpu import devobs
    from pilosa_tpu.parallel import meshexec
    from pilosa_tpu.parallel.mesh import shard_map

    one = _one_query(counts)
    leaf_spec = P(None, None, meshexec.SHARD_AXIS, None)

    def body(tapes_blk: Any, leaves_blk: Any) -> Any:
        out = jax.vmap(one)(tapes_blk, leaves_blk)
        if counts:
            return lax.all_gather(out, meshexec.SHARD_AXIS,
                                  axis=1, tiled=True)
        return out

    sm = shard_map(body, mesh=mesh, in_specs=(P(), leaf_spec),
                   out_specs=(P() if counts
                              else P(None, meshexec.SHARD_AXIS, None)),
                   check_rep=False)

    def run(tapes: Any, leaves: Any) -> Any:
        return sm(tapes, leaves)

    name = ("tape.mesh_interpret_counts" if counts
            else "tape.mesh_interpret")
    prog = devobs.instrument(name, jax.jit(run))
    _programs[key] = prog
    return prog


def _host_exec(tp: Tape, leaves: tuple, counts: bool) -> np.ndarray:
    """Eager numpy interpretation of one tape (host-mode engine)."""
    outs: list[np.ndarray] = []

    def operand(ref: int) -> np.ndarray:
        return leaves[ref] if ref >= 0 else outs[~ref]

    for op, a, b in tp.instrs:
        xa = operand(a)
        if op == OP_COPY:
            outs.append(xa)
            continue
        xb = operand(b)
        if op == OP_AND:
            outs.append(np.bitwise_and(xa, xb))
        elif op == OP_OR:
            outs.append(np.bitwise_or(xa, xb))
        elif op == OP_XOR:
            outs.append(np.bitwise_xor(xa, xb))
        else:
            outs.append(np.bitwise_and(xa, np.bitwise_not(xb)))
    res = outs[-1]
    if counts:
        from pilosa_tpu.ops import hostkernels as hk

        lead = res.shape[:-1]
        return hk.row_counts(
            res.reshape(-1, res.shape[-1])).reshape(lead)
    return res


def execute(batch: Sequence[tuple[Tape, tuple]], counts: bool = False,
            tape_len: int | None = None,
            slots: int | None = None, mesh: Any = None) -> list[Any]:
    """Execute a batch of (Tape, leaves) pairs in ONE launch.

    Every query's leaf stacks must share one array shape (the
    coalescer's bucket key guarantees it).  ``tape_len``/``slots`` pin
    the bucket the batch pads into (defaults: the batch's own pow2
    size class).  Returns one result per query, in order — the bitmap
    stack, or int32 per-row popcounts with ``counts=True``.  Pad rows
    (batch pow2, slot and tape padding) are never returned.

    ``mesh`` (meshexec.query_mesh) routes the shard_map interpreter:
    the batch's register file shards on the stack's shard axis and
    the one launch spans every mesh device, bit-identically.  None
    (and host mode) keeps the existing engines.
    """
    if not batch:
        return []
    tb, lb = size_class(max(len(t.instrs) for t, _ in batch),
                        max(t.n_leaves for t, _ in batch))
    tape_len = tape_len or tb
    slots = slots or lb
    for tp, ls in batch:
        if len(tp.instrs) > tape_len or len(ls) > slots:
            raise TapeError("tape exceeds its bucket")
    n = len(batch)
    bm.note_dispatch("tape")
    bump("tape.executions")
    bump("tape.queries", n)
    t0 = _perfobs.t0()
    if all(isinstance(lv, np.ndarray) for _, ls in batch for lv in ls):
        outs = [_host_exec(tp, ls, counts) for tp, ls in batch]
        _perfobs.sample(
            "tape", outs, t0,
            nbytes=sum(lv.nbytes for _, ls in batch for lv in ls)
            + sum(getattr(o, "nbytes", 0) for o in outs))
        return outs

    import jax.numpy as jnp

    first = batch[0][1][0]
    stack_shape = tuple(first.shape)
    zero = jnp.zeros(stack_shape, first.dtype)
    # batch pads to the next power of two, like the coalescer's device
    # batches: the jitted interpreter re-lowers per input shape, and
    # free-running occupancies would each pay a fresh XLA compile in
    # the serving path
    b_pad = _pow2(n)
    tape_rows = np.zeros((b_pad, tape_len, 3), dtype=np.int32)
    tape_rows[:, :, 0] = OP_COPY  # pad rows: COPY of leaf slot 0
    leaf_rows = []
    pad_leaves = None
    for qi in range(b_pad):
        if qi >= n:
            if pad_leaves is None:
                pad_leaves = jnp.stack([zero] * slots)
            leaf_rows.append(pad_leaves)
            continue
        tp, ls = batch[qi]
        for ti, (op, a, b) in enumerate(tp.instrs):
            tape_rows[qi, ti] = (op, _abs_operand(a, slots),
                                 _abs_operand(b, slots))
        final = slots + len(tp.instrs) - 1
        # short tapes chain COPYs of the final real register forward,
        # so the LAST register holds the result after the full scan
        tape_rows[qi, len(tp.instrs):, 1] = final
        leaf_rows.append(jnp.stack(
            list(ls) + [zero] * (slots - len(ls))))
    leaves_arr = jnp.stack(leaf_rows)
    with _lock:
        _lowered.add((counts, b_pad, tape_len, slots) + stack_shape)
    if mesh is not None:
        from pilosa_tpu.parallel import meshexec

        if len(stack_shape) >= 2 and meshexec.shardable(
                mesh, stack_shape[0]):
            meshexec.note_launch(n)
            tapes_dev = meshexec.ensure_replicated(
                jnp.asarray(tape_rows), mesh)
            leaves_dev = meshexec.ensure_placed(leaves_arr, mesh, 2)
            # dispatch under the process-wide mesh launch lock (see
            # meshexec.launch_lock: concurrent collective dispatches
            # can deadlock the backend)
            with meshexec.launch_lock():
                out = _mesh_program(counts, mesh)(tapes_dev,
                                                  leaves_dev)
            # the perfobs block waits OUTSIDE the launch lock
            _perfobs.sample(
                "mesh", out, t0,
                nbytes=leaves_arr.nbytes + tape_rows.nbytes
                + getattr(out, "nbytes", 0))
            return [out[i] for i in range(n)]
    out = _program(counts)(jnp.asarray(tape_rows), leaves_arr)
    _perfobs.sample("tape", out, t0,
                    nbytes=leaves_arr.nbytes + tape_rows.nbytes
                    + getattr(out, "nbytes", 0))
    return [out[i] for i in range(n)]


def execute_vm(batch: Sequence[tuple[Tape, list]], pool: Any,
               zero_index: int, tape_len: int | None = None,
               slots: int | None = None, interpret: bool = False,
               max_prefetch: int | None = None) -> list[np.ndarray]:
    """Execute a megabatch of (Tape, gather rows) queries over ONE
    pooled compressed operand as ONE bitmap-VM launch
    (ops/pallas_kernels.vm_counts).

    Each query's second element is its per-leaf-slot list of int32[D]
    GLOBAL pool row indices (the coalescer globalizes the staged
    per-leaf directories against the bucket megapool —
    ops/containers.megapool); every query in the batch shares one
    domain width D.  ``zero_index`` is the megapool's canonical
    all-zero row: pad slots, pad batch rows and absent containers all
    gather it and contribute nothing.  Returns one int64[D] per-cell
    count vector per query, in order — the query's total is the plain
    sum (there is no shard-row alignment to trim; the domain already
    concatenated the per-shard walks).

    ``max_prefetch`` bounds the scalar-prefetch directory
    (slots x batch x D int32 entries live in SMEM on chip): an
    oversized batch splits in half recursively, each half its own
    launch — the ≤2-launch degradation the acceptance pin allows."""
    if not batch:
        return []
    tb, lb = size_class(max(len(t.instrs) for t, _ in batch),
                        max(t.n_leaves for t, _ in batch))
    tape_len = tape_len or tb
    slots = slots or lb
    for tp, idxs in batch:
        if len(tp.instrs) > tape_len or len(idxs) > slots:
            raise TapeError("tape exceeds its bucket")
    n = len(batch)
    D = len(batch[0][1][0])
    b_pad = _pow2(n)
    if (max_prefetch is not None and n > 1
            and slots * b_pad * D > max_prefetch):
        mid = (n + 1) // 2
        return (execute_vm(batch[:mid], pool, zero_index, tape_len,
                           slots, interpret, max_prefetch)
                + execute_vm(batch[mid:], pool, zero_index, tape_len,
                             slots, interpret, max_prefetch))
    bm.note_dispatch("vm")
    bump("vm.executions")
    bump("vm.queries", n)
    t0 = _perfobs.t0()
    prog = np.zeros((b_pad, tape_len, 3), dtype=np.int32)
    prog[:, :, 0] = OP_COPY  # pad rows: COPY of leaf slot 0
    gidx = np.full((slots, b_pad, D), zero_index, dtype=np.int32)
    for qi, (tp, idxs) in enumerate(batch):
        for ti, (op, a, b) in enumerate(tp.instrs):
            prog[qi, ti] = (op, _abs_operand(a, slots),
                            _abs_operand(b, slots))
        final = slots + len(tp.instrs) - 1
        # short tapes chain COPYs of the final real register forward,
        # exactly like execute() — the LAST register holds the result
        prog[qi, len(tp.instrs):, 1] = final
        for li, ix in enumerate(idxs):
            gidx[li, qi, :len(ix)] = ix
    with _lock:
        _vm_lowered.add((b_pad, tape_len, slots, D))
    from pilosa_tpu.ops import pallas_kernels as pk

    cts = np.asarray(pk.vm_counts(pool, prog, gidx,
                                  interpret=interpret),
                     dtype=np.int64)
    # what the VM launch actually touches: the gathered container
    # blocks (every directory entry DMAs one pool row), the SMEM
    # directory + programs, and the count outputs — never the dense
    # register file (the engine's whole point).  A kind-split megapool
    # bundle (containers.MegaPools) samples as its own engine cell —
    # the launch's decode arms are a different cost shape than the
    # plain dense-pool gather
    from pilosa_tpu.ops import containers as _containers

    if isinstance(pool, _containers.MegaPools):
        engine = "vm_kinds"
        touched = int(pool.nbytes)
    else:
        engine = "vm"
        cwords = int(pool.shape[-1]) if getattr(pool, "ndim", 0) else 0
        touched = gidx.size * cwords * 4
    _perfobs.sample(engine, cts, t0,
                    nbytes=touched + gidx.nbytes
                    + prog.nbytes + cts.nbytes)
    return [cts[i] for i in range(n)]


# --------------------------------------------------------------- prewarm


def _prewarm_worthwhile() -> bool:
    """Whether lowering interpreter programs ahead of traffic pays on
    THIS process's devices.  Host mode runs the numpy engine (nothing
    to lower); CPU backends — one device or a virtual multi-device
    test mesh alike — lower these programs cheaply on first use while
    the warm-up's register file (batch x (slots + tape) x stack
    words) would transiently cost real host memory.  Accelerator
    backends pay multi-hundred-ms serving-path compiles, which is
    what prewarm exists to move off the first window."""
    import jax

    if bm.host_mode():
        return False
    return jax.devices()[0].platform != "cpu"


def prewarm(stack_shape: tuple[int, ...], max_batch: int,
            max_tape: int, max_leaves: int,
            counts: bool = True, mesh: Any = None) -> int:
    """Lower the bucket programs a serving process will hit first.
    Flushes pad the BATCH axis to pow2(occupancy), so a window
    sealing at 5 queries dispatches a b=8 program — warming only the
    full batch width would leave every partially-filled first window
    paying a serving-path XLA compile (the convoy the pow2 padding
    exists to kill).  So: the smallest size class (where shallow-tree
    traffic lands) warms across the whole pow2 batch ladder
    2..pow2(max_batch), and the largest class (the configured caps,
    the worst single compile) warms at full width.

    The programs warmed are keyed on the ACTUAL device layout:
    ``mesh`` (the caller's meshexec.active_mesh(), threaded from
    server open) selects the shard_map interpreter variants, and its
    absence the single-device ones — so a 1-device process never
    lowers mesh-shaped programs and an N-device mesh never wastes its
    warm-up on programs serving traffic won't run.  ``stack_shape``
    must carry the same device-count-derived padding serving stacks
    get (models/field._padded_rows).  Called from server open on a
    background thread; best-effort, and a no-op where lowering is
    cheap (``_prewarm_worthwhile``).  Returns the number of programs
    warmed."""
    import jax

    if not _prewarm_worthwhile():
        return 0
    import jax.numpy as jnp

    use_mesh = mesh is not None and len(stack_shape) >= 2
    if use_mesh:
        from pilosa_tpu.parallel import meshexec

        if not meshexec.shardable(mesh, stack_shape[0]):
            use_mesh = False

    b_full = max(2, _pow2(max_batch))
    small = size_class(1, 1)
    large = size_class(max_tape, max_leaves)
    jobs: list[tuple[int, int, int]] = []
    b = 2
    while b <= b_full:
        jobs.append((b,) + small)
        b <<= 1
    if large != small:
        jobs.append((b_full,) + large)
    warmed = 0
    for b, tape_len, slots in jobs:
        tape_rows = np.zeros((b, tape_len, 3), dtype=np.int32)
        tape_rows[:, :, 0] = OP_COPY
        leaves = jnp.zeros((b, slots) + tuple(stack_shape),
                           dtype=jnp.uint32)
        if use_mesh:
            from pilosa_tpu.parallel import meshexec

            tapes_dev = meshexec.ensure_replicated(
                jnp.asarray(tape_rows), mesh)
            leaves_dev = meshexec.ensure_placed(leaves, mesh, 2)
            # the every-mesh-dispatch rule applies to warm-up too: a
            # prewarm thread racing a serving thread's collective
            # launch is the same enqueue-interleave deadlock
            with meshexec.launch_lock():
                out = _mesh_program(counts, mesh)(tapes_dev,
                                                  leaves_dev)
        else:
            out = _program(counts)(jnp.asarray(tape_rows), leaves)
        jax.block_until_ready(out)
        with _lock:
            _lowered.add((counts, b, tape_len, slots)
                         + tuple(stack_shape))
        warmed += 1
    bump("tape.prewarmed", warmed)
    return warmed
