"""Bit-sliced index (BSI) kernels: integer compare/aggregate over bit planes.

A BSI field stores per-column integers as bit planes (reference encoding,
fragment.go:91-93: row 0 = exists, row 1 = sign, rows 2.. = magnitude bits
LSB-first; values are sign+magnitude offsets from a base).  Here the planes
are one dense uint32 matrix ``P[2 + depth, words]`` so every comparison or
aggregate is a single fused XLA kernel over the whole plane stack — the
TPU-native replacement for the reference's per-plane Row walks
(fragment.go:1273-1537 rangeEQ/LT/GT/Between, :1111 sum, :1147/:1191
min/max).

Comparisons are branch-free: instead of the reference's keep/filter row
dance, we track ``lt`` (strictly-less-so-far) and ``eq`` (equal-so-far)
masks down the planes — mathematically the same result, but fully
vectorized.  Predicate magnitudes arrive as two uint32 limbs (lo, hi) so
depths up to 64 work without enabling x64; the host splits the Python int.

Sign dispatch (negative vs positive predicates) happens host-side in the
fragment/executor — the predicate value is query text, so no recompilation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

EXISTS_PLANE = 0
SIGN_PLANE = 1
OFFSET_PLANE = 2


# Maximum supported bit depth: magnitudes are int64-range, as in the
# reference (bsiGroup values are int64, field.go:1563).
MAX_BIT_DEPTH = 63


def split_predicate(upred: int) -> tuple[np.uint32, np.uint32]:
    """Split a non-negative magnitude into two uint32 limbs for the kernels."""
    if upred < 0:
        raise ValueError("magnitude must be non-negative")
    if upred >= (1 << 64):
        raise ValueError(f"magnitude {upred} exceeds 64-bit kernel range")
    return np.uint32(upred & 0xFFFFFFFF), np.uint32((upred >> 32) & 0xFFFFFFFF)


def _pred_bit_mask(lo, hi, i: int):
    """All-ones uint32 word when predicate bit i is set, else zero."""
    if i >= 64:
        raise ValueError(f"bit plane {i} beyond 64-bit predicate range")
    limb, off = (lo, i) if i < 32 else (hi, i - 32)
    bit = (limb >> np.uint32(off)) & np.uint32(1)
    return jnp.uint32(0) - bit  # 0xFFFFFFFF or 0


def compare(P, filt, lo, hi):
    """Dispatcher: host numpy plane stacks run the loop in numpy (the
    CPU engine — no per-query host->device copy), device stacks jit."""
    if isinstance(P, np.ndarray) and isinstance(filt, np.ndarray):
        depth = P.shape[0] - OFFSET_PLANE
        lt = np.zeros_like(filt)
        eq = filt
        for i in range(depth - 1, -1, -1):
            plane = P[OFFSET_PLANE + i]
            limb, off = (lo, i) if i < 32 else (hi, i - 32)
            bit = (np.uint32(limb) >> np.uint32(off)) & np.uint32(1)
            bmask = np.uint32(0xFFFFFFFF) if bit else np.uint32(0)
            lt = lt | (eq & ~plane & bmask)
            eq = eq & (plane ^ ~bmask)
        return lt, eq
    return _jit_compare(P, filt, lo, hi)


@jax.jit
def _jit_compare(P, filt, lo, hi):
    """One pass down the planes -> (lt, eq) masks within ``filt``.

    lt = columns whose magnitude < predicate; eq = columns equal to it.
    Callers derive every comparison: LTE = lt|eq, GT = filt & ~(lt|eq),
    GTE = filt & ~lt, EQ = eq, NEQ = exists & ~eq.
    """
    depth = P.shape[0] - OFFSET_PLANE
    lt = jnp.zeros_like(filt)
    eq = filt
    for i in range(depth - 1, -1, -1):
        plane = P[OFFSET_PLANE + i]
        bmask = _pred_bit_mask(lo, hi, i)
        # strictly less: equal so far, predicate bit 1, plane bit 0
        lt = lt | (eq & ~plane & bmask)
        # still equal: plane bit must match predicate bit
        eq = eq & (plane ^ ~bmask)
    return lt, eq


def plane_counts(P, consider):
    """Dispatcher (see compare)."""
    if isinstance(P, np.ndarray) and isinstance(consider, np.ndarray):
        from pilosa_tpu.ops import hostkernels as hk

        sign = P[SIGN_PLANE]
        prow = consider & ~sign
        nrow = consider & sign
        planes = np.ascontiguousarray(P[OFFSET_PLANE:])
        return (hk.row_counts_masked(planes, prow),
                hk.row_counts_masked(planes, nrow))
    return _jit_plane_counts(P, consider)


@jax.jit
def _jit_plane_counts(P, consider):
    """Per-plane intersection counts split by sign -> (pos, neg) int32[depth].

    Sum = sum_i (1<<i) * (pos_i - neg_i), assembled host-side with exact
    Python ints (reference fragment.sum, fragment.go:1111-1143)."""
    sign = P[SIGN_PLANE]
    prow = consider & ~sign
    nrow = consider & sign
    planes = P[OFFSET_PLANE:]
    pos = jnp.sum(lax.population_count(planes & prow[None, :]), axis=-1, dtype=jnp.int32)
    neg = jnp.sum(lax.population_count(planes & nrow[None, :]), axis=-1, dtype=jnp.int32)
    return pos, neg


def plane_counts_stacked(P, consider):
    """Dispatcher (see compare)."""
    if isinstance(P, np.ndarray) and isinstance(consider, np.ndarray):
        from pilosa_tpu.ops import hostkernels as hk

        S, nplanes, _words = P.shape
        depth = nplanes - OFFSET_PLANE
        sign = P[:, SIGN_PLANE]
        prow = consider & ~sign
        nrow = consider & sign
        pos = np.empty((S, depth), dtype=np.int32)
        neg = np.empty((S, depth), dtype=np.int32)
        # per-shard slices of a C-contiguous P are themselves contiguous,
        # so this loop is copy-free (a flattened P[:, OFFSET_PLANE:]
        # would memcpy the whole magnitude stack every query)
        for i in range(S):
            planes = P[i, OFFSET_PLANE:]
            pos[i] = hk.row_counts_masked(planes, prow[i])
            neg[i] = hk.row_counts_masked(planes, nrow[i])
        return pos, neg, hk.row_counts(consider)
    return _jit_plane_counts_stacked(P, consider)


@jax.jit
def _jit_plane_counts_stacked(P, consider):
    """Batched plane counts over a [shards, planes, words] stack ->
    (pos int32[S, depth], neg int32[S, depth], count int32[S]).

    Per-shard counts stay < 2^20 so int32 is exact; the caller sums
    across shards in Python ints (the fused executor Sum path — one
    dispatch for all shards instead of one per shard)."""
    sign = P[:, SIGN_PLANE]
    prow = consider & ~sign
    nrow = consider & sign
    planes = P[:, OFFSET_PLANE:]
    pos = jnp.sum(lax.population_count(planes & prow[:, None, :]),
                  axis=2, dtype=jnp.int32)
    neg = jnp.sum(lax.population_count(planes & nrow[:, None, :]),
                  axis=2, dtype=jnp.int32)
    count = jnp.sum(lax.population_count(consider), axis=1,
                    dtype=jnp.int32)
    return pos, neg, count


def extremes_stacked(P, consider, want: str):
    """Dispatcher (see compare)."""
    if isinstance(P, np.ndarray) and isinstance(consider, np.ndarray):
        from pilosa_tpu.ops import hostkernels as hk

        S = P.shape[0]
        sign = P[:, SIGN_PLANE]
        selected = consider & sign if want == "min" else consider & ~sign
        signed_cnt = hk.row_counts(selected)
        all_cnt = hk.row_counts(consider)
        pt, pn, ft, fn = [], [], [], []
        for s_i in range(S):
            t, n = _np_extreme_max(P[s_i], selected[s_i])
            pt.append(t)
            pn.append(n)
            t, n = _np_extreme_min(P[s_i], consider[s_i])
            ft.append(t)
            fn.append(n)
        return (signed_cnt, all_cnt, np.stack(pt), np.stack(ft),
                np.array(pn, dtype=np.int32), np.array(fn, dtype=np.int32))
    return _jit_extremes_stacked(P, consider, want)


def _np_extreme_max(P, filt):
    """Host mirror of extreme_max: keep filt when a plane has no bits."""
    from pilosa_tpu.ops import hostkernels as hk

    depth = P.shape[0] - OFFSET_PLANE
    taken = np.zeros(depth, dtype=np.int32)
    for i in range(depth - 1, -1, -1):
        row = P[OFFSET_PLANE + i] & filt
        if hk.count(row) > 0:
            taken[i] = 1
            filt = row
    return taken, np.int32(hk.count(filt))


def _np_extreme_min(P, filt):
    """Host mirror of extreme_min."""
    from pilosa_tpu.ops import hostkernels as hk

    depth = P.shape[0] - OFFSET_PLANE
    taken = np.zeros(depth, dtype=np.int32)
    for i in range(depth - 1, -1, -1):
        without = filt & ~P[OFFSET_PLANE + i]
        if hk.count(without) > 0:
            filt = without
        else:
            taken[i] = 1
    return taken, np.int32(hk.count(filt))


@functools.partial(jax.jit, static_argnames=("want",))
def _jit_extremes_stacked(P, consider, want: str):
    """Batched Min/Max scan over a [shards, planes, words] stack.

    `want` selects which two scans run ("min": neg-magnitude max +
    all-magnitude min; "max": pos-magnitude max + all-magnitude min) —
    each query needs exactly two of the three possible scans.  Returns
    per-shard arrays (signed_cnt, all_cnt int32[S], primary_taken,
    fallback_taken int32[S, depth], primary_n, fallback_n int32[S]) for
    the host to apply fragment.min/max's sign-branching
    (fragment.go:1147/1191) without a device sync per shard."""
    sign = P[:, SIGN_PLANE]
    selected = consider & sign if want == "min" else consider & ~sign
    signed_cnt = jnp.sum(lax.population_count(selected), axis=1,
                         dtype=jnp.int32)
    all_cnt = jnp.sum(lax.population_count(consider), axis=1,
                      dtype=jnp.int32)
    primary_taken, primary_n = jax.vmap(extreme_max)(P, selected)
    fallback_taken, fallback_n = jax.vmap(extreme_min)(P, consider)
    return (signed_cnt, all_cnt, primary_taken, fallback_taken,
            primary_n, fallback_n)


@jax.jit
def extreme_max(P, filt):
    """Unsigned max under ``filt`` -> (taken int32[depth], count int32).

    taken[i] = 1 if the max value has bit i set; count = #columns holding
    the max (reference maxUnsigned, fragment.go:1215-1230).  Host assembles
    value = sum(taken[i] << i)."""
    depth = P.shape[0] - OFFSET_PLANE
    taken = []
    for i in range(depth - 1, -1, -1):
        row = P[OFFSET_PLANE + i] & filt
        cnt = jnp.sum(lax.population_count(row), dtype=jnp.int32)
        has = cnt > 0
        taken.append(has.astype(jnp.int32))
        filt = jnp.where(has, row, filt)
    count = jnp.sum(lax.population_count(filt), dtype=jnp.int32)
    return jnp.stack(taken[::-1]), count


@jax.jit
def extreme_min(P, filt):
    """Unsigned min under ``filt`` (reference minUnsigned, fragment.go:1173)."""
    depth = P.shape[0] - OFFSET_PLANE
    taken = []
    for i in range(depth - 1, -1, -1):
        without = filt & ~P[OFFSET_PLANE + i]
        cnt = jnp.sum(lax.population_count(without), dtype=jnp.int32)
        keep_zero = cnt > 0
        # if some column has bit i clear, min has bit i clear; else bit set
        taken.append((~keep_zero).astype(jnp.int32))
        filt = jnp.where(keep_zero, without, filt)
    count = jnp.sum(lax.population_count(filt), dtype=jnp.int32)
    return jnp.stack(taken[::-1]), count


def range_words(P, op: str, predicate: int):
    """BSI comparison over one plane stack [planes, words] -> packed
    words (the pure core of fragment.rangeOp, fragment.go:1273; the
    fused executor vmaps this over [shards, planes, words]).

    Sign dispatch: predicate >= 0 -> compare magnitudes among positives
    (negatives are all smaller); predicate < 0 -> compare among
    negatives with the order inverted.  NOTE: deliberate divergence from
    the reference — its rangeLT/rangeGT route `predicate == -1 &&
    !allowEquality` through the positive branch with upredicate=1
    (fragment.go:1343,1412), which drops 0/±1 columns from `> -1` and
    adds 0-columns to `< -1`; that edge is untested upstream, so we use
    correct integer semantics instead."""
    exists = P[EXISTS_PLANE]
    sign = P[SIGN_PLANE]
    upred = -predicate if predicate < 0 else predicate
    lo, hi = split_predicate(upred)

    def u_lt(filt, allow_eq):
        lt, eq = compare(P, filt, lo, hi)
        return lt | eq if allow_eq else lt

    def u_gt(filt, allow_eq):
        lt, eq = compare(P, filt, lo, hi)
        gt = filt & ~lt & ~eq
        return gt | eq if allow_eq else gt

    if op == "==":
        base = exists & sign if predicate < 0 else exists & ~sign
        _, eq = compare(P, base, lo, hi)
        return eq
    if op == "!=":
        base = exists & sign if predicate < 0 else exists & ~sign
        _, eq = compare(P, base, lo, hi)
        return exists & ~eq
    if op in ("<", "<="):
        allow_eq = op == "<="
        if predicate >= 0:
            return (exists & sign) | u_lt(exists & ~sign, allow_eq)
        return u_gt(exists & sign, allow_eq)
    if op in (">", ">="):
        allow_eq = op == ">="
        if predicate >= 0:
            return u_gt(exists & ~sign, allow_eq)
        return (exists & ~sign) | u_lt(exists & sign, allow_eq)
    raise ValueError(f"invalid range operation: {op}")


def between_words(P, pred_min: int, pred_max: int):
    """BSI between [min, max] inclusive over one plane stack (the pure
    core of fragment.rangeBetween, fragment.go:1465)."""
    exists = P[EXISTS_PLANE]
    sign = P[SIGN_PLANE]

    def u_between(filt, ulo, uhi):
        lo1, hi1 = split_predicate(ulo)
        lo2, hi2 = split_predicate(uhi)
        lt1, _ = compare(P, filt, lo1, hi1)
        lt2, eq2 = compare(P, filt, lo2, hi2)
        return (filt & ~lt1) & (lt2 | eq2)

    if pred_min >= 0:
        return u_between(exists & ~sign, pred_min, pred_max)
    if pred_max < 0:
        return u_between(exists & sign, -pred_max, -pred_min)
    lo2, hi2 = split_predicate(pred_max)
    lt2, eq2 = compare(P, exists & ~sign, lo2, hi2)
    pos = lt2 | eq2
    lo1, hi1 = split_predicate(-pred_min)
    lt1, eq1 = compare(P, exists & sign, lo1, hi1)
    neg = lt1 | eq1
    return pos | neg


def assemble_value(taken) -> int:
    """Host: fold per-bit takes into an exact Python int magnitude."""
    v = 0
    for i, t in enumerate(np.asarray(taken)):
        if int(t):
            v |= 1 << i
    return v


# Compile telemetry (pilosa_tpu.devobs): cache-miss first lowerings of
# the BSI kernels are detected and timed per canonical shape, mirroring
# the ops/bitmap.py instrumentation loop.
from pilosa_tpu import devobs as _devobs  # noqa: E402

for _n in ("_jit_compare", "_jit_plane_counts",
           "_jit_plane_counts_stacked", "_jit_extremes_stacked",
           "extreme_max", "extreme_min"):
    globals()[_n] = _devobs.instrument(f"bsi.{_n.removeprefix('_jit_')}",
                                       globals()[_n])
del _n
