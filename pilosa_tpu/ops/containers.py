"""Roaring-on-TPU: compressed container-directory execution engine.

Fragments have lived on device as fully dense bit planes, so a sparse
row spends ~all of its HBM traffic reading zero words and HBM capacity
caps the column count per chip (BENCH_r05: bw_util 0.148).  The
reference's entire performance story is container specialization
(Chambi et al., "Better bitmap performance with Roaring bitmaps";
Lemire et al., "Consistently faster and smaller compressed bitmaps
with Roaring"): a row decomposes into 2^16-bit containers and only the
non-empty ones exist.  This module ports that idea to the device:

- **Layout** — per fragment row, the non-empty 1024x64-bit (= 2048
  uint32-word) containers are materialized into a contiguous device
  WORD POOL, driven by a small host-side DIRECTORY (per row: container
  keys, pool offsets, kind).  ``storage/roaring.py`` already decodes
  official roaring into exactly this ``(keys, 1024-word blocks)``
  shape, so the host side is a re-plumb, not a rewrite
  (``Fragment.row_containers`` builds it straight off the row words;
  ``Field.device_container_leaf`` pools a row's containers across the
  query's shard set and uploads once, cached under the same base
  generation tokens as the dense row stacks).
- **Execution** — a fused-supported expression tree evaluates over
  compressed leaves by (1) walking the leaf directories on host and
  computing the ROOT's container-key domain per shard with roaring's
  set rules (Intersect intersects key sets, Union/Xor unions,
  Difference keeps the left side, Not keeps the existence row's keys
  — containers absent from the domain are never touched, and two
  disjoint sparse rows intersect in ZERO device work), then (2)
  launching ONE jitted gather-program over the pooled operands
  (``ops/expr.evaluate_gathered``: per-leaf ``take`` from its pool +
  the same fused tree body + the optional popcount Count root, all
  inside one launch).  Domains and pools pad to powers of two so the
  lowered-program count stays O(log), never one per query shape (the
  PR-6 recompile-convoy lesson, enforced by pilosa-lint P4).
- **Fallback** — hot/full rows stay dense: a fragment row whose fill
  ratio (set bits / shard width) exceeds the ``[containers]``
  threshold marks its query dense, and the query routes through the
  exact pre-existing dense fused path (also the ``?nocontainers=1``
  escape, the ``[containers] enabled=false`` switch, pending ingest
  deltas on a queried row, and trees with non-row leaves — BSI
  ranges, time ranges, Shift).  The fallback is query-level by design
  so a fused read always costs exactly ONE launch either way (the
  dispatch-count pins across the suite stay valid).

Process-wide configuration mirrors ``pilosa_tpu.ingest``: ``configure``
applies explicit values in place, the FIRST server to retain() captures
the pre-server baseline and the LAST to release() restores it.
"""

from __future__ import annotations

import threading
from typing import Any

import numpy as np

from pilosa_tpu import perfobs as _perfobs

#: Container geometry: 2^16 bits = 1024 uint64 = 2048 uint32 words —
#: the reference's container size and storage/roaring.py's block shape.
CONTAINER_BITS = 1 << 16
CWORDS = CONTAINER_BITS // 32

DEFAULT_THRESHOLD = 0.25

#: Kind-selection defaults ([containers] kinds / array-max / run-cap):
#: the device pick mirrors the serializer's cost rule
#: (storage/roaring.pick_kind); ``array_max`` narrows the array-kind
#: cardinality ceiling below the canonical 4096 and ``run_cap`` bounds
#: the run pool's interval size class (a container with more maximal
#: runs re-picks array/bitmap).
DEFAULT_KINDS = True
DEFAULT_ARRAY_MAX = 4096
DEFAULT_RUN_CAP = 256


def _pow2(n: int) -> int:
    """Smallest power of two >= n (domain/pool padding so the gather
    programs lower O(log) distinct shapes, not one per query)."""
    b = 1
    while b < n:
        b <<= 1
    return b


# ------------------------------------------------------------ runtime config


class ContainersRuntimeConfig:
    """The process-wide [containers] knobs (one per process, like the
    residency budget and the [ingest] runtime config)."""

    __slots__ = ("enabled", "threshold", "kinds", "array_max",
                 "run_cap")

    def __init__(self) -> None:
        self.enabled = True
        self.threshold = DEFAULT_THRESHOLD
        self.kinds = DEFAULT_KINDS
        self.array_max = DEFAULT_ARRAY_MAX
        self.run_cap = DEFAULT_RUN_CAP


_cfg = ContainersRuntimeConfig()
_cfg_lock = threading.Lock()
_baseline: tuple | None = None
_refs = 0


def config() -> ContainersRuntimeConfig:
    return _cfg


def configure(enabled: bool | None = None,
              threshold: float | None = None,
              kinds: bool | None = None,
              array_max: int | None = None,
              run_cap: int | None = None) -> ContainersRuntimeConfig:
    """Apply [containers] config in place — only explicit values land,
    so a second in-process server cannot wipe the first's settings
    with defaults (same contract as ingest.configure)."""
    with _cfg_lock:
        if enabled is not None:
            _cfg.enabled = bool(enabled)
        if threshold is not None:
            _cfg.threshold = float(threshold)
        if kinds is not None:
            _cfg.kinds = bool(kinds)
        if array_max is not None:
            _cfg.array_max = int(array_max)
        if run_cap is not None:
            _cfg.run_cap = int(run_cap)
    return _cfg


def retain() -> None:
    """Take a server reference; the FIRST holder snapshots the
    pre-server baseline config (restore composes correctly under any
    close order — the PR-6 [ingest] lesson, pilosa-lint P5)."""
    global _refs, _baseline
    with _cfg_lock:
        if _refs == 0 and _baseline is None:
            _baseline = (_cfg.enabled, _cfg.threshold, _cfg.kinds,
                         _cfg.array_max, _cfg.run_cap)
        _refs += 1


def release() -> None:
    """Drop a server reference; the LAST holder restores the captured
    baseline for every other user of the process."""
    global _refs, _baseline
    with _cfg_lock:
        if _refs > 0:
            _refs -= 1
        if _refs == 0 and _baseline is not None:
            (_cfg.enabled, _cfg.threshold, _cfg.kinds,
             _cfg.array_max, _cfg.run_cap) = _baseline
            _baseline = None


def reset() -> ContainersRuntimeConfig:
    """Restore defaults and drop any held baseline (tests)."""
    global _cfg, _baseline, _refs
    with _cfg_lock:
        _cfg = ContainersRuntimeConfig()
        _baseline = None
        _refs = 0
    return _cfg


# ---------------------------------------------------------------- counters

_lock = threading.Lock()
_counters = {
    "container.queries": 0,             # fused reads served compressed
    "container.fallbacks": 0,           # eligible trees routed dense
                                        # (hot rows / pending deltas)
    "container.containers_gathered": 0,  # domain containers launched
    "container.containers_skipped": 0,   # dense-layout containers the
                                         # directory walk never touched
    "container.empty_domains": 0,       # whole-query zero-work answers
    # per-kind breakout of containers_gathered (kind-specialized
    # algebra: which layouts the domain walks actually touch)
    "container.bitmap_gathered": 0,
    "container.array_gathered": 0,
    "container.run_gathered": 0,
}


def bump(name: str, value: int = 1) -> None:
    with _lock:
        _counters[name] += value


def counters() -> dict[str, int]:
    with _lock:
        return dict(_counters)


def reset_counters() -> None:
    with _lock:
        for k in _counters:
            _counters[k] = 0


def publish_gauges(stats: Any) -> None:
    """Push the container.* family into a stats registry at scrape
    time — cumulative values as gauges, same rule as tape/devobs
    publish_gauges (re-publishing a cumulative total through a counter
    would double-count)."""
    for name, value in counters().items():
        stats.gauge(name, value)


def debug() -> dict[str, Any]:
    """The container section of the debug surface: config in force,
    counters, and the residency split (compressed vs dense bytes are
    on /debug/devices via residency.kinds)."""
    return {
        "enabled": _cfg.enabled,
        "threshold": _cfg.threshold,
        "kinds": _cfg.kinds,
        "arrayMax": _cfg.array_max,
        "runCap": _cfg.run_cap,
        "counters": counters(),
    }


# -------------------------------------------------------------- leaf pooling


import itertools as _itertools

_LEAF_UID = _itertools.count(1)


class ContainerLeaf:
    """One expression leaf (a standard-view row across the query's
    shard set) in pooled compressed form.

    ``entries[i]`` describes shard ``shards[i]``: ``None`` for a
    hot/ineligible fragment row (dense fallback evidence), else a
    sorted int64 key array of the row's non-empty container slots
    (possibly empty).  ``starts[i]`` is the shard's base offset into
    the pool; ``pool`` is the uint32[P, CWORDS] block pool (host numpy
    in host mode, device array otherwise) whose rows [n:] are zeros —
    gather index ``n`` is the canonical absent-container row.  ``kinds``
    mirrors the directory's per-container kind byte (1 = dense bitmap
    block, 2 = sorted-uint16 array, 3 = interval-list run).

    A KINDS leaf (``slots`` non-None) splits its containers across
    three pools: ``pool`` holds only the kind-1 dense blocks (``n`` is
    the bitmap count, row ``n`` still the canonical zero), ``apool`` /
    ``acard`` the array kind (uint16[Pa, acap] + int32[Pa], row ``an``
    the canonical empty array), ``rpool`` the run kind (uint16[Pr,
    2*rcap] interleaved (start, last), row ``rn`` all invalid pairs).
    ``slots[i]`` gives each directory container its kind-LOCAL pool
    row.  A legacy all-bitmap leaf keeps ``slots`` None and the other
    pools empty — every pre-kinds code path sees exactly the old
    layout.
    """

    __slots__ = ("shards", "entries", "starts", "kinds", "pool", "n",
                 "nbytes", "uid", "slots", "apool", "acard", "rpool",
                 "an", "rn")

    def __init__(self, shards: tuple, entries: list, starts: list,
                 kinds: list, pool: Any, n: int, nbytes: int,
                 slots: list | None = None, apool: Any = None,
                 acard: Any = None, rpool: Any = None,
                 an: int = 0, rn: int = 0) -> None:
        self.shards = shards
        self.entries = entries
        self.starts = starts
        self.kinds = kinds
        self.pool = pool
        self.n = n
        self.nbytes = nbytes
        self.slots = slots
        self.apool = apool
        self.acard = acard
        self.rpool = rpool
        self.an = an
        self.rn = rn
        # identity for the staging memo: a rebuilt leaf (any base
        # mutation) is a NEW object with a fresh uid, so stale staged
        # gathers can never be addressed
        self.uid = next(_LEAF_UID)

    def dense_slots(self) -> list[int]:
        """Shard positions whose fragment row is too hot to compress."""
        return [i for i, e in enumerate(self.entries) if e is None]

    @property
    def has_kinds(self) -> bool:
        """True when this leaf carries array/run containers (the
        kind-dispatched execution protocol applies)."""
        return self.slots is not None


# ------------------------------------------------------------ domain algebra


def _domain(shape: tuple, keysets: list) -> np.ndarray:
    """The ROOT's container-key domain for one shard: the minimal set
    of container keys that can hold a set bit of the result, from the
    leaves' key sets by roaring's per-op rules.  Containers outside
    the domain are skipped entirely — for Intersect that is exactly
    the reference's co-present-container walk
    (roaring.Intersect, roaring/roaring.go:595)."""
    kind = shape[0]
    if kind == "leaf":
        return keysets[shape[1]]
    if kind == "and":
        out = _domain(shape[1], keysets)
        for c in shape[2:]:
            out = np.intersect1d(out, _domain(c, keysets),
                                 assume_unique=True)
        return out
    if kind in ("or", "xor"):
        out = _domain(shape[1], keysets)
        for c in shape[2:]:
            out = np.union1d(out, _domain(c, keysets))
        return out
    if kind == "andnot":
        # a \ b can only be non-empty where a is
        return _domain(shape[1], keysets)
    if kind == "not":
        # exist & ~child lives inside the existence row's containers
        return _domain(shape[1], keysets)
    if kind == "dfuse":
        # (child & ~clear) | set — a result bit can only live in the
        # child's or the set-overlay's containers (clear only removes)
        return np.union1d(_domain(shape[1], keysets),
                          _domain(shape[2], keysets))
    raise ValueError(f"container-ineligible node: {kind!r}")


def _leaf_indices(leaf: ContainerLeaf, domains: list[np.ndarray],
                  pad_to: int) -> np.ndarray:
    """Gather indices into ``leaf.pool`` for the concatenated per-shard
    domains; absent containers (and the pow2 tail padding) point at the
    pool's canonical zero row."""
    zero = leaf.n
    parts: list[np.ndarray] = []
    for i, dom in enumerate(domains):
        if len(dom) == 0:
            continue
        keys = leaf.entries[i]
        if keys is None or len(keys) == 0:
            parts.append(np.full(len(dom), zero, dtype=np.int32))
            continue
        pos = np.searchsorted(keys, dom)
        pos_c = np.minimum(pos, len(keys) - 1)
        hit = keys[pos_c] == dom
        idx = np.where(hit, leaf.starts[i] + pos_c, zero)
        parts.append(idx.astype(np.int32))
    total = sum(len(p) for p in parts)
    out = np.full(pad_to, zero, dtype=np.int32)
    if parts:
        np.concatenate(parts, out=out[:total])
    return out


def _leaf_kind_indices(leaf: ContainerLeaf, domains: list[np.ndarray],
                       pad_to: int) -> tuple:
    """Kind-dispatched gather rows for the concatenated per-shard
    domains: ``(kv, ib, ia, ir)`` — per-lane kind byte (0 = absent /
    pad) plus per-kind-pool row indices.  Lanes whose kind differs
    from a pool point at that pool's canonical zero row (bitmap row
    ``n``, empty-array row ``an``, invalid-pairs row ``rn``), so a
    gather-then-OR across the three decoded pools reconstructs each
    lane's dense block exactly.  A legacy all-bitmap leaf yields kv in
    {0, 1} with ``ib`` identical to ``_leaf_indices``."""
    kv = np.zeros(pad_to, dtype=np.uint8)
    ib = np.full(pad_to, leaf.n, dtype=np.int32)
    ia = np.full(pad_to, leaf.an, dtype=np.int32)
    ir = np.full(pad_to, leaf.rn, dtype=np.int32)
    off = 0
    for i, dom in enumerate(domains):
        if len(dom) == 0:
            continue
        keys = leaf.entries[i]
        if keys is None or len(keys) == 0:
            off += len(dom)
            continue
        pos = np.searchsorted(keys, dom)
        pos_c = np.minimum(pos, len(keys) - 1)
        hit = keys[pos_c] == dom
        if leaf.slots is None:
            k = np.where(hit, 1, 0).astype(np.uint8)
            loc = (leaf.starts[i] + pos_c).astype(np.int32)
        else:
            k = np.where(hit, leaf.kinds[i][pos_c], 0).astype(np.uint8)
            loc = leaf.slots[i][pos_c].astype(np.int32)
        seg = slice(off, off + len(dom))
        kv[seg] = k
        ib[seg] = np.where(k == 1, loc, leaf.n)
        ia[seg] = np.where(k == 2, loc, leaf.an)
        ir[seg] = np.where(k == 3, loc, leaf.rn)
        off += len(dom)
    return kv, ib, ia, ir


# Staged-gather memo: (shape, leaf uids) -> (domains, bounds, total,
# idxs).  The domain algebra and searchsorted index builds are pure
# functions of the leaf directories, which are themselves cached per
# base generation — recomputing them per query would put ~0.5 ms of
# host numpy on a hot path whose whole launch costs less.  Leaf uids
# change on every rebuild, so stale entries simply stop being
# addressed; the LRU cap bounds memory.
_stage_lock = threading.Lock()
_stage_memo: dict = {}
_STAGE_MEMO_CAP = 256


def _apool_row_bytes(leaf: ContainerLeaf) -> int:
    """Gathered bytes per array-pool lane (values + cardinality)."""
    return int(leaf.apool.shape[-1]) * 2 + 4


def _bump_kind_gathers(idxs: list, total: int) -> None:
    """Per-kind breakout of containers_gathered from the staged gather
    rows (the live lanes only — the pow2 tail is kind 0)."""
    bm = ar = rn = 0
    for ix in idxs:
        if isinstance(ix, tuple):
            kv = ix[0][:total]
            bm += int((kv == 1).sum())
            ar += int((kv == 2).sum())
            rn += int((kv == 3).sum())
        else:
            # legacy all-bitmap staging: every present lane is kind 1
            bm += total
    if bm:
        bump("container.bitmap_gathered", bm)
    if ar:
        bump("container.array_gathered", ar)
    if rn:
        bump("container.run_gathered", rn)


# ------------------------------------------------------------------ planning


class Plan:
    """A fused read staged for compressed execution over ALL its
    shards.  ``counts()`` / ``row_words()`` perform the one-launch
    evaluation; both tick exactly one dispatch, like the dense fused
    path, so launch-count pins hold on either route."""

    def __init__(self, shape: tuple, leaves: list[ContainerLeaf],
                 shards: tuple, cpr: int, n_words: int) -> None:
        self.shape = shape
        self.leaves = leaves
        self.shards = shards
        self.cpr = cpr
        self.n_words = n_words
        self._staged: tuple | None = None

    # ------------------------------------------------------------- staging

    def _stage(self) -> tuple:
        """(domains, bounds, total, idxs) — the per-shard root domains,
        their concatenation boundaries, and the per-leaf gather
        indices.  Memoized across queries on (shape, leaf uids): the
        whole stage is a pure function of the cached directories."""
        if self._staged is not None:
            return self._staged
        mkey = (self.shape, tuple(leaf.uid for leaf in self.leaves))
        with _stage_lock:
            hit = _stage_memo.get(mkey)
            if hit is not None:
                _stage_memo[mkey] = _stage_memo.pop(mkey)  # LRU touch
        if hit is None:
            domains: list[np.ndarray] = []
            for i in range(len(self.shards)):
                keysets = [leaf.entries[i] for leaf in self.leaves]
                domains.append(_domain(self.shape, keysets))
            bounds = np.cumsum([0] + [len(d) for d in domains])
            total = int(bounds[-1])
            # pow2 padding rounded to a mesh-axis multiple so the
            # domain shards evenly under the mesh gather program
            # (parallel/meshexec.py; identical pow2 when no mesh)
            from pilosa_tpu.parallel import meshexec

            pad = meshexec.pad_domain(total) if total else 0
            # any array/run leaf switches the WHOLE query to the
            # kind-dispatched gather protocol (uniform per-lane
            # (kv, ib, ia, ir) tuples); all-bitmap queries keep the
            # exact legacy index arrays
            if any(leaf.has_kinds for leaf in self.leaves):
                idxs = [_leaf_kind_indices(leaf, domains, pad)
                        for leaf in self.leaves]
            else:
                idxs = [_leaf_indices(leaf, domains, pad)
                        for leaf in self.leaves]
            hit = (domains, bounds, total, idxs)
            with _stage_lock:
                _stage_memo[mkey] = hit
                while len(_stage_memo) > _STAGE_MEMO_CAP:
                    _stage_memo.pop(next(iter(_stage_memo)))
        domains, bounds, total, idxs = hit
        n_leaves = len(self.leaves)
        bump("container.containers_gathered", total * n_leaves)
        _bump_kind_gathers(idxs, total)
        # what the dense layout would have streamed vs what the
        # directory walk actually touches — the bandwidth story
        bump("container.containers_skipped",
             n_leaves * (len(self.shards) * self.cpr - total))
        self._staged = hit
        return self._staged

    def _gathered(self, counts: bool, mesh=None) -> Any:
        """ONE launch over the pooled operands; None when the root
        domain is empty everywhere (zero device work).  ``mesh``
        routes the shard_map gather program (domain axis sharded,
        pools replicated — parallel/meshexec.py)."""
        from pilosa_tpu.ops import expr
        from pilosa_tpu.ops import pallas_kernels as pk

        _domains, _bounds, total, idxs = self._stage()
        if total == 0:
            bump("container.empty_domains")
            # the dense path would still have launched once; tick the
            # dispatch hook so launch accounting is route-invariant
            from pilosa_tpu.ops import bitmap as bm

            bm.note_dispatch("fused_gather")
            return None
        pools = [leaf.pool for leaf in self.leaves]
        # engine-observatory coordinates for this launch: the dense
        # stacks the gather replaced (size-class key) and the fraction
        # of possible containers the directory walk actually touches
        # (the sparsity the compressed engine exploits)
        dense_work = len(self.leaves) * len(self.shards) * self.n_words
        sparsity = total / max(1, len(self.shards) * self.cpr)
        if any(isinstance(ix, tuple) for ix in idxs):
            # kind-dispatched protocol: pair-matrix arms for the
            # homogeneous AND pair, else the generic decode-at-gather
            # program.  Always single-device — plan_fused builds
            # legacy all-bitmap leaves while a mesh is active, so a
            # non-None mesh here can only be a toggle race; the
            # single-device program stays bit-exact regardless.
            return self._gathered_kinds(counts, idxs, total,
                                        dense_work, sparsity)
        if (counts and mesh is None
                and self.shape == ("and", ("leaf", 0), ("leaf", 1))
                and pk.on_tpu() and not isinstance(pools[0], np.ndarray)):
            # the north-star pair: the Pallas directory-walk kernel
            # intersects+counts co-present containers in one pass
            # (single-device; the mesh route splits the domain walk
            # across chips through the shard_map gather instead)
            t0 = _perfobs.t0()
            out = pk.gathered_count_and(pools[0], idxs[0],
                                        pools[1], idxs[1])
            _perfobs.sample("gather", out, t0,
                            nbytes=(len(idxs[0]) + len(idxs[1]))
                            * CWORDS * 4,
                            work=dense_work, sparsity=sparsity)
            return out
        with _perfobs.context(sparsity=sparsity, work=dense_work):
            return expr.evaluate_gathered(self.shape, tuple(pools),
                                          tuple(idxs), counts=counts,
                                          mesh=mesh)

    def _gathered_kinds(self, counts: bool, idxs: list, total: int,
                        dense_work: int, sparsity: float) -> Any:
        """The kind-dispatched launch: host directory algebra has
        already resolved every lane's (kind, pool-row) pair, so this
        picks the cheapest ARM for the query — the Roaring pair
        matrix's array∩array (galloping membership) and array∩bitmap
        (gather-test) specializations for the homogeneous counts-root
        AND pair, else the generic decode-at-gather program (gather
        compact rows, decode to dense blocks, fold the tree — still
        ONE launch).  Bit-exact with the dense route by construction:
        every arm computes the same container algebra."""
        from pilosa_tpu.ops import bitmap as bm
        from pilosa_tpu.ops import expr
        from pilosa_tpu.ops import pallas_kernels as pk

        if counts and self.shape == ("and", ("leaf", 0), ("leaf", 1)):
            # an AND domain is the keyset intersection, so every live
            # lane is present in BOTH leaves: the lane kinds alone
            # decide the arm
            kv0 = idxs[0][0][:total]
            kv1 = idxs[1][0][:total]
            l0, l1 = self.leaves[0], self.leaves[1]
            if (kv0 == 2).all() and (kv1 == 2).all():
                bm.note_dispatch("fused_gather")
                t0 = _perfobs.t0()
                out = pk.gathered_count_array_array(
                    l0.apool, l0.acard, idxs[0][2],
                    l1.apool, l1.acard, idxs[1][2])
                _perfobs.sample(
                    "gather_aa", out, t0,
                    nbytes=(len(idxs[0][2]) * _apool_row_bytes(l0)
                            + len(idxs[1][2]) * _apool_row_bytes(l1)),
                    work=dense_work, sparsity=sparsity)
                return out
            pair = None
            if (kv0 == 2).all() and (kv1 == 1).all():
                pair = (l0, idxs[0], l1, idxs[1])
            elif (kv0 == 1).all() and (kv1 == 2).all():
                pair = (l1, idxs[1], l0, idxs[0])
            if pair is not None:
                al, aix, bl, bix = pair
                bm.note_dispatch("fused_gather")
                t0 = _perfobs.t0()
                out = pk.gathered_count_array_bitmap(
                    al.apool, al.acard, aix[2], bl.pool, bix[1])
                _perfobs.sample(
                    "gather_ab", out, t0,
                    nbytes=(len(aix[2]) * _apool_row_bytes(al)
                            + len(bix[1]) * CWORDS * 4),
                    work=dense_work, sparsity=sparsity)
                return out
        leafops = []
        for leaf, ix in zip(self.leaves, idxs):
            _kv, ib, ia, ir = ix
            if leaf.has_kinds:
                leafops.append(("k", leaf.pool, leaf.apool, leaf.acard,
                                leaf.rpool, ib, ia, ir))
            else:
                # legacy all-bitmap leaf inside a kinds query: plain
                # gather (kv is {0, 1} and ib already routes absents
                # at the zero row)
                leafops.append(("b", leaf.pool, ib))
        with _perfobs.context(sparsity=sparsity, work=dense_work):
            return expr.evaluate_gathered_kinds(self.shape,
                                                tuple(leafops),
                                                counts=counts)

    # ----------------------------------------------------------- execution

    def counts(self, mesh=None) -> list[int]:
        """Per-shard popcounts of the tree, aligned with ``shards`` —
        the Count root folded into the same launch."""
        bump("container.queries")
        out = self._gathered(counts=True, mesh=mesh)
        _domains, bounds, total, _idxs = self._staged  # set by _gathered
        if out is None:
            return [0] * len(self.shards)
        cts = np.asarray(out, dtype=np.int64)[:total]
        return [int(cts[bounds[i]:bounds[i + 1]].sum())
                for i in range(len(self.shards))]

    def row_words(self, mesh=None) -> list[tuple[int, np.ndarray]]:
        """Non-empty per-shard result words, scattered back to the
        dense row layout the Row reduce consumes."""
        bump("container.queries")
        out = self._gathered(counts=False, mesh=mesh)
        if out is None:
            return []
        domains, bounds, total, _idxs = self._staged
        res = np.asarray(out)[:total]
        partials: list[tuple[int, np.ndarray]] = []
        for i, s in enumerate(self.shards):
            dom = domains[i]
            if len(dom) == 0:
                continue
            blocks = res[int(bounds[i]):int(bounds[i + 1])]
            if not blocks.any():
                continue
            words = np.zeros(self.n_words, dtype=np.uint32)
            words.reshape(self.cpr, CWORDS)[dom] = blocks
            partials.append((s, words))
        return partials


#: Default ``[vm]`` knobs: the minimum padded domain width a staged VM
#: query rounds up to (keeps the lowered-variant count down for tiny
#: domains and gives empty-domain queries a real — all-zero-row — batch
#: slot, so the ONE-launch accounting never special-cases them), and
#: the per-launch scalar-prefetch budget in int32 directory entries
#: (slots x batch x domain live in SMEM on chip; oversized batches
#: split, oversized single queries decline to the dense engines).
VM_MIN_DOMAIN = 8
VM_MAX_PREFETCH = 1 << 16


class VMStage:
    """One fused Count read staged for the Pallas bitmap VM: the
    (possibly delta-substituted) shape, its compiled op-tape, the
    container leaves in slot order, the per-leaf LOCAL gather rows for
    the concatenated per-shard root domains (each int32[pad], absent
    containers and the pow2 tail pointing at the leaf's own zero row),
    and the live domain total.  parallel/coalescer.py globalizes the
    rows against the bucket megapool at flush."""

    __slots__ = ("shape", "tape", "leaves", "idxs", "total", "pad")

    def __init__(self, shape: tuple, tape: Any, leaves: list,
                 idxs: list, total: int, pad: int) -> None:
        self.shape = shape
        self.tape = tape
        self.leaves = leaves
        self.idxs = idxs
        self.total = total
        self.pad = pad


def stage_vm(idx: Any, call: Any, shards: tuple,
             use_delta: bool = True, max_tape: int | None = None,
             max_leaves: int | None = None,
             min_domain: int = VM_MIN_DOMAIN,
             max_prefetch: int | None = VM_MAX_PREFETCH) -> VMStage | None:
    """Stage one fused Count read for the bitmap VM, or None to route
    the pre-existing engines (dense fused / plain ragged) — the
    all-or-nothing per-query contract of ``plan_fused``, with one
    deliberate difference: a pending ingest delta does NOT decline.
    The overlay stages as two extra compressed leaves under a
    ``dfuse`` node ((base & ~clear) | set, two tape instructions), so
    ingest-warm rows stay on the compressed path instead of falling
    back dense — the delta leaves stage BEFORE the base leaf, which
    makes a concurrent compaction safe (idempotent re-apply, the
    device_delta_stacks discipline)."""
    from pilosa_tpu.ops import tape as _tp

    if not _cfg.enabled or not shards:
        _tp.bump("vm.fallbacks.disabled")
        return None
    leaf_descs: list = []
    shape = _walk(idx, call, leaf_descs)
    if shape is None or not leaf_descs:
        _tp.bump("vm.fallbacks.ineligible_leaf")
        return None
    nodemap: dict = {}
    leaves: list[ContainerLeaf] = []
    for i, (f, row_id) in enumerate(leaf_descs):
        pair = None
        if not use_delta:
            # the ?nodelta=1 contract: compact up front, then a real
            # pure-base read — which the VM is
            f.flush_deltas(shards)
        else:
            pair = f.device_delta_container_leaves(row_id, shards)
        base = f.device_container_leaf(row_id, shards)
        if base.dense_slots():
            bump("container.fallbacks")
            _tp.bump("vm.fallbacks.ineligible_leaf")
            return None
        if base.has_kinds and any(
                k is not None and len(k) and int(k.max()) > 3
                for k in base.kinds):
            # a kind byte this VM has no decode arm for (forward
            # compatibility: directories may carry future kinds)
            _tp.bump("vm.fallbacks.kind_unsupported")
            return None
        bi = len(leaves)
        leaves.append(base)
        if pair is None:
            nodemap[i] = ("leaf", bi)
        else:
            si = len(leaves)
            leaves.append(pair[0])
            ci = len(leaves)
            leaves.append(pair[1])
            nodemap[i] = ("dfuse", ("leaf", bi), ("leaf", si),
                          ("leaf", ci))

    def subst(node: tuple) -> tuple:
        if node[0] == "leaf":
            return nodemap[node[1]]
        return (node[0],) + tuple(subst(c) for c in node[1:])

    vshape = subst(shape)
    if max_leaves is not None and len(leaves) > max_leaves:
        _tp.bump("tape.oversize_fallbacks")
        _tp.bump("vm.fallbacks.oversize")
        return None
    tp = _tp.try_compile(vshape, len(leaves), max_tape)
    if tp is None:
        _tp.bump("vm.fallbacks.oversize")
        return None
    mkey = ("vm", vshape, tuple(leaf.uid for leaf in leaves),
            int(min_domain))
    with _stage_lock:
        hit = _stage_memo.get(mkey)
        if hit is not None:
            _stage_memo[mkey] = _stage_memo.pop(mkey)  # LRU touch
    if hit is None:
        domains: list[np.ndarray] = []
        for i in range(len(shards)):
            keysets = [leaf.entries[i] for leaf in leaves]
            domains.append(_domain(vshape, keysets))
        total = int(sum(len(d) for d in domains))
        pad = max(int(min_domain), _pow2(max(1, total)))
        if any(leaf.has_kinds for leaf in leaves):
            idxs = [_leaf_kind_indices(leaf, domains, pad)
                    for leaf in leaves]
        else:
            idxs = [_leaf_indices(leaf, domains, pad)
                    for leaf in leaves]
        hit = (total, pad, idxs)
        with _stage_lock:
            _stage_memo[mkey] = hit
            while len(_stage_memo) > _STAGE_MEMO_CAP:
                _stage_memo.pop(next(iter(_stage_memo)))
    total, pad, idxs = hit
    if max_prefetch is not None and len(leaves) * pad > max_prefetch:
        # a single query's directory would blow the per-launch scalar
        # budget even unbatched — the dense engines take it.  When the
        # plain pow2 pad would have fit, the configured min-domain
        # floor itself blew the budget — its own reason cell
        if len(leaves) * _pow2(max(1, total)) <= max_prefetch:
            _tp.bump("vm.fallbacks.min_domain")
        else:
            _tp.bump("vm.fallbacks.max_prefetch")
        return None
    from pilosa_tpu.shardwidth import SHARD_WIDTH

    cpr = SHARD_WIDTH // CONTAINER_BITS
    n_leaves = len(leaves)
    bump("container.containers_gathered", total * n_leaves)
    _bump_kind_gathers(idxs, total)
    bump("container.containers_skipped",
         n_leaves * (len(shards) * cpr - total))
    if total == 0:
        # the query still rides the batch (all-zero-row directory,
        # count 0) — ONE launch either way, so the empty-domain case
        # never forks the dispatch accounting like Plan._gathered must
        bump("container.empty_domains")
    return VMStage(vshape, tp, leaves, idxs, total, pad)


# Megapool memo: a VM bucket's distinct leaves concatenate into ONE
# device word pool the kernel gathers from; steady traffic re-flushes
# the same leaf sets, and re-concatenating device pools per flush would
# put an HBM copy on the hot path.  Keyed on the leaf uid tuple — uids
# change on every rebuild, so stale megapools stop being addressed and
# age out of the small LRU.
_mega_lock = threading.Lock()
_megapool_memo: dict = {}
_MEGAPOOL_MEMO_CAP = 8


class MegaPools:
    """A VM bucket's per-kind megapools: the bitmap rows plus the
    compact array/run pools whose DECODED dense rows conceptually
    append after them — one virtual dense pool of ``shape[0]`` rows
    the combined gather index addresses (``[0, Rb)`` bitmap, ``[Rb,
    Rb + Ra)`` array, ``[Rb + Ra, Rb + Ra + Rr)`` run).  The decode
    happens INSIDE the one jitted VM launch
    (ops/pallas_kernels.vm_counts), so resident and transferred bytes
    stay compact.  ``shape``/``ndim`` quack like the plain dense pool
    for the tape's size accounting; ``nbytes`` is the real compact
    total."""

    __slots__ = ("bpool", "apool", "acard", "rpool")

    def __init__(self, bpool: Any, apool: Any, acard: Any,
                 rpool: Any) -> None:
        self.bpool = bpool
        self.apool = apool
        self.acard = acard
        self.rpool = rpool

    @property
    def ndim(self) -> int:
        return 2

    @property
    def shape(self) -> tuple:
        rows = (int(self.bpool.shape[0]) + int(self.apool.shape[0])
                + int(self.rpool.shape[0]))
        return (rows, CWORDS)

    @property
    def nbytes(self) -> int:
        return (int(self.bpool.nbytes) + int(self.apool.nbytes)
                + int(self.acard.nbytes) + int(self.rpool.nbytes))


def megapool(leaves: list) -> tuple:
    """(pool, bases, zero_index) for a set of container leaves: the
    concatenated word pool a VM bucket gathers from, each leaf's row
    offset keyed by uid, and a canonical all-zero row (the first
    leaf's own zero tail).  Device megapools pad their row count to
    pow2 with zero rows so the gather programs keep lowering O(log)
    distinct shapes (the P4 rule); host pools stay tight.

    When any leaf carries array/run containers the pool is a
    ``MegaPools`` bundle and ``bases[uid]`` is the per-kind offset
    triple ``(bb, ab, rb)`` into the bundle's virtual dense row space;
    otherwise the legacy scalar-base dense pool is returned
    byte-identically."""
    order = sorted({leaf.uid: leaf for leaf in leaves}.values(),
                   key=lambda leaf: leaf.uid)
    key = tuple(leaf.uid for leaf in order)
    with _mega_lock:
        hit = _megapool_memo.get(key)
        if hit is not None:
            _megapool_memo[key] = _megapool_memo.pop(key)  # LRU touch
            return hit
    if any(leaf.has_kinds for leaf in order):
        hit = _megapool_kinds(order)
    else:
        hit = _megapool_plain(order)
    with _mega_lock:
        _megapool_memo[key] = hit
        while len(_megapool_memo) > _MEGAPOOL_MEMO_CAP:
            _megapool_memo.pop(next(iter(_megapool_memo)))
    return hit


def _megapool_plain(order: list) -> tuple:
    bases: dict = {}
    off = 0
    for leaf in order:
        bases[leaf.uid] = off
        off += int(leaf.pool.shape[0])
    zero_index = bases[order[0].uid] + order[0].n
    host = all(isinstance(leaf.pool, np.ndarray) for leaf in order)
    if len(order) == 1:
        pool = order[0].pool
    elif host:
        pool = np.concatenate([leaf.pool for leaf in order], axis=0)
    else:
        import jax.numpy as jnp

        parts = [jnp.asarray(leaf.pool) for leaf in order]
        rows = _pow2(off)
        if rows > off:
            parts.append(jnp.zeros((rows - off, CWORDS),
                                   dtype=jnp.uint32))
        pool = jnp.concatenate(parts, axis=0)
    return (pool, bases, zero_index)


def _megapool_kinds(order: list) -> tuple:
    """Concatenate per-kind pools across leaves into one MegaPools
    bundle.  Column widths re-pad to the cross-leaf pow2 maximum and
    device row counts pad to pow2 per kind pool (array tails with the
    sorted-safe 0xFFFF pad, run tails with the invalid (1, 0) pair —
    both decode to nothing); a leaf without a kind contributes zero
    rows to that pool."""
    from pilosa_tpu.ops import kindpools as kp

    host = all(isinstance(leaf.pool, np.ndarray) for leaf in order)
    acap = max([int(leaf.apool.shape[-1]) for leaf in order
                if leaf.apool is not None] or [1])
    rcap = max([int(leaf.rpool.shape[-1]) for leaf in order
                if leaf.rpool is not None] or [2])
    boffs: dict = {}
    aoffs: dict = {}
    roffs: dict = {}
    boff = aoff = roff = 0
    bparts: list = []
    aparts: list = []
    cparts: list = []
    rparts: list = []
    for leaf in order:
        boffs[leaf.uid] = boff
        aoffs[leaf.uid] = aoff
        roffs[leaf.uid] = roff
        boff += int(leaf.pool.shape[0])
        bparts.append(leaf.pool)
        if leaf.apool is not None and int(leaf.apool.shape[0]):
            rows = int(leaf.apool.shape[0])
            aparts.append((leaf.apool, rows, int(leaf.apool.shape[-1])))
            cparts.append(leaf.acard)
            aoff += rows
        if leaf.rpool is not None and int(leaf.rpool.shape[0]):
            rows = int(leaf.rpool.shape[0])
            rparts.append((leaf.rpool, rows, int(leaf.rpool.shape[-1])))
            roff += rows

    def _apad(rows: int, cols: int) -> np.ndarray:
        return np.full((rows, cols), kp.ARRAY_PAD, dtype=np.uint16)

    def _rpad(rows: int, cols: int) -> np.ndarray:
        out = np.zeros((rows, cols), dtype=np.uint16)
        out[:, 0::2] = 1  # (1, 0): the canonical invalid pair
        return out

    if host:
        xp = np
    else:
        import jax.numpy as jnp

        xp = jnp
    # row counts: pow2 per kind pool on device (the P4 O(log)-shapes
    # rule for the decode program); tight on host
    rb = boff if host else _pow2(max(1, boff))
    ra = max(1, aoff) if host else _pow2(max(1, aoff))
    rr = max(1, roff) if host else _pow2(max(1, roff))
    bits = [xp.asarray(p) for p in bparts]
    if rb > boff:
        bits.append(xp.zeros((rb - boff, CWORDS), dtype=xp.uint32))
    bpool = bits[0] if len(bits) == 1 else xp.concatenate(bits, axis=0)
    avs: list = []
    for p, rows, cols in aparts:
        p = xp.asarray(p)
        if cols < acap:
            p = xp.concatenate([p, xp.asarray(_apad(rows, acap - cols))],
                               axis=1)
        avs.append(p)
    if ra > aoff:
        avs.append(xp.asarray(_apad(ra - aoff, acap)))
    apool = avs[0] if len(avs) == 1 else xp.concatenate(avs, axis=0)
    cvs = [xp.asarray(c) for c in cparts]
    if ra > aoff:
        cvs.append(xp.zeros(ra - aoff, dtype=xp.int32))
    acard = cvs[0] if len(cvs) == 1 else xp.concatenate(cvs, axis=0)
    rvs: list = []
    for p, rows, cols in rparts:
        p = xp.asarray(p)
        if cols < rcap:
            p = xp.concatenate([p, xp.asarray(_rpad(rows, rcap - cols))],
                               axis=1)
        rvs.append(p)
    if rr > roff:
        rvs.append(xp.asarray(_rpad(rr - roff, rcap)))
    rpool = rvs[0] if len(rvs) == 1 else xp.concatenate(rvs, axis=0)
    # bases address the VIRTUAL dense row space: bitmap rows first,
    # then the decoded array rows, then the decoded run rows
    bases = {leaf.uid: (boffs[leaf.uid], rb + aoffs[leaf.uid],
                        rb + ra + roffs[leaf.uid])
             for leaf in order}
    zero_index = boffs[order[0].uid] + order[0].n
    return (MegaPools(bpool, apool, acard, rpool), bases, zero_index)


def _walk(idx: Any, call: Any, leaves: list) -> tuple | None:
    """Shape + (field, row) leaf descriptors for a tree whose every
    leaf is a plain standard-view row — the container-eligible grammar.
    Returns None for BSI condition rows, time ranges, Shift (bits cross
    container boundaries), and anything unknown."""
    name = call.name
    if name == "Row":
        if call.condition_arg() is not None:
            return None
        if "from" in call.args or "to" in call.args:
            return None
        try:
            fname = call.field_arg()
        except ValueError:
            return None
        row_id = call.args.get(fname)
        if not isinstance(row_id, int) or isinstance(row_id, bool):
            return None
        f = idx.field(fname)
        if f is None:
            return None
        o = f.options
        if o.type == "int" or (o.type == "time" and o.no_standard_view):
            return None
        leaves.append((f, row_id))
        return ("leaf", len(leaves) - 1)
    if name in ("Union", "Intersect", "Difference", "Xor"):
        op = {"Union": "or", "Intersect": "and",
              "Difference": "andnot", "Xor": "xor"}[name]
        kids = []
        for c in call.children:
            k = _walk(idx, c, leaves)
            if k is None:
                return None
            kids.append(k)
        if not kids:
            return None
        return (op, *kids)
    if name == "Not":
        if len(call.children) != 1:
            return None
        ef = idx.existence_field()
        if ef is None:
            return None
        leaves.append((ef, 0))
        exist = ("leaf", len(leaves) - 1)
        child = _walk(idx, call.children[0], leaves)
        if child is None:
            return None
        return ("not", exist, child)
    return None


def plan_fused(executor: Any, idx: Any, call: Any, shards: tuple,
               opt: Any, counts: bool = True) -> Plan | None:
    """Stage a fused read for compressed execution, or None to route
    the exact pre-existing dense path.  All-or-nothing per query: every
    leaf row must be compression-eligible (under the fill-ratio
    threshold, no pending delta overlay) in EVERY shard — so the read
    costs one launch on either route and partial results never mix.

    ``counts`` is the root kind: a bare-leaf Row tree is declined when
    ``counts=False`` because the dense path answers it as a ZERO-launch
    passthrough of the resident stack (expr.evaluate's leaf case) —
    gathering would both tick a launch the dense route doesn't (the
    route-invariant accounting would break) and redo work the stack
    cache already holds."""
    from pilosa_tpu.models.view import VIEW_STANDARD
    from pilosa_tpu.ops import bitmap as bm
    from pilosa_tpu.shardwidth import SHARD_WIDTH

    if not _cfg.enabled or not shards:
        return None
    if opt is not None and not getattr(opt, "containers", True):
        return None
    leaf_descs: list = []
    shape = _walk(idx, call, leaf_descs)
    if shape is None or not leaf_descs:
        return None
    if not counts and shape[0] == "leaf":
        return None
    use_delta = opt is None or opt.delta
    for f, row_id in leaf_descs:
        view = f.view(VIEW_STANDARD)
        if view is None:
            continue
        if not use_delta:
            # the ?nodelta=1 contract: compact up front, then a real
            # pure-base read — which the compressed path is
            f.flush_deltas(shards)
            continue
        for s in shards:
            fr = view.fragment(s)
            if fr is not None and fr._delta_row_seq(row_id):
                # pending overlay on a queried row: the dense path
                # fuses it (expr "dfuse"); compressed pools hold base
                # content only
                bump("container.fallbacks")
                return None
    leaves = []
    for f, row_id in leaf_descs:
        leaf = f.device_container_leaf(row_id, shards)
        if leaf.dense_slots():
            bump("container.fallbacks")
            return None
        leaves.append(leaf)
    return Plan(shape, leaves, shards, SHARD_WIDTH // CONTAINER_BITS,
                bm.n_words(SHARD_WIDTH))
