"""Native host popcount kernels — the CPU half of the execution engine.

When the framework runs without an accelerator (relay down, CI, laptop)
the fused query pipeline keeps operand stacks host-resident as numpy
arrays and counts them here: single-pass AND+popcount in C++
(native/bitcount.cpp, compiled -march=native → AVX-512 VPOPCNTDQ on
capable hosts), no intermediates.  The role the reference's per-container
fast paths play on CPU (roaring/roaring.go:570 intersectionCount*).

Every function falls back to vectorized numpy (np.bitwise_count) when
the native library is unavailable, so behavior is identical everywhere —
only speed differs.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from pilosa_tpu.native_loader import NativeLib

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")


def _isa_tag() -> str:
    """Short hash of the host's CPU feature flags, embedded in the .so
    name.  -march=native binaries are host-specific; a checkout reused
    on a different CPU (NFS, baked image) must rebuild rather than
    SIGILL on the first AVX-512 instruction — dlopen alone can't catch
    an ISA mismatch."""
    import hashlib

    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    return hashlib.sha1(line.encode()).hexdigest()[:8]
    except OSError:
        pass
    import platform

    return hashlib.sha1(platform.processor().encode()).hexdigest()[:8]


def _setup(lib) -> None:
    LL, VP, IP = ctypes.c_longlong, ctypes.c_void_p, ctypes.c_void_p
    lib.pt_set_threads.restype = None
    lib.pt_set_threads.argtypes = [ctypes.c_int]
    lib.pt_effective_threads.restype = ctypes.c_int
    lib.pt_effective_threads.argtypes = [LL]
    lib.pt_count.restype = LL
    lib.pt_count.argtypes = [VP, LL]
    lib.pt_count_and.restype = LL
    lib.pt_count_and.argtypes = [VP, VP, LL]
    lib.pt_row_counts.restype = None
    lib.pt_row_counts.argtypes = [VP, LL, LL, IP]
    lib.pt_row_counts_and.restype = None
    lib.pt_row_counts_and.argtypes = [VP, VP, LL, LL, IP]
    lib.pt_row_counts_masked.restype = None
    lib.pt_row_counts_masked.argtypes = [VP, VP, LL, LL, IP]
    lib.pt_row_counts_gathered.restype = None
    lib.pt_row_counts_gathered.argtypes = [VP, VP, IP, LL, LL, IP]
    lib.pt_masked_matrix_counts.restype = None
    lib.pt_masked_matrix_counts.argtypes = [VP, VP, LL, LL, LL, IP]
    lib.pt_merge_positions.restype = LL
    lib.pt_merge_positions.argtypes = [VP, VP, VP, LL, VP,
                                       ctypes.c_uint64, ctypes.c_int]
    # 0 (default) = auto: hardware_concurrency capped at >=4 MiB of
    # operand per thread; ctypes releases the GIL for the call, so the
    # kernel threads own the cores (the reference's per-shard worker
    # pool, executor.go:2561, collapsed into the kernel).
    lib.pt_set_threads(int(os.environ.get("PILOSA_TPU_HOST_THREADS", "0")))


_NATIVE = NativeLib(
    src=os.path.join(_NATIVE_DIR, "bitcount.cpp"),
    so=os.path.join(_NATIVE_DIR, "build",
                    f"libpilosa_bitcount.{_isa_tag()}.so"),
    setup=_setup,
    # -march=native: built lazily on the host that runs it; the ISA tag
    # in the filename forces a rebuild on any other CPU
    extra_flags=("-march=native", "-funroll-loops", "-pthread"),
)


def set_threads(n: int) -> bool:
    """Override the kernel thread count (0 = auto).  Returns False when
    the native library is unavailable (numpy fallback is serial)."""
    lib = _NATIVE.load()
    if lib is None:
        return False
    lib.pt_set_threads(int(n))
    return True


def effective_threads(words: int) -> int:
    """Thread count a kernel touching `words` uint32s would use under
    the current setting (test/diagnostic hook; 1 when the native
    library is unavailable — the numpy fallback is serial)."""
    lib = _NATIVE.load()
    if lib is None:
        return 1
    return int(lib.pt_effective_threads(int(words)))


def native_available() -> bool:
    return _NATIVE.available()


def _c(a: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(a)


def count(a: np.ndarray) -> int:
    """Total set bits of a uint32 array (any shape)."""
    lib = _NATIVE.load()
    if lib is None:
        return int(np.bitwise_count(a).sum(dtype=np.uint64))
    a = _c(a)
    return int(lib.pt_count(a.ctypes.data, a.size))


def count_and(a: np.ndarray, b: np.ndarray) -> int:
    """|a & b| without materializing the intersection."""
    if a.shape != b.shape:
        raise ValueError(f"shape mismatch: {a.shape} vs {b.shape}")
    lib = _NATIVE.load()
    if lib is None:
        return int(np.bitwise_count(a & b).sum(dtype=np.uint64))
    a, b = _c(a), _c(b)
    return int(lib.pt_count_and(a.ctypes.data, b.ctypes.data, a.size))


def row_counts(mat: np.ndarray) -> np.ndarray:
    """int32[rows] popcounts of a [rows, words] matrix (stacks flatten
    leading dims: a [shards, rows, words] input counts per (shard,row))."""
    lead = mat.shape[:-1]
    rows = int(np.prod(lead)) if lead else 1
    words = mat.shape[-1]
    lib = _NATIVE.load()
    if lib is None:
        return np.bitwise_count(mat).sum(axis=-1).astype(np.int32)
    mat = _c(mat)
    out = np.empty(lead, dtype=np.int32)
    lib.pt_row_counts(mat.ctypes.data, rows, words, out.ctypes.data)
    return out


def row_counts_and(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """int32[rows] of |a[r] & b[r]| — no materialized intersection."""
    if a.shape != b.shape:
        raise ValueError(f"shape mismatch: {a.shape} vs {b.shape}")
    lib = _NATIVE.load()
    if lib is None:
        return np.bitwise_count(a & b).sum(axis=-1).astype(np.int32)
    a, b = _c(a), _c(b)
    rows, words = a.shape
    out = np.empty(rows, dtype=np.int32)
    lib.pt_row_counts_and(a.ctypes.data, b.ctypes.data,
                          rows, words, out.ctypes.data)
    return out


def row_counts_masked(mat: np.ndarray, filt: np.ndarray) -> np.ndarray:
    """int32[rows] of |mat[r] & filt|."""
    if mat.shape[-1] != filt.shape[-1]:
        raise ValueError(f"word-count mismatch: {mat.shape} vs {filt.shape}")
    lib = _NATIVE.load()
    if lib is None:
        return np.bitwise_count(mat & filt[None, :]).sum(axis=-1).astype(np.int32)
    mat, filt = _c(mat), _c(filt)
    rows, words = mat.shape
    out = np.empty(rows, dtype=np.int32)
    lib.pt_row_counts_masked(mat.ctypes.data, filt.ctypes.data,
                             rows, words, out.ctypes.data)
    return out


def row_counts_gathered(mat: np.ndarray, filt_stack: np.ndarray,
                        shard_pos: np.ndarray) -> np.ndarray:
    """int32[rows] of |mat[r] & filt_stack[shard_pos[r]]|."""
    pos = np.ascontiguousarray(shard_pos, dtype=np.int32)
    if mat.shape[-1] != filt_stack.shape[-1]:
        raise ValueError(
            f"word-count mismatch: {mat.shape} vs {filt_stack.shape}")
    if pos.size and (pos.min() < 0 or pos.max() >= len(filt_stack)):
        raise IndexError("shard_pos out of range")
    lib = _NATIVE.load()
    if lib is None:
        filt = filt_stack[pos]
        return np.bitwise_count(mat & filt).sum(axis=-1).astype(np.int32)
    mat, filt_stack = _c(mat), _c(filt_stack)
    rows, words = mat.shape
    out = np.empty(rows, dtype=np.int32)
    lib.pt_row_counts_gathered(mat.ctypes.data, filt_stack.ctypes.data,
                               pos.ctypes.data, rows, words, out.ctypes.data)
    return out


def masked_matrix_counts(mat: np.ndarray, masks: np.ndarray) -> np.ndarray:
    """int32[groups, rows] of |mat[r] & masks[g]|."""
    if mat.shape[-1] != masks.shape[-1]:
        raise ValueError(f"word-count mismatch: {mat.shape} vs {masks.shape}")
    lib = _NATIVE.load()
    if lib is None:
        # per-mask loop bounds memory at O(rows*words), like the native
        # kernel and the jit lax.map — a broadcast would materialize a
        # [groups, rows, words] intermediate
        return np.stack([
            np.bitwise_count(mat & m).sum(axis=-1).astype(np.int32)
            for m in masks]) if len(masks) else np.empty(
                (0, mat.shape[0]), dtype=np.int32)
    mat, masks = _c(mat), _c(masks)
    rows, words = mat.shape
    groups = masks.shape[0]
    out = np.empty((groups, rows), dtype=np.int32)
    lib.pt_masked_matrix_counts(mat.ctypes.data, masks.ctypes.data,
                                groups, rows, words, out.ctypes.data)
    return out


def merge_positions(row_arrays: list, seg_start: np.ndarray,
                    seg_end: np.ndarray, pos: np.ndarray,
                    width_mask: int, clear: bool) -> int | None:
    """Sparse position-space merge into per-row bitmap buffers: for row
    r, OR (or ANDN when clear) the sorted absolute positions
    pos[seg_start[r]:seg_end[r]] (in-row offset = pos & width_mask)
    into row_arrays[r], in place.  Returns flipped-bit count, or None
    when the native library is unavailable (caller runs its numpy
    fallback).  One C call replaces the whole numpy aggregation
    pipeline — the import-roaring sparse hot path
    (fragment._merge_positions)."""
    lib = _NATIVE.load()
    if lib is None:
        return None
    # __array_interface__ is ~10x cheaper per array than .ctypes.data
    ptrs = np.array([a.__array_interface__["data"][0]
                     for a in row_arrays], dtype=np.uint64)
    seg_start = np.ascontiguousarray(seg_start, dtype=np.int64)
    seg_end = np.ascontiguousarray(seg_end, dtype=np.int64)
    pos = np.ascontiguousarray(pos, dtype=np.uint64)
    return int(lib.pt_merge_positions(
        ptrs.ctypes.data, seg_start.ctypes.data, seg_end.ctypes.data,
        len(row_arrays), pos.ctypes.data, width_mask,
        1 if clear else 0))
