"""Pallas TPU kernels for the non-trivially-XLA hot ops.

SURVEY.md §7 names three ops worth hand-scheduling below XLA: the TopN
rank scan (segmented popcount), the BSI range compare (bit-sliced ripple
compare), and the fused intersection count.  XLA already fuses the
elementwise chains well; what Pallas buys is (a) a single pass over HBM
for AND+popcount+row-reduce with explicit VMEM blocking, and (b) keeping
the D-plane ripple compare's intermediates entirely in VMEM.

Every kernel has a jnp reference implementation in pilosa_tpu.ops used
as the differential oracle (the roaring/naive.go pattern) and as the
dispatch fallback off-TPU or for small inputs where kernel launch
overhead dominates.  `interpret=True` runs the same kernels on CPU for
tests.

Reference analogs: roaring.IntersectionCount (roaring/roaring.go:570),
fragment.top scan (fragment.go:1570), BSI rangeLT/GT
(fragment.go:1111-1537).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Row-block of 128 keeps the int32 output a native (8,128)-tileable
# [1, 128] block; 2048 uint32 words = 8KB lanes per row block.
ROW_BLOCK = 128
WORD_BLOCK = 2048


def on_tpu() -> bool:
    # the axon-relayed chip registers as platform "tpu" in practice,
    # but accept the plugin's own name too — a silent False here would
    # quietly reroute every Pallas call site to the XLA fallback
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


def pallas_enabled() -> bool:
    """Operator gate for the on-TPU Pallas routing:
    PILOSA_TPU_PALLAS=0/off disables it (the escape hatch for a Mosaic
    regression in a new toolchain); any other value (or unset) leaves
    it enabled.  The knob only matters ON a TPU — off-chip the XLA
    path always runs, because Mosaic kernels need a TPU (tests reach
    them via interpret=True).  benchmarks/validate_tpu.py records
    per-kernel pallas-vs-XLA chip timings so the default tracks
    evidence, not hope."""
    import os

    v = os.environ.get("PILOSA_TPU_PALLAS", "auto").lower()
    return v not in ("0", "off", "false", "no")


@functools.cache
def _kernel_winners() -> dict:
    """Per-kernel chip A/B winners ('pallas' | 'xla') from the
    committed validation artifact (PALLAS_TPU_VALIDATION.json, written
    by benchmarks/validate_tpu.py with per-kernel timings during a
    relay window).  Empty when the artifact is absent, untimed, or was
    not captured on a real chip — routing then defaults to Pallas on
    TPU as before."""
    import json
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "PALLAS_TPU_VALIDATION.json")
    try:
        with open(path) as f:
            doc = json.load(f)
        if doc.get("platform") not in ("tpu", "axon"):
            return {}
        return {name: k["perf"]["winner"]
                for name, k in doc.get("kernels", {}).items()
                if isinstance(k, dict) and k.get("ok")
                and isinstance(k.get("perf"), dict)
                and k["perf"].get("winner") in ("pallas", "xla")
                # timings the validator itself flagged as beating the
                # HBM roof (memoized dispatches) must not decide
                # routing — treat them as no evidence
                and not k["perf"].get("suspect_memoized_dispatch")}
    except Exception:  # noqa: BLE001 — unreadable evidence = no evidence
        return {}


def _use_pallas(interpret: bool, elems: int, floor: int = 1 << 16,
                kernel: str | None = None) -> bool:
    """The single routing gate every dispatcher shares: interpret mode
    always exercises the kernel (how CPU tests reach it); below
    ``floor`` elements launch overhead dominates so XLA always runs;
    otherwise Pallas runs on a TPU with the operator knob enabled —
    UNLESS the committed chip validation timed this kernel slower than
    XLA's fusion (per-kernel evidence beats the blanket default;
    PILOSA_TPU_PALLAS=force overrides the evidence for A/B work)."""
    if interpret:
        return True
    if elems < floor:
        return False
    if not (on_tpu() and pallas_enabled()):
        return False
    import os

    if os.environ.get("PILOSA_TPU_PALLAS", "").lower() == "force":
        return True
    return _kernel_winners().get(kernel) != "xla"


def _pad_to(x: jnp.ndarray, axis: int, multiple: int):
    size = x.shape[axis]
    rem = (-size) % multiple
    if rem == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, rem)
    return jnp.pad(x, pad)


# ---------------------------------------------------------------------------
# masked row counts: out[r] = sum(popcount(mat[r] & filt)) — the TopN scan
# ---------------------------------------------------------------------------


def _row_counts_kernel(mat_ref, filt_ref, out_ref):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    blk = lax.population_count(mat_ref[:] & filt_ref[0, :])
    # counts broadcast across the 128 lanes — the lane dim only exists
    # to satisfy TPU tiling; the wrapper reads lane 0
    out_ref[:] += jnp.sum(blk, axis=1, dtype=jnp.int32)[:, None]


@functools.partial(jax.jit, static_argnames=("interpret",))
def _row_counts_masked_pallas(mat, filt, interpret: bool = False):
    R, W = mat.shape
    mat = _pad_to(_pad_to(mat, 1, WORD_BLOCK), 0, ROW_BLOCK)
    filt = _pad_to(filt.reshape(1, -1), 1, WORD_BLOCK)
    Rp, Wp = mat.shape
    grid = (Rp // ROW_BLOCK, Wp // WORD_BLOCK)
    out = pl.pallas_call(
        _row_counts_kernel,
        out_shape=jax.ShapeDtypeStruct((Rp, 128), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ROW_BLOCK, WORD_BLOCK), lambda i, j: (i, j)),
            pl.BlockSpec((1, WORD_BLOCK), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((ROW_BLOCK, 128), lambda i, j: (i, 0)),
        interpret=interpret,
    )(mat, filt)
    return out[:R, 0]


def row_counts_masked(mat, filt, interpret: bool = False):
    """Dispatching wrapper: Pallas on TPU for big matrices, fused jnp
    otherwise (the two produce identical int32 counts)."""
    from pilosa_tpu.ops import bitmap as bm

    R, W = mat.shape
    if _use_pallas(interpret, R * W, kernel="row_counts_masked"):
        return _row_counts_masked_pallas(mat, jnp.asarray(filt),
                                         interpret=interpret)
    return bm.row_counts_masked(mat, filt)


# ---------------------------------------------------------------------------
# fused intersection count: |a & b| — the north-star op
# ---------------------------------------------------------------------------


def _count_and_kernel(a_ref, b_ref, out_ref):
    j = pl.program_id(0)

    @pl.when(j == 0)
    def _init():
        out_ref[0, 0] = 0

    out_ref[0, 0] += jnp.sum(
        lax.population_count(a_ref[:] & b_ref[:]), dtype=jnp.int32)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _count_and_pallas(a, b, interpret: bool = False):
    a = _pad_to(a.reshape(1, -1), 1, WORD_BLOCK)
    b = _pad_to(b.reshape(1, -1), 1, WORD_BLOCK)
    Wp = a.shape[1]
    out = pl.pallas_call(
        _count_and_kernel,
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.int32),
        grid=(Wp // WORD_BLOCK,),
        in_specs=[
            pl.BlockSpec((1, WORD_BLOCK), lambda j: (0, j)),
            pl.BlockSpec((1, WORD_BLOCK), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1), lambda j: (0, 0), memory_space=pltpu.SMEM),
        interpret=interpret,
    )(a, b)
    return out[0, 0]


def count_and(a, b, interpret: bool = False):
    """|a & b| with Pallas on TPU (single pass; no intermediate), jnp
    fusion elsewhere (roaring.IntersectionCount, roaring/roaring.go:570)."""
    from pilosa_tpu.ops import bitmap as bm

    if _use_pallas(interpret, a.size, kernel="count_and"):
        return _count_and_pallas(jnp.asarray(a), jnp.asarray(b),
                                 interpret=interpret)
    return bm.popcount_and(a, b)


# ---------------------------------------------------------------------------
# compressed-container intersection count: the directory walk on TPU.
# Scalar-prefetched gather indices drive the BlockSpec index maps, so the
# DMA engine fetches exactly the directory-matched container blocks from
# the two word pools — absent containers (index = the pool's zero row)
# cost one zero block, and the dense layout's zero words never stream
# (ops/containers.py; roaring.IntersectionCount's co-present-container
# walk, roaring/roaring.go:570, as hardware-prefetched gathers).
# ---------------------------------------------------------------------------

CONTAINER_WORDS = 2048  # uint32 words per 2^16-bit container


def _gathered_count_and_kernel(ai_ref, bi_ref, a_ref, b_ref, out_ref):
    del ai_ref, bi_ref  # consumed by the BlockSpec index maps
    out_ref[0, 0] = jnp.sum(
        lax.population_count(a_ref[:] & b_ref[:]), dtype=jnp.int32)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _gathered_count_and_pallas(a_pool, ai, b_pool, bi,
                               interpret: bool = False):
    P = ai.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(P,),
        in_specs=[
            pl.BlockSpec((1, CONTAINER_WORDS),
                         lambda p, ai, bi: (ai[p], 0)),
            pl.BlockSpec((1, CONTAINER_WORDS),
                         lambda p, ai, bi: (bi[p], 0)),
        ],
        out_specs=pl.BlockSpec((1, 1), lambda p, ai, bi: (p, 0),
                               memory_space=pltpu.SMEM),
    )
    out = pl.pallas_call(
        _gathered_count_and_kernel,
        out_shape=jax.ShapeDtypeStruct((P, 1), jnp.int32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(ai, bi, a_pool, b_pool)
    return out[:, 0]


def gathered_count_and(a_pool, ai, b_pool, bi, interpret: bool = False):
    """Per-pair |a_pool[ai[p]] & b_pool[bi[p]]| -> int32[P]: Pallas
    directory-walk on TPU, the fused jnp gather kernel elsewhere
    (bm.gathered_pair_counts) — identical counts.  Exactly one
    dispatch tick on either route, like every bm op."""
    from pilosa_tpu.ops import bitmap as bm

    ai = jnp.asarray(ai, dtype=jnp.int32)
    bi = jnp.asarray(bi, dtype=jnp.int32)
    if (a_pool.shape[-1] == CONTAINER_WORDS
            and _use_pallas(interpret, ai.shape[0] * CONTAINER_WORDS,
                            kernel="gathered_count_and")):
        bm.note_dispatch("gathered_count_and")
        return _gathered_count_and_pallas(jnp.asarray(a_pool), ai,
                                          jnp.asarray(b_pool), bi,
                                          interpret=interpret)
    return bm.gathered_pair_counts(a_pool, ai, b_pool, bi)


# ---------------------------------------------------------------------------
# kind-specialized pair counts (roaring pair-matrix arms, ops/kindpools.py
# layouts).  array∩array runs a vectorized binary-search membership test
# (the galloping/binary-search hybrid of roaring's array-array intersect,
# roaring/arraycontainer.go) over the compact uint16 pools; array∩bitmap
# gather-tests each value's word/bit.  Both touch ONLY compact rows —
# no dense 2048-word block exists anywhere on these arms — and both have
# numpy twins that are bit-exact by construction (same integer algebra).
# The caller (containers.Plan._gathered_kinds) owns the dispatch tick.
# ---------------------------------------------------------------------------


def _count_aa_one(v0, c0, v1, c1):
    import jax.numpy as jnp  # shadows module alias inside vmap trace

    pos = jnp.searchsorted(v1, v0)
    probe = jnp.take(v1, jnp.minimum(pos, v1.shape[0] - 1))
    # pos < c1 rejects pad hits: padding is 0xFFFF, so a REAL 65535 in
    # v1 sits at pos c1-1 and still passes
    hit = (pos < c1) & (probe == v0)
    valid = jnp.arange(v0.shape[0], dtype=jnp.int32) < c0
    return jnp.sum((hit & valid).astype(jnp.int32), dtype=jnp.int32)


@jax.jit
def _count_aa_jnp(apool0, acard0, ia0, apool1, acard1, ia1):
    v0 = jnp.take(apool0, ia0, axis=0, mode="clip")
    c0 = jnp.take(acard0, ia0, mode="clip")
    v1 = jnp.take(apool1, ia1, axis=0, mode="clip")
    c1 = jnp.take(acard1, ia1, mode="clip")
    return jax.vmap(_count_aa_one)(v0, c0, v1, c1)


def _count_aa_np(apool0, acard0, ia0, apool1, acard1, ia1):
    # sort-and-count-duplicates, vectorized over all pairs: each side's
    # values are unique within a row, so after sorting the two rows
    # together every intersection element appears as exactly one
    # adjacent equal pair.  ~4x faster than per-element binary search
    # on host (row-local sorts are cache-resident; searchsorted pays a
    # cache miss per probe).  Pad slots get side- AND slot-distinct
    # sentinels above the uint16 range so they never pair up
    ia0 = np.asarray(ia0)
    ia1 = np.asarray(ia1)
    v0 = apool0[ia0].astype(np.int32)
    v1 = apool1[ia1].astype(np.int32)
    c0 = acard0[ia0].astype(np.int32)[:, None]
    c1 = acard1[ia1].astype(np.int32)[:, None]
    slot0 = np.arange(v0.shape[1], dtype=np.int32)[None, :]
    slot1 = np.arange(v1.shape[1], dtype=np.int32)[None, :]
    v0 = np.where(slot0 < c0, v0, 0x10000 + slot0)
    v1 = np.where(slot1 < c1, v1, 0x20000 + slot1)
    m = np.sort(np.concatenate([v0, v1], axis=1), axis=1)
    return (m[:, 1:] == m[:, :-1]).sum(axis=1, dtype=np.int32)


def gathered_count_array_array(apool0, acard0, ia0, apool1, acard1, ia1):
    """Per-pair |A0[ia0[p]] ∩ A1[ia1[p]]| -> int32[P] over two array
    pools: binary-search membership of the smaller-capacity side's
    values in the other's sorted row.  Pad lanes point at the pools'
    zero rows (card 0) and count 0."""
    if isinstance(apool0, np.ndarray) and isinstance(apool1, np.ndarray):
        return _count_aa_np(apool0, acard0, ia0, apool1, acard1, ia1)
    return _count_aa_jnp(
        jnp.asarray(apool0), jnp.asarray(acard0),
        jnp.asarray(ia0, dtype=jnp.int32),
        jnp.asarray(apool1), jnp.asarray(acard1),
        jnp.asarray(ia1, dtype=jnp.int32))


def _count_ab_one(v, c, brow):
    import jax.numpy as jnp

    word = jnp.take(brow, (v >> 5).astype(jnp.int32), mode="clip")
    bit = (word >> (v & 31).astype(jnp.uint32)) & jnp.uint32(1)
    valid = jnp.arange(v.shape[0], dtype=jnp.int32) < c
    return jnp.sum(jnp.where(valid, bit, 0).astype(jnp.int32),
                   dtype=jnp.int32)


@jax.jit
def _count_ab_jnp(apool, acard, ia, bpool, ib):
    v = jnp.take(apool, ia, axis=0, mode="clip")
    c = jnp.take(acard, ia, mode="clip")
    b = jnp.take(bpool, ib, axis=0, mode="clip")
    return jax.vmap(_count_ab_one)(v, c, b)


def _count_ab_np(apool, acard, ia, bpool, ib):
    # vectorized over all pairs (the aa twin's discipline): one fancy
    # word gather per batch; pad values (0xFFFF -> word 2047) stay in
    # range and the validity mask zeroes them
    ia = np.asarray(ia)
    ib = np.asarray(ib)
    v = apool[ia].astype(np.int64)
    c = acard[ia].astype(np.int64)[:, None]
    b = bpool[ib]
    rows = np.arange(v.shape[0], dtype=np.int64)[:, None]
    bits = (b[rows, v >> 5] >> (v & 31).astype(np.uint32)) & 1
    valid = np.arange(v.shape[1], dtype=np.int64)[None, :] < c
    return np.where(valid, bits, 0).sum(axis=1).astype(np.int32)


def gathered_count_array_bitmap(apool, acard, ia, bpool, ib):
    """Per-pair |A[ia[p]] ∩ B[ib[p]]| -> int32[P], array values
    gather-tested against the bitmap row's words (roaring's
    array-bitmap intersect).  Only the array side's compact rows and
    the bitmap rows the directory matched are touched."""
    if isinstance(apool, np.ndarray) and isinstance(bpool, np.ndarray):
        return _count_ab_np(apool, acard, ia, bpool, ib)
    return _count_ab_jnp(
        jnp.asarray(apool), jnp.asarray(acard),
        jnp.asarray(ia, dtype=jnp.int32),
        jnp.asarray(bpool), jnp.asarray(ib, dtype=jnp.int32))


# ---------------------------------------------------------------------------
# bitmap VM: ONE scalar-prefetch kernel for a megabatch of ragged op-tapes
# over compressed container pools.  Each grid step (q, d) interprets query
# q's flat register program (ops/tape.py grammar: AND/OR/XOR/ANDNOT/COPY
# over leaf slots + instruction outputs) on domain slot d's container
# blocks, which the BlockSpec index maps gather straight from the pooled
# word storage via the host-computed directory (ops/containers.py) — the
# Ragged Paged Attention recipe (heterogeneous work items driven by
# scalar-prefetched indirection in one kernel) applied to expression
# trees over roaring containers.  No dense register file and no dense
# row word ever materializes: absent containers cost one canonical zero
# block, and the fused popcount root reduces each (q, d) cell to a
# single int32 in SMEM.
# ---------------------------------------------------------------------------


def _vm_counts_kernel(prog_ref, gidx_ref, *refs, slots: int,
                      tape_len: int):
    """One (query, domain-slot) cell: interpret the tape over the
    gathered leaf blocks.  ``prog_ref`` is the scalar-prefetched
    int32[B, T, 3] program (absolute register operands — ops/tape.py's
    ``_abs_operand`` encoding, COPY-chain padded so the LAST register
    holds the result); ``gidx_ref`` was consumed by the index maps.
    The register file lives entirely in VMEM: ``slots`` gathered leaf
    blocks + ``tape_len`` instruction outputs, each one container."""
    del gidx_ref  # consumed by the BlockSpec index maps
    out_ref = refs[-1]
    leaf_refs = refs[:-1]
    q = pl.program_id(0)
    regs = jnp.concatenate(
        [r[:] for r in leaf_refs]
        + [jnp.zeros((tape_len, CONTAINER_WORDS), jnp.uint32)])
    for t in range(tape_len):
        # opcode constants are ops/tape.py's OP_AND..OP_COPY = range(5)
        # (literal here so the kernel module stays import-light)
        op = prog_ref[q, t, 0]
        a = prog_ref[q, t, 1]
        b = prog_ref[q, t, 2]
        xa = lax.dynamic_slice(regs, (a, 0), (1, CONTAINER_WORDS))[0]
        xb = lax.dynamic_slice(regs, (b, 0), (1, CONTAINER_WORDS))[0]
        out = jnp.where(
            op == 0, xa & xb,
            jnp.where(op == 1, xa | xb,
                      jnp.where(op == 2, xa ^ xb,
                                jnp.where(op == 3, xa & ~xb, xa))))
        regs = lax.dynamic_update_slice(regs, out[None],
                                        (slots + t, 0))
    out_ref[0, 0] = jnp.sum(
        lax.population_count(regs[slots + tape_len - 1]),
        dtype=jnp.int32)


def _vm_counts_pallas_body(pool, prog, gidx, interpret: bool):
    """grid (B, D): every query x domain-slot cell is one step whose
    ``slots`` leaf blocks DMA from the ONE megapool through per-slot
    index maps over the scalar-prefetched directory — the same buffer
    is passed once per leaf slot, so no operand copy exists.  Output
    is per-cell int32 popcounts (each <= 2^16, overflow-free); the
    host sums them in int64."""
    B, T, _ = prog.shape
    L, _, D = gidx.shape
    kernel = functools.partial(_vm_counts_kernel, slots=L, tape_len=T)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, D),
        in_specs=[
            pl.BlockSpec((1, CONTAINER_WORDS),
                         lambda q, d, prog, gidx, _l=l: (gidx[_l, q, d], 0))
            for l in range(L)
        ],
        out_specs=pl.BlockSpec((1, 1), lambda q, d, prog, gidx: (q, d),
                               memory_space=pltpu.SMEM),
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, D), jnp.int32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(prog, gidx, *([pool] * L))
    return out


@functools.partial(jax.jit, static_argnames=("interpret",))
def _vm_counts_pallas(pool, prog, gidx, interpret: bool = False):
    return _vm_counts_pallas_body(pool, prog, gidx, interpret)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _vm_counts_kinds_pallas(bpool, apool, acard, rpool, prog, gidx,
                            interpret: bool = False):
    """Kind-split megapool variant: decode the compact array/run pools
    to dense blocks and concatenate behind the bitmap rows INSIDE the
    same launch, reproducing the virtual dense row space the
    coalescer's global indices address ([0, Rb) bitmap, [Rb, Rb+Ra)
    array, the rest run — ops/containers.MegaPools), then run the
    UNCHANGED VM kernel over it.  Resident and transferred bytes stay
    compact; only this launch's VMEM/HBM scratch is dense."""
    from pilosa_tpu.ops import kindpools as kp

    pool = jnp.concatenate(
        [bpool, kp.decode_array_jnp(apool, acard),
         kp.decode_runs_jnp(rpool)], axis=0)
    return _vm_counts_pallas_body(pool, prog, gidx, interpret)


def _vm_counts_host(pool, prog, gidx):
    """Eager numpy twin of the VM kernel (host-mode engine and the
    differential oracle for interpret-mode tests) — same register
    grammar, per-cell int32 counts."""
    from pilosa_tpu.ops import hostkernels as hk

    B, T, _ = prog.shape
    L, _, D = gidx.shape
    pool = np.asarray(pool)
    out = np.zeros((B, D), dtype=np.int32)
    for q in range(B):
        # vectorized over the domain axis: each register is [D, W], so a
        # query costs T whole-array ops instead of D x T per-cell ops
        regs = [pool[gidx[l, q]] for l in range(L)]
        for t in range(T):
            op, a, b = (int(v) for v in prog[q, t])
            xa = regs[a]
            if op == 4:
                regs.append(xa)
                continue
            xb = regs[b]
            if op == 0:
                regs.append(xa & xb)
            elif op == 1:
                regs.append(xa | xb)
            elif op == 2:
                regs.append(xa ^ xb)
            else:
                regs.append(xa & ~xb)
        out[q] = hk.row_counts(regs[-1])
    return out


def _vm_counts_jnp_body(pool, prog, gidx):
    from pilosa_tpu.ops import tape as _tape_mod

    leaves = jnp.take(pool, gidx, axis=0)   # [L, B, D, W]
    leaves = jnp.moveaxis(leaves, 1, 0)     # [B, L, D, W]
    one = _tape_mod._one_query(True)
    return jax.vmap(one)(prog, leaves)      # [B, D] int32


@jax.jit
def _vm_counts_jnp(pool, prog, gidx):
    """Jitted XLA twin: gather every leaf block from the pool, then
    run the EXACT tape-interpreter closure (ops/tape._one_query) per
    query over [slots, D, W] leaf stacks — the two engines cannot
    drift because they trace the same scan/switch body.  Re-lowers
    per (B, T, L, D) bucket shape, which pow2 bucketing bounds."""
    return _vm_counts_jnp_body(pool, prog, gidx)


@jax.jit
def _vm_counts_kinds_jnp(bpool, apool, acard, rpool, prog, gidx):
    """XLA twin of the kind-split VM: same decode + concatenate as the
    Pallas wrapper, same interpreter body — one launch either way."""
    from pilosa_tpu.ops import kindpools as kp

    pool = jnp.concatenate(
        [bpool, kp.decode_array_jnp(apool, acard),
         kp.decode_runs_jnp(rpool)], axis=0)
    return _vm_counts_jnp_body(pool, prog, gidx)


def _vm_counts_kinds(bundle, prog, gidx, interpret: bool):
    """Dispatch the kind-split megapool bundle (containers.MegaPools):
    host pools decode eagerly in numpy and reuse the eager twin; on
    device the decode happens inside the single jitted launch."""
    B, T, _ = prog.shape
    _L, _, D = gidx.shape
    if isinstance(bundle.bpool, np.ndarray):
        from pilosa_tpu.ops import kindpools as kp

        pool = np.concatenate(
            [np.asarray(bundle.bpool),
             kp.decode_array_np(np.asarray(bundle.apool),
                                np.asarray(bundle.acard)),
             kp.decode_runs_np(np.asarray(bundle.rpool))], axis=0)
        return _vm_counts_host(pool, prog, gidx)
    progj = jnp.asarray(prog)
    gidxj = jnp.asarray(gidx)
    if _use_pallas(interpret, B * D * CONTAINER_WORDS,
                   kernel="vm_counts"):
        return _vm_counts_kinds_pallas(bundle.bpool, bundle.apool,
                                       bundle.acard, bundle.rpool,
                                       progj, gidxj,
                                       interpret=interpret)
    return _vm_counts_kinds_jnp(bundle.bpool, bundle.apool,
                                bundle.acard, bundle.rpool,
                                progj, gidxj)


def vm_counts(pool, prog, gidx, interpret: bool = False):
    """Per-cell popcounts int32[B, D] of a batch of op-tapes over one
    pooled compressed operand: the Pallas VM on TPU, the jitted
    gather+interpret twin elsewhere, eager numpy for host pools —
    bit-identical counts on every route.  ``pool`` may also be a
    kind-split ``containers.MegaPools`` bundle, which decodes inside
    the launch.  The caller (ops/tape.execute_vm) owns the single
    dispatch tick."""
    prog = np.ascontiguousarray(prog, dtype=np.int32)
    gidx = np.ascontiguousarray(gidx, dtype=np.int32)
    B, T, _ = prog.shape
    _L, _, D = gidx.shape
    from pilosa_tpu.ops import containers as _containers

    if isinstance(pool, _containers.MegaPools):
        return _vm_counts_kinds(pool, prog, gidx, interpret)
    if isinstance(pool, np.ndarray):
        return _vm_counts_host(pool, prog, gidx)
    progj = jnp.asarray(prog)
    gidxj = jnp.asarray(gidx)
    if (pool.shape[-1] == CONTAINER_WORDS
            and _use_pallas(interpret, B * D * CONTAINER_WORDS,
                            kernel="vm_counts")):
        return _vm_counts_pallas(jnp.asarray(pool), progj, gidxj,
                                 interpret=interpret)
    return _vm_counts_jnp(jnp.asarray(pool), progj, gidxj)


# ---------------------------------------------------------------------------
# GroupBy cartesian counts: out[g, r] = |mat[r] & masks[g]| — one pass
# over the row matrix per mask block, [GB, RB, WB] intermediate in VMEM
# (SURVEY §7's third Pallas target; groupByIterator, executor.go:3058)
# ---------------------------------------------------------------------------

MMC_GROUP_BLOCK = 8
MMC_ROW_BLOCK = 128
MMC_WORD_BLOCK = 256


def _mmc_kernel(mat_ref, masks_ref, out_ref):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    m = mat_ref[:]        # [RB, WB]
    g = masks_ref[:]      # [GB, WB]
    cnt = lax.population_count(g[:, None, :] & m[None, :, :])  # [GB,RB,WB]
    out_ref[:] += jnp.sum(cnt, axis=2, dtype=jnp.int32)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _mmc_pallas(mat, masks, interpret: bool = False):
    R, W = mat.shape
    G = masks.shape[0]
    mat = _pad_to(_pad_to(mat, 1, MMC_WORD_BLOCK), 0, MMC_ROW_BLOCK)
    masks = _pad_to(_pad_to(masks, 1, MMC_WORD_BLOCK), 0, MMC_GROUP_BLOCK)
    Rp, Wp = mat.shape
    Gp = masks.shape[0]
    grid = (Gp // MMC_GROUP_BLOCK, Rp // MMC_ROW_BLOCK,
            Wp // MMC_WORD_BLOCK)
    out = pl.pallas_call(
        _mmc_kernel,
        out_shape=jax.ShapeDtypeStruct((Gp, Rp), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((MMC_ROW_BLOCK, MMC_WORD_BLOCK),
                         lambda i, j, k: (j, k)),
            pl.BlockSpec((MMC_GROUP_BLOCK, MMC_WORD_BLOCK),
                         lambda i, j, k: (i, k)),
        ],
        out_specs=pl.BlockSpec((MMC_GROUP_BLOCK, MMC_ROW_BLOCK),
                               lambda i, j, k: (i, j)),
        interpret=interpret,
    )(mat, masks)
    return out[:G, :R]


def masked_matrix_counts(mat, masks, interpret: bool = False):
    """counts[g, r] = |mat[r] & masks[g]| — the GroupBy inner product.
    Pallas on TPU for big products (single HBM pass per block, VMEM
    accumulation); the bm dispatcher elsewhere (native C++ on host
    stacks, lax.map of fused row counts on other devices)."""
    from pilosa_tpu.ops import bitmap as bm

    R, W = mat.shape
    G = masks.shape[0]
    if (_use_pallas(interpret, G * R * W, floor=1 << 18,
                    kernel="masked_matrix_counts")
            and not isinstance(mat, np.ndarray)):
        return _mmc_pallas(jnp.asarray(mat), jnp.asarray(masks),
                           interpret=interpret)
    return bm.masked_matrix_counts(mat, masks)


# ---------------------------------------------------------------------------
# BSI ripple compare: keep/lt/gt masks across bit planes, all in VMEM
# ---------------------------------------------------------------------------


def _bsi_compare_kernel(planes_ref, filt_ref, pred_ref, out_lt_ref,
                        out_gt_ref, *, depth: int):
    """One word-block: ripple from the MSB plane down, computing
    columns strictly-below / strictly-above the predicate among
    non-null, non-negative, filtered columns (the unsigned core of
    fragment.rangeLTUnsigned/rangeGTUnsigned, fragment.go:1277-1343).
    pred is pre-split into per-plane broadcast masks by the host."""
    exists = planes_ref[0, :]
    sign = planes_ref[1, :]
    consider = exists & ~sign & filt_ref[0, :]
    lt = jnp.zeros_like(consider)
    gt = jnp.zeros_like(consider)
    eq = consider
    for i in range(depth - 1, -1, -1):
        plane = planes_ref[2 + i, :]
        pred_bit = pred_ref[i, 0]  # 0 or 0xFFFFFFFF broadcast mask
        # predicate bit 1: plane-0 columns fall below; bit 0: plane-1
        # columns rise above
        lt = lt | (eq & pred_bit & ~plane)
        gt = gt | (eq & ~pred_bit & plane)
        eq = eq & ~(plane ^ pred_bit)
    out_lt_ref[0, :] = lt
    out_gt_ref[0, :] = gt


@functools.partial(jax.jit, static_argnames=("depth", "interpret"))
def _bsi_compare_pallas(planes, filt, pred_masks, depth: int,
                        interpret: bool = False):
    W = planes.shape[1]
    # pad the PLANE axis to the uint32 sublane tile (8): a block whose
    # second-minor dim is the raw depth+2 (e.g. 19) risks a Mosaic
    # lowering rejection; padded planes are zeros the kernel never
    # indexes (it reads exactly [0], [1], [2..2+depth))
    planes = _pad_to(_pad_to(planes, 1, WORD_BLOCK), 0, 8)
    P = planes.shape[0]
    filt = _pad_to(filt.reshape(1, -1), 1, WORD_BLOCK)
    Wp = planes.shape[1]
    kernel = functools.partial(_bsi_compare_kernel, depth=depth)
    out_lt, out_gt = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((1, Wp), jnp.uint32),
            jax.ShapeDtypeStruct((1, Wp), jnp.uint32),
        ),
        grid=(Wp // WORD_BLOCK,),
        in_specs=[
            pl.BlockSpec((P, WORD_BLOCK), lambda j: (0, j)),
            pl.BlockSpec((1, WORD_BLOCK), lambda j: (0, j)),
            pl.BlockSpec((depth, 1), lambda j: (0, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, WORD_BLOCK), lambda j: (0, j)),
            pl.BlockSpec((1, WORD_BLOCK), lambda j: (0, j)),
        ),
        interpret=interpret,
    )(planes, filt, pred_masks)
    return out_lt[0, :W], out_gt[0, :W]


def bsi_compare_unsigned(planes, filt, upred: int, depth: int,
                         interpret: bool = False):
    """(strictly_lt, strictly_gt) word masks among filtered non-negative
    columns vs an unsigned predicate.  Pallas on TPU, the shared jnp
    ripple (pilosa_tpu.ops.bsi.compare) elsewhere — bit-identical."""
    if upred < 0:
        raise ValueError("predicate magnitude must be non-negative")
    if upred >= 1 << depth:
        # every depth-bit value is strictly below the predicate; the
        # kernels only ripple `depth` planes, so handle this here rather
        # than silently truncating predicate bits
        consider = jnp.asarray(planes[0]) & ~jnp.asarray(planes[1]) \
            & jnp.asarray(filt)
        return consider, jnp.zeros_like(consider)
    if _use_pallas(interpret, planes.shape[1], floor=1 << 12,
                   kernel="bsi_compare_unsigned"):
        pred_masks = np.array(
            [[0xFFFFFFFF if (upred >> i) & 1 else 0]
             for i in range(depth)],
            dtype=np.uint32,
        )
        return _bsi_compare_pallas(jnp.asarray(planes), jnp.asarray(filt),
                                   jnp.asarray(pred_masks), depth,
                                   interpret=interpret)
    return _bsi_compare_jnp(planes, filt, upred, depth)


def _bsi_compare_jnp(planes, filt, upred: int, depth: int):
    """Fallback via the canonical jitted ripple (bsi.compare takes the
    predicate as traced uint32 limbs — no per-predicate recompilation)."""
    from pilosa_tpu.ops import bsi

    planes = jnp.asarray(planes)
    consider = planes[0] & ~planes[1] & jnp.asarray(filt)
    lo, hi = bsi.split_predicate(upred)
    lt, eq = bsi.compare(planes, consider, lo, hi)
    return lt, consider & ~lt & ~eq


# Compile telemetry (pilosa_tpu.devobs): Mosaic lowerings are the most
# expensive compiles in the process, so the Pallas entry points carry
# the same cache-miss detection as the XLA kernels (ops/bitmap.py).
from pilosa_tpu import devobs as _devobs  # noqa: E402

for _n in ("_row_counts_masked_pallas", "_count_and_pallas",
           "_gathered_count_and_pallas", "_vm_counts_pallas",
           "_vm_counts_jnp", "_vm_counts_kinds_pallas",
           "_vm_counts_kinds_jnp", "_count_aa_jnp", "_count_ab_jnp",
           "_mmc_pallas", "_bsi_compare_pallas"):
    globals()[_n] = _devobs.instrument(f"pallas.{_n.strip('_')}",
                                       globals()[_n])
del _n
