"""Device kernels: packed-bitmap set algebra, popcounts, BSI, TopN.

The TPU-native replacement for the reference's roaring container engine
(roaring/roaring.go).  Everything here operates on dense uint32-packed
bitmap tensors and is jit-compiled to XLA.
"""

from pilosa_tpu.ops.bitmap import (
    WORD_BITS,
    n_words,
    pack_positions,
    unpack_positions,
    pack_positions_matrix,
    b_and,
    b_or,
    b_xor,
    b_andnot,
    b_not,
    b_shift,
    b_flip_range,
    popcount,
    popcount_and,
    row_counts,
    row_counts_masked,
    set_bits,
    clear_bits,
    get_bits,
    reduce_or_rows,
    reduce_and_rows,
)

__all__ = [
    "WORD_BITS",
    "n_words",
    "pack_positions",
    "unpack_positions",
    "pack_positions_matrix",
    "b_and",
    "b_or",
    "b_xor",
    "b_andnot",
    "b_not",
    "b_shift",
    "b_flip_range",
    "popcount",
    "popcount_and",
    "row_counts",
    "row_counts_masked",
    "set_bits",
    "clear_bits",
    "get_bits",
    "reduce_or_rows",
    "reduce_and_rows",
]
