"""Per-kind container pools: the array/run compact layouts behind the
compressed container directory (ops/containers.py).

PR 10 put roaring's *directory* on device but kept every container a
kind-1 dense block: a 100-bit container still costs 2048 pool words.
This module supplies the other two reference kinds (Chambi et al. /
Lemire et al.; PAPERS.md 1402.6407, 1603.06549) as DEVICE layouts:

- **array** (kind 2) — sorted uint16 values, cardinality <= 4096,
  packed ``uint16[n, acap]`` with per-container cardinality; ``acap``
  is the pow2 size class of the pool's largest card, so megapool bytes
  track real cardinality instead of 8 KiB per container.
- **run** (kind 3) — maximal ``(start, last)`` inclusive intervals,
  packed ``uint16[n, 2*rcap]`` interleaved; padding pairs are the
  canonical invalid interval ``(1, 0)``.

Kind selection is ``storage/roaring.pick_kind`` — the SAME cost rule
the serializer uses, so wire and device kinds cannot drift.  Decoders
come in numpy and jnp twins that are bit-exact by construction: pure
integer scatter/shift algebra, no floats —

- array decode scatters ``1 << (v & 31)`` at word ``v >> 5``; values
  are sorted-unique so in-word contributions are distinct powers of
  two and add == or, and the cardinality mask zeroes the padding tail;
- run decode scatters XOR toggles at ``start`` and ``last + 1`` (runs
  are maximal, so toggle positions are strictly increasing and add ==
  xor), then a log-shift in-word prefix-XOR plus a word-level carry
  parity turns toggles into coverage — O(words) with no 2^16-wide
  temporary.

Everything here is a pure function of its inputs (no module state);
jax imports are lazy so host-mode paths never touch the device stack.
"""

from __future__ import annotations

import numpy as np

from pilosa_tpu.storage.roaring import (ARRAY_MAX_CARD, KIND_ARRAY,
                                        KIND_BITMAP, KIND_RUN)

#: Container geometry (must match ops/containers.py).
CONTAINER_BITS = 1 << 16
CWORDS = CONTAINER_BITS // 32

#: Default ceiling on interval count for the run kind: a container
#: whose maximal-run count exceeds this re-picks array/bitmap, so the
#: run pool's pow2 size class stays bounded ([containers] run-cap).
DEFAULT_RUN_CAP = 256

#: Array-pool padding value: >= every real uint16, so padded rows stay
#: sorted for the galloping/binary-search intersection arms.
ARRAY_PAD = 0xFFFF

_PICK_CHUNK = 256  # containers unpacked per chunk (bounds the 2^16-bit
                   # temporary at ~16 MiB)


def _pow2(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return b


def pick_kinds(blocks: np.ndarray, array_max: int = ARRAY_MAX_CARD,
               run_cap: int = DEFAULT_RUN_CAP) -> np.ndarray:
    """Cheapest kind per dense container block (uint32[n, CWORDS]) by
    the serializer's cost rule, with the device-only ``run_cap``
    demotion (too many intervals -> array/bitmap) applied."""
    cards, runs = block_stats(blocks)
    eff_runs = np.where(runs <= run_cap, runs, ARRAY_MAX_CARD)
    run_size = 2 + 4 * eff_runs
    array_size = np.where(cards <= array_max, 2 * cards, np.int64(1) << 40)
    kinds = np.where(
        (run_size < array_size) & (run_size < 8192), KIND_RUN,
        np.where(array_size <= 8192, KIND_ARRAY, KIND_BITMAP))
    return kinds.astype(np.uint8)


def block_stats(blocks: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(cardinality int64[n], maximal-run count int64[n]) per dense
    container block — vectorized twin of roaring.container_stats."""
    n = len(blocks)
    cards = np.zeros(n, dtype=np.int64)
    runs = np.zeros(n, dtype=np.int64)
    for lo in range(0, n, _PICK_CHUNK):
        chunk = np.ascontiguousarray(blocks[lo:lo + _PICK_CHUNK])
        bits = np.unpackbits(chunk.view(np.uint8), axis=1,
                             bitorder="little")
        cards[lo:lo + len(chunk)] = bits.sum(axis=1, dtype=np.int64)
        first = bits[:, :1].astype(np.int64)
        rises = (np.diff(bits.astype(np.int8), axis=1) == 1)
        runs[lo:lo + len(chunk)] = (first[:, 0]
                                    + rises.sum(axis=1, dtype=np.int64))
    return cards, runs


def split_pools(blocks: np.ndarray, kinds: np.ndarray) -> tuple:
    """Split a directory's dense blocks into per-kind compact pools.

    Returns ``(slots, bblocks, apool, acard, rpool)``: ``slots`` is
    the kind-LOCAL row of each container (int32[n], numbering within
    its own kind pool, directory order preserved per kind);
    ``bblocks`` the kind-1 dense rows uint32[bn, CWORDS]; ``apool`` /
    ``acard`` the array pool uint16[an, acap] + int32[an]; ``rpool``
    the run pool uint16[rn, 2*rcap].  Pool column widths are pow2 size
    classes of the pool's own maxima (the P4 O(log)-shapes rule)."""
    n = len(kinds)
    slots = np.zeros(n, dtype=np.int32)
    for k in (KIND_BITMAP, KIND_ARRAY, KIND_RUN):
        sel = kinds == k
        slots[sel] = np.arange(int(sel.sum()), dtype=np.int32)
    bblocks = np.ascontiguousarray(blocks[kinds == KIND_BITMAP])

    avals: list[np.ndarray] = []
    rpairs: list[np.ndarray] = []
    for i in range(n):
        if kinds[i] == KIND_BITMAP:
            continue
        bits = np.unpackbits(
            np.ascontiguousarray(blocks[i]).view(np.uint8),
            bitorder="little")
        if kinds[i] == KIND_ARRAY:
            avals.append(np.flatnonzero(bits).astype(np.uint16))
        else:
            starts = np.flatnonzero(
                np.diff(np.concatenate(([0], bits))) == 1)
            ends = np.flatnonzero(
                np.diff(np.concatenate((bits, [0]))) == -1)
            pr = np.empty((len(starts), 2), dtype=np.uint16)
            pr[:, 0] = starts
            pr[:, 1] = ends
            rpairs.append(pr)

    acap = _pow2(max([len(v) for v in avals], default=0) or 1)
    apool = np.full((len(avals), acap), ARRAY_PAD, dtype=np.uint16)
    acard = np.zeros(len(avals), dtype=np.int32)
    for i, v in enumerate(avals):
        apool[i, :len(v)] = v
        acard[i] = len(v)

    rcap = _pow2(max([len(p) for p in rpairs], default=0) or 1)
    rpool = np.zeros((len(rpairs), 2 * rcap), dtype=np.uint16)
    rpool[:, 0::2] = 1  # (1, 0): the canonical invalid padding pair
    for i, p in enumerate(rpairs):
        rpool[i, :2 * len(p)] = p.reshape(-1)
    return slots, bblocks, apool, acard, rpool


# ------------------------------------------------------------- decoders
#
# numpy and jnp twins of the same integer algebra — bit-exact by
# construction (see module docstring).  Both accept a zero-row pool
# (n == 0) and return uint32[n, CWORDS].


def decode_array_np(apool: np.ndarray, acard: np.ndarray) -> np.ndarray:
    n, cap = apool.shape
    out = np.zeros((n, CWORDS), dtype=np.uint32)
    if n == 0:
        return out
    vals = apool.astype(np.int64)
    valid = np.arange(cap, dtype=np.int64)[None, :] < acard[:, None]
    contrib = np.where(valid, np.int64(1) << (vals & 31),
                       0).astype(np.uint32)
    rows = np.broadcast_to(np.arange(n)[:, None], vals.shape)
    word = np.where(valid, vals >> 5, 0)
    np.bitwise_or.at(out, (rows, word), contrib)
    return out


def decode_runs_np(rpool: np.ndarray) -> np.ndarray:
    n = rpool.shape[0]
    if n == 0:
        return np.zeros((0, CWORDS), dtype=np.uint32)
    pairs = rpool.reshape(n, -1, 2).astype(np.int64)
    s, l = pairs[..., 0], pairs[..., 1]
    valid = l >= s
    rows = np.broadcast_to(np.arange(n)[:, None], s.shape)
    t = np.zeros((n, CWORDS + 1), dtype=np.uint32)
    for pos in (s, l + 1):
        p = np.where(valid, pos, 0)
        contrib = np.where(valid, np.int64(1) << (p & 31),
                           0).astype(np.uint32)
        np.add.at(t, (rows, p >> 5), contrib)
    x = t[:, :CWORDS]  # a toggle at bit 2^16 covers nothing in-range
    for sh in (1, 2, 4, 8, 16):
        x = x ^ (x << np.uint32(sh))
    wordpar = (x >> np.uint32(31)).astype(np.int64)
    carry = ((np.cumsum(wordpar, axis=1) - wordpar) & 1).astype(np.uint32)
    return x ^ (carry * np.uint32(0xFFFFFFFF))


def decode_array_jnp(apool, acard):
    import jax.numpy as jnp

    n, cap = apool.shape
    if n == 0:
        return jnp.zeros((0, CWORDS), dtype=jnp.uint32)
    vals = apool.astype(jnp.int32)
    valid = jnp.arange(cap, dtype=jnp.int32)[None, :] < acard[:, None]
    contrib = jnp.where(valid,
                        jnp.uint32(1) << (vals & 31).astype(jnp.uint32),
                        jnp.uint32(0))
    rows = jnp.broadcast_to(jnp.arange(n)[:, None], vals.shape)
    word = jnp.where(valid, vals >> 5, 0)
    out = jnp.zeros((n, CWORDS), dtype=jnp.uint32)
    # sorted-unique values: in-word contributions are distinct powers
    # of two, so scatter-add == scatter-or (no carries)
    return out.at[rows, word].add(contrib)


def decode_runs_jnp(rpool):
    import jax.numpy as jnp

    n = rpool.shape[0]
    if n == 0:
        return jnp.zeros((0, CWORDS), dtype=jnp.uint32)
    pairs = rpool.reshape(n, -1, 2).astype(jnp.int32)
    s, l = pairs[..., 0], pairs[..., 1]
    valid = l >= s
    rows = jnp.broadcast_to(jnp.arange(n)[:, None], s.shape)
    t = jnp.zeros((n, CWORDS + 1), dtype=jnp.uint32)
    for pos in (s, l + 1):
        p = jnp.where(valid, pos, 0)
        contrib = jnp.where(valid,
                            jnp.uint32(1) << (p & 31).astype(jnp.uint32),
                            jnp.uint32(0))
        # maximal runs: toggle positions strictly increase, so in-word
        # contributions are distinct powers of two and add == xor
        t = t.at[rows, p >> 5].add(contrib)
    x = t[:, :CWORDS]
    for sh in (1, 2, 4, 8, 16):
        x = x ^ (x << jnp.uint32(sh))
    wordpar = (x >> jnp.uint32(31)).astype(jnp.int32)
    carry = ((jnp.cumsum(wordpar, axis=1) - wordpar)
             & 1).astype(jnp.uint32)
    return x ^ (carry * jnp.uint32(0xFFFFFFFF))
