"""Small file-IO helpers shared across the storage tier."""

from __future__ import annotations

import json
import os


def atomic_write_json(path: str, obj) -> None:
    """Write JSON atomically: tmp file + os.replace, cleaning the tmp on
    failure.  Callers serialize per-file writes with their own locks, so
    a fixed tmp name is safe and self-overwriting (no stale tmp
    accumulation after crashes)."""
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(obj, f)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
