"""Logger: the reference's logger.Logger interface (logger/logger.go) —
Printf/Debugf split, nop + standard + verbose implementations."""

from __future__ import annotations

import sys
import time


class Logger:
    def printf(self, fmt: str, *args) -> None:
        pass

    def debugf(self, fmt: str, *args) -> None:
        pass


NOP = Logger()


class StandardLogger(Logger):
    def __init__(self, stream=None):
        self.stream = stream or sys.stderr

    def _emit(self, fmt: str, *args) -> None:
        ts = time.strftime("%Y-%m-%dT%H:%M:%S")
        msg = (fmt % args) if args else fmt
        self.stream.write(f"{ts} {msg}\n")

    def printf(self, fmt, *args):
        self._emit(fmt, *args)


class VerboseLogger(StandardLogger):
    def debugf(self, fmt, *args):
        self._emit("DEBUG " + fmt, *args)
