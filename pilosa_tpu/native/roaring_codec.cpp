// Roaring file codec (Pilosa 64-bit variant, cookie 12348).
//
// Implements the on-disk format described in the reference's
// docs/architecture.md:9-24 and written by roaring/roaring.go:1046
// (WriteTo) / parsed by roaring/unmarshal_binary.go:
//
//   [0:4)   cookie: u16 magic 12348 | u8 version (0) | u8 flags
//   [4:8)   container count (u32)
//   then per container, 12 bytes of descriptive header:
//           key (u64), type (u16: 1=array, 2=bitmap, 3=run), N-1 (u16)
//   then per container: absolute data offset (u32) as its own section
//   then container payloads:
//           array:  N x u16 sorted values
//           bitmap: 1024 x u64
//           run:    run count (u16), then (start u16, last u16) pairs
//   all little-endian; an op log of unspecified length may follow the
//   container section (ignored here — our fragments carry their own WAL).
//
// The decode side expands every container to a dense 1024-word (u64)
// block keyed by the container key: the packed-tensor layout the TPU
// kernels consume directly.  The encode side picks the smallest of
// array (2N bytes), bitmap (8192), or run (2+4*runs) per container, as
// the reference's Optimize() does.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

constexpr uint16_t kMagic = 12348;
constexpr uint32_t kWordsPerContainer = 1024;  // 2^16 bits
constexpr uint32_t kHeaderBaseSize = 8;
constexpr uint16_t kTypeArray = 1;
constexpr uint16_t kTypeBitmap = 2;
constexpr uint16_t kTypeRun = 3;

inline uint16_t rd16(const uint8_t* p) {
  uint16_t v;
  std::memcpy(&v, p, 2);
  return v;
}
inline uint32_t rd32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}
inline uint64_t rd64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;
}
inline void wr16(std::vector<uint8_t>& b, uint16_t v) {
  const uint8_t* p = reinterpret_cast<const uint8_t*>(&v);
  b.insert(b.end(), p, p + 2);
}
inline void wr32(std::vector<uint8_t>& b, uint32_t v) {
  const uint8_t* p = reinterpret_cast<const uint8_t*>(&v);
  b.insert(b.end(), p, p + 4);
}
inline void wr64(std::vector<uint8_t>& b, uint64_t v) {
  const uint8_t* p = reinterpret_cast<const uint8_t*>(&v);
  b.insert(b.end(), p, p + 8);
}

}  // namespace

extern "C" {

// Error codes.
enum {
  ROARING_OK = 0,
  ROARING_ERR_TRUNCATED = -1,
  ROARING_ERR_MAGIC = -2,
  ROARING_ERR_VERSION = -3,
  ROARING_ERR_TYPE = -4,
  ROARING_ERR_OFFSET = -5,
  ROARING_ERR_CAP = -7,
};

// Decode a serialized bitmap into dense containers.
// keys_out/words_out are malloc'd; caller frees with pilosa_roaring_free_buf.
// words_out holds n_out * 1024 u64 words.
int pilosa_roaring_decode(const uint8_t* data, uint64_t len,
                          uint64_t** keys_out, uint64_t** words_out,
                          uint64_t* n_out, uint8_t* flags_out) {
  if (len < kHeaderBaseSize) return ROARING_ERR_TRUNCATED;
  uint16_t magic = rd16(data);
  if (magic != kMagic) return ROARING_ERR_MAGIC;
  if (data[2] != 0) return ROARING_ERR_VERSION;
  *flags_out = data[3];
  uint64_t n = rd32(data + 4);
  if (len < kHeaderBaseSize + n * 12ULL + n * 4ULL) return ROARING_ERR_TRUNCATED;

  uint64_t* keys = static_cast<uint64_t*>(std::malloc(n * sizeof(uint64_t)));
  uint64_t* words =
      static_cast<uint64_t*>(std::calloc(n * kWordsPerContainer, sizeof(uint64_t)));
  if ((n > 0 && (!keys || !words))) {
    std::free(keys);
    std::free(words);
    return ROARING_ERR_TRUNCATED;
  }

  // Descriptive header entries are 12 bytes (key u64, type u16, N-1 u16);
  // the 4-byte offsets follow as their own section (WriteTo layout:
  // header total = 8 + 16*n).
  const uint8_t* desc = data + kHeaderBaseSize;
  const uint8_t* offs = desc + n * 12;
  for (uint64_t i = 0; i < n; i++) {
    uint64_t key = rd64(desc + i * 12);
    uint16_t typ = rd16(desc + i * 12 + 8);
    uint32_t card = static_cast<uint32_t>(rd16(desc + i * 12 + 10)) + 1;
    uint32_t off = rd32(offs + i * 4);
    keys[i] = key;
    uint64_t* w = words + i * kWordsPerContainer;
    switch (typ) {
      case kTypeArray: {
        if (static_cast<uint64_t>(off) + 2ULL * card > len) goto fail_offset;
        const uint8_t* p = data + off;
        for (uint32_t j = 0; j < card; j++) {
          uint16_t v = rd16(p + 2 * j);
          w[v >> 6] |= 1ULL << (v & 63);
        }
        break;
      }
      case kTypeBitmap: {
        if (static_cast<uint64_t>(off) + 8192ULL > len) goto fail_offset;
        std::memcpy(w, data + off, 8192);
        break;
      }
      case kTypeRun: {
        if (static_cast<uint64_t>(off) + 2ULL > len) goto fail_offset;
        uint16_t run_count = rd16(data + off);
        if (static_cast<uint64_t>(off) + 2ULL + 4ULL * run_count > len)
          goto fail_offset;
        const uint8_t* p = data + off + 2;
        for (uint32_t r = 0; r < run_count; r++) {
          uint16_t start = rd16(p + 4 * r);
          uint16_t last = rd16(p + 4 * r + 2);
          // set bits [start, last] inclusive, word-blasted
          uint32_t ws = start >> 6, we = last >> 6;
          if (ws == we) {
            w[ws] |= (~0ULL >> (63 - (last & 63))) & (~0ULL << (start & 63));
          } else {
            w[ws] |= ~0ULL << (start & 63);
            for (uint32_t k = ws + 1; k < we; k++) w[k] = ~0ULL;
            w[we] |= ~0ULL >> (63 - (last & 63));
          }
        }
        break;
      }
      default:
        std::free(keys);
        std::free(words);
        return ROARING_ERR_TYPE;
    }
  }
  *keys_out = keys;
  *words_out = words;
  *n_out = n;
  return ROARING_OK;

fail_offset:
  std::free(keys);
  std::free(words);
  return ROARING_ERR_OFFSET;
}

void pilosa_roaring_free_buf(void* p) { std::free(p); }

// Decode a serialized bitmap straight to absolute bit positions
// (key<<16 | in-container offset), WITHOUT materializing dense words —
// O(set bits), the sparse-ingest fast path.  Positions come out sorted
// ascending iff the wire's container keys are sorted (the format
// guarantees it; callers defensively re-sort if a hostile payload
// isn't).  max_positions bounds the output on the ACTUAL emitted
// count, not the descriptor cardinalities — run containers expand from
// run data, so a hostile payload whose descriptors lie small must hit
// ROARING_ERR_CAP instead of allocating unbounded memory (the caller
// falls back to the chunk-bounded dense path).  pos_out is malloc'd;
// caller frees with pilosa_roaring_free_buf.
int pilosa_roaring_decode_positions(const uint8_t* data, uint64_t len,
                                    uint64_t max_positions,
                                    uint64_t** pos_out, uint64_t* n_out,
                                    uint8_t* flags_out) try {
  if (len < kHeaderBaseSize) return ROARING_ERR_TRUNCATED;
  if (rd16(data) != kMagic) return ROARING_ERR_MAGIC;
  if (data[2] != 0) return ROARING_ERR_VERSION;
  *flags_out = data[3];
  uint64_t n = rd32(data + 4);
  if (len < kHeaderBaseSize + n * 12ULL + n * 4ULL) return ROARING_ERR_TRUNCATED;

  const uint8_t* desc = data + kHeaderBaseSize;
  const uint8_t* offs = desc + n * 12;
  // capacity pass: descriptor cardinalities bound the array/bitmap
  // output exactly; run containers re-count from run data below
  uint64_t cap = 0;
  for (uint64_t i = 0; i < n; i++)
    cap += static_cast<uint64_t>(rd16(desc + i * 12 + 10)) + 1;
  if (cap > max_positions) return ROARING_ERR_CAP;
  std::vector<uint64_t> pos;
  pos.reserve(cap);
  for (uint64_t i = 0; i < n; i++) {
    uint64_t base = rd64(desc + i * 12) << 16;
    uint16_t typ = rd16(desc + i * 12 + 8);
    uint32_t card = static_cast<uint32_t>(rd16(desc + i * 12 + 10)) + 1;
    uint32_t off = rd32(offs + i * 4);
    switch (typ) {
      case kTypeArray: {
        if (static_cast<uint64_t>(off) + 2ULL * card > len)
          return ROARING_ERR_OFFSET;
        const uint8_t* p = data + off;
        for (uint32_t j = 0; j < card; j++)
          pos.push_back(base | rd16(p + 2 * j));
        break;
      }
      case kTypeBitmap: {
        if (static_cast<uint64_t>(off) + 8192ULL > len)
          return ROARING_ERR_OFFSET;
        for (uint32_t k = 0; k < kWordsPerContainer; k++) {
          uint64_t v = rd64(data + off + 8 * k);
          while (v) {
            pos.push_back(base | (k * 64 +
                static_cast<uint32_t>(__builtin_ctzll(v))));
            v &= v - 1;
          }
        }
        break;
      }
      case kTypeRun: {
        if (static_cast<uint64_t>(off) + 2ULL > len)
          return ROARING_ERR_OFFSET;
        uint16_t run_count = rd16(data + off);
        if (static_cast<uint64_t>(off) + 2ULL + 4ULL * run_count > len)
          return ROARING_ERR_OFFSET;
        const uint8_t* p = data + off + 2;
        for (uint32_t r = 0; r < run_count; r++) {
          uint32_t start = rd16(p + 4 * r);
          uint32_t last = rd16(p + 4 * r + 2);
          if (pos.size() + (last - start + 1) > max_positions)
            return ROARING_ERR_CAP;
          for (uint32_t b = start; b <= last; b++) pos.push_back(base | b);
        }
        break;
      }
      default:
        return ROARING_ERR_TYPE;
    }
    if (pos.size() > max_positions) return ROARING_ERR_CAP;
  }
  uint64_t* out =
      static_cast<uint64_t*>(std::malloc(pos.size() * sizeof(uint64_t)));
  if (!out && !pos.empty()) return ROARING_ERR_TRUNCATED;
  std::memcpy(out, pos.data(), pos.size() * sizeof(uint64_t));
  *pos_out = out;
  *n_out = pos.size();
  return ROARING_OK;
} catch (...) {
  // never let bad_alloc (or anything) cross the ctypes boundary
  return ROARING_ERR_CAP;
}

// Encode dense containers into the serialized format.
// keys must be sorted ascending; words is n * 1024 u64.
// Empty containers (no bits) are skipped, as in the reference's WriteTo.
int pilosa_roaring_encode(const uint64_t* keys, const uint64_t* words,
                          uint64_t n, uint8_t flags, uint8_t** buf_out,
                          uint64_t* len_out) {
  struct Plan {
    uint64_t key;
    uint32_t card;
    uint16_t typ;
    uint32_t runs;
    const uint64_t* w;
  };
  std::vector<Plan> plans;
  plans.reserve(n);
  for (uint64_t i = 0; i < n; i++) {
    const uint64_t* w = words + i * kWordsPerContainer;
    uint32_t card = 0;
    uint32_t runs = 0;
    uint64_t prev_msb = 0;  // bit 63 of previous word
    for (uint32_t k = 0; k < kWordsPerContainer; k++) {
      uint64_t v = w[k];
      card += static_cast<uint32_t>(__builtin_popcountll(v));
      // runs = number of 0->1 transitions across the bit sequence
      uint64_t starts = v & ~((v << 1) | prev_msb);
      runs += static_cast<uint32_t>(__builtin_popcountll(starts));
      prev_msb = v >> 63;
    }
    if (card == 0) continue;
    uint64_t array_size = (card <= 4096) ? 2ULL * card : UINT64_MAX;
    uint64_t run_size = 2ULL + 4ULL * runs;
    uint64_t bitmap_size = 8192;
    uint16_t typ;
    if (run_size < array_size && run_size < bitmap_size) {
      typ = kTypeRun;
    } else if (array_size <= bitmap_size) {
      typ = kTypeArray;
    } else {
      typ = kTypeBitmap;
    }
    plans.push_back({keys[i], card, typ, runs, w});
  }

  std::vector<uint8_t> buf;
  uint64_t count = plans.size();
  buf.reserve(kHeaderBaseSize + count * 20 + count * 512);
  wr16(buf, kMagic);
  buf.push_back(0);      // version
  buf.push_back(flags);  // flags
  wr32(buf, static_cast<uint32_t>(count));
  for (const Plan& p : plans) {
    wr64(buf, p.key);
    wr16(buf, p.typ);
    wr16(buf, static_cast<uint16_t>(p.card - 1));
  }
  // offset section
  uint64_t offset = kHeaderBaseSize + count * 12 + count * 4;
  for (const Plan& p : plans) {
    if (offset > UINT32_MAX) return -6;  // 4 GiB offset-field limit
    wr32(buf, static_cast<uint32_t>(offset));
    switch (p.typ) {
      case kTypeArray: offset += 2ULL * p.card; break;
      case kTypeBitmap: offset += 8192; break;
      case kTypeRun: offset += 2ULL + 4ULL * p.runs; break;
    }
  }
  // payloads
  for (const Plan& p : plans) {
    switch (p.typ) {
      case kTypeArray: {
        for (uint32_t k = 0; k < kWordsPerContainer; k++) {
          uint64_t v = p.w[k];
          while (v) {
            uint32_t bit = static_cast<uint32_t>(__builtin_ctzll(v));
            wr16(buf, static_cast<uint16_t>(k * 64 + bit));
            v &= v - 1;
          }
        }
        break;
      }
      case kTypeBitmap: {
        const uint8_t* p8 = reinterpret_cast<const uint8_t*>(p.w);
        buf.insert(buf.end(), p8, p8 + 8192);
        break;
      }
      case kTypeRun: {
        wr16(buf, static_cast<uint16_t>(p.runs));
        bool in_run = false;
        uint32_t start = 0;
        for (uint32_t bitpos = 0; bitpos < 65536; bitpos++) {
          bool set = (p.w[bitpos >> 6] >> (bitpos & 63)) & 1;
          if (set && !in_run) {
            in_run = true;
            start = bitpos;
          } else if (!set && in_run) {
            in_run = false;
            wr16(buf, static_cast<uint16_t>(start));
            wr16(buf, static_cast<uint16_t>(bitpos - 1));
          }
        }
        if (in_run) {
          wr16(buf, static_cast<uint16_t>(start));
          wr16(buf, 65535);
        }
        break;
      }
    }
  }

  uint8_t* out = static_cast<uint8_t*>(std::malloc(buf.size()));
  if (!out && !buf.empty()) return ROARING_ERR_TRUNCATED;
  std::memcpy(out, buf.data(), buf.size());
  *buf_out = out;
  *len_out = buf.size();
  return ROARING_OK;
}

}  // extern "C"
