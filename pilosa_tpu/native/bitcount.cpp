// Host-side fused popcount kernels for the CPU execution engine.
//
// On TPU the set-algebra hot path is XLA (ops/bitmap.py jit kernels);
// when the framework runs on a plain CPU host (relay down, laptop dev,
// CI) the same ops dispatch here instead: single-pass AND+popcount with
// no materialized intermediates, compiled -march=native so gcc lowers
// __builtin_popcountll to POPCNT / AVX-512 VPOPCNTDQ where available.
// This is the moral analog of the reference's hand-tuned container
// fast paths (roaring/roaring.go:570 intersectionCount*) — the exact
// counting loop a CPU should run, where XLA:CPU's generic codegen loses
// to vectorized popcount by ~8x at bench shapes.
//
// Large inputs fan out over std::thread (the analog of the reference's
// per-shard worker pool, executor.go:2561, collapsed to one kernel):
// the ctypes caller has already released the GIL, so the threads own
// the cores.  Auto mode (pt_set_threads(0), the default) uses
// hardware_concurrency capped so each thread gets >= 4 MiB of operand —
// below that, spawn cost and memory-bandwidth saturation make threading
// a wash and the loops stay serial.  An explicit pt_set_threads(n>0)
// is honored exactly (tests force threading on tiny inputs with it).
//
// Buffers arrive as raw bytes from numpy uint32 arrays (C-contiguous,
// little-endian), processed as uint64 lanes with a uint32 tail — the
// same reinterpret-cast equivalence the file codec relies on
// (storage/roaring.py layout note).

#include <algorithm>
#include <cstdint>
#include <mutex>
#include <thread>
#include <vector>

namespace {

// Alias- and alignment-safe 8-byte load: row pointers into a [rows, n32]
// uint32 matrix are only 4-byte aligned for odd n32 x odd row, and a
// uint32->uint64 pointer pun is UB regardless; __builtin_memcpy folds to
// a single unaligned vector load under -O3.
inline uint64_t load64(const uint32_t* p) {
    uint64_t v;
    __builtin_memcpy(&v, p, 8);
    return v;
}

int g_threads = 0;  // 0 = auto; >0 = exact count (1 = always serial)

// 4 MiB of uint32 operand per extra thread before auto mode fans out.
constexpr long long kMinWordsPerThread = 1LL << 20;

// Thread count for a kernel touching `words` uint32s of operand total.
int effective_threads(long long words) {
    if (g_threads > 0) return g_threads;
    int t = (int)std::thread::hardware_concurrency();
    if (t < 2) return 1;
    long long cap = words / kMinWordsPerThread;
    if (cap < (long long)t) t = (int)(cap < 1 ? 1 : cap);
    return t;
}

// Split `total` items into contiguous chunks, each a multiple of
// `align` items (except the final chunk, which absorbs the tail).
std::vector<std::pair<long long, long long>> make_chunks(long long total,
                                                         long long align,
                                                         int t) {
    long long chunk = ((total / t) / align) * align;
    if (chunk < align) chunk = align;
    std::vector<std::pair<long long, long long>> chunks;
    for (long long lo = 0; lo < total; lo += chunk) {
        long long hi = std::min(total, lo + chunk);
        if (hi + chunk > total) hi = total;  // fold the tail into the last
        chunks.emplace_back(lo, hi);
        if (hi == total) break;
    }
    return chunks;
}

template <class F>
void run_chunks(const std::vector<std::pair<long long, long long>>& chunks,
                F fn) {
    std::vector<std::thread> ths;
    ths.reserve(chunks.size());
    for (size_t i = 0; i < chunks.size(); i++)
        ths.emplace_back(
            [&chunks, &fn, i] { fn(chunks[i].first, chunks[i].second, (int)i); });
    for (auto& th : ths) th.join();
}

// Run fn(lo, hi, slot) over `total` items; serial fast path when one
// thread suffices for `total * work_per_item` uint32s of operand.
template <class F>
void parallel_chunks(long long total, long long align, long long work_per_item,
                     F fn) {
    int t = effective_threads(total * work_per_item);
    if (t <= 1 || total < 2) {
        fn(0, total, 0);
        return;
    }
    run_chunks(make_chunks(total, align, t), fn);
}

long long count_serial(const uint32_t* a, long long n32) {
    long long n64 = n32 / 2, t = 0;
    for (long long i = 0; i < n64; i++)
        t += __builtin_popcountll(load64(a + 2 * i));
    if (n32 & 1) t += __builtin_popcount(a[n32 - 1]);
    return t;
}

long long count_and_serial(const uint32_t* a, const uint32_t* b,
                           long long n32) {
    long long n64 = n32 / 2, t = 0;
    for (long long i = 0; i < n64; i++)
        t += __builtin_popcountll(load64(a + 2 * i) & load64(b + 2 * i));
    if (n32 & 1) t += __builtin_popcount(a[n32 - 1] & b[n32 - 1]);
    return t;
}

// Scatter-reduce over word-range chunks: each thread counts its slice
// into a private slot (no false sharing at this granularity — one write
// per thread), summed after the join.  align=2 keeps every non-tail
// chunk on a uint64 lane boundary.
template <class Body>
long long chunked_count(long long n32, Body body) {
    int t = effective_threads(n32);
    if (t <= 1 || n32 < 2) return body(0, n32);
    auto chunks = make_chunks(n32, /*align=*/2, t);
    std::vector<long long> part(chunks.size(), 0);
    run_chunks(chunks, [&](long long lo, long long hi, int slot) {
        part[slot] = body(lo, hi);
    });
    long long total = 0;
    for (long long v : part) total += v;
    return total;
}

}  // namespace

extern "C" {

// 0 = auto (hardware_concurrency, >=4 MiB/thread); n>0 = exactly n.
void pt_set_threads(int n) { g_threads = n < 0 ? 0 : n; }

// The thread count a kernel touching `words` uint32s would use —
// exported so tests can pin the auto-mode cap arithmetic on any box.
int pt_effective_threads(long long words) { return effective_threads(words); }

// Popcount of one buffer of n32 uint32 words.
long long pt_count(const uint32_t* a, long long n32) {
    return chunked_count(n32, [a](long long lo, long long hi) {
        return count_serial(a + lo, hi - lo);
    });
}

// |a & b| fused: the north-star IntersectionCount.
long long pt_count_and(const uint32_t* a, const uint32_t* b, long long n32) {
    return chunked_count(n32, [a, b](long long lo, long long hi) {
        return count_and_serial(a + lo, b + lo, hi - lo);
    });
}

// out[r] = popcount(mat[r]) over a [rows, n32] matrix.
void pt_row_counts(const uint32_t* mat, long long rows, long long n32,
                   int32_t* out) {
    parallel_chunks(rows, 1, n32, [=](long long lo, long long hi, int) {
        for (long long r = lo; r < hi; r++)
            out[r] = (int32_t)count_serial(mat + r * n32, n32);
    });
}

// out[r] = |a[r] & b[r]| — pairwise per-row intersection counts with no
// materialized intermediate (the Count(Intersect(Row,Row)) hot path on
// stacked shard operands).
void pt_row_counts_and(const uint32_t* a, const uint32_t* b,
                       long long rows, long long n32, int32_t* out) {
    parallel_chunks(rows, 1, n32, [=](long long lo, long long hi, int) {
        for (long long r = lo; r < hi; r++)
            out[r] = (int32_t)count_and_serial(a + r * n32, b + r * n32, n32);
    });
}

// out[r] = |mat[r] & filt| (TopN/GroupBy inner loop).
void pt_row_counts_masked(const uint32_t* mat, const uint32_t* filt,
                          long long rows, long long n32, int32_t* out) {
    parallel_chunks(rows, 1, n32, [=](long long lo, long long hi, int) {
        for (long long r = lo; r < hi; r++)
            out[r] = (int32_t)count_and_serial(mat + r * n32, filt, n32);
    });
}

// out[r] = |mat[r] & filt_stack[pos[r]]| (fused cross-shard TopN scan).
void pt_row_counts_gathered(const uint32_t* mat, const uint32_t* filt_stack,
                            const int32_t* pos, long long rows, long long n32,
                            int32_t* out) {
    parallel_chunks(rows, 1, n32, [=](long long lo, long long hi, int) {
        for (long long r = lo; r < hi; r++)
            out[r] = (int32_t)count_and_serial(
                mat + r * n32, filt_stack + (long long)pos[r] * n32, n32);
    });
}

// out[g*rows + r] = |mat[r] & masks[g]| (GroupBy cartesian product).
// Parallel over rows (not groups): every thread streams the same
// mat rows for all masks, so the split stays balanced when groups
// is small and rows is large (the common GroupBy shape).
void pt_masked_matrix_counts(const uint32_t* mat, const uint32_t* masks,
                             long long groups, long long rows, long long n32,
                             int32_t* out) {
    parallel_chunks(rows, 1, groups * n32,
                    [=](long long lo, long long hi, int) {
                        for (long long g = 0; g < groups; g++)
                            for (long long r = lo; r < hi; r++)
                                out[g * rows + r] = (int32_t)count_and_serial(
                                    mat + r * n32, masks + g * n32, n32);
                    });
}

// Sparse position-space merge: OR (clear=0) or ANDN (clear=1) sorted
// absolute bit positions into per-row bitmap buffers, returning the
// number of bits actually flipped.  One call per payload: row r's
// positions are pos[seg_start[r]..seg_end[r]) (absolute fragment
// positions; the in-row offset is pos & width_mask), applied to
// row_ptrs[r] (a u64 view of the row's packed words).  Start/end are
// separate so a clear that skips absent rows passes a sparse subset
// of segments.  Same-word positions are consecutive (pos sorted), so
// the inner loop accumulates a register mask per word run — one pass,
// no materialized per-word aggregates.  Parallel over rows (each
// row's buffer is touched by exactly one thread); the changed-bit
// total folds under a mutex at join.
long long pt_merge_positions(uint64_t* const* row_ptrs,
                             const long long* seg_start,
                             const long long* seg_end, long long n_rows,
                             const uint64_t* pos, uint64_t width_mask,
                             int clear) {
    long long total_pos = 0;
    for (long long r = 0; r < n_rows; r++)
        total_pos += seg_end[r] - seg_start[r];
    // Parallel over rows: each row's words live in exactly one
    // segment, so threads never touch the same buffer.  Fresh rows are
    // fault-bound (zero-fill-on-demand on first touch), which
    // parallelizes well — weight the thread gate by ~8 words touched
    // per position to reflect that.
    long long changed = 0;
    std::mutex mu;
    parallel_chunks(n_rows, 1, (total_pos / (n_rows ? n_rows : 1)) * 8 + 1,
                    [&](long long rlo, long long rhi, int) {
        long long local = 0;
        for (long long r = rlo; r < rhi; r++) {
            uint64_t* w = row_ptrs[r];
            long long i = seg_start[r];
            const long long end = seg_end[r];
            while (i < end) {
                // sparse payloads touch ~1 word per cache line; the
                // scattered read-modify-write is miss-bound, so pull
                // lines ~16 positions ahead while this one resolves
                if (i + 16 < end)
                    __builtin_prefetch(w + ((pos[i + 16] & width_mask) >> 6), 1);
                uint64_t off = pos[i] & width_mask;
                uint64_t widx = off >> 6;
                uint64_t mask = 1ULL << (off & 63);
                i++;
                while (i < end && ((pos[i] & width_mask) >> 6) == widx) {
                    mask |= 1ULL << (pos[i] & width_mask & 63);
                    i++;
                }
                uint64_t cur = w[widx];
                uint64_t delta = clear ? (cur & mask) : (mask & ~cur);
                if (delta) {
                    local += __builtin_popcountll(delta);
                    w[widx] = clear ? (cur & ~mask) : (cur | mask);
                }
            }
        }
        std::lock_guard<std::mutex> g(mu);
        changed += local;
    });
    return changed;
}

}  // extern "C"
