// Host-side fused popcount kernels for the CPU execution engine.
//
// On TPU the set-algebra hot path is XLA (ops/bitmap.py jit kernels);
// when the framework runs on a plain CPU host (relay down, laptop dev,
// CI) the same ops dispatch here instead: single-pass AND+popcount with
// no materialized intermediates, compiled -march=native so gcc lowers
// __builtin_popcountll to POPCNT / AVX-512 VPOPCNTDQ where available.
// This is the moral analog of the reference's hand-tuned container
// fast paths (roaring/roaring.go:570 intersectionCount*) — the exact
// counting loop a CPU should run, where XLA:CPU's generic codegen loses
// to vectorized popcount by ~8x at bench shapes.
//
// Buffers arrive as raw bytes from numpy uint32 arrays (C-contiguous,
// little-endian), processed as uint64 lanes with a uint32 tail — the
// same reinterpret-cast equivalence the file codec relies on
// (storage/roaring.py layout note).

#include <cstdint>

namespace {

// Alias- and alignment-safe 8-byte load: row pointers into a [rows, n32]
// uint32 matrix are only 4-byte aligned for odd n32 x odd row, and a
// uint32->uint64 pointer pun is UB regardless; __builtin_memcpy folds to
// a single unaligned vector load under -O3.
inline uint64_t load64(const uint32_t* p) {
    uint64_t v;
    __builtin_memcpy(&v, p, 8);
    return v;
}

}  // namespace

extern "C" {

// Popcount of one buffer of n32 uint32 words.
long long pt_count(const uint32_t* a, long long n32) {
    long long n64 = n32 / 2, t = 0;
    for (long long i = 0; i < n64; i++)
        t += __builtin_popcountll(load64(a + 2 * i));
    if (n32 & 1) t += __builtin_popcount(a[n32 - 1]);
    return t;
}

// |a & b| fused: the north-star IntersectionCount.
long long pt_count_and(const uint32_t* a, const uint32_t* b, long long n32) {
    long long n64 = n32 / 2, t = 0;
    for (long long i = 0; i < n64; i++)
        t += __builtin_popcountll(load64(a + 2 * i) & load64(b + 2 * i));
    if (n32 & 1) t += __builtin_popcount(a[n32 - 1] & b[n32 - 1]);
    return t;
}

// out[r] = popcount(mat[r]) over a [rows, n32] matrix.
void pt_row_counts(const uint32_t* mat, long long rows, long long n32,
                   int32_t* out) {
    for (long long r = 0; r < rows; r++)
        out[r] = (int32_t)pt_count(mat + r * n32, n32);
}

// out[r] = |a[r] & b[r]| — pairwise per-row intersection counts with no
// materialized intermediate (the Count(Intersect(Row,Row)) hot path on
// stacked shard operands).
void pt_row_counts_and(const uint32_t* a, const uint32_t* b,
                       long long rows, long long n32, int32_t* out) {
    for (long long r = 0; r < rows; r++)
        out[r] = (int32_t)pt_count_and(a + r * n32, b + r * n32, n32);
}

// out[r] = |mat[r] & filt| (TopN/GroupBy inner loop).
void pt_row_counts_masked(const uint32_t* mat, const uint32_t* filt,
                          long long rows, long long n32, int32_t* out) {
    for (long long r = 0; r < rows; r++)
        out[r] = (int32_t)pt_count_and(mat + r * n32, filt, n32);
}

// out[r] = |mat[r] & filt_stack[pos[r]]| (fused cross-shard TopN scan).
void pt_row_counts_gathered(const uint32_t* mat, const uint32_t* filt_stack,
                            const int32_t* pos, long long rows, long long n32,
                            int32_t* out) {
    for (long long r = 0; r < rows; r++)
        out[r] = (int32_t)pt_count_and(mat + r * n32,
                                       filt_stack + (long long)pos[r] * n32,
                                       n32);
}

// out[g*rows + r] = |mat[r] & masks[g]| (GroupBy cartesian product).
void pt_masked_matrix_counts(const uint32_t* mat, const uint32_t* masks,
                             long long groups, long long rows, long long n32,
                             int32_t* out) {
    for (long long g = 0; g < groups; g++)
        pt_row_counts_masked(mat, masks + g * n32, rows, n32,
                             out + g * rows);
}

}  // extern "C"
