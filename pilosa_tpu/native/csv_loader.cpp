// Fast bulk CSV parser for the import CLI (libcsvload).
//
// The reference's importer (ctl/import.go:173 bufferBits) reads CSV
// records of "row,col[,timestamp]" or "col,value", buffers millions of
// bits and ships them via the bulk import API.  Python's csv module is
// the bottleneck at that scale, so this parser handles the dominant
// all-integer two-column form natively: one pass over the byte buffer,
// no allocation, results written straight into caller-provided int64
// arrays (numpy buffers on the Python side).
//
// The native path NEVER judges validity: any record it cannot read —
// timestamps, quoting, non-integer syntax, 64-bit overflow — returns
// the fallback sentinel and the caller re-parses the chunk with the
// Python csv path, which remains the single semantics oracle.  A file
// therefore imports (or fails, with Python's full error detail)
// identically whether or not the native library is built.

#include <cstdint>

namespace {

inline bool is_digit(char c) { return c >= '0' && c <= '9'; }

// NOTE: '\r' is NOT whitespace — a CR may appear only as a trailing
// CRLF tail (trimmed per record below).  Skipping it mid-field would
// accept records the Python universal-newlines oracle rejects.
inline void skip_ws(const char *&p, const char *end) {
    while (p < end && (*p == ' ' || *p == '\t')) ++p;
}

// Parse a signed 64-bit integer; advances p past the digits.  Fails
// (-> fallback) on anything outside [-2^63+1, 2^63-1] rather than
// wrapping, so out-of-range ids reach Python's arbitrary-precision
// path instead of silently corrupting.
inline bool parse_ll(const char *&p, const char *end, long long &out) {
    bool neg = false;
    if (p < end && (*p == '-' || *p == '+')) {
        neg = (*p == '-');
        ++p;
    }
    if (p >= end || !is_digit(*p)) return false;
    unsigned long long v = 0;
    while (p < end && is_digit(*p)) {
        unsigned long long d = (unsigned long long)(*p - '0');
        if (v > (0x7FFFFFFFFFFFFFFFull - d) / 10ull) return false;
        v = v * 10ull + d;
        ++p;
    }
    out = neg ? -(long long)v : (long long)v;
    return true;
}

} // namespace

extern "C" {

// Parse "A,B" integer pairs, one record per line.  Blank lines are
// skipped.  A record may carry a trailing comma with an EXACTLY empty
// third field (the reference emits "row,col," for no-timestamp
// records); anything else after the second integer falls back.
//
// Returns the number of records parsed, or:
//   -2  a record needs the general path  (*err_line = 1-based line)
//   -3  cap exceeded                     (*err_line set)
long long csvload_parse2(const char *data, long long len,
                         long long *a, long long *b, long long cap,
                         long long *err_line) {
    const char *p = data;
    const char *end = data + len;
    long long n = 0, line = 0;
    while (p < end) {
        ++line;
        const char *eol = p;
        while (eol < end && *eol != '\n') ++eol;
        // trim ONE CRLF tail CR; further CRs fall back — Python's
        // universal newlines would count "\r\r\n" as two lines, so the
        // native path must not absorb them
        const char *eot = eol;
        if (eot > p && eot[-1] == '\r') --eot;
        const char *q = p;
        skip_ws(q, eot);
        if (q == eot) {
            p = eol + 1;
            continue;
        }
        long long va, vb;
        if (!parse_ll(q, eot, va)) { *err_line = line; return -2; }
        skip_ws(q, eot);
        if (q >= eot || *q != ',') { *err_line = line; return -2; }
        ++q;
        skip_ws(q, eot);
        if (!parse_ll(q, eot, vb)) { *err_line = line; return -2; }
        skip_ws(q, eot);
        if (q < eot) {
            // only an exactly-empty third field is the no-timestamp form
            if (*q != ',' || q + 1 != eot) { *err_line = line; return -2; }
        }
        if (n >= cap) { *err_line = line; return -3; }
        a[n] = va;
        b[n] = vb;
        ++n;
        p = eol + 1;
    }
    return n;
}

} // extern "C"
