// Native PQL parser (libpql): recursive-descent, mirroring the Python
// parser in pilosa_tpu/pql/parser.py token for token, which in turn
// accepts the reference's PEG grammar (pql/pql.peg).  SURVEY.md §7
// calls for a C++ parser exposed to both the server and clients so
// query parsing stays off Python in the request hot path.
//
// Output: a JSON AST string the Python side converts into Query/Call
// objects.  Numbers are emitted verbatim (arbitrary precision survives);
// conditions are {"$cond":{"op":..,"value":..}}, nested calls used as
// argument values are {"$call": <call>}.  Errors return
// {"error": "...", "pos": N}.
//
// C ABI:   char* pql_parse(const char* src);   void pql_free(char*);

#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <memory>
#include <string>
#include <vector>

namespace {

struct CallNode;

// ---- arbitrary-precision decimal helpers (conditional-sugar bounds
// must not saturate at 64 bits; the Python parser has bigints) ----

std::string dec_strip(const std::string& s) {
    // canonical integer text: strip leading zeros, normalize -0 -> 0
    bool neg = !s.empty() && s[0] == '-';
    size_t i = neg ? 1 : 0;
    while (i + 1 < s.size() && s[i] == '0') i++;
    std::string mag = s.substr(i);
    if (mag == "0") return "0";
    return neg ? "-" + mag : mag;
}

std::string mag_incr(std::string m) {
    int carry = 1;
    for (size_t i = m.size(); i-- > 0 && carry;) {
        if (m[i] == '9') { m[i] = '0'; } else { m[i]++; carry = 0; }
    }
    if (carry) m.insert(m.begin(), '1');
    return m;
}

std::string mag_decr(std::string m) {  // requires m > 0
    for (size_t i = m.size(); i-- > 0;) {
        if (m[i] == '0') { m[i] = '9'; } else { m[i]--; break; }
    }
    return dec_strip(m);
}

std::string int_incr(const std::string& s0) {
    std::string s = dec_strip(s0);
    if (s[0] == '-') {
        std::string r = mag_decr(s.substr(1));
        return r == "0" ? "0" : "-" + r;
    }
    return mag_incr(s);
}

std::string int_decr(const std::string& s0) {
    std::string s = dec_strip(s0);
    if (s[0] == '-') return "-" + mag_incr(s.substr(1));
    if (s == "0") return "-1";
    return mag_decr(s);
}

struct Value {
    enum Kind { NUL, BOOL_T, BOOL_F, NUMBER, STRING, LIST, COND, CALLV } kind = NUL;
    std::string text;                 // NUMBER: verbatim token; STRING: contents
    std::vector<Value> list;          // LIST
    std::string op;                   // COND
    std::unique_ptr<Value> cond_val;  // COND
    std::unique_ptr<CallNode> call;   // CALLV

    Value() = default;
    Value(Value&&) = default;
    Value& operator=(Value&&) = default;
};

struct Arg {
    std::string key;
    Value val;
};

struct CallNode {
    std::string name;
    std::vector<Arg> args;            // insertion order preserved
    std::vector<CallNode> children;

    void set(const std::string& key, Value v) {
        for (auto& a : args) {
            if (a.key == key) { a.val = std::move(v); return; }
        }
        args.push_back(Arg{key, std::move(v)});
    }
};

struct ParseErr {
    std::string message;
    size_t pos;
};

// Maximum call-nesting depth: parsing is recursive, and untrusted query
// strings must exhaust a counter, not the C stack (the Python parser
// enforces the same limit for parity).
constexpr int MAX_DEPTH = 128;

struct Parser {
    const std::string& src;
    size_t pos = 0;
    int depth = 0;

    explicit Parser(const std::string& s) : src(s) {}

    [[noreturn]] void fail(const std::string& msg) { throw ParseErr{msg, pos}; }

    char peek() const { return pos < src.size() ? src[pos] : '\0'; }
    char at(size_t i) const { return i < src.size() ? src[i] : '\0'; }

    void sp() {
        while (pos < src.size() &&
               (src[pos] == ' ' || src[pos] == '\t' || src[pos] == '\n'))
            pos++;
    }

    bool literal(const char* text) {
        size_t n = std::strlen(text);
        if (src.compare(pos, n, text) == 0) { pos += n; return true; }
        return false;
    }

    void expect(const char* text) {
        if (!literal(text)) fail(std::string("expected '") + text + "'");
    }

    bool comma() {
        size_t save = pos;
        sp();
        if (literal(",")) { sp(); return true; }
        pos = save;
        return false;
    }

    void open() { expect("("); sp(); }
    void close() { sp(); expect(")"); sp(); }

    // ------------------------------------------------------------- tokens

    static bool is_alpha(char c) {
        return (c >= 'A' && c <= 'Z') || (c >= 'a' && c <= 'z');
    }
    static bool is_digit(char c) { return c >= '0' && c <= '9'; }
    static bool is_alnum(char c) { return is_alpha(c) || is_digit(c); }

    // [A-Za-z_][A-Za-z0-9]* — the leading underscore admits the
    // executor's internal sentinel calls (_Empty/_Noop/_EmptyRows),
    // whose String() form must re-parse on remote scatter (mirrors
    // the Python parser's _IDENT_RE)
    bool ident(std::string& out) {
        if (!is_alpha(peek()) && peek() != '_') return false;
        size_t start = pos;
        pos++;
        while (is_alnum(peek())) pos++;
        out = src.substr(start, pos - start);
        return true;
    }

    // [A-Za-z][A-Za-z0-9_-]*
    bool field_token(std::string& out) {
        if (!is_alpha(peek())) return false;
        size_t start = pos;
        pos++;
        while (is_alnum(peek()) || peek() == '_' || peek() == '-') pos++;
        out = src.substr(start, pos - start);
        return true;
    }

    // [A-Za-z0-9:_-]+
    bool bare_string(std::string& out) {
        size_t start = pos;
        while (is_alnum(peek()) || peek() == ':' || peek() == '_' ||
               peek() == '-')
            pos++;
        if (pos == start) return false;
        out = src.substr(start, pos - start);
        return true;
    }

    // -?(\d+(\.\d*)?|\.\d+)  — verbatim text
    bool number(std::string& out) {
        size_t start = pos;
        size_t p = pos;
        if (at(p) == '-') p++;
        size_t digits = p;
        while (is_digit(at(p))) p++;
        if (p > digits) {               // \d+(\.\d*)?
            if (at(p) == '.') { p++; while (is_digit(at(p))) p++; }
        } else if (at(p) == '.') {      // \.\d+
            p++;
            size_t frac = p;
            while (is_digit(at(p))) p++;
            if (p == frac) { pos = start; return false; }
        } else {
            pos = start;
            return false;
        }
        out = src.substr(start, p - start);
        pos = p;
        return true;
    }

    bool uint_token(std::string& out) {
        size_t start = pos;
        while (is_digit(peek())) pos++;
        if (pos == start) return false;
        out = src.substr(start, pos - start);
        return true;
    }

    bool int_token(std::string& out) {
        size_t start = pos;
        if (peek() == '-') pos++;
        size_t digits = pos;
        while (is_digit(peek())) pos++;
        if (pos == digits) { pos = start; return false; }
        out = src.substr(start, pos - start);
        return true;
    }

    // \d{4}-[01]\d-[0-3]\dT\d\d:\d\d
    bool timestamp_token(std::string& out) {
        size_t p = pos;
        auto d = [&](size_t i) { return is_digit(at(i)); };
        if (!(d(p) && d(p + 1) && d(p + 2) && d(p + 3) && at(p + 4) == '-' &&
              (at(p + 5) == '0' || at(p + 5) == '1') && d(p + 6) &&
              at(p + 7) == '-' && at(p + 8) >= '0' && at(p + 8) <= '3' &&
              d(p + 9) && at(p + 10) == 'T' && d(p + 11) && d(p + 12) &&
              at(p + 13) == ':' && d(p + 14) && d(p + 15)))
            return false;
        out = src.substr(p, 16);
        pos = p + 16;
        return true;
    }

    // --------------------------------------------------------------- strings

    bool quoted_string(std::string& out) {
        char q = peek();
        if (q != '\'' && q != '"') return false;
        pos++;
        out.clear();
        while (true) {
            char c = peek();
            if (c == '\0') fail("unterminated string");
            if (c == '\\' && pos + 1 < src.size() &&
                (src[pos + 1] == q || src[pos + 1] == '\\')) {
                out.push_back(src[pos + 1]);
                pos += 2;
                continue;
            }
            if (c == q) { pos++; return true; }
            out.push_back(c);
            pos++;
        }
    }

    // bare or quoted timestamp
    bool timestamp_fmt(std::string& out) {
        size_t save = pos;
        char q = peek();
        if (q == '\'' || q == '"') {
            pos++;
            if (timestamp_token(out)) {
                if (peek() == q) { pos++; return true; }
            }
            pos = save;
            return false;
        }
        if (timestamp_token(out)) return true;
        pos = save;
        return false;
    }

    // ---------------------------------------------------------------- values

    bool at_rbrack() {
        size_t save = pos;
        sp();
        bool at_it = peek() == ']';
        pos = save;
        return at_it;
    }

    bool keyword_guard_ok() {
        size_t save = pos;
        sp();
        bool ok = peek() == ',' || peek() == ')';
        pos = save;
        return ok;
    }

    Value value() {
        if (literal("[")) {
            sp();
            Value v;
            v.kind = Value::LIST;
            if (!at_rbrack()) {
                v.list.push_back(item());
                while (comma()) v.list.push_back(item());
            }
            sp();
            expect("]");
            sp();
            return v;
        }
        return item();
    }

    Value item() {
        static const struct { const char* kw; Value::Kind kind; } kws[] = {
            {"null", Value::NUL}, {"true", Value::BOOL_T},
            {"false", Value::BOOL_F}};
        for (auto& k : kws) {
            size_t save = pos;
            if (literal(k.kw)) {
                if (keyword_guard_ok()) {
                    Value v;
                    v.kind = k.kind;
                    return v;
                }
                pos = save;
            }
        }
        {
            std::string ts;
            if (timestamp_fmt(ts)) {
                Value v;
                v.kind = Value::STRING;
                v.text = std::move(ts);
                return v;
            }
        }
        {
            size_t save = pos;
            std::string num;
            if (number(num)) {
                char c = peek();
                if (!(is_alnum(c) || c == '_' || c == ':' || c == '-')) {
                    Value v;
                    v.kind = Value::NUMBER;
                    v.text = std::move(num);
                    return v;
                }
                pos = save;
            }
        }
        {
            size_t save = pos;
            std::string id;
            if (ident(id)) {
                sp();
                if (peek() == '(') {
                    pos = save;
                    Value v;
                    v.kind = Value::CALLV;
                    v.call = std::make_unique<CallNode>(call());
                    return v;
                }
                pos = save;
            }
        }
        {
            std::string bare;
            if (bare_string(bare)) {
                Value v;
                v.kind = Value::STRING;
                v.text = std::move(bare);
                return v;
            }
        }
        {
            std::string s;
            if (quoted_string(s)) {
                Value v;
                v.kind = Value::STRING;
                v.text = std::move(s);
                return v;
            }
        }
        fail("expected value");
    }

    // ------------------------------------------------------------------ args

    std::string field_name() {
        std::string name;
        if (field_token(name)) return name;
        static const char* reserved[] = {"_row", "_col", "_start", "_end",
                                         "_timestamp", "_field"};
        for (auto* r : reserved)
            if (literal(r)) return r;
        fail("expected field name");
    }

    bool cond_op(std::string& out) {
        static const char* ops[] = {"><", "<=", ">=", "==", "!=", "<", ">"};
        for (auto* op : ops)
            if (literal(op)) { out = op; return true; }
        return false;
    }

    void arg_into(CallNode& call_node) {
        // conditional sugar: int <[=] field <[=] int
        if (is_digit(peek()) ||
            (peek() == '-' && is_digit(at(pos + 1)))) {
            std::string low_s;
            if (!int_token(low_s)) fail("expected integer");
            sp();
            bool op1_le = literal("<=");
            bool op1_lt = !op1_le && literal("<");
            if (!op1_le && !op1_lt) fail("expected < or <= in conditional");
            sp();
            std::string field = field_name();
            sp();
            bool op2_le = literal("<=");
            bool op2_lt = !op2_le && literal("<");
            if (!op2_le && !op2_lt) fail("expected < or <= in conditional");
            sp();
            std::string high_s;
            if (!int_token(high_s)) fail("expected integer");
            // strict bounds tighten by one (pql/ast.go:89-95) — in
            // decimal string space so >64-bit bounds survive exactly
            std::string low = op1_lt ? int_incr(low_s) : dec_strip(low_s);
            std::string high = op2_lt ? int_decr(high_s) : dec_strip(high_s);
            Value cond;
            cond.kind = Value::COND;
            cond.op = "><";
            cond.cond_val = std::make_unique<Value>();
            cond.cond_val->kind = Value::LIST;
            Value lo_v; lo_v.kind = Value::NUMBER; lo_v.text = low;
            Value hi_v; hi_v.kind = Value::NUMBER; hi_v.text = high;
            cond.cond_val->list.push_back(std::move(lo_v));
            cond.cond_val->list.push_back(std::move(hi_v));
            call_node.set(field, std::move(cond));
            return;
        }
        std::string field = field_name();
        sp();
        std::string op;
        if (cond_op(op)) {
            sp();
            Value cond;
            cond.kind = Value::COND;
            cond.op = op;
            cond.cond_val = std::make_unique<Value>(value());
            call_node.set(field, std::move(cond));
            return;
        }
        if (literal("=")) {
            sp();
            call_node.set(field, value());
            return;
        }
        fail("expected = or condition operator after '" + field + "'");
    }

    void args_into(CallNode& call_node) {
        arg_into(call_node);
        while (true) {
            size_t save = pos;
            if (!comma()) return;
            try {
                arg_into(call_node);
            } catch (const ParseErr&) {
                pos = save;
                return;
            }
        }
    }

    // ----------------------------------------------------------------- calls

    void pos_uint_or_str(const char* key, CallNode& call_node) {
        std::string num;
        if (uint_token(num)) {
            Value v;
            v.kind = Value::NUMBER;
            v.text = std::move(num);
            call_node.set(key, std::move(v));
            return;
        }
        std::string s;
        if (quoted_string(s)) {
            Value v;
            v.kind = Value::STRING;
            v.text = std::move(s);
            call_node.set(key, std::move(v));
            return;
        }
        fail(std::string("expected integer or quoted key for ") + key);
    }

    struct DepthGuard {  // RAII: depth unwinds on backtracking throws too
        int& d;
        explicit DepthGuard(int& d_) : d(d_) { ++d; }
        ~DepthGuard() { --d; }
    };

    CallNode call() {
        DepthGuard g(depth);
        if (depth > MAX_DEPTH) fail("query too deeply nested");
        return call_inner();
    }

    CallNode call_inner() {
        std::string name;
        if (!ident(name)) fail("expected call name");
        sp();
        size_t save = pos;
        try {
            if (name == "Set") return call_Set();
            if (name == "SetRowAttrs") return call_SetRowAttrs();
            if (name == "SetColumnAttrs") return call_SetColumnAttrs();
            if (name == "Clear") return call_Clear();
            if (name == "ClearRow") return call_ClearRow();
            if (name == "Store") return call_Store();
            if (name == "TopN") return posfield_call("TopN");
            if (name == "Rows") return posfield_call("Rows");
            if (name == "Range") return call_Range();
        } catch (const ParseErr&) {
            // PEG ordered choice: special form fails -> generic rule
            pos = save;
        }
        return generic_call(name);
    }

    CallNode generic_call(const std::string& name) {
        CallNode c;
        c.name = name;
        open();
        allargs_into(c);
        comma();  // tolerate trailing comma
        close();
        return c;
    }

    CallNode call_Set() {
        CallNode c;
        c.name = "Set";
        open();
        pos_uint_or_str("_col", c);
        if (!comma()) fail("expected ,");
        args_into(c);
        size_t save = pos;
        if (comma()) {
            std::string ts;
            if (timestamp_fmt(ts)) {
                Value v;
                v.kind = Value::STRING;
                v.text = std::move(ts);
                c.set("_timestamp", std::move(v));
            } else {
                pos = save;
            }
        }
        close();
        return c;
    }

    CallNode call_SetRowAttrs() {
        CallNode c;
        c.name = "SetRowAttrs";
        open();
        {
            Value v;
            v.kind = Value::STRING;
            v.text = field_name();
            c.set("_field", std::move(v));
        }
        if (!comma()) fail("expected ,");
        pos_uint_or_str("_row", c);
        if (!comma()) fail("expected ,");
        args_into(c);
        close();
        return c;
    }

    CallNode call_SetColumnAttrs() {
        CallNode c;
        c.name = "SetColumnAttrs";
        open();
        pos_uint_or_str("_col", c);
        if (!comma()) fail("expected ,");
        args_into(c);
        close();
        return c;
    }

    CallNode call_Clear() {
        CallNode c;
        c.name = "Clear";
        open();
        pos_uint_or_str("_col", c);
        if (!comma()) fail("expected ,");
        args_into(c);
        close();
        return c;
    }

    CallNode call_ClearRow() {
        CallNode c;
        c.name = "ClearRow";
        open();
        arg_into(c);
        close();
        return c;
    }

    CallNode call_Store() {
        CallNode c;
        c.name = "Store";
        open();
        c.children.push_back(call());
        if (!comma()) fail("expected ,");
        arg_into(c);
        close();
        return c;
    }

    CallNode posfield_call(const char* name) {
        CallNode c;
        c.name = name;
        open();
        std::string fe;
        if (!field_token(fe)) fail("expected field name");
        {
            Value v;
            v.kind = Value::STRING;
            v.text = std::move(fe);
            c.set("_field", std::move(v));
        }
        if (comma()) allargs_into(c);
        close();
        return c;
    }

    CallNode call_Range() {
        CallNode c;
        c.name = "Range";
        open();
        std::string field = field_name();
        sp();
        expect("=");
        sp();
        c.set(field, value());
        if (!comma()) fail("expected ,");
        literal("from=");
        std::string ts;
        if (!timestamp_fmt(ts)) fail("expected timestamp");
        {
            Value v;
            v.kind = Value::STRING;
            v.text = std::move(ts);
            c.set("from", std::move(v));
        }
        if (!comma()) fail("expected ,");
        literal("to=");
        sp();
        std::string ts2;
        if (!timestamp_fmt(ts2)) fail("expected timestamp");
        {
            Value v;
            v.kind = Value::STRING;
            v.text = std::move(ts2);
            c.set("to", std::move(v));
        }
        close();
        return c;
    }

    void allargs_into(CallNode& c) {
        while (true) {
            size_t save = pos;
            std::string id;
            if (ident(id)) {
                sp();
                if (peek() == '(') {
                    pos = save;
                    c.children.push_back(call());
                    if (comma()) continue;
                    return;
                }
            }
            pos = save;
            break;
        }
        size_t save = pos;
        sp();
        if (peek() != ')') {
            pos = save;
            args_into(c);
        }
    }

    std::vector<CallNode> parse() {
        std::vector<CallNode> calls;
        sp();
        while (pos < src.size()) {
            calls.push_back(call());
            sp();
        }
        return calls;
    }
};

// ------------------------------------------------------------- JSON output

void json_escape(const std::string& s, std::string& out) {
    out.push_back('"');
    for (unsigned char c : s) {
        switch (c) {
            case '"': out += "\\\""; break;
            case '\\': out += "\\\\"; break;
            case '\n': out += "\\n"; break;
            case '\t': out += "\\t"; break;
            case '\r': out += "\\r"; break;
            default:
                if (c < 0x20) {
                    char buf[8];
                    std::snprintf(buf, sizeof buf, "\\u%04x", c);
                    out += buf;
                } else {
                    out.push_back(static_cast<char>(c));
                }
        }
    }
    out.push_back('"');
}

void emit_call(const CallNode& c, std::string& out);

void emit_value(const Value& v, std::string& out) {
    switch (v.kind) {
        case Value::NUL: out += "null"; break;
        case Value::BOOL_T: out += "true"; break;
        case Value::BOOL_F: out += "false"; break;
        case Value::NUMBER: {
            // normalize to valid JSON: PQL allows ".5", "1.", and
            // leading zeros ("007"), none of which JSON accepts
            std::string t = v.text;
            bool neg = t[0] == '-';
            std::string body = neg ? t.substr(1) : t;
            size_t dot = body.find('.');
            std::string ip = dot == std::string::npos ? body : body.substr(0, dot);
            std::string fp = dot == std::string::npos ? "" : body.substr(dot + 1);
            size_t i = 0;
            while (i + 1 < ip.size() && ip[i] == '0') i++;
            ip = ip.empty() ? "0" : ip.substr(i);
            if (ip.empty()) ip = "0";
            std::string norm = (neg ? "-" : "") + ip;
            if (dot != std::string::npos)
                norm += "." + (fp.empty() ? "0" : fp);
            out += norm;
            break;
        }
        case Value::STRING: json_escape(v.text, out); break;
        case Value::LIST: {
            out.push_back('[');
            for (size_t i = 0; i < v.list.size(); i++) {
                if (i) out.push_back(',');
                emit_value(v.list[i], out);
            }
            out.push_back(']');
            break;
        }
        case Value::COND: {
            out += "{\"$cond\":{\"op\":";
            json_escape(v.op, out);
            out += ",\"value\":";
            emit_value(*v.cond_val, out);
            out += "}}";
            break;
        }
        case Value::CALLV: {
            out += "{\"$call\":";
            emit_call(*v.call, out);
            out.push_back('}');
            break;
        }
    }
}

void emit_call(const CallNode& c, std::string& out) {
    out += "{\"name\":";
    json_escape(c.name, out);
    out += ",\"args\":{";
    for (size_t i = 0; i < c.args.size(); i++) {
        if (i) out.push_back(',');
        json_escape(c.args[i].key, out);
        out.push_back(':');
        emit_value(c.args[i].val, out);
    }
    out += "},\"children\":[";
    for (size_t i = 0; i < c.children.size(); i++) {
        if (i) out.push_back(',');
        emit_call(c.children[i], out);
    }
    out += "]}";
}

}  // namespace

extern "C" {

char* pql_parse(const char* src_c) {
    std::string src(src_c ? src_c : "");
    std::string out;
    try {
        Parser p(src);
        std::vector<CallNode> calls = p.parse();
        out += "{\"calls\":[";
        for (size_t i = 0; i < calls.size(); i++) {
            if (i) out.push_back(',');
            emit_call(calls[i], out);
        }
        out += "]}";
    } catch (const ParseErr& e) {
        out = "{\"error\":";
        json_escape(e.message, out);
        out += ",\"pos\":" + std::to_string(e.pos) + "}";
    } catch (const std::exception& e) {
        out = "{\"error\":";
        json_escape(std::string("internal: ") + e.what(), out);
        out += ",\"pos\":0}";
    }
    char* buf = static_cast<char*>(std::malloc(out.size() + 1));
    std::memcpy(buf, out.c_str(), out.size() + 1);
    return buf;
}

void pql_free(char* p) { std::free(p); }

}  // extern "C"
