"""Background delta compaction: merges pending delta planes into base
roaring state without ever blocking a writer.

Policy lives here, mechanism in ``Fragment.flush_delta``: a fragment
registers on its first delta write (``note_delta``), and the
compactor's scan thread merges it once the delta crosses the size
threshold ([ingest] compact-threshold-bits), exceeds one scan interval
in age ([ingest] compact-interval — trickle writes never pend
forever), or the process-wide pending-byte budget is exceeded ([ingest]
delta-budget-bytes; past it the WRITER flushes its own fragment inline,
the same backpressure shape as snapqueue's inline overflow).

The scan runs under admission's ``internal`` class when a controller is
wired (Server assembly): each round acquires one internal ticket with a
one-interval deadline, so compaction yields to user queries exactly the
way anti-entropy does — saturating query traffic PAUSES compaction
(counted in ``ingest.compact_skipped``) rather than competing with it.
``pause()``/``resume()`` give operators/tests a hard switch.

Lock order is fragment -> compactor everywhere: ``note_delta`` /
``note_flushed`` run under the fragment lock and take the registry lock
inside; the scan thread snapshots the registry under its own lock,
RELEASES, then calls ``flush_delta`` (which takes fragment -> registry)
— no cycle.

Stats families (``ingest.*``, published at /metrics + /debug/vars
scrape time like cache.*): delta_writes, delta_bits, delta_rows,
delta_bytes (pending gauges), fragments_pending, compactions,
compacted_bits, inline_flushes, compact_skipped.  Debug surface:
``GET /debug/ingest``.
"""

from __future__ import annotations

import threading
import time
import weakref

from pilosa_tpu import ingest as _ingest
from pilosa_tpu.serve.deadline import Deadline


class Compactor:
    """Process-wide delta-compaction policy + scan thread."""

    def __init__(self):
        from pilosa_tpu import lockcheck

        self._lock = lockcheck.lock("compactor")
        #: id(frag) -> (weakref, last-known pending bytes)
        self._frags: dict[int, tuple] = {}
        self._pending_bytes = 0
        self.admission = None  # serve.admission.AdmissionController
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._paused = False
        self.compactions = 0
        self.compacted_bits = 0
        self.inline_flushes = 0
        self.compact_skipped = 0
        self.delta_writes = 0

    # -------------------------------------------------- fragment callbacks

    def note_delta(self, frag) -> bool:
        """A delta write landed on ``frag`` (caller holds the fragment
        lock).  Registers the fragment and returns True when the
        process-wide pending-byte budget is exceeded — the caller then
        flushes ITS OWN fragment inline (bounded memory; the writer
        pays, queued readers don't)."""
        # pilosa-lint: allow(lock-discipline) -- caller holds the fragment lock (documented contract above); lock order fragment -> compactor forbids taking it here
        d = frag._delta
        nbytes = 0 if d is None else d.nbytes
        budget = _ingest.config().delta_budget_bytes
        with self._lock:
            self.delta_writes += 1
            fid = id(frag)
            prev = self._frags.get(fid)
            self._pending_bytes += nbytes - (prev[1] if prev else 0)
            self._frags[fid] = (weakref.ref(frag), nbytes)
            return self._pending_bytes > budget > 0

    def note_flushed(self, frag, bits: int, inline: bool = False) -> None:
        """``frag`` merged its delta (caller holds the fragment lock)."""
        with self._lock:
            prev = self._frags.pop(id(frag), None)
            if prev is not None:
                self._pending_bytes -= prev[1]
                if self._pending_bytes < 0:
                    self._pending_bytes = 0
            # cumulative counters exposed as gauges at scrape time
            # (publish_gauges) — never ALSO pushed as counts, which
            # would render a second TYPE line for the same family and
            # fail the strict exposition parser
            self.compactions += 1
            self.compacted_bits += bits
            if inline:
                self.inline_flushes += 1

    def forget(self, frag) -> None:
        """Drop a closing fragment from the registry (Fragment.close);
        its WAL carries the pending bits durably."""
        with self._lock:
            prev = self._frags.pop(id(frag), None)
            if prev is not None:
                self._pending_bytes -= prev[1]
                if self._pending_bytes < 0:
                    self._pending_bytes = 0

    # ------------------------------------------------------------- policy

    def _due(self, frag, cfg) -> bool:
        # pilosa-lint: allow(lock-discipline) -- deliberately racy policy read: a stale size/age only defers the merge one scan; flush_delta re-checks under the fragment lock
        d = frag._delta
        if d is None or d.empty():
            return True  # flush_delta no-ops; dereg happens in run_once
        return (d.bits >= cfg.compact_threshold_bits
                or d.age_s() >= cfg.compact_interval
                or d.nbytes > cfg.delta_budget_bytes)

    def run_once(self, force: bool = False) -> int:
        """One scan: merge every due (or, with ``force``, every
        pending) delta.  Returns the number of fragments flushed.
        Tests call this directly for determinism; the thread calls it
        per interval."""
        cfg = _ingest.config()
        with self._lock:
            if self._paused and not force:
                return 0
            snapshot = [(fid, ref) for fid, (ref, _) in
                        self._frags.items()]
        flushed = 0
        for fid, ref in snapshot:
            frag = ref()
            if frag is None:
                with self._lock:
                    prev = self._frags.pop(fid, None)
                    if prev is not None:
                        self._pending_bytes -= prev[1]
                continue
            if force or self._due(frag, cfg):
                from pilosa_tpu import faultinject as _fi

                if _fi.armed:
                    # failpoint: the production delta-merge path (an
                    # injected error aborts this scan; pending deltas
                    # stay WAL-durable and merge on the next one)
                    _fi.hit("compactor.merge")
                # flush_delta takes fragment -> registry (note_flushed);
                # no compactor lock is held here
                if frag.flush_delta() == 0:
                    # already empty (raced a read-side flush): deregister
                    # — but only while the delta is STILL empty under the
                    # fragment lock.  A writer landing between
                    # flush_delta's return and an unconditional forget()
                    # re-registers the fragment (note_delta), and popping
                    # that fresh entry would hide its pending delta from
                    # every future scan until another write happened by.
                    # Holding frag._lock across check+forget excludes
                    # note_delta (writers hold the same lock); order is
                    # fragment -> registry, same as note_delta itself.
                    with frag._lock:
                        d = frag._delta
                        if d is None or d.empty():
                            self.forget(frag)
                else:
                    flushed += 1
        if flushed:
            from pilosa_tpu import observe as _observe

            if _observe.journal_on:
                _observe.emit("compaction.run", flushed=flushed,
                              forced=bool(force))
        return flushed

    # ------------------------------------------------------------- thread

    def start(self) -> None:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop = threading.Event()
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True,
                                            name="ingest-compactor")
            self._thread.start()

    def stop(self) -> None:
        with self._lock:
            thread = self._thread
            self._thread = None
        self._stop.set()
        if thread is not None:
            thread.join(timeout=5)

    def pause(self) -> None:
        with self._lock:
            self._paused = True

    def resume(self) -> None:
        with self._lock:
            self._paused = False

    def _loop(self) -> None:
        while not self._stop.wait(_ingest.config().compact_interval):
            try:
                self._run_gated()
            except Exception:  # noqa: BLE001 — scan must never die; the
                pass  # next interval retries (WAL holds durability)

    def _run_gated(self) -> None:
        """One scan under the internal admission class: shed by the
        gate (query pressure / saturation) means SKIP this round — the
        deltas stay pending and the next interval retries."""
        adm = self.admission
        if adm is None or not getattr(adm, "enabled", False):
            self.run_once()
            return
        from pilosa_tpu.serve.admission import ShedError

        try:
            ticket = adm.acquire(
                "internal", Deadline(_ingest.config().compact_interval))
        except ShedError:
            with self._lock:
                self.compact_skipped += 1
            return
        try:
            self.run_once()
        finally:
            ticket.release()

    # -------------------------------------------------------------- views

    def pending(self) -> list[tuple]:
        """Live (fragment, delta-stats) pairs, largest pending first."""
        with self._lock:
            refs = [ref for ref, _ in self._frags.values()]
        out = []
        for ref in refs:
            frag = ref()
            if frag is None:
                continue
            with frag._lock:
                d = frag._delta
                if d is None or d.empty():
                    continue
                out.append((frag, d.stats()))
        out.sort(key=lambda fs: -fs[1]["bits"])
        return out

    def totals(self, pend: list[tuple] | None = None) -> dict:
        """Aggregate view; pass a precomputed ``pending()`` snapshot to
        avoid a second per-fragment lock sweep (debug() does)."""
        if pend is None:
            pend = self.pending()
        with self._lock:
            return {
                "fragmentsPending": len(pend),
                "pendingBits": sum(s["bits"] for _, s in pend),
                "pendingRows": sum(s["rows"] for _, s in pend),
                "pendingBytes": sum(s["bytes"] for _, s in pend),
                "deltaWrites": self.delta_writes,
                "compactions": self.compactions,
                "compactedBits": self.compacted_bits,
                "inlineFlushes": self.inline_flushes,
                "compactSkipped": self.compact_skipped,
                "paused": self._paused,
                "running": (self._thread is not None
                            and self._thread.is_alive()),
            }

    def debug(self, top_n: int = 32) -> dict:
        """The /debug/ingest document: config, totals, and the largest
        pending deltas (fragment identity + size/age)."""
        cfg = _ingest.config()
        out = {
            "config": {
                "deltaEnabled": cfg.delta_enabled,
                "deltaBudgetBytes": cfg.delta_budget_bytes,
                "compactThresholdBits": cfg.compact_threshold_bits,
                "compactInterval": cfg.compact_interval,
            },
        }
        pend = self.pending()
        out.update(self.totals(pend))
        out["top"] = [{
            "index": frag.index, "field": frag.field,
            "view": frag.view, "shard": frag.shard,
            "deltaSeq": frag._delta_seq, **s,
        } for frag, s in pend[:top_n]]
        return out

    def publish_gauges(self, stats) -> None:
        """Push the ingest.* families into a stats registry at scrape
        time (/metrics, /debug/vars) — cumulative totals as gauges,
        same rule as resultcache.publish_gauges."""
        t = self.totals()
        stats.gauge("ingest.delta_writes", t["deltaWrites"])
        stats.gauge("ingest.delta_bits", t["pendingBits"])
        stats.gauge("ingest.delta_rows", t["pendingRows"])
        stats.gauge("ingest.delta_bytes", t["pendingBytes"])
        stats.gauge("ingest.fragments_pending", t["fragmentsPending"])
        stats.gauge("ingest.compactions", t["compactions"])
        stats.gauge("ingest.compacted_bits", t["compactedBits"])
        stats.gauge("ingest.inline_flushes", t["inlineFlushes"])
        stats.gauge("ingest.compact_skipped", t["compactSkipped"])


# ----------------------------------------------------------- process-wide


_global: Compactor | None = None
_global_lock = threading.Lock()


def compactor() -> Compactor:
    """The process-wide compactor (one per process, like the snapshot
    queue the design mirrors)."""
    global _global
    c = _global
    if c is not None:
        return c
    with _global_lock:
        if _global is None:
            _global = Compactor()
        return _global


def reset() -> Compactor:
    """Replace the process-wide compactor (tests)."""
    global _global, _refs
    with _global_lock:
        if _global is not None:
            _global.stop()
        _global = Compactor()
        _refs = 0
        return _global


# The scan thread and the [ingest] config are process-wide but servers
# open and close independently (in-process clusters, embedders):
# reference-count the ingest-enabled servers so an early closer cannot
# stop the thread — or restore the config — out from under a still-open
# one.

_refs = 0


def retain() -> Compactor:
    """One more open ingest-enabled server: start (or keep) the shared
    scan thread."""
    global _refs
    with _global_lock:
        _refs += 1
    c = compactor()
    c.start()
    return c


def release() -> bool:
    """Drop one reference.  Stops the shared thread and returns True
    only when this was the LAST open ingest-enabled server — the
    caller may then restore the process-wide [ingest] config."""
    global _refs
    with _global_lock:
        _refs = max(0, _refs - 1)
        last = _refs == 0
    if last:
        compactor().stop()
    return last


def refs() -> int:
    return _refs
