"""Per-fragment delta plane: the bounded in-memory landing zone for
streaming writes.

One ``DeltaPlane`` holds two packed-word overlays per touched row —
**set-bits** and **clear-bits** — in the same uint32 word layout as the
fragment's base rows, so the effective content of a row is

    effective = (base & ~clear) | set

exactly the fusion the read side evaluates (``ops/expr.py`` ``dfuse``
node on device; ``Fragment.row``/``bit`` host overlays).  The two
planes are kept DISJOINT per row (a later set removes the bit from the
clear plane and vice versa), so within one plane application order
cannot matter and double-application is idempotent — the property that
makes the executor's delta-stacks-then-base staging order safe under a
concurrent compaction (re-applying an already-merged delta reproduces
the same effective words).

The plane is deliberately dumb: no locking (the owning fragment's lock
guards every access), no WAL (the fragment appends the same records the
base path would at write time), no thresholds (the compactor owns
policy).  It only tracks what policy needs: pending bit-position count,
allocated bytes, per-row and whole-plane monotone write sequence, and
its creation time (the age trigger).
"""

from __future__ import annotations

import time

import numpy as np


class DeltaPlane:
    """Pending set/clear overlays for one fragment.  Caller holds the
    fragment lock for every method."""

    __slots__ = ("n_words", "width_shift", "sets", "clears", "row_seq",
                 "bits", "created_t", "last_write_t")

    def __init__(self, n_words: int, width: int):
        self.n_words = n_words
        self.width_shift = width.bit_length() - 1
        self.sets: dict[int, np.ndarray] = {}
        self.clears: dict[int, np.ndarray] = {}
        #: row -> fragment _delta_seq at last write touching it (the
        #: per-row invalidation token for the executor's delta stacks)
        self.row_seq: dict[int, int] = {}
        #: pending bit POSITIONS absorbed (not exact flips — the
        #: compaction-threshold currency, like the reference's opN)
        self.bits = 0
        self.created_t = time.monotonic()
        self.last_write_t = self.created_t

    # ------------------------------------------------------------- state

    def empty(self) -> bool:
        return self.bits == 0 and not self.sets and not self.clears

    @property
    def nbytes(self) -> int:
        return 4 * self.n_words * (len(self.sets) + len(self.clears))

    def touched_rows(self):
        return self.row_seq.keys()

    def row_touched(self, row: int) -> bool:
        return row in self.row_seq

    def age_s(self) -> float:
        return time.monotonic() - self.created_t

    def stats(self) -> dict:
        return {
            "bits": self.bits,
            "rows": len(self.row_seq),
            "bytes": self.nbytes,
            "ageS": round(self.age_s(), 3),
        }

    # ------------------------------------------------------------ writes

    def _plane_row(self, plane: dict, row: int) -> np.ndarray:
        arr = plane.get(row)
        if arr is None:
            arr = np.zeros(self.n_words, dtype=np.uint32)
            plane[row] = arr
        return arr

    def add_bit(self, row: int, off: int, clear: bool, seq: int) -> None:
        w = off >> 5
        m = np.uint32(1) << np.uint32(off & 31)
        tgt = self._plane_row(self.clears if clear else self.sets, row)
        tgt[w] |= m
        other = (self.sets if clear else self.clears).get(row)
        if other is not None:
            other[w] &= ~m
        self.row_seq[row] = seq
        self.bits += 1
        self.last_write_t = time.monotonic()

    def add_positions(self, pos: np.ndarray, clear: bool,
                      seq: int) -> None:
        """Absorb absolute fragment positions (pos = row*width + off),
        sorted or not; duplicates are harmless (OR/ANDN idempotent)."""
        if len(pos) == 0:
            return
        pos = np.asarray(pos, dtype=np.uint64)
        row_of = (pos >> np.uint64(self.width_shift)).astype(np.int64)
        offs = pos & np.uint64((1 << self.width_shift) - 1)
        words = (offs >> np.uint64(5)).astype(np.int64)
        masks = (np.uint32(1)
                 << (offs & np.uint64(31)).astype(np.uint32))
        tgt_plane = self.clears if clear else self.sets
        other_plane = self.sets if clear else self.clears
        # group by row with ONE sort, not one full-array mask per
        # unique row — this runs under the fragment lock, and an
        # import near the roaring cap spanning thousands of rows would
        # otherwise cost rows x positions comparisons while readers
        # wait on the lock
        order = np.argsort(row_of, kind="stable")
        row_s, words_s, masks_s = row_of[order], words[order], masks[order]
        bounds = np.flatnonzero(np.diff(row_s)) + 1
        starts = np.concatenate(([0], bounds))
        ends = np.concatenate((bounds, [len(row_s)]))
        for i in range(len(starts)):
            r = int(row_s[starts[i]])
            w = words_s[starts[i]:ends[i]]
            m = masks_s[starts[i]:ends[i]]
            tgt = self._plane_row(tgt_plane, r)
            # .at: duplicate word slots must accumulate, not last-write
            np.bitwise_or.at(tgt, w, m)
            other = other_plane.get(r)
            if other is not None:
                np.bitwise_and.at(other, w, ~m)
            self.row_seq[r] = seq
        self.bits += len(pos)
        self.last_write_t = time.monotonic()

    # ------------------------------------------------------------- reads

    def override(self, row: int, off: int):
        """Effective-bit override for one position: True (pending set),
        False (pending clear), or None (base decides)."""
        w, m = off >> 5, np.uint32(1) << np.uint32(off & 31)
        arr = self.sets.get(row)
        if arr is not None and arr[w] & m:
            return True
        arr = self.clears.get(row)
        if arr is not None and arr[w] & m:
            return False
        return None

    def apply_row(self, row: int, arr: np.ndarray) -> None:
        """In-place overlay: arr = (arr & ~clear) | set."""
        c = self.clears.get(row)
        if c is not None:
            np.bitwise_and(arr, ~c, out=arr)
        s = self.sets.get(row)
        if s is not None:
            np.bitwise_or(arr, s, out=arr)

    def row_any(self, row: int, base: np.ndarray | None) -> bool:
        """Whether the EFFECTIVE row has any set bit, without
        materializing the overlay when the answer is cheap."""
        s = self.sets.get(row)
        if s is not None and s.any():
            return True
        if base is None or not base.any():
            return False
        c = self.clears.get(row)
        if c is None:
            return True  # base non-empty, nothing cleared
        return bool(np.bitwise_and(base, ~c).any())

    def check(self) -> None:
        """Structural invariants (Fragment.check extension): correct
        dtype/shape, and the set/clear planes disjoint per row."""
        for name, plane in (("set", self.sets), ("clear", self.clears)):
            for row, arr in plane.items():
                if not isinstance(row, int) or row < 0:
                    raise ValueError(f"delta {name} row id {row!r}")
                if arr.dtype != np.uint32 or arr.shape != (self.n_words,):
                    raise ValueError(
                        f"delta {name} row {row}: bad words "
                        f"{arr.dtype}{arr.shape}")
        for row, s in self.sets.items():
            c = self.clears.get(row)
            if c is not None and bool(np.bitwise_and(s, c).any()):
                raise ValueError(
                    f"delta row {row}: set and clear planes overlap")
