"""Streaming ingest subsystem: device-side delta planes with
background compaction.

Every prior round optimized reads; writes still took the fragment lock,
mutated host roaring state, and bumped ``_gen`` — which (by design)
invalidates device caches and evicts result-cache entries, so sustained
ingest held warm hit rates near zero and forced full re-upload of
mutated fragments.  This package is the LSM-flavored write path the
reference absorbs writes with (the roaring op-log appended ahead of
snapshots, PAPER.md §roaring op-log; Chambi et al., *Better bitmap
performance with Roaring bitmaps*): batched imports and
``set_bit``/``clear_bit`` land in a small, bounded per-fragment **delta
plane** (set-bits and clear-bits planes, ``deltaplane.DeltaPlane``)
WITHOUT bumping the base generation, reads fuse ``base ⊕ delta`` inside
the existing fused expression programs (``ops/expr.py`` ``dfuse``
leaves), and a background compactor (``compactor.Compactor``, under
admission's ``internal`` class) merges deltas into the base roaring
state once a delta crosses size/age thresholds — only compaction bumps
``_gen``.

Cache discipline (the point of the whole subsystem):

- ``Fragment._gen`` — BASE generation.  Bumped by direct base
  mutations and by compaction only.  Device residency (row stacks,
  matrices, BSI planes) keys on it, so deltas leave the resident base
  tensors warm.
- ``Fragment._delta_seq`` — monotone delta sequence, bumped on every
  delta-landing write, NEVER reset (compaction leaves it alone).  The
  result cache stamps extend to ``(base_gen, delta_seq)``
  (``Executor._rc_collect_gens``), so a cached entry stays valid until
  *its* fragment's delta actually changes, and a compaction refill is
  one recompute against the already-resident base — not an eviction
  storm across every read path.

Durability is unchanged: delta-landing writes append the SAME WAL
records as the base path at write time; compaction merely moves bits
from the delta plane into the base rows (no WAL append — replay is
idempotent and in order), so a crash at any point replays losslessly.

Process-wide configuration (the ``[ingest]`` config section;
``configure`` mirrors ``runtime/resultcache.configure``).  The module
default is **disabled** — bare ``Fragment``/``Holder`` embedders keep
the exact pre-delta semantics; the server assembly turns deltas on
from ``[ingest] delta-enabled`` (default true in config.py).
"""

from __future__ import annotations

import threading

#: Process-wide budget on PENDING delta bytes across all fragments;
#: past it the writing thread flushes its own fragment inline
#: (backpressure on the writer, like snapqueue's inline overflow).
DEFAULT_DELTA_BUDGET_BYTES = 64 << 20

#: Per-fragment flush threshold: a delta holding at least this many
#: pending bit positions is merged on the compactor's next scan.
DEFAULT_COMPACT_THRESHOLD_BITS = 1 << 17

#: Compactor scan period (seconds) AND the age bound: a delta older
#: than one interval is merged on the next scan even when small, so
#: trickle writes never pend unboundedly.
DEFAULT_COMPACT_INTERVAL_S = 2.0


class IngestRuntimeConfig:
    """The process-wide [ingest] knobs (one per process, like the
    residency manager's budget)."""

    __slots__ = ("delta_enabled", "delta_budget_bytes",
                 "compact_threshold_bits", "compact_interval")

    def __init__(self):
        self.delta_enabled = False
        self.delta_budget_bytes = DEFAULT_DELTA_BUDGET_BYTES
        self.compact_threshold_bits = DEFAULT_COMPACT_THRESHOLD_BITS
        self.compact_interval = DEFAULT_COMPACT_INTERVAL_S


_cfg = IngestRuntimeConfig()
_cfg_lock = threading.Lock()


def config() -> IngestRuntimeConfig:
    return _cfg


def configure(delta_enabled: bool | None = None,
              delta_budget_bytes: int | None = None,
              compact_threshold_bits: int | None = None,
              compact_interval: float | None = None) -> IngestRuntimeConfig:
    """Apply [ingest] config to the process-wide runtime in place (a
    second in-process server must not wipe the first's settings with
    defaults — only explicit values land)."""
    with _cfg_lock:
        if delta_enabled is not None:
            _cfg.delta_enabled = bool(delta_enabled)
        if delta_budget_bytes is not None:
            _cfg.delta_budget_bytes = int(delta_budget_bytes)
        if compact_threshold_bits is not None:
            _cfg.compact_threshold_bits = int(compact_threshold_bits)
        if compact_interval is not None:
            _cfg.compact_interval = float(compact_interval)
    return _cfg


def reset() -> IngestRuntimeConfig:
    """Restore defaults (tests; also Server.close, so a closed server
    cannot leave delta semantics enabled for unrelated library users
    in the same process)."""
    global _cfg, _baseline
    with _cfg_lock:
        _cfg = IngestRuntimeConfig()
        _baseline = None
    return _cfg


# Servers configure the process-wide knobs in place, but open and
# close independently (in-process clusters, embedders).  Per-server
# restore snapshots compose wrongly under create-A-create-B-close-A-
# close-B (B's snapshot contains A's override, so the last closer
# re-installs it).  Instead the FIRST server to configure captures the
# true pre-server baseline, and the LAST server to close restores it —
# correct in any close order.

_baseline: tuple | None = None


def capture_baseline() -> None:
    """Snapshot the pre-existing config once per overlapping group of
    in-process servers (no-op while a baseline is already held)."""
    global _baseline
    with _cfg_lock:
        if _baseline is None:
            _baseline = (_cfg.delta_enabled, _cfg.delta_budget_bytes,
                         _cfg.compact_threshold_bits,
                         _cfg.compact_interval)


def restore_baseline() -> None:
    """Re-install the captured baseline and release it (the last
    closing server calls this)."""
    global _baseline
    with _cfg_lock:
        if _baseline is None:
            return
        (_cfg.delta_enabled, _cfg.delta_budget_bytes,
         _cfg.compact_threshold_bits, _cfg.compact_interval) = _baseline
        _baseline = None


def delta_enabled() -> bool:
    return _cfg.delta_enabled
