"""Query flight recorder: per-query telemetry for the dispatch-bound
serving path.

The round-5 verdict's last big unknown is the dispatch window — the
committed chip number understates the engine ~5.6x — yet process-wide
stats (count/sum/min/max) cannot attribute latency to a QUERY.  This
module holds one ``QueryRecord`` per in-flight query: stage timings at
the executor's map/reduce boundaries, per-shard and per-node map
timings, the device-launch count from the ``ops/bitmap.py`` dispatch
hook, coalescer batch occupancy and queue-wait vs launch split, the
fused-vs-fallback expression path, and result sizes — the per-stage
timing discipline DrJAX (arxiv 2403.07128) and Ragged Paged Attention
(arxiv 2604.15464) use to diagnose TPU dispatch overhead, applied to
the reference's map-reduce executor (executor.go:2455).

Exposure (server/handler.py):

- ``GET /debug/queries`` — active-query table + ring buffer of recent
  records (``?sort=``/``?min_ms=``).
- ``?profile=1`` on ``POST /index/{index}/query`` — the breakdown
  inline in the response.
- slow-query log — ``[observe] long_query_time`` (config.py), logging
  PQL + trace id + breakdown (the reference's ``LongQueryTime``,
  api.go:1157, with a breakdown attached).

Lock discipline: the record is assembled THREAD-LOCALLY (``attach``
installs it on worker threads for the duration of one shard's
evaluation; list appends are GIL-atomic) — no lock on the per-stage /
per-launch hot path.  The recorder's own lock is touched once at
begin and once at publish (keeping the active table and ring buffer
safely iterable from /debug/queries), plus the stats registry's on
the latency-histogram observation.  The recorder must stay under 1%
of the coalesced Count path — benchmarked by ``bench.py``
(extras.observe).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import Counter, deque

from pilosa_tpu import lockcheck as _lockcheck
from pilosa_tpu import tracing as _tracing
from pilosa_tpu.serve.deadline import tls_scope as _tls_scope

_tls = threading.local()  # .rec: active QueryRecord; .last: last published

#: PQL longer than this is truncated in records (a query string is
#: operator-facing debug data, not an archive).
MAX_PQL = 2048

#: Detail-list caps: the ring buffer pins `recent` finished records,
#: so a 10k-shard per-shard-path query must not make each record
#: hundreds of KB.  Per-shard timings keep the first MAX_SHARD_TIMINGS
#: entries (shards_n still reports the true fan-out); launch names cap
#: at MAX_LAUNCHES — far above any real query, so deviceLaunches stays
#: exact everywhere the regression tests pin it, while a pathological
#: loop cannot grow a record without bound.
MAX_SHARD_TIMINGS = 4096
MAX_LAUNCHES = 65536


def current() -> "QueryRecord | None":
    """The query record being assembled on THIS thread, or None.  The
    executor's map wrappers re-``attach`` it on pool workers, so shard
    evaluations tick the right record."""
    return getattr(_tls, "rec", None)


class attach(_tls_scope):
    """Install a record (or None) as this thread's active record for a
    scope.  Re-entrant: restores whatever was active before, so a
    remote re-execution beginning its OWN record inside an IO thread
    shadows rather than clobbers."""

    __slots__ = ()

    def __init__(self, rec: "QueryRecord | None"):
        super().__init__(_tls, "rec", rec)


class admission_scope(_tls_scope):
    """Install an admission stamp ({"class", "queue_wait_ns"}) for a
    request's scope; ``FlightRecorder.begin`` copies it onto every
    record begun inside (the handler admits BEFORE the executor opens
    the record, so the handoff is this thread-local).  Re-entrant."""

    __slots__ = ()

    def __init__(self, info: dict | None):
        super().__init__(_tls, "admission", info)


def current_admission() -> dict | None:
    return getattr(_tls, "admission", None)


def take_last() -> "QueryRecord | None":
    """Pop the record most recently PUBLISHED on this thread (the
    ``?profile=1`` handoff: the handler thread that ran the query reads
    its own record back).  Clears on read so a bypassed execution (the
    SPMD collective path publishes its own record; a parse error
    publishes none) can never serve a stale profile."""
    rec = getattr(_tls, "last", None)
    _tls.last = None
    return rec


class AccessStats:
    """Per-cache-entry access statistics for the predictive
    prefetcher (runtime/prefetch.py): every tiered stack access —
    HBM hit, host-tier promotion, or cold build — ticks a decayed
    score per entry id, so 'which demoted entries is traffic about to
    want' is answerable by rank.  Scores decay by half every
    ``HALF_LIFE_S`` so yesterday's hot rows don't pin today's
    prefetch bandwidth; the table is LRU-capped (a per-row cache key
    churn must not grow it without bound).

    Lock discipline: one short lock per note — the note sits on the
    stack-accessor path (~µs against a rebuild measured in ms), not
    on the per-dispatch hot path."""

    HALF_LIFE_S = 30.0
    MAX_ENTRIES = 4096

    def __init__(self):
        self._lock = threading.Lock()
        # eid -> [score, last_monotonic]; insertion order = LRU
        self._scores: dict = {}

    def note(self, eid) -> None:
        now = time.monotonic()
        with self._lock:
            rec = self._scores.pop(eid, None)
            if rec is None:
                rec = [0.0, now]
                if len(self._scores) >= self.MAX_ENTRIES:
                    self._scores.pop(next(iter(self._scores)))
            score, last = rec
            score *= 0.5 ** ((now - last) / self.HALF_LIFE_S)
            self._scores[eid] = [score + 1.0, now]

    def score(self, eid) -> float:
        now = time.monotonic()
        with self._lock:
            rec = self._scores.get(eid)
            if rec is None:
                return 0.0
            return rec[0] * 0.5 ** ((now - rec[1]) / self.HALF_LIFE_S)

_access = AccessStats()


def access_stats() -> AccessStats:
    """The process-wide access-statistics table (process-wide like the
    residency budget the prefetcher feeds)."""
    return _access


def note_access(eid) -> None:
    _access.note(eid)


def result_size(res) -> int:
    """Cheap size proxy for one query result: list length, populated
    shard-segment count for Row-shaped results (duck-typed on
    ``.segments`` — materializing columns just to count them would cost
    more than the query), 1 for scalars.  Never raises."""
    if isinstance(res, list):
        return len(res)
    segments = getattr(res, "segments", None)
    if segments is not None:
        try:
            return len(segments)
        except TypeError:
            return 1
    return 1


class QueryRecord:
    """One query's telemetry, assembled lock-free on the threads that
    execute it.  ``launches`` is a list (not an int) because list
    appends are GIL-atomic while ``+= 1`` is a read-modify-write race
    across map workers — and the launch NAMES are the breakdown."""

    __slots__ = (
        "qid", "trace_id", "index", "pql", "start_unix", "t0_ns",
        "elapsed_ns", "shards_n", "stages", "shard_ns", "node_ns",
        "launches", "path", "coalesce", "result_sizes", "error", "slow",
        "admission", "outcome", "compiles", "cached", "cache_key",
        "delta_notes", "compacted", "hedged", "hedge_wins",
        "hedge_losers", "missing_shards", "tier_notes", "tenant",
        "engine", "would_choose", "remote",
    )

    def __init__(self, qid: int, index: str, pql: str,
                 trace_id: str | None = None):
        self.qid = qid
        self.index = index
        self.pql = pql[:MAX_PQL]
        now_ns = time.time_ns()
        self.trace_id = trace_id or f"{now_ns:016x}{qid & 0xFFFF:04x}"
        self.start_unix = now_ns / 1e9
        self.t0_ns = time.perf_counter_ns()
        self.elapsed_ns: int | None = None  # None while in flight
        self.shards_n = 0
        self.stages: list[tuple[str, int]] = []       # (name, ns)
        self.shard_ns: list[tuple[int, int]] = []     # (shard, ns)
        self.node_ns: list[tuple[str, int, int]] = [] # (node, ns, n_shards)
        self.launches: list[str] = []
        self.path: str | None = None  # fused|per-shard|coalesced|collective
        # the ONE canonical engine enum (pilosa_tpu.perfobs.ENGINES:
        # dense|gather|tape|vm|mesh|host|collective) — unifies the
        # scattered path string + tape/vm booleans; ``path`` stays
        # populated for compat.  Stamped by perfobs.sample per launch
        # (last launch wins — the engine that produced the result);
        # plain attribute store, race-free under the GIL
        self.engine: str | None = None
        # SHADOW cost-model verdict ([cost] shadow=true): the engine
        # the observed-cost table would have picked when it disagrees
        # with routing (rendered wouldChoose + costDisagree) — routing
        # itself is never changed by it
        self.would_choose: str | None = None
        self.coalesce: dict | None = None
        self.result_sizes: list[int] = []
        self.error: str | None = None
        self.slow = False
        # admission stamp ({"class", "queue_wait_ns"}) and outcome
        # (ok | error | shed | expired; None resolves at to_dict time)
        self.admission: dict | None = None
        self.outcome: str | None = None
        # XLA compiles this query triggered: (kernel, ns) pairs stamped
        # by pilosa_tpu.devobs — list appends are GIL-atomic, matching
        # the launches discipline
        self.compiles: list[tuple[str, int]] = []
        # result-cache outcome (runtime/resultcache): ``cached`` is
        # set when a cache hit served (part of) the query; the rendered
        # flag (to_dict) additionally requires zero device launches so
        # it keeps the documented "answered without device work on this
        # node" meaning.  ``cache_key`` (a stable digest) is stamped
        # whenever a canonical key was computed — hit or miss, so
        # /debug/queries correlates repeated shapes either way
        self.cached = False
        self.cache_key: str | None = None
        # streaming-ingest annotations (pilosa_tpu.ingest): rendered
        # ``deltaDepth`` counts the fused leaves this query evaluated
        # WITH a pending delta overlay (``dfuse`` nodes staged — how
        # much un-compacted write traffic the read absorbed); a list
        # because leaves stage on concurrent map workers and appends
        # are GIL-atomic (the launches discipline).  ``compacted``
        # marks that a merge of a pending delta ran inside this query
        # (a ?nodelta=1 escape, a whole-matrix path, or an export) —
        # "slow because it compacted", symmetric with ``compiled``;
        # a single idempotent True store, race-free
        self.delta_notes: list[int] = []
        self.compacted = False
        # failure-handling annotations (the chaos round): ``hedged``
        # counts remote flights this query re-issued to a replica
        # past the peer's latency threshold, ``hedge_wins`` how many
        # of those races the hedge side won; ``missing_shards`` are
        # the shards a ?partial=1 request accounted as unavailable
        # (or, on a ShardsUnavailableError, the shards that failed
        # it).  All touched only by the origin map thread.
        self.hedged = 0
        self.hedge_wins = 0
        # the LOSING side of each settled hedge race: (node, ns the
        # abandoned flight had been in the air when the race committed)
        # — cross-node trace assembly shows the loser's spans too, so
        # "we paid for two flights" is visible on the origin record.
        # List appends from the map loop thread only.
        self.hedge_losers: list[tuple[str, int]] = []
        self.missing_shards: list[int] = []
        # True for remote sub-executions (ExecOptions.remote): the
        # trace assembler tells origin records from per-node remote
        # map records by this flag when both share a trace id
        self.remote = False
        # the request's tenant id ([tenants] isolation; None for
        # anonymous/default-tier traffic) — stamped by the executor
        # from ExecOptions.tenant, rendered on /debug/queries and the
        # slow-query log so abusive-tenant triage reads straight off
        # the flight recorder
        self.tenant: str | None = None
        # tiered-residency attribution (runtime/residency.py):
        # (outcome, ns) per tiered stack access — outcome one of
        # ``hbm`` (resident hit), ``promoted`` (waited for an async
        # host->HBM promotion), ``fallback`` (served host-compute
        # past the promotion wait), ``cold`` (assembled from fragment
        # state).  List appends, GIL-atomic across map workers (the
        # launches discipline); rendered as the ``tier`` dict — the
        # stall-vs-hit split ?profile=1 and /debug/queries carry.
        self.tier_notes: list[tuple[str, int]] = []

    # ------------------------------------------------------------ notes

    def note_stage(self, name: str, ns: int) -> None:
        self.stages.append((name, ns))

    def note_launch(self, name: str) -> None:
        """One kernel launch (called from ops/bitmap.note_dispatch).
        List append is GIL-atomic; the len guard may overshoot the cap
        by a few concurrent appends, which only bounds memory, never
        undercounts below the cap."""
        if len(self.launches) < MAX_LAUNCHES:
            self.launches.append(name)

    def note_compile(self, kernel: str, ns: int) -> None:
        """One XLA compile paid by this query (devobs.instrument) —
        the "slow because it compiled" attribution."""
        if len(self.compiles) < 256:
            self.compiles.append((kernel, ns))

    def note_delta(self, n: int = 1) -> None:
        """``n`` fused leaves staged with a pending delta overlay
        (Executor._fused_row_leaf) — list append, GIL-atomic."""
        if len(self.delta_notes) < MAX_SHARD_TIMINGS:
            self.delta_notes.append(n)

    def note_shard(self, shard: int, ns: int) -> None:
        if len(self.shard_ns) < MAX_SHARD_TIMINGS:
            self.shard_ns.append((shard, ns))

    def note_node(self, node: str, ns: int, n_shards: int) -> None:
        self.node_ns.append((node, ns, n_shards))

    def note_shards(self, n: int) -> None:
        if n > self.shards_n:
            self.shards_n = n

    def note_path(self, path: str) -> None:
        self.path = path

    def note_engine(self, engine: str) -> None:
        """The canonical engine that executed (a perfobs.ENGINES
        value) — last launch wins, so a fallback ladder ends up
        attributed to the engine that actually produced the result."""
        self.engine = engine

    def note_tier(self, outcome: str, ns: int = 0) -> None:
        """One tiered stack access: ``hbm`` | ``promoted`` |
        ``fallback`` | ``cold``, with the wall time the access cost
        this query (the promotion wait / rebuild — the stall side of
        stall-vs-hit).  List append, GIL-atomic."""
        if len(self.tier_notes) < MAX_SHARD_TIMINGS:
            self.tier_notes.append((outcome, ns))

    def note_missing(self, shard: int) -> None:
        """One shard accounted unavailable (partial degradation or a
        structured exhaustion error)."""
        if len(self.missing_shards) < MAX_SHARD_TIMINGS:
            self.missing_shards.append(shard)

    # ----------------------------------------------------------- export

    def elapsed_live_ns(self) -> int:
        """Elapsed so far (in-flight) or final elapsed (published)."""
        if self.elapsed_ns is not None:
            return self.elapsed_ns
        return time.perf_counter_ns() - self.t0_ns

    def to_dict(self) -> dict:
        ms = 1e6
        d = {
            "id": self.qid,
            "traceID": self.trace_id,
            "index": self.index,
            "pql": self.pql,
            "startTime": self.start_unix,
            "elapsedMs": round(self.elapsed_live_ns() / ms, 3),
            "active": self.elapsed_ns is None,
            "shards": self.shards_n,
            "stages": [{"name": n, "ms": round(v / ms, 3)}
                       for n, v in self.stages],
            "shardTimings": [{"shard": s, "ms": round(v / ms, 3)}
                             for s, v in self.shard_ns],
            "nodeTimings": [{"node": n, "ms": round(v / ms, 3),
                             "shards": k}
                            for n, v, k in self.node_ns],
            "deviceLaunches": len(self.launches),
            "launchKinds": dict(Counter(self.launches)),
            "compiled": bool(self.compiles),
            "compileMs": round(sum(ns for _, ns in self.compiles) / ms,
                               3),
            "resultSizes": list(self.result_sizes),
            "outcome": self.outcome or ("error" if self.error else "ok"),
            # rendered ``cached`` keeps the documented meaning — served
            # without device work on this node.  A PARTIAL hit (e.g.
            # filtered TopN whose unfiltered full-counts pass hit while
            # the filtered scan dispatched) marks the flag internally
            # but still launched, so it must not read as fully
            # cache-served; the "cached" path note records the partial
            # hit either way
            "cached": self.cached and not self.launches,
        }
        if self.cache_key is not None:
            d["cacheKey"] = self.cache_key
        if self.tenant is not None:
            d["tenant"] = self.tenant
        # streaming-ingest annotations: present only when the query
        # actually met a delta (the common no-ingest record stays small)
        if self.delta_notes:
            d["deltaDepth"] = sum(self.delta_notes)
        if self.compacted:
            d["compacted"] = True
        # chaos-round annotations: present only when the query hedged
        # or degraded (the common healthy record stays small)
        if self.hedged:
            d["hedged"] = self.hedged
            d["hedgeWins"] = self.hedge_wins
        if self.hedge_losers:
            d["hedgeLosers"] = [{"node": n, "ms": round(ns / ms, 3)}
                                for n, ns in self.hedge_losers]
        if self.remote:
            d["remote"] = True
        if self.missing_shards:
            d["missingShards"] = sorted(self.missing_shards)
        # tiered-residency attribution: present only when the query
        # crossed the tier machinery (the common fully-resident record
        # stays small).  ``stallMs`` is the time THIS query spent
        # waiting on promotions / host fallbacks / cold assembly —
        # the "slow because the working set exceeded HBM" answer.
        if self.tier_notes:
            by = Counter(o for o, _ in self.tier_notes)
            d["tier"] = {
                "hbm": by.get("hbm", 0),
                "promoted": by.get("promoted", 0),
                "fallback": by.get("fallback", 0),
                "cold": by.get("cold", 0),
                "stallMs": round(
                    sum(ns for o, ns in self.tier_notes
                        if o != "hbm") / ms, 3),
            }
        if self.admission is not None:
            d["admission"] = {
                "class": self.admission.get("class"),
                "queueWaitMs": round(
                    self.admission.get("queue_wait_ns", 0) / ms, 3),
            }
        if self.compiles:
            d["compileKernels"] = dict(
                Counter(k for k, _ in self.compiles))
        if len(self.shard_ns) >= MAX_SHARD_TIMINGS:
            d["shardTimingsTruncated"] = True
        if self.path is not None:
            d["path"] = self.path
        if self.engine is not None:
            d["engine"] = self.engine
        # shadow cost-model verdict: present only on a disagreement
        # (the common agreeing record stays small)
        if self.would_choose is not None:
            d["wouldChoose"] = self.would_choose
            d["costDisagree"] = True
        if self.coalesce is not None:
            c = self.coalesce
            d["coalescer"] = {
                "batch": c["batch"],
                # ragged-megabatch evidence (parallel/coalescer.py +
                # ops/tape.py): how many DISTINCT tree shapes shared
                # this query's flushed batch, and whether the
                # tape-interpreter engine ran the launch (false =
                # same-shape fast path / single-query passthrough)
                "shapes": c.get("shapes", 1),
                "tape": c.get("tape", False),
                "queueWaitMs": round(c["queue_wait_ns"] / ms, 3),
                "launchMs": round(c["launch_ns"] / ms, 3),
                "leader": c.get("leader", True),
            }
            if c.get("launch_trace"):
                # a follower names the batch leader's trace — the
                # span that owns the shared device launch
                d["coalescer"]["launchTrace"] = c["launch_trace"]
        if self.error is not None:
            d["error"] = self.error
        if self.slow:
            d["slow"] = True
        return d


class FlightRecorder:
    """Active-query table + ring buffer of recent records.

    One per executor (the server wires config + logger + stats in).
    Record ASSEMBLY (the note_* calls on the hot path) is lock-free;
    the recorder's own lock is touched once per query transition
    (begin/publish) to keep the active table and ring buffer safely
    iterable from /debug/queries while queries publish."""

    def __init__(self, recent: int = 256, long_query_time: float = 0.0,
                 enabled: bool = True, logger=None, stats=None):
        self.enabled = enabled
        self.long_query_time = long_query_time  # seconds; 0 = log off
        self.logger = logger
        self.stats = stats
        self._seq = itertools.count(1)  # next() is atomic
        self._lock = threading.Lock()
        self._active: dict[int, QueryRecord] = {}
        self._recent: deque[QueryRecord] = deque(maxlen=recent)
        # shed-log throttle: overload sheds thousands/sec; one line
        # per second (with a suppressed count) keeps the log honest
        # without letting the log itself become the overload
        self._shed_log_t = 0.0
        self._shed_suppressed = 0

    # ----------------------------------------------------------- record

    def begin(self, index: str, pql: str,
              trace_id: str | None = None) -> QueryRecord:
        rec = QueryRecord(next(self._seq), index, pql, trace_id)
        # the admission gate runs before the executor opens the record;
        # its stamp (class + queue wait) rides a thread-local scope
        rec.admission = current_admission()
        with self._lock:
            self._active[rec.qid] = rec
        return rec

    def record_shed(self, index: str, pql: str, klass: str,
                    outcome: str, reason: str,
                    wait_ns: int = 0,
                    tenant: str | None = None,
                    trace_id: str | None = None) -> None:
        """A request refused at the admission gate never executes, so
        no record is begun for it — synthesize one straight into the
        ring buffer (outcome ``shed``/``expired``) so /debug/queries
        and the slow-query log tell the overload story, and skip the
        latency histogram (a refusal's sub-millisecond turnaround
        would drag the admitted-query percentiles down).  ``trace_id``
        (extracted from the refused request's traceparent — the shed
        happens before any span opens) links the refusal to the
        client's trace: a logged shed is one /debug/trace/{id} away."""
        if not self.enabled:
            return
        rec = QueryRecord(next(self._seq), index, pql,
                          trace_id=trace_id)
        rec.admission = {"class": klass, "queue_wait_ns": wait_ns}
        rec.tenant = tenant
        rec.outcome = outcome
        rec.error = reason
        rec.elapsed_ns = wait_ns
        suppressed = 0
        with self._lock:
            self._recent.append(rec)
            if self.logger is not None:
                now = time.monotonic()
                if now - self._shed_log_t < 1.0:
                    self._shed_suppressed += 1
                    return
                suppressed = self._shed_suppressed
                self._shed_suppressed = 0
                self._shed_log_t = now
        if self.logger is not None:
            # shed events ride the slow-query log: overload must be
            # diagnosable from the same place slow queries are
            self.logger.printf(
                "%s query (class=%s, waited %.1fms, trace=%s) on %s: %s"
                "%s",
                outcome, klass, wait_ns / 1e6, rec.trace_id,
                index or "-", reason,
                f" (+{suppressed} more shed in the last second)"
                if suppressed else "")

    def discard(self, rec: QueryRecord) -> None:
        """Drop an active record without publishing (a path that turned
        out not to execute, e.g. the collective upgrade declining)."""
        with self._lock:
            self._active.pop(rec.qid, None)

    def publish(self, rec: QueryRecord, error: str | None = None) -> None:
        rec.elapsed_ns = time.perf_counter_ns() - rec.t0_ns
        if error is not None:
            rec.error = error
        elapsed_s = rec.elapsed_ns / 1e9
        if self.long_query_time > 0 and elapsed_s > self.long_query_time:
            rec.slow = True
        with self._lock:
            self._active.pop(rec.qid, None)
            self._recent.append(rec)
        _tls.last = rec
        if self.stats is not None:
            # the /metrics + /debug/vars surface: a native Prometheus
            # histogram with this query's trace id as the bucket
            # exemplar (stats._Registry)
            self.stats.histogram("pilosa_query_latency", elapsed_s,
                                 exemplar=rec.trace_id)
        if rec.slow and self.logger is not None:
            compile_ms = sum(ns for _, ns in rec.compiles) / 1e6
            self.logger.printf(
                "slow query (%.3fs) trace=%s on %s: %s | stages=%s "
                "shards=%d launches=%d path=%s engine=%s compiled=%s%s%s",
                elapsed_s, rec.trace_id, rec.index, rec.pql,
                ",".join(f"{n}:{v / 1e6:.1f}ms" for n, v in rec.stages),
                rec.shards_n, len(rec.launches), rec.path or "-",
                rec.engine or "-",
                "true" if rec.compiles else "false",
                f" compile_ms={compile_ms:.1f}" if rec.compiles else "",
                f" tenant={rec.tenant}" if rec.tenant else "")

    # ------------------------------------------------------------- views

    def active_records(self) -> list[QueryRecord]:
        with self._lock:
            return list(self._active.values())

    def recent_records(self) -> list[QueryRecord]:
        with self._lock:
            return list(self._recent)

    def records_for_trace(self, trace_id: str) -> list[QueryRecord]:
        """Every record (in-flight AND recent) linked to ``trace_id``
        — the per-node section of cross-node trace assembly.  Active
        records matter: the hedge LOSER's remote execution may still
        be running on its node when the origin assembles the tree.
        Matching is on normalized ids (records may carry the 20-hex
        self-generated fallback; headers zero-pad to 32)."""
        want = _tracing.normalize_trace_id(trace_id)
        with self._lock:
            recs = list(self._active.values()) + list(self._recent)
        return [r for r in recs
                if _tracing.normalize_trace_id(r.trace_id) == want]


# --------------------------------------------------------------------
# cluster event journal
# --------------------------------------------------------------------


class EventJournal:
    """Process-wide ring of structured events at the state transitions
    that previously only ticked counters — breaker open/close, hedge
    fired/won, rebalance shard transitions, AE round lifecycle,
    compaction runs, OOM evict-and-retry, residency demote/promote,
    failpoint arm/disarm, config baseline changes.  Each event is
    stamped with a monotonically increasing ``seq``, wall + monotonic
    time, the node id, and the active trace id when one is in scope —
    so a trace view can answer "p99 spiked because node2's breaker
    opened mid-backfill".

    Exposure: ``GET /debug/events`` per node (``?since=``/``?kind=``)
    plus the fanned-in ``GET /debug/cluster/events`` merged timeline.

    Lock discipline: one short lock per emit (append + counter tick);
    NEVER emit while holding another subsystem's lock — every
    emission site releases its own lock first (the breaker/faultinject
    discipline).  Disarmed cost (``journal_on`` false) is one module
    bool read at each site, the faultinject gate shape."""

    def __init__(self, size: int = 2048, node_id: str = "",
                 kinds: frozenset | None = None):
        self._lock = _lockcheck.lock("eventjournal")
        self._ring: deque[dict] = deque(maxlen=max(1, int(size)))
        self._seq = 0
        self._by_kind: Counter = Counter()
        self._dropped = 0
        self.node_id = node_id
        # empty/None = every kind; a non-empty set filters at emit
        # (the dropped counter keeps the suppression visible)
        self.kinds = frozenset(kinds) if kinds else frozenset()

    def emit(self, kind: str, trace_id: str | None = None,
             **fields) -> None:
        ev = {"t": time.time(), "mono": time.perf_counter_ns(),
              "kind": kind}
        if trace_id:
            ev["traceId"] = _tracing.normalize_trace_id(trace_id)
        if fields:
            ev.update(fields)
        with self._lock:
            # prefix allowlist, same contract as the events() filter:
            # kinds={"breaker"} keeps breaker.open AND breaker.close
            if self.kinds and not any(kind.startswith(k)
                                      for k in self.kinds):
                self._dropped += 1
                return
            self._seq += 1
            ev["seq"] = self._seq
            ev["node"] = self.node_id
            self._ring.append(ev)
            self._by_kind[kind] += 1

    def events(self, since: int = 0, kind: str | None = None,
               trace_id: str | None = None,
               limit: int = 512) -> list[dict]:
        """Ring contents, oldest first.  ``since`` keeps events with
        seq strictly greater (the incremental-poll cursor); ``kind``
        is a prefix match (``kind=breaker`` covers breaker.open /
        breaker.close); ``trace_id`` keeps events stamped with that
        trace; ``limit`` keeps the NEWEST matches."""
        want = (_tracing.normalize_trace_id(trace_id)
                if trace_id else None)
        with self._lock:
            evs = list(self._ring)
        out = [e for e in evs
               if e["seq"] > since
               and (kind is None or e["kind"].startswith(kind))
               and (want is None or e.get("traceId") == want)]
        return out[-max(0, int(limit)):]

    def counters(self) -> dict:
        with self._lock:
            return {"total": self._seq, "dropped": self._dropped,
                    "depth": len(self._ring),
                    "kinds": dict(self._by_kind)}


#: The one-word fast gate every emission site reads FIRST:
#: ``if observe.journal_on: observe.emit(kind, ...)`` — the
#: faultinject ``armed`` discipline, so the disarmed journal costs
#: one module-bool read on the hot path.
journal_on = True
_journal = EventJournal()
_cfg_lock = threading.Lock()
_baseline: tuple | None = None
_refs = 0


def journal() -> EventJournal:
    return _journal


def emit(kind: str, trace_id: str | None = None, **fields) -> None:
    """Emit one journal event.  ``trace_id=None`` auto-captures the
    thread's active trace id (``tracing.active_trace_id``) so events
    emitted inside a traced request link the trace for free."""
    if not journal_on:
        return
    if trace_id is None:
        trace_id = _tracing.active_trace_id()
    _journal.emit(kind, trace_id=trace_id, **fields)


def configure(node_id: str | None = None, size: int | None = None,
              kinds: str | None = None,
              enabled: bool | None = None) -> EventJournal:
    """Apply explicit journal settings in place (None leaves a knob
    alone).  ``kinds`` is a comma-separated prefix list ("" = every
    kind).  Emits a ``config.applied`` event — config baseline
    changes are themselves journal-worthy state transitions."""
    global journal_on, _journal
    with _cfg_lock:
        j = _journal
        if size is not None and int(size) != j._ring.maxlen:
            nj = EventJournal(size=int(size), node_id=j.node_id,
                              kinds=j.kinds)
            with j._lock:
                nj._seq = j._seq
                nj._by_kind = j._by_kind
                nj._dropped = j._dropped
                for ev in j._ring:
                    nj._ring.append(ev)
            _journal = j = nj
        if node_id is not None:
            j.node_id = node_id
        if kinds is not None:
            j.kinds = frozenset(
                k.strip() for k in kinds.split(",") if k.strip())
        if enabled is not None:
            journal_on = bool(enabled)
    if journal_on:
        emit("config.applied", section="observe.journal",
             node=node_id or _journal.node_id)
    return _journal


def retain() -> None:
    """First retain captures the pre-server journal baseline (the
    hints/perfobs P5 refcount idiom)."""
    global _refs, _baseline
    with _cfg_lock:
        if _refs == 0 and _baseline is None:
            _baseline = (journal_on, _journal.node_id, _journal.kinds)
        _refs += 1


def release() -> None:
    """Last release restores the baseline for library users."""
    global _refs, _baseline, journal_on
    restored = False
    with _cfg_lock:
        if _refs > 0:
            _refs -= 1
        if _refs == 0 and _baseline is not None:
            on, node_id, kinds = _baseline
            journal_on = on
            _journal.node_id = node_id
            _journal.kinds = kinds
            _baseline = None
            restored = True
    if restored and journal_on:
        emit("config.restored", section="observe.journal")


def reset_journal() -> EventJournal:
    """Test hook: a fresh default journal, no baseline, zero refs."""
    global _journal, _baseline, _refs, journal_on
    with _cfg_lock:
        _journal = EventJournal()
        _baseline = None
        _refs = 0
        journal_on = True
    return _journal


# trace-assembly counters (pilosa_tpu.traceasm ticks these): rendered
# as the trace_* gauge family next to the journal's event_* family
_trace_lock = _lockcheck.lock("trace-counters")
_trace_counters = {
    "trace.assemblies": 0,   # /debug/trace/{id} trees assembled
    "trace.fanins": 0,       # peer record fetches issued
    "trace.errors": 0,       # peers that failed/timed out in a fan-in
    "trace.orphans": 0,      # assemblies that found no origin record
}


def bump_trace(name: str, value: int = 1) -> None:
    with _trace_lock:
        _trace_counters[name] += value


def trace_counters() -> dict:
    with _trace_lock:
        return dict(_trace_counters)


def publish_journal_gauges(stats) -> None:
    """event.* + trace.* gauge families for /metrics and /debug/vars —
    published unconditionally (zeros on a clean server) so both
    families are scrape-visible before the first event or assembly."""
    c = _journal.counters()
    stats.gauge("event.total", c["total"])
    stats.gauge("event.dropped", c["dropped"])
    stats.gauge("event.depth", c["depth"])
    stats.gauge("event.kinds", len(c["kinds"]))
    stats.gauge("trace.assemblies",
                trace_counters()["trace.assemblies"])
    stats.gauge("trace.fanins", trace_counters()["trace.fanins"])
    stats.gauge("trace.errors", trace_counters()["trace.errors"])
    stats.gauge("trace.orphans", trace_counters()["trace.orphans"])
