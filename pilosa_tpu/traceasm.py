"""Cross-node trace assembly: one causal span tree per query.

``GET /debug/trace/{id}`` fans flight records in from every node
(``parallel/cluster.fan_in`` + ``client.debug_json``, the
``/debug/cluster/*`` machinery) and this module joins them on the
normalized trace id into ONE tree:

    query (origin node)
      admission.wait
      coalescer.window
      stage:translate
      stage:execute            <- engine enum, launch count, tier notes
        map                    <- per-node children from nodeTimings
          node/node1  — remote subtree attached when that node's own
          node/node2    flight record arrived in the fan-in
          node/node2 (hedge loser) — the abandoned side of a hedge race
        reduce                 <- execute minus map
      stage:translateResults
      (unattributed)           <- filler so child walls sum EXACTLY

Per-span wall times add up to the observed latency by construction:
each level carries an explicit ``(unattributed)`` child absorbing the
gap between the parent's wall and the sum of its measured children, so
the accounting identity ``observedMs == sum(leaf walls)`` holds and a
triage reader can see exactly how much time the recorder could NOT
attribute.  Dead peers degrade to an ``errors`` entry, same contract
as ``/debug/cluster/*``.

Pure functions over already-fetched JSON sections — no I/O here; the
handler owns the fan-in and ticks ``observe.bump_trace`` counters.
"""

from __future__ import annotations

from pilosa_tpu import tracing as _tracing

#: Below this a filler span is measurement noise, not information.
_MIN_FILLER_MS = 0.005


def _span(name: str, ms: float, node: str = "", **attrs) -> dict:
    d = {"name": name, "ms": round(max(0.0, ms), 3)}
    if node:
        d["node"] = node
    d.update(attrs)
    d["children"] = []
    return d


def _fill(parent: dict) -> None:
    """Append the ``(unattributed)`` child absorbing the gap between
    the parent wall and its children's summed walls — the invariant
    that makes every level's walls add up."""
    accounted = sum(c["ms"] for c in parent["children"])
    gap = parent["ms"] - accounted
    if gap > _MIN_FILLER_MS:
        parent["children"].append(
            _span("(unattributed)", gap, parent.get("node", "")))


def _leaf_sum(span: dict) -> float:
    if not span["children"]:
        return span["ms"]
    return sum(_leaf_sum(c) for c in span["children"])


def _remote_subtree(rec: dict, node: str) -> dict:
    """A remote node's own flight record rendered as the subtree under
    the origin's per-node map span."""
    sub = _span("remote/" + rec.get("index", ""),
                rec.get("elapsedMs", 0.0), node,
                pql=rec.get("pql", ""))
    if rec.get("engine"):
        sub["engine"] = rec["engine"]
    sub["children"].extend(_stage_spans(rec, node, {}))
    if rec.get("deviceLaunches"):
        sub["launches"] = rec["deviceLaunches"]
    _fill(sub)
    return sub


def _stage_spans(rec: dict, node: str,
                 remote_by_node: dict[str, list[dict]]) -> list[dict]:
    """The record's stage list as sibling spans, order-aware: the
    recorder appends stages as they FINISH, and the shard fan-out runs
    inside its execute call — so a ``map``/``map.fused`` entry belongs
    to the next ``execute.*`` entry and must nest under it (rendering
    both at the top level would double-count the map wall and break
    the accounting identity)."""
    out: list[dict] = []
    pending_map: dict | None = None
    for st in rec.get("stages", []):
        name = st.get("name", "?")
        if name in ("map", "map.fused"):
            pending_map = st
            continue
        if name.startswith("execute"):
            out.append(_execute_span(st, pending_map, rec, node,
                                     remote_by_node))
            pending_map = None
        else:
            out.append(_span("stage:" + name, st.get("ms", 0.0), node))
    if pending_map is not None:  # map without an execute parent: keep
        out.append(_span("stage:" + pending_map.get("name", "map"),
                         pending_map.get("ms", 0.0), node))
    return out


def _execute_span(st: dict, map_st: dict | None, rec: dict, node: str,
                  remote_by_node: dict[str, list[dict]]) -> dict:
    """One execute stage: the shard map (per-node children off
    nodeTimings, remote subtrees attached) plus the derived reduce
    tail (execute minus map)."""
    sp = _span("stage:" + st.get("name", "?"), st.get("ms", 0.0), node)
    sp["engine"] = rec.get("engine", "")
    if rec.get("deviceLaunches"):
        sp["launches"] = rec["deviceLaunches"]
    if rec.get("tier"):
        sp["tier"] = rec["tier"]
    timings = rec.get("nodeTimings", [])
    # map wall: the recorded map stage when present (covers local
    # shard work too), else the slowest node group (the scatter-gather
    # critical path)
    map_ms = (map_st.get("ms", 0.0) if map_st is not None
              else max((t.get("ms", 0.0) for t in timings),
                       default=0.0))
    if map_st is not None or timings:
        mp = _span(map_st.get("name", "map") if map_st is not None
                   else "map", map_ms, node)
        for t in timings:
            peer = t.get("node", "?")
            child = _span("node/" + peer, t.get("ms", 0.0), node,
                          shards=t.get("shards"))
            pool = remote_by_node.get(peer)
            if pool:
                child["children"].append(_remote_subtree(pool.pop(0),
                                                         peer))
                _fill(child)
            mp["children"].append(child)
        if mp["children"]:
            _fill(mp)
        sp["children"].append(mp)
        sp["children"].append(
            _span("reduce", sp["ms"] - map_ms, node))
    for loser in rec.get("hedgeLosers", []):
        peer = loser.get("node", "?")
        lost = _span("node/" + peer + " (hedge loser)",
                     loser.get("ms", 0.0), node)
        pool = remote_by_node.get(peer)
        if pool:
            lost["children"].append(_remote_subtree(pool.pop(0), peer))
            _fill(lost)
        # abandoned work is OFF the critical path: report it under the
        # execute span but exclude it from the wall accounting
        lost["offCriticalPath"] = True
        sp.setdefault("abandoned", []).append(lost)
    return sp


def assemble_trace(sections: dict, errors: dict,
                   trace_id: str) -> dict:
    """Join per-node ``{"records": [...], "events": [...]}`` sections
    (keyed by node id, from the fan-in) into one causal span tree.

    Returns ``{"traceId", "origin", "root", "records", "events",
    "accounting", "errors"}``; ``root`` is None when no node holds an
    origin (non-remote) record for the trace."""
    want = _tracing.normalize_trace_id(trace_id)
    all_recs: list[tuple[str, dict]] = []
    all_events: list[dict] = []
    for node, sec in sections.items():
        for rec in (sec or {}).get("records", []):
            all_recs.append((node, rec))
        all_events.extend((sec or {}).get("events", []))

    origin_node, origin = None, None
    remote_by_node: dict[str, list[dict]] = {}
    for node, rec in all_recs:
        if rec.get("remote"):
            remote_by_node.setdefault(node, []).append(rec)
        elif origin is None:
            origin_node, origin = node, rec

    out = {
        "traceId": want,
        "origin": origin_node,
        "root": None,
        "records": [dict(r, node=n) for n, r in all_recs],
        "events": sorted(all_events, key=lambda e: e.get("t", 0)),
        "errors": errors,
    }
    if origin is None:
        out["accounting"] = {"observedMs": 0.0, "accountedMs": 0.0,
                             "unaccountedMs": 0.0}
        return out

    root = _span("query/" + origin.get("index", ""),
                 origin.get("elapsedMs", 0.0), origin_node,
                 pql=origin.get("pql", ""))
    adm = origin.get("admission", {})
    if adm.get("queueWaitMs"):
        root["children"].append(
            _span("admission.wait", adm["queueWaitMs"], origin_node,
                  **{"class": adm.get("class", "")}))
    co = origin.get("coalescer", {})
    if co:
        root["children"].append(
            _span("coalescer.window", co.get("queueWaitMs", 0.0),
                  origin_node, batch=co.get("batch"),
                  leader=co.get("leader")))
    root["children"].extend(
        _stage_spans(origin, origin_node, remote_by_node))
    _fill(root)
    for child in root["children"]:
        if child["children"]:
            _fill(child)

    out["root"] = root
    observed = root["ms"]
    accounted = _leaf_sum(root)
    out["accounting"] = {
        "observedMs": round(observed, 3),
        "accountedMs": round(accounted, 3),
        "unaccountedMs": round(max(0.0, observed - accounted), 3),
    }
    return out


def merge_events(sections: dict, errors: dict, since: int = 0,
                 kind: str | None = None) -> dict:
    """The fanned-in cluster timeline for ``/debug/cluster/events``:
    every node's journal slice merged, wall-clock ordered.  ``seq`` is
    per-node, so the merged order key is the emit wall time (nodes'
    clocks; good enough for triage, same caveat as /debug/cluster/*)."""
    merged: list[dict] = []
    counters: dict[str, dict] = {}
    for node, sec in sections.items():
        merged.extend((sec or {}).get("events", []))
        if (sec or {}).get("counters"):
            counters[node] = sec["counters"]
    merged.sort(key=lambda e: (e.get("t", 0), e.get("node", ""),
                               e.get("seq", 0)))
    return {"events": merged, "counters": counters, "errors": errors,
            "since": since, "kind": kind}
