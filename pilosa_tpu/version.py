"""Framework version (reference version.go)."""

VERSION = "0.1.0"
