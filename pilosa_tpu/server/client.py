"""Node-to-node HTTP client + transport.

Parity target: the reference's InternalClient (http/client.go:37) — the
RPC used for remote query execution, control-plane messages, fragment
block diffs/data, translate streaming, and resize transfers — plus the
Transport adapter that plugs it into the cluster layer.
"""

from __future__ import annotations

import json
import random
import threading
import time

from pilosa_tpu import faultinject as _fi
from pilosa_tpu.parallel.cluster import (
    Node,
    ShedByPeerError,
    Transport,
    TransportError,
)
from pilosa_tpu.serve import admission as _admission
from pilosa_tpu.serve import deadline as _deadline
from pilosa_tpu.serve.deadline import DeadlineExceededError


class ClientError(RuntimeError):
    def __init__(self, status: int, message: str):
        super().__init__(f"http {status}: {message}")
        self.status = status


class InternalClient:
    """Thin JSON/binary HTTP client against a node's Handler routes
    (http/client.go:37)."""

    #: idle keep-alive connections retained per (scheme, host)
    MAX_IDLE_PER_HOST = 8

    #: shed-retry policy: a 429/503 with Retry-After is retried at most
    #: this many times, each sleep capped here (with up-to-25% jitter
    #: so a shed burst does not re-arrive in lockstep) and always
    #: bounded by the caller's remaining deadline
    MAX_SHED_RETRIES = 3
    RETRY_AFTER_CAP_S = 2.0

    #: injectable for tests (class attr so instances share the default)
    _sleep = staticmethod(time.sleep)

    def __init__(self, timeout: float = 30.0,
                 tls_skip_verify: bool = False):
        self.timeout = timeout
        self._ssl_ctx = None
        if tls_skip_verify:
            # self-signed intra-cluster certs (reference tls.skip-verify,
            # server/config.go:64)
            import ssl

            self._ssl_ctx = ssl.create_default_context()
            self._ssl_ctx.check_hostname = False
            self._ssl_ctx.verify_mode = ssl.CERT_NONE
        # keep-alive pool: (scheme, netloc) -> idle HTTPConnections.
        # The reference's InternalClient rides net/http's pooled
        # transport (http/client.go:55); without reuse every RPC pays a
        # TCP (+TLS) handshake, which dominates small-query latency.
        self._pool: dict[tuple[str, str], list] = {}
        self._pool_lock = threading.Lock()
        self._closed = False

    # ------------------------------------------------------------- basics

    def _connect(self, scheme: str, netloc: str,
                 timeout: float | None = None):
        import http.client
        import socket
        import ssl as _ssl

        t = self.timeout if timeout is None else timeout
        if scheme == "https":
            ctx = self._ssl_ctx or _ssl.create_default_context()
            conn = http.client.HTTPSConnection(netloc, timeout=t,
                                               context=ctx)
        else:
            conn = http.client.HTTPConnection(netloc, timeout=t)
        conn.connect()
        # Nagle + delayed-ACK stalls kill keep-alive RPC latency (the
        # header and body go out as separate small segments); urllib
        # never noticed because closing the connection flushed it
        conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return conn

    def _acquire(self, scheme: str, netloc: str,
                 timeout: float | None = None):
        """-> (connection, came_from_pool)"""
        with self._pool_lock:
            idle = self._pool.get((scheme, netloc))
            if idle:
                return idle.pop(), True
        return self._connect(scheme, netloc, timeout), False

    def close(self) -> None:
        """Drop every pooled connection and refuse re-pooling from
        in-flight requests (deterministic FD release; the server's
        close path calls this so peers' sockets don't linger)."""
        with self._pool_lock:
            self._closed = True
            pools, self._pool = list(self._pool.values()), {}
        for idle in pools:
            for conn in idle:
                try:
                    conn.close()
                except Exception:
                    pass

    def _release(self, scheme: str, netloc: str, conn) -> None:
        with self._pool_lock:
            if not self._closed:
                idle = self._pool.setdefault((scheme, netloc), [])
                if len(idle) < self.MAX_IDLE_PER_HOST:
                    idle.append(conn)
                    return
        conn.close()

    def _request(self, method: str, url: str, body: bytes | None = None,
                 ctype: str = "application/json",
                 accept: str | None = None,
                 error_decoder=None,
                 timeout: float | None = None,
                 retry_shed: bool = True) -> bytes:
        """One transport path for JSON and protobuf requests over
        pooled keep-alive connections; ``error_decoder(raw) -> str``
        extracts the error detail from a non-2xx body (default: JSON
        {"error": ...})."""
        from urllib.parse import urlsplit

        parts = urlsplit(url)
        path = parts.path + (f"?{parts.query}" if parts.query else "")
        headers = {}
        if body is not None:
            headers["Content-Type"] = ctype
        if accept:
            headers["Accept"] = accept
        from pilosa_tpu import tracing

        headers.update(tracing.inject_headers())  # trace follows the RPC
        # admission class follows the RPC too: call sites wrapped in
        # serve.admission.rpc_class (syncer/resize/replication =
        # internal, import fan-out = ingest) land in the right gate on
        # the receiving node
        klass = _admission.current_rpc_class()
        if klass is not None:
            headers["X-Pilosa-Class"] = klass
        # the caller's budget: an active deadline scope wins; otherwise
        # the request timeout IS the budget (the deadline header is
        # derived from it so the server never works past the point this
        # client would have hung up)
        dl = _deadline.current()
        budget_end = (dl.expires_mono if dl is not None
                      else time.monotonic()
                      + (self.timeout if timeout is None else timeout))
        shed_retries = 0
        import http.client as _hc

        # Disconnect-class failures on a POOLED connection retry on the
        # next connection (the pool drains toward a fresh one, so a
        # node that idled out ALL pooled sockets still answers on the
        # first request).  A retried request MAY have reached the
        # server when the drop happened at the response stage — safe
        # here because this wire's writes are idempotent by design
        # (Set/import are set-semantics, DDL and attrs are upserts, key
        # allocation returns existing ids); timeouts never retry.
        _stale = (_hc.RemoteDisconnected, _hc.BadStatusLine,
                  _hc.CannotSendRequest, BrokenPipeError,
                  ConnectionResetError, ConnectionAbortedError)
        while True:
            if _fi.armed:
                # failpoint: the production RPC send path (errors here
                # surface as TransportError, exactly like a dead wire)
                _fi.hit("client.request.send")
            remaining = budget_end - time.monotonic()
            if remaining <= 0:
                # the caller's deadline is spent: stop, never silently
                # outlive short budgets on the flat request timeout
                raise DeadlineExceededError(
                    f"caller deadline spent before request to {url}")
            headers[_deadline.HEADER] = f"{remaining:.3f}"
            # effective socket timeout: the per-call override (or the
            # pooled default) CLAMPED to the caller's remaining budget
            # — a stalled peer must not hold this thread (and its
            # admission slot) 30s past an expired deadline
            eff_timeout = min(self.timeout if timeout is None
                              else timeout, remaining)
            conn = None
            pooled = False
            try:
                # _acquire may CONNECT (refused/unreachable raises here,
                # inside the same error mapping as request IO)
                conn, pooled = self._acquire(parts.scheme, parts.netloc,
                                             eff_timeout)
                if conn.sock is not None:
                    # restored before the connection re-pools below
                    conn.sock.settimeout(eff_timeout)
                conn.request(method, path, body=body, headers=headers)
                resp = conn.getresponse()
                raw = resp.read()
                if conn.sock is not None:
                    conn.sock.settimeout(self.timeout)
            except (ConnectionError, TimeoutError, OSError,
                    _hc.HTTPException) as e:
                if conn is not None:
                    conn.close()
                if pooled and isinstance(e, _stale):
                    # each failed pooled conn was closed, not re-pooled,
                    # so this loop reaches a fresh connection within
                    # MAX_IDLE_PER_HOST iterations
                    continue
                raise TransportError(
                    f"node unreachable: {url}: {e}") from e
            if resp.will_close:
                conn.close()
            else:
                self._release(parts.scheme, parts.netloc, conn)
            if retry_shed and resp.status in (429, 503):
                # the peer shed this request (admission control);
                # honor Retry-After with a cap + jitter, bounded by
                # the caller's remaining budget
                delay = self._shed_delay(resp.getheader("Retry-After"))
                if (shed_retries < self.MAX_SHED_RETRIES
                        and delay is not None
                        and budget_end - time.monotonic() > delay):
                    shed_retries += 1
                    self._sleep(delay)
                    continue
            if resp.status >= 400:
                detail = ""
                try:
                    if error_decoder is not None:
                        detail = error_decoder(raw)
                    else:
                        detail = json.loads(raw).get("error", "")
                except Exception:
                    pass
                if (resp.status in (429, 503)
                        and resp.getheader("Retry-After") is not None):
                    # the peer's admission gate shed this request:
                    # a TransportError subclass so best-effort
                    # fan-outs skip the overloaded peer like an
                    # unreachable one, while liveness checks can
                    # still read it as proof of life
                    raise ShedByPeerError(
                        f"shed by peer: {url}: "
                        f"{detail or f'http {resp.status}'}",
                        resp.status)
                raise ClientError(resp.status,
                                  detail or f"http {resp.status}")
            if _fi.armed:
                # failpoint: the response was read off the wire but is
                # "lost" before the caller sees it (a mid-response
                # drop; the request DID execute on the peer)
                _fi.hit("client.request.recv")
            return raw

    @classmethod
    def _shed_delay(cls, retry_after: str | None) -> float | None:
        """Retry-After header -> sleep seconds (capped, jittered), or
        None when the response carried no usable hint — a 503 without
        Retry-After is not an admission shed and is not retried."""
        if retry_after is None:
            return None
        try:
            base = float(retry_after)
        except ValueError:
            return None
        base = min(max(base, 0.0), cls.RETRY_AFTER_CAP_S)
        return base * (1.0 + 0.25 * random.random())

    def _json(self, method: str, url: str, obj=None):
        body = None if obj is None else json.dumps(obj).encode()
        return json.loads(self._request(method, url, body) or b"null")

    def post_json(self, url: str, obj=None):
        """Public JSON POST over the pooled transport (benchmarks and
        embedding clients)."""
        return self._json("POST", url, obj)

    # -------------------------------------------------------------- query

    def query_node(self, uri: str, index: str, pql: str,
                   shards: list[int] | None = None, remote: bool = True,
                   nocache: bool = False, nodelta: bool = False,
                   nocontainers: bool = False, nomesh: bool = False,
                   notiers: bool = False, novm: bool = False,
                   partial: bool = False,
                   tenant: str | None = None):
        """POST /index/{i}/query with Remote semantics over the
        protobuf wire — node-to-node RPC speaks protobuf like the
        reference's InternalClient (http/client.go:268 QueryNode;
        external clients may still POST JSON).  Returns decoded result
        objects.  ``nocache`` rides as the same ?nocache=1 query param
        external clients use, so the peer's handler opts the sub-query
        out of its result cache; ``nodelta`` rides as ?nodelta=1 the
        same way (the peer compacts its pending ingest deltas and
        answers from pure base state); ``nocontainers`` rides as
        ?nocontainers=1 (the peer routes its fused reads through the
        dense pre-container path); ``nomesh`` rides as ?nomesh=1 (the
        peer runs its fused dispatches on the pre-mesh single-device
        programs); ``notiers`` rides as ?notiers=1 (the peer bypasses
        its tiered residency: inline rebuilds, drop-not-demote);
        ``novm`` rides as ?novm=1 (the peer routes its coalesced
        sparse reads through the pre-VM engines); ``tenant`` rides as
        ?tenant= so the peer charges the origin's tenant ([tenants]
        isolation)."""
        from pilosa_tpu import proto

        body = proto.encode(proto.QUERY_REQUEST, {
            "query": pql,
            "shards": [int(s) for s in (shards or [])],
            "remote": remote,
        })
        path = f"{uri}/index/{index}/query"
        flags = [f for f, on in (("nocache=1", nocache),
                                 ("nodelta=1", nodelta),
                                 ("nocontainers=1", nocontainers),
                                 ("nomesh=1", nomesh),
                                 ("notiers=1", notiers),
                                 ("novm=1", novm),
                                 ("partial=1", partial)) if on]
        if tenant:
            from urllib.parse import quote

            flags.append("tenant=" + quote(tenant, safe=""))
        if flags:
            path += "?" + "&".join(flags)
        raw = self._request(
            "POST", path, body,
            ctype="application/x-protobuf",
            accept="application/x-protobuf",
            error_decoder=lambda b: proto.decode(proto.QUERY_RESPONSE,
                                                 b)["err"],
        )
        d = proto.decode(proto.QUERY_RESPONSE, raw)
        return [proto.proto_to_result(r) for r in d["results"]]

    def send_message(self, uri: str, message: dict,
                     timeout: float | None = None,
                     retry_shed: bool = True) -> dict:
        body = json.dumps(message).encode()
        raw = self._request("POST", f"{uri}/internal/cluster/message",
                            body, timeout=timeout,
                            retry_shed=retry_shed)
        return json.loads(raw or b"null")

    # ------------------------------------------------------------- schema

    def schema(self, uri: str) -> list[dict]:
        return self._json("GET", f"{uri}/schema")["indexes"]

    def create_index(self, uri: str, index: str, options: dict | None = None):
        return self._json("POST", f"{uri}/index/{index}",
                          {"options": options or {}})

    def create_field(self, uri: str, index: str, field: str,
                     options: dict | None = None):
        return self._json("POST", f"{uri}/index/{index}/field/{field}",
                          {"options": options or {}})

    def status(self, uri: str) -> dict:
        return self._json("GET", f"{uri}/status")

    # ------------------------------------------------------------- import

    def import_bits(self, uri: str, index: str, field: str, rows, cols,
                    timestamps=None, row_keys=None, col_keys=None,
                    clear: bool = False):
        body = {}
        if rows:
            body["rowIDs"] = list(rows)
        if cols:
            body["columnIDs"] = list(cols)
        if timestamps:
            body["timestamps"] = list(timestamps)
        if row_keys:
            body["rowKeys"] = list(row_keys)
        if col_keys:
            body["columnKeys"] = list(col_keys)
        q = "?clear=true" if clear else ""
        return self._json("POST", f"{uri}/index/{index}/field/{field}/import{q}",
                          body)

    def import_values(self, uri: str, index: str, field: str, cols, values,
                      col_keys=None):
        body = {"columnIDs": list(cols), "values": list(values)}
        if col_keys:
            body["columnKeys"] = list(col_keys)
        return self._json("POST",
                          f"{uri}/index/{index}/field/{field}/import-value",
                          body)

    def import_roaring(self, uri: str, index: str, field: str, shard: int,
                       data: bytes, clear: bool = False):
        q = "?clear=true" if clear else ""
        return self._request(
            "POST",
            f"{uri}/index/{index}/field/{field}/import-roaring/{shard}{q}",
            data, ctype="application/octet-stream")

    # ------------------------------------------------------ anti-entropy

    def fragment_blocks(self, uri: str, index: str, field: str, view: str,
                        shard: int) -> list[dict]:
        d = self._json(
            "GET",
            f"{uri}/internal/fragment/blocks?index={index}&field={field}"
            f"&view={view}&shard={shard}")
        return d["blocks"]

    def fragment_block_data(self, uri: str, index: str, field: str,
                            view: str, shard: int, block: int):
        d = self._json(
            "GET",
            f"{uri}/internal/fragment/block/data?index={index}&field={field}"
            f"&view={view}&shard={shard}&block={block}")
        return d["rowIDs"], d["columnIDs"]

    def retrieve_fragment(self, uri: str, index: str, field: str, view: str,
                          shard: int) -> bytes:
        """Serialized roaring fragment for resize transfer
        (http/client.go:742 RetrieveShardFromURI)."""
        return self._request(
            "GET",
            f"{uri}/internal/fragment/data?index={index}&field={field}"
            f"&view={view}&shard={shard}")

    def debug_json(self, uri: str, path: str,
                   timeout: float | None = None) -> dict:
        """GET a peer's JSON debug surface (/debug/queries,
        /debug/devices) for the cluster-wide fan-in routes.  Tagged
        ``rpc_class("internal")`` at the call site and bounded by the
        fan-in timeout; the deadline header rides the request like any
        other RPC, so a peer drowning in queries sheds this probe
        instead of queueing it."""
        raw = self._request("GET", f"{uri}{path}", timeout=timeout,
                            retry_shed=False)
        return json.loads(raw or b"null")

    def translate_data(self, uri: str, index: str, field: str | None,
                       offset: int):
        q = f"?index={index}&offset={offset}"
        if field:
            q += f"&field={field}"
        d = self._json("GET", f"{uri}/internal/translate/data{q}")
        return [(e["offset"], e["id"], e["key"]) for e in d["entries"]]


class HTTPTransport(Transport):
    """Cluster transport over the HTTP control plane — the production
    fabric (reference: InternalClient used by executor/cluster); tests
    use LocalTransport instead."""

    def __init__(self, client: InternalClient | None = None):
        self.client = client or InternalClient()

    def query_node(self, node: Node, index: str, pql: str, shards,
                   nocache: bool = False, nodelta: bool = False,
                   nocontainers: bool = False, nomesh: bool = False,
                   notiers: bool = False, novm: bool = False,
                   partial: bool = False,
                   tenant: str | None = None):
        # the protobuf client already returns decoded result objects
        return self.client.query_node(node.uri, index, pql, shards,
                                      nocache=nocache, nodelta=nodelta,
                                      nocontainers=nocontainers,
                                      nomesh=nomesh, notiers=notiers,
                                      novm=novm,
                                      partial=partial, tenant=tenant)

    def send_message(self, node: Node, message: dict) -> dict:
        return self.client.send_message(node.uri, message)

    def send_message_timeout(self, node: Node, message: dict,
                             timeout: float) -> dict:
        """Bounded-dial variant for membership probes: a dead host
        that swallows packets must fail the ping at the probe budget,
        not the pooled connection's 30 s default.  Shed responses are
        NOT retried here — a 429/503 from the peer's admission gate is
        already proof of life (membership treats it as such), and
        sleeping out Retry-After inside the failure detector would
        stall the SWIM round exactly when the cluster is overloaded."""
        return self.client.send_message(node.uri, message,
                                        timeout=timeout,
                                        retry_shed=False)
