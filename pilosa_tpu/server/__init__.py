"""Server assembly: HTTP handler, node-to-node client, lifecycle.

Parity target: the reference's layers 7-8 (http/ package, server.go,
server/ package).
"""

from pilosa_tpu.server.handler import (
    Handler,
    deserialize_results,
    serialize_result,
)
from pilosa_tpu.server.client import InternalClient, HTTPTransport

__all__ = [
    "Handler",
    "serialize_result",
    "deserialize_results",
    "InternalClient",
    "HTTPTransport",
]
