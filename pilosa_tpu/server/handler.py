"""HTTP handler: the REST surface of one node.

Parity target: the reference's gorilla/mux route table
(http/handler.go:273-322) — public ``/index/...`` + ``/schema`` +
``/status`` routes, internal ``/internal/...`` node-to-node routes, and
infra routes (``/metrics``, ``/debug/vars``, ``/version``).  The query
and import endpoints negotiate JSON vs protobuf like the reference
(http/handler.go:499 handlePostQuery, :1002 content negotiation;
wire schemas in ``pilosa_tpu.proto``); the control plane speaks JSON.

Built on the stdlib ThreadingHTTPServer — the server side of the DCN
control plane; the TPU data path never goes through HTTP.
"""

from __future__ import annotations

import base64
import io
import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import numpy as np

from pilosa_tpu.api import (
    API,
    ApiError,
    ApiMethodNotAllowedError,
    ConflictError,
    NotFoundError,
)
from pilosa_tpu.models.field import FieldOptions
from pilosa_tpu.models.index import IndexOptions
from pilosa_tpu.models.row import Row
from pilosa_tpu.parallel.cluster import ShedByPeerError
from pilosa_tpu.parallel.executor import ShardsUnavailableError
from pilosa_tpu.parallel.results import GroupCount, Pair, PairField, ValCount
from pilosa_tpu.serve import admission as _admission
from pilosa_tpu.serve import deadline as _deadline
from pilosa_tpu.serve import tenant as _tenant
from pilosa_tpu.serve.deadline import DeadlineExceededError


def serialize_result(res):
    """Query result -> JSON-able value, matching the reference's JSON
    response shapes (http/handler.go handlePostQuery; pilosa.go
    MarshalJSON impls)."""
    if isinstance(res, Row):
        out = {}
        if res.exclude_columns:
            pass  # columns never materialized (Options excludeColumns)
        elif res.keys:
            out["keys"] = list(res.keys)
        else:
            out["columns"] = [int(c) for c in res.columns()]
        if res.attrs:
            out["attrs"] = res.attrs
        return out
    if isinstance(res, Pair):
        return _pair_dict(res)
    if isinstance(res, PairField):
        return _pair_dict(res.pair)
    if isinstance(res, ValCount):
        return {"value": int(res.val), "count": int(res.count)}
    if isinstance(res, GroupCount):
        return {
            "group": [_field_row_dict(fr) for fr in res.group],
            "count": int(res.count),
        }
    if isinstance(res, list):
        return [serialize_result(r) for r in res]
    if isinstance(res, (np.integer,)):
        return int(res)
    if isinstance(res, (bool, int, str)) or res is None:
        return res
    raise TypeError(f"unserializable result type: {type(res)!r}")


def deserialize_results(raw: list) -> list:
    """JSON query results -> internal result types; the inverse of
    ``serialize_result``, used by HTTPTransport so remote partials feed
    the same reduce paths as local ones (the reference decodes protobuf
    QueryResponse into the same structs, encoding/proto/proto.go)."""
    return [deserialize_result(r) for r in raw]


def deserialize_result(r):
    if isinstance(r, dict):
        if "columns" in r or "keys" in r:
            row = Row.from_columns(r.get("columns") or [])
            row.keys = list(r.get("keys") or [])
            row.attrs = r.get("attrs") or {}
            return row
        if "group" in r:
            from pilosa_tpu.parallel.results import FieldRow

            return GroupCount(
                group=[
                    FieldRow(
                        field=g["field"],
                        row_id=int(g.get("rowID", 0)),
                        row_key=g.get("rowKey", ""),
                        value=g.get("value"),
                    )
                    for g in r["group"]
                ],
                count=int(r["count"]),
            )
        if "value" in r:
            return ValCount(val=int(r["value"]), count=int(r["count"]))
        if "count" in r:
            return Pair(id=int(r.get("id", 0)), key=r.get("key", ""),
                        count=int(r["count"]))
    if isinstance(r, list):
        return [deserialize_result(x) for x in r]
    return r


def _pair_dict(p: Pair) -> dict:
    d = {"count": int(p.count)}
    if p.key:
        d["key"] = p.key
    else:
        d["id"] = int(p.id)
    return d


def _field_row_dict(fr) -> dict:
    d = {"field": fr.field}
    if fr.row_key:
        d["rowKey"] = fr.row_key
    else:
        d["rowID"] = int(fr.row_id)
    if fr.value is not None:
        d["value"] = int(fr.value)
    return d


# Upper bound on accepted request bodies; large enough for bulk roaring
# imports, small enough that one request cannot exhaust host memory.
MAX_REQUEST_BYTES = 256 << 20

# (method, compiled path regex, handler-method name, admission class)
_ROUTES: list[tuple[str, re.Pattern, str, str | None]] = []


def route(method: str, pattern: str, klass: str | None = None):
    """Register a route; `{name}` segments capture path params
    (the gorilla/mux analog, http/handler.go:273).  ``klass`` assigns
    the route's admission class (serve/admission.py): ``query`` for
    user PQL, ``ingest`` for imports, ``internal`` for node-to-node
    RPC; None leaves the route ungated (cheap control-plane and debug
    surfaces)."""
    rx = re.compile(
        "^" + re.sub(r"\{(\w+)\}", r"(?P<\1>[^/]+)", pattern) + "$"
    )

    def deco(fn):
        _ROUTES.append((method, rx, fn.__name__, klass))
        return fn

    return deco


class Handler:
    """Routes HTTP requests to an API instance and serves forever on a
    background thread (http/handler.go:46)."""

    #: accept-side headroom above the admission gate's capacity for
    #: ungated infra routes (/metrics, /debug/*, schema) and idle
    #: keep-alive connections.  NOTE the cap counts CONNECTIONS (each
    #: holds one handler thread for its lifetime — that is the
    #: resource being bounded), not active requests: a large fleet of
    #: idle keep-alive clients consumes headroom even while the
    #: admission gate is empty.  Idle connections are reaped by the
    #: per-connection 60 s read timeout, so the steady state tracks
    #: live clients; size the headroom for the expected client pool
    #: (MAX_IDLE_PER_HOST per peer node + monitoring scrapers).
    ACCEPT_HEADROOM = 64

    def __init__(self, api: API, host: str = "127.0.0.1", port: int = 0,
                 stats=None, tracer=None, tls_cert: str | None = None,
                 tls_key: str | None = None, heap_frames: int = 4,
                 admission=None, max_threads: int | None = None,
                 peer_client=None, fanin_timeout: float = 2.0):
        self.api = api
        self.stats = stats
        self.tracer = tracer
        self.heap_frames = heap_frames  # ?start=1 tracemalloc depth
        # cluster-wide debug fan-in (/debug/cluster/*): the server
        # assembly passes its pooled InternalClient; None builds one
        # lazily on first use ([observe] fanin-timeout bounds each peer)
        self.peer_client = peer_client
        self._peer_client_lock = threading.Lock()
        self._owns_peer_client = False  # lazily built -> closed here
        self.fanin_timeout = fanin_timeout
        # admission gate (serve/admission.AdmissionController) — the
        # only accept-side gate between HTTP and device dispatch
        self.admission = admission
        # cap on in-flight handler threads: a connection flood degrades
        # to fast 503s instead of thread exhaustion.  Defaults to the
        # admission gate's total capacity (sum of class caps + queue
        # depths) + headroom; None disables the cap.
        if max_threads is None and admission is not None \
                and admission.enabled:
            max_threads = admission.total_capacity() + self.ACCEPT_HEADROOM
        self.max_threads = max_threads
        self._threads_lock = threading.Lock()
        self._threads_active = 0
        # optional zero-arg callable returning the latest released
        # version string (diagnostics.check_version); None = the
        # local-only default, never phones home
        self.version_fetcher = None
        self.tls = bool(tls_cert)
        handler_self = self

        class _Req(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # keep-alive responses must not sit in Nagle's buffer
            # waiting for the client's delayed ACK
            disable_nagle_algorithm = True
            timeout = 60  # per-connection read timeout

            def setup(self):
                # the TLS handshake runs HERE, in the per-request thread
                # with a timeout — never inside the accept loop, where a
                # stalled client would hang the whole node
                self.request.settimeout(self.timeout)
                if handler_self.tls:
                    self.request.do_handshake()
                super().setup()

            def log_message(self, fmt, *args):  # quiet by default
                pass

            def _dispatch(self, method: str):
                handler_self._handle(self, method)

            def do_GET(self):
                self._dispatch("GET")

            def do_POST(self):
                self._dispatch("POST")

            def do_DELETE(self):
                self._dispatch("DELETE")

        class _Srv(ThreadingHTTPServer):
            # the stdlib default listen backlog of 5 drops/resets
            # connections under a burst of concurrent clients — exactly
            # the arrival pattern the query coalescer exists to serve
            request_queue_size = 128

            def process_request(self, request, client_address):
                # accept-side thread cap: past the limit, refuse with a
                # fast 503 written from the accept loop (bounded by a
                # short socket timeout) instead of spawning yet another
                # thread — a connection flood degrades to fast refusals
                # rather than thread exhaustion
                if not handler_self._thread_slot_acquire():
                    handler_self._refuse_connection(request)
                    self.shutdown_request(request)
                    return
                try:
                    super().process_request(request, client_address)
                except BaseException:
                    # the worker thread never started; its release in
                    # process_request_thread will not run
                    handler_self._thread_slot_release()
                    raise

            def process_request_thread(self, request, client_address):
                try:
                    super().process_request_thread(request,
                                                   client_address)
                finally:
                    handler_self._thread_slot_release()

        self.httpd = _Srv((host, port), _Req)
        # close() must not block on handler threads parked in idle
        # keep-alive reads (daemon threads die with the process; bounded
        # by the per-connection timeout otherwise)
        self.httpd.block_on_close = False
        if tls_cert:
            # TLS termination (reference server/tlsconfig.go; https
            # scheme config server/config.go:60)
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(tls_cert, tls_key or tls_cert)
            self.httpd.socket = ctx.wrap_socket(
                self.httpd.socket, server_side=True,
                do_handshake_on_connect=False)
        self.port = self.httpd.server_address[1]
        self.host = host
        # reopen support: close() closes the listening socket, so a
        # reopened server must REBUILD it (on the same port — s.uri
        # stays valid) instead of serve_forever-ing a dead fd
        self._srv_cls, self._req_cls = _Srv, _Req
        self._tls_cert, self._tls_key = tls_cert, tls_key
        self._thread: threading.Thread | None = None
        # /debug/pprof/profile serialization: a second concurrent
        # sampler would double-count stacks and burn CPU for up to 30 s
        # while holding an HTTP worker thread; try-lock -> 409
        self._profile_lock = threading.Lock()
        # set by close(): surviving keep-alive worker threads refuse
        # (503) instead of serving from a closed holder
        self._draining = False

    @property
    def uri(self) -> str:
        scheme = "https" if self.tls else "http"
        return f"{scheme}://{self.host}:{self.port}"

    def serve_background(self) -> None:
        self._draining = False
        if self.httpd.fileno() == -1:
            # reopened after close(): rebuild the listener on the SAME
            # port (server_close() closed the old socket; serving the
            # dead fd raised in the accept thread and the reopened
            # server silently refused every connection)
            self.httpd = self._srv_cls((self.host, self.port),
                                       self._req_cls)
            self.httpd.block_on_close = False
            if self._tls_cert:
                import ssl

                ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
                ctx.load_cert_chain(self._tls_cert,
                                    self._tls_key or self._tls_cert)
                self.httpd.socket = ctx.wrap_socket(
                    self.httpd.socket, server_side=True,
                    do_handshake_on_connect=False)
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._draining = True
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        with self._peer_client_lock:
            # only a client this handler lazily built is its to close;
            # a server-injected one is closed by the server
            if self._owns_peer_client and self.peer_client is not None:
                self.peer_client.close()
                self.peer_client = None
                self._owns_peer_client = False

    # --------------------------------------------------- accept-side cap

    def _thread_slot_acquire(self) -> bool:
        if self.max_threads is None:
            return True
        with self._threads_lock:
            if self._threads_active < self.max_threads:
                self._threads_active += 1
                return True
        # stats OUTSIDE the lock every accept contends on, and
        # exception-guarded: a slow or raising backend must neither
        # serialize the accept path nor swallow the raw 503 refusal
        if self.stats is not None:
            try:
                self.stats.count("admission.accept_503", 1)
            except Exception:  # noqa: BLE001
                pass
        return False

    def _thread_slot_release(self) -> None:
        if self.max_threads is None:
            return
        with self._threads_lock:
            self._threads_active -= 1

    _REFUSE_BODY = b'{"error":"server overloaded"}'
    _REFUSE_RESPONSE = (
        b"HTTP/1.1 503 Service Unavailable\r\n"
        b"Content-Type: application/json\r\n"
        b"Content-Length: " + str(len(_REFUSE_BODY)).encode() + b"\r\n"
        b"Retry-After: 1\r\n"
        b"Connection: close\r\n\r\n" + _REFUSE_BODY)

    def _refuse_connection(self, request) -> None:
        """Best-effort raw 503 from the accept loop (short timeout so a
        stalled client cannot hang accepts).  TLS sockets have not
        handshaken yet (do_handshake_on_connect=False), so they just
        close — a plaintext 503 would read as a protocol error."""
        if self.tls:
            return
        try:
            request.settimeout(1.0)
            request.sendall(self._REFUSE_RESPONSE)
        except OSError:
            pass

    # ------------------------------------------------------------ plumbing

    def _handle(self, req: BaseHTTPRequestHandler, method: str) -> None:
        if self._draining:
            # close() ran, but a pooled keep-alive connection's worker
            # thread outlives httpd.shutdown(): refuse instead of
            # answering from a closed holder (an empty fragment set
            # would serve WRONG results, not an error)
            try:
                req.send_response(503)
                req.send_header("Retry-After", "1")
                req.send_header("Content-Length", "0")
                req.send_header("Connection", "close")
                req.end_headers()
            except OSError:
                pass
            return
        parsed = urlparse(req.path)
        path = parsed.path.rstrip("/") or "/"
        params = {k: v[0] for k, v in parse_qs(parsed.query).items()}
        for m, rx, name, klass in _ROUTES:
            if m != method:
                continue
            match = rx.match(path)
            if match is None:
                continue
            if self.stats is not None:
                self.stats.count_with_tags("http.request", 1, 1.0,
                                           [f"useragent:{req.headers.get('User-Agent', '')}"])
            # deadline + admission run BEFORE the body is read: a shed
            # request must not pay a 256MB body upload first (the
            # unread body forces the connection closed, like 413)
            dl_hdr = req.headers.get(_deadline.HEADER)
            dl = None
            if dl_hdr is not None:
                try:
                    dl = _deadline.parse_header(dl_hdr)
                except ValueError:
                    # the body stays unread (like 413/shed): the
                    # keep-alive connection must close or its bytes
                    # would parse as the next request
                    req.close_connection = True
                    self._error(req, 400,
                                f"invalid {_deadline.HEADER} header: "
                                f"{dl_hdr!r}")
                    return
            # tenant identity ([tenants] isolation): the
            # X-Pilosa-Tenant header (authenticated clients), or
            # ?tenant= (tools and node-to-node sub-query forwarding —
            # exactly like ?nocache).  A missing/empty id rides the
            # default tier; the label is an accounting key, never a
            # credential, so malformed values degrade instead of 400.
            tenant = _tenant.clean(req.headers.get("X-Pilosa-Tenant")
                                   or params.get("tenant"))
            # stash the cleaned label on the request so handle_query's
            # ExecOptions reuses THIS value — parsing twice invites the
            # two sites drifting apart (quota charged to one tenant,
            # cache/residency to another)
            req._pilosa_tenant = tenant
            ticket = None
            if self.admission is not None and klass is not None:
                k = klass
                if klass == "internal":
                    # node-to-node routes accept ONE class re-tag (the
                    # X-Pilosa-Class stamped by serve.admission
                    # rpc_class at the call site) so import replica
                    # deliveries and key allocation ride the ingest
                    # gate, not internal.  "query" is deliberately NOT
                    # honored — a header must never let internal
                    # traffic jump into the highest-priority gate.
                    if req.headers.get("X-Pilosa-Class") == "ingest":
                        k = "ingest"
                if dl is None and self.admission.default_deadline > 0:
                    dl = _deadline.Deadline(
                        self.admission.default_deadline)
                try:
                    ticket = self.admission.acquire(k, dl,
                                                    tenant=tenant)
                except _admission.ShedError as e:
                    self._record_shed(
                        match.groupdict().get("index", path), k, e,
                        headers=req.headers)
                    req.close_connection = True
                    # structured shed body: ``reason`` + the tenant id
                    # let a client tell "I am over quota"
                    # (tenant-queue-full) from "the server is
                    # drowning" (queue-full / deadline-unmeetable)
                    body_obj = {"error": str(e), "reason": e.reason,
                                "class": e.klass}
                    if e.tenant is not None:
                        body_obj["tenant"] = e.tenant
                    self._json(req, body_obj, e.status,
                               headers={"Retry-After":
                                        str(e.retry_after)})
                    return
                except ShedByPeerError as e:
                    # an armed admission.acquire failpoint injects
                    # error(shed) here — surface it exactly like a
                    # capacity refusal (503 + Retry-After), never an
                    # unhandled 500
                    req.close_connection = True
                    self._error(req, 503, str(e),
                                headers={"Retry-After": "1"})
                    return
            try:
                body = b""
                length = int(req.headers.get("Content-Length") or 0)
                if length > MAX_REQUEST_BYTES:
                    # the body stays unread; the keep-alive connection
                    # must close or its bytes would parse as the next
                    # request
                    req.close_connection = True
                    self._error(req, 413,
                                f"request body exceeds "
                                f"{MAX_REQUEST_BYTES} bytes")
                    return
                if length:
                    body = req.rfile.read(length)
                # trace-context extract + a server span per route (the
                # reference's tracing middleware, http/handler.go:321);
                # entering the span makes it the parent of every span
                # the handler starts (api.*, executor.*)
                from pilosa_tpu import observe, tracing

                parent = tracing.extract_headers(req.headers)
                adm = ticket.info() if ticket is not None else None
                with tracing.start_span(f"http.{name}",
                                        parent=parent) as span, \
                        _deadline.scope(dl), \
                        observe.admission_scope(adm):
                    span.set_tag("http.path", path)
                    getattr(self, name)(req, params, match.groupdict(),
                                        body)
            except NotFoundError as e:
                self._error(req, 404, str(e))
            except ConflictError as e:
                self._error(req, 409, str(e))
            except ApiMethodNotAllowedError as e:
                self._error(req, 405, str(e))
            except DeadlineExceededError as e:
                # admitted but expired mid-execution: the executor's
                # stage checks dropped it before device dispatch
                if self.admission is not None and ticket is not None:
                    self.admission.count_expired(ticket.klass)
                self._error(req, 503, str(e))
            except ShardsUnavailableError as e:
                # structured replica exhaustion (chaos round): an
                # availability condition, not a client error — 503
                # with the shard list and per-replica causes so
                # operators (and retrying clients) see WHAT is gone
                # and WHY, not a flat string
                self._json(req, {
                    "error": str(e),
                    "unavailableShards": e.shards,
                    "causes": {str(s): e.causes.get(s, {})
                               for s in e.shards},
                }, 503, headers={"Retry-After": "1"})
            except (ApiError, ValueError, KeyError, TypeError) as e:
                self._error(req, 400, str(e))
            except ShedByPeerError as e:
                # a remote sub-request was shed by a peer's admission
                # gate (and the client's retries are exhausted):
                # surface overload honestly, with a back-off signal,
                # instead of masking it as a 500
                self._error(req, 503, str(e),
                            headers={"Retry-After": "1"})
            except Exception as e:  # internal error; keep serving
                from pilosa_tpu.server.client import ClientError

                if (isinstance(e, ClientError)
                        and e.status in (429, 503)):
                    # a shed that reached us as a raw ClientError
                    # (non-standard transport) still reads as overload
                    self._error(req, 503, str(e))
                else:
                    self._error(req, 500, f"{type(e).__name__}: {e}")
            finally:
                if ticket is not None:
                    ticket.release()
            return
        self._error(req, 404, "not found")

    def _record_shed(self, index: str, klass: str,
                     e: "_admission.ShedError", headers=None) -> None:
        """Shed requests never execute, so the flight recorder is told
        directly — /debug/queries and the slow-query log must show the
        overload story (outcome ``shed``/``expired``, with the queue
        wait the request burned before the refusal).  The shed happens
        BEFORE the handler span opens, so the caller's traceparent is
        extracted here — a shed record must still be one
        /debug/trace/{id} away."""
        recorder = getattr(self.api.executor, "recorder", None)
        if recorder is not None:
            from pilosa_tpu import tracing

            parent = (tracing.extract_headers(headers)
                      if headers is not None else None)
            recorder.record_shed(
                index, "", klass, e.outcome, str(e),
                wait_ns=e.wait_ns, tenant=e.tenant,
                trace_id=parent.trace_id if parent is not None
                else None)

    def _json(self, req, obj, status: int = 200,
              headers: dict | None = None) -> None:
        data = json.dumps(obj).encode()
        req.send_response(status)
        req.send_header("Content-Type", "application/json")
        req.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            req.send_header(k, v)
        req.end_headers()
        req.wfile.write(data)

    def _bytes(self, req, data: bytes, ctype: str = "application/octet-stream",
               status: int = 200) -> None:
        req.send_response(status)
        req.send_header("Content-Type", ctype)
        req.send_header("Content-Length", str(len(data)))
        req.end_headers()
        req.wfile.write(data)

    def _error(self, req, status: int, msg: str,
               headers: dict | None = None) -> None:
        try:
            self._json(req, {"error": msg}, status, headers=headers)
        except (BrokenPipeError, ConnectionResetError):
            pass

    # ------------------------------------------------------ public routes

    @route("GET", "/")
    def handle_root(self, req, params, path, body):
        self._json(req, {
            "name": "pilosa-tpu",
            "version": self.api.version(),
            "docs": "see /schema, /status, /index/{index}/query",
        })

    @route("GET", "/version")
    def handle_version(self, req, params, path, body):
        # update-check surface (reference handleGetVersion +
        # diagnostics CheckVersion); local-only by design — see
        # diagnostics.check_version
        from pilosa_tpu import diagnostics

        self._json(req, diagnostics.check_version(self.version_fetcher))

    @route("GET", "/info")
    def handle_info(self, req, params, path, body):
        self._json(req, self.api.info())

    @route("GET", "/status")
    def handle_status(self, req, params, path, body):
        self._json(req, {
            "state": self.api.state(),
            "nodes": self.api.hosts(),
            "localID": self.api.cluster.local_id,
        })

    @route("GET", "/hosts")
    def handle_hosts(self, req, params, path, body):
        self._json(req, self.api.hosts())

    @route("GET", "/internal/nodes")
    def handle_internal_nodes(self, req, params, path, body):
        # reference /internal/nodes (http/handler.go handleGetNodes)
        self._json(req, self.api.hosts())

    @route("POST", "/recalculate-caches")
    def handle_recalculate_caches(self, req, params, path, body):
        """Force TopN caches up to date cluster-wide (reference
        handleRecalculateCaches, http/handler.go)."""
        self.api.recalculate_caches(
            remote=params.get("remote") == "true")
        self._json(req, {})

    @route("POST", "/internal/translate/keys", klass="ingest")
    def handle_translate_keys(self, req, params, path, body):
        """Key -> id translation RPC (reference handlePostTranslateKeys;
        wire form TranslateKeysRequest/Response).  Accepts protobuf or
        JSON {"index", "field", "keys"}; ids are allocated via the
        single-writer path."""
        from pilosa_tpu import proto

        if "protobuf" in req.headers.get("Content-Type", ""):
            d = proto.decode(proto.TRANSLATE_KEYS_REQUEST, body)
        else:
            d = json.loads(body)
        ids = self.api.node.translate_keys_cluster(
            d["index"], d.get("field") or None, d.get("keys") or [],
            create=True)
        if "protobuf" in req.headers.get("Accept", ""):
            self._proto(req, proto.encode(
                proto.TRANSLATE_KEYS_RESPONSE, {"ids": [int(i) for i in ids]}))
        else:
            self._json(req, {"ids": [int(i) for i in ids]})

    @route("GET", "/index")
    def handle_get_indexes(self, req, params, path, body):
        # reference handleGetIndexes: same shape as /schema
        self._json(req, {"indexes": self.api.schema()})

    @route("GET", "/schema")
    def handle_get_schema(self, req, params, path, body):
        self._json(req, {"indexes": self.api.schema()})

    @route("POST", "/schema")
    def handle_post_schema(self, req, params, path, body):
        d = json.loads(body or b"{}")
        self.api.apply_schema(d.get("indexes", []))
        self._json(req, {})

    @route("POST", "/index/{index}/query", klass="query")
    def handle_post_query(self, req, params, path, body):
        """PQL query with content negotiation: raw-PQL or JSON bodies
        answered in JSON, ``application/x-protobuf`` QueryRequest bodies
        answered in protobuf when Accept asks for it (reference
        handlePostQuery, http/handler.go:499,1002)."""
        from pilosa_tpu import proto

        ctype = req.headers.get("Content-Type", "")
        proto_accept = "protobuf" in req.headers.get("Accept", "")
        shards = None
        if "protobuf" in ctype:
            preq = proto.decode(proto.QUERY_REQUEST, body)
            pql = preq["query"]
            shards = [int(s) for s in preq["shards"]] or None
            remote = preq["remote"]
            column_attrs = preq["columnAttrs"]
            exclude_row_attrs = preq["excludeRowAttrs"]
            exclude_columns = preq["excludeColumns"]
        else:
            pql = body.decode()
            if "json" in ctype:
                pql = json.loads(pql).get("query", "")
            remote = params.get("remote") == "true"
            column_attrs = params.get("columnAttrs") == "true"
            exclude_row_attrs = params.get("excludeRowAttrs") == "true"
            exclude_columns = params.get("excludeColumns") == "true"
        if params.get("shards"):
            shards = [int(s) for s in params["shards"].split(",")]
        # ?profile=1: attach this query's flight-recorder breakdown to
        # the JSON response (protobuf responses have no profile slot).
        # Clear this thread's last-published record FIRST so a bypassed
        # execution can never serve a stale profile.
        profile = params.get("profile") == "1"
        if profile:
            from pilosa_tpu import observe

            observe.take_last()
        # ?partial=1 (or the X-Pilosa-Partial header): degraded reads —
        # unavailable shards are accounted in the response
        # (missingShards/missingFraction) instead of failing the query.
        # JSON responses only; the protobuf wire has no meta slot, so
        # protobuf clients keep all-or-error semantics.
        partial = (params.get("partial") in ("1", "true")
                   or req.headers.get("X-Pilosa-Partial")
                   in ("1", "true"))
        partial_meta: dict | None = \
            {} if partial and not proto_accept else None
        # degraded execution is only honored where the response can
        # CARRY the accounting: JSON responses (partial_meta) and
        # remote sub-queries (the origin accounts its own failures).
        # A protobuf origin request keeps all-or-error semantics — an
        # unannotated undercount would be silently wrong data.
        partial = partial and (partial_meta is not None or remote)
        try:
            results = self.api.query(
                path["index"], pql, shards=shards, remote=remote,
                column_attrs=column_attrs,
                exclude_row_attrs=exclude_row_attrs,
                exclude_columns=exclude_columns,
                # ?nocoalesce=true: opt this request out of cross-query
                # micro-batching (debugging / latency-sensitive callers)
                coalesce=params.get("nocoalesce") != "true",
                # ?nocache=1: opt this request out of the result cache
                # (symmetric with ?nocoalesce — force a real execution)
                cache=params.get("nocache") not in ("1", "true"),
                # ?nodelta=1: compact pending ingest deltas up front
                # and answer from pure base state (debugging escape;
                # results are bit-exact either way)
                delta=params.get("nodelta") not in ("1", "true"),
                # ?nocontainers=1: route fused reads through the dense
                # pre-container path (debugging escape; results are
                # bit-identical either way)
                containers=params.get("nocontainers")
                not in ("1", "true"),
                # ?nomesh=1: run fused dispatches on the pre-mesh
                # single-device programs (debugging escape; results
                # are byte-identical either way)
                mesh=params.get("nomesh") not in ("1", "true"),
                # ?notiers=1: bypass tiered residency (host-tier
                # lookups miss, evictions drop, misses rebuild
                # inline — the pre-tier behavior; results are
                # byte-identical either way)
                tiers=params.get("notiers") not in ("1", "true"),
                # ?novm=1: route coalesced sparse reads through the
                # pre-VM ragged/fused engines instead of the Pallas
                # bitmap VM (debugging escape; results are
                # byte-identical either way)
                vm=params.get("novm") not in ("1", "true"),
                partial=partial,
                partial_meta=partial_meta,
                # tenant identity (X-Pilosa-Tenant / ?tenant=): rides
                # ExecOptions so every shared resource charges the
                # right tenant, and forwards on sub-queries — the
                # dispatch loop already parsed and cleaned it (ONE
                # parse site; a second would invite the two drifting)
                tenant=getattr(req, "_pilosa_tenant", None),
            )
        except Exception as e:
            if not proto_accept:
                raise
            # protobuf clients get errors as QueryResponse.Err with 400
            # (reference writeProtobufQueryResponse)
            self._proto(req, proto.encode(proto.QUERY_RESPONSE,
                                          {"err": str(e)}), status=400)
            return
        if exclude_columns:
            for r in results:
                if isinstance(r, Row):
                    r.exclude_columns = True
        want_attr_rows = [r for r in results
                          if isinstance(r, Row)
                          and (column_attrs or r.wants_column_attrs)]
        # attach column attribute sets for result columns when requested
        # by the URL param or a per-call Options(columnAttrs=true) —
        # present (possibly empty) whenever requested, so clients can
        # index the key unconditionally
        # (reference executor.go:206 / QueryResponse.columnAttrSets)
        attr_sets = (self._column_attr_sets(path["index"], want_attr_rows)
                     if column_attrs or want_attr_rows else None)
        if proto_accept:
            pb = {"results": [proto.result_to_proto(r) for r in results]}
            if attr_sets is not None:
                pb["columnAttrSets"] = [
                    {"id": a.get("id", 0), "key": a.get("key", ""),
                     "attrs": proto.attrs_to_proto(a["attrs"])}
                    for a in attr_sets
                ]
            self._proto(req, proto.encode(proto.QUERY_RESPONSE, pb))
            return
        resp = {"results": [serialize_result(r) for r in results]}
        if attr_sets is not None:
            resp["columnAttrs"] = attr_sets
        if partial_meta is not None:
            # always present on partial requests — [] / 0.0 when the
            # whole shard set was reachable, so clients can read the
            # keys unconditionally
            resp["missingShards"] = partial_meta.get("missingShards",
                                                     [])
            resp["missingFraction"] = partial_meta.get(
                "missingFraction", 0.0)
        if profile:
            from pilosa_tpu import observe

            rec = observe.take_last()
            resp["profile"] = rec.to_dict() if rec is not None else None
        self._json(req, resp)

    def _import_ok(self, req) -> None:
        """Success response for import endpoints: an empty protobuf
        ImportResponse for protobuf clients (reference handlePostImport,
        http/handler.go:1161), JSON {} otherwise."""
        if "protobuf" in req.headers.get("Accept", ""):
            from pilosa_tpu import proto

            self._proto(req, proto.encode(proto.IMPORT_RESPONSE, {}))
        else:
            self._json(req, {})

    def _proto(self, req, payload: bytes, status: int = 200) -> None:
        try:
            self._bytes(req, payload, ctype="application/protobuf",
                        status=status)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _column_attr_sets(self, index: str, rows: list[Row]) -> list[dict]:
        idx = self.api.index(index)
        cols: set[int] = set()
        for r in rows:
            cols.update(int(c) for c in r.columns())
        ordered = sorted(cols)
        attrs_by_id = idx.column_attrs.attrs_bulk(ordered)
        keys_by_id = {}
        if idx.options.keys:
            keys = idx.translate_store.translate_ids(ordered)
            keys_by_id = dict(zip(ordered, keys))
        out = []
        for col in ordered:
            attrs = attrs_by_id.get(col)
            if not attrs:
                continue
            entry = {"attrs": attrs}
            if idx.options.keys:
                entry["key"] = keys_by_id.get(col) or ""
            else:
                entry["id"] = col
            out.append(entry)
        return out

    @route("POST", "/index/{index}")
    def handle_create_index(self, req, params, path, body):
        d = json.loads(body or b"{}")
        opts = IndexOptions.from_dict(d.get("options", {}))
        self.api.create_index(path["index"], opts)
        self._json(req, {})

    @route("DELETE", "/index/{index}")
    def handle_delete_index(self, req, params, path, body):
        self.api.delete_index(path["index"])
        self._json(req, {})

    @route("GET", "/index/{index}")
    def handle_get_index(self, req, params, path, body):
        idx = self.api.index(path["index"])
        self._json(req, {"name": idx.name,
                         "options": idx.options.to_dict()})

    @route("POST", "/index/{index}/field/{field}")
    def handle_create_field(self, req, params, path, body):
        d = json.loads(body or b"{}")
        opts = FieldOptions.from_dict(d.get("options", {}))
        self.api.create_field(path["index"], path["field"], opts)
        self._json(req, {})

    @route("DELETE", "/index/{index}/field/{field}")
    def handle_delete_field(self, req, params, path, body):
        self.api.delete_field(path["index"], path["field"])
        self._json(req, {})

    @route("POST", "/index/{index}/field/{field}/import",
       klass="ingest")
    def handle_import(self, req, params, path, body):
        """Bit import: JSON {"rowIDs": [...], "columnIDs": [...],
        "timestamps": [...], "rowKeys": [...], "columnKeys": [...]} or a
        protobuf ImportRequest body (reference handlePostImport; wire
        form internal/public.proto ImportRequest).  Timestamps are unix
        seconds or RFC3339 in JSON, unix NANOseconds in protobuf (the
        reference encodes time.Time.UnixNano)."""
        if "protobuf" in req.headers.get("Content-Type", ""):
            from pilosa_tpu import proto

            # arrays=True: large packed ID fields stay ndarrays all the
            # way into field.import_bits' vectorized grouping (length
            # checks below must use len(), never truthiness)
            d = proto.decode(proto.IMPORT_REQUEST, body, arrays=True)
            ts = d.get("timestamps")
            if ts is not None and len(ts):
                # 0 = "no timestamp" in the reference's wire form
                d["timestamps"] = [int(t) or None for t in ts]
            # empty repeated fields mean "unkeyed", like absent JSON keys
            for k in ("rowKeys", "columnKeys", "timestamps"):
                v = d.get(k)
                if v is None or not len(v):
                    d[k] = None
        else:
            d = json.loads(body)
        timestamps = d.get("timestamps")
        if timestamps:
            timestamps = [None if t is None else _parse_ts(t)
                          for t in timestamps]
        rows_in = d.get("rowIDs")
        cols_in = d.get("columnIDs")
        self.api.import_bits(
            path["index"], path["field"],
            rows_in if rows_in is not None and len(rows_in) else [],
            cols_in if cols_in is not None and len(cols_in) else [],
            timestamps=timestamps,
            row_keys=d.get("rowKeys"), col_keys=d.get("columnKeys"),
            clear=params.get("clear") == "true",
        )
        self._import_ok(req)

    @route("POST", "/index/{index}/field/{field}/import-value",
       klass="ingest")
    def handle_import_value(self, req, params, path, body):
        if "protobuf" in req.headers.get("Content-Type", ""):
            from pilosa_tpu import proto

            d = proto.decode(proto.IMPORT_VALUE_REQUEST, body)
            if not d.get("columnKeys"):
                d["columnKeys"] = None
        else:
            d = json.loads(body)
        self.api.import_values(
            path["index"], path["field"],
            d.get("columnIDs") or [], d.get("values") or [],
            col_keys=d.get("columnKeys"),
        )
        self._import_ok(req)

    @route("POST", "/index/{index}/field/{field}/import-roaring/{shard}",
       klass="ingest")
    def handle_import_roaring(self, req, params, path, body):
        """Binary roaring import.  Body: raw roaring bytes for the
        standard view, or JSON {"views": {name: base64}}
        (reference handlePostImportRoaring, ImportRoaringRequest)."""
        ctype = req.headers.get("Content-Type", "")
        clear = params.get("clear") == "true"
        if "protobuf" in ctype:
            from pilosa_tpu import proto

            d = proto.decode(proto.IMPORT_ROARING_REQUEST, body)
            views = {v["name"]: v["data"] for v in d["views"]}
            clear = clear or d["clear"]
        elif "json" in ctype:
            d = json.loads(body)
            views = {k: base64.b64decode(v)
                     for k, v in (d.get("views") or {}).items()}
        else:
            views = {"": body}
        self.api.import_roaring(path["index"], path["field"],
                                int(path["shard"]), views,
                                clear=clear,
                                remote=params.get("remote") == "true")
        self._import_ok(req)

    @route("GET", "/export", klass="query")
    def handle_export(self, req, params, path, body):
        buf = io.StringIO()
        self.api.export_csv(params["index"], params["field"],
                            int(params.get("shard", 0)), buf)
        self._bytes(req, buf.getvalue().encode(), "text/csv")

    # ---------------------------------------------------- internal routes

    @route("POST", "/internal/cluster/message", klass="internal")
    def handle_cluster_message(self, req, params, path, body):
        resp = self.api.node.receive_message(json.loads(body))
        self._json(req, resp)

    @route("GET", "/internal/shards/max")
    def handle_shards_max(self, req, params, path, body):
        self._json(req, {"standard": self.api.shards_max()})

    @route("GET", "/internal/fragment/nodes")
    def handle_fragment_nodes(self, req, params, path, body):
        self._json(req, self.api.shard_nodes(params["index"],
                                             int(params["shard"])))

    @route("GET", "/internal/fragment/blocks", klass="internal")
    def handle_fragment_blocks(self, req, params, path, body):
        blocks = self.api.fragment_blocks(
            params["index"], params["field"], params["view"],
            int(params["shard"]))
        self._json(req, {"blocks": blocks})

    @route("GET", "/internal/fragment/block/data", klass="internal")
    def handle_fragment_block_data(self, req, params, path, body):
        rows, cols = self.api.fragment_block_data(
            params["index"], params["field"], params["view"],
            int(params["shard"]), int(params["block"]))
        self._json(req, {"rowIDs": rows, "columnIDs": cols})

    @route("GET", "/internal/fragment/data", klass="internal")
    def handle_fragment_data(self, req, params, path, body):
        data = self.api.fragment_data(
            params["index"], params["field"], params["view"],
            int(params["shard"]))
        self._bytes(req, data)

    @route("GET", "/internal/translate/data", klass="internal")
    def handle_translate_data(self, req, params, path, body):
        entries = self.api.translate_data(
            params["index"], params.get("field"),
            int(params.get("offset", 0)))
        self._json(req, {"entries": [
            {"offset": o, "id": i, "key": k} for o, i, k in entries
        ]})

    @route("POST", "/cluster/resize/set-coordinator")
    def handle_set_coordinator(self, req, params, path, body):
        d = json.loads(body)
        self.api.set_coordinator(d["id"])
        self._json(req, {"old": None, "new": d["id"]})

    @route("POST", "/cluster/resize/remove-node")
    def handle_remove_node(self, req, params, path, body):
        d = json.loads(body)
        removed = self.api.remove_node(d["id"])
        self._json(req, {"remove": removed})

    @route("POST", "/cluster/resize/abort")
    def handle_resize_abort(self, req, params, path, body):
        self.api.resize_abort()
        self._json(req, {})

    @route("POST", "/cluster/resize")
    def handle_cluster_resize(self, req, params, path, body):
        """Node add/remove control route: ``mode=online`` (default)
        drives the live rebalance, ``mode=offline`` the legacy
        stop-the-world resize (see API.cluster_resize)."""
        d = json.loads(body or b"{}")
        if "mode" not in d and params.get("mode"):
            d["mode"] = params["mode"]
        self._json(req, self.api.cluster_resize(d))

    # ------------------------------------------------------- infra routes

    @route("GET", "/metrics")
    def handle_metrics(self, req, params, path, body):
        """Prometheus text exposition (http/handler.go:282).

        Trace-id exemplars on histogram buckets are an OpenMetrics
        feature the legacy 0.0.4 parser rejects, so they render only on
        explicit request (``?exemplars=1`` — operators and tooling);
        the scrape default stays a clean 0.0.4 exposition a stock
        Prometheus accepts.  (Deliberately NOT keyed on the Accept
        header: modern Prometheus offers openmetrics-text by default,
        and this exposition is 0.0.4-shaped, not fully OpenMetrics.)"""
        exemplars = params.get("exemplars") == "1"
        if self.stats is not None and hasattr(self.stats, "prometheus_text"):
            # refresh every module gauge family at scrape time so the
            # exposition is never stale (push backends get the same
            # families from the [observe] device-sample-interval loop)
            self._publish_all_gauges()
            text = self.stats.prometheus_text(exemplars=exemplars)
        else:
            text = ""
        # Snapshot-queue health is process-wide (the queue is shared by
        # every holder in the process), so append it here rather than
        # routing through any one server's registry — compaction
        # starvation must be alert-able from any node's /metrics.
        from pilosa_tpu.parallel import spmd
        from pilosa_tpu.runtime import filebudget, prewarm, snapqueue

        text += snapqueue.prometheus_lines()
        text += prewarm.prometheus_lines()
        text += spmd.prometheus_lines()
        text += filebudget.prometheus_lines()
        self._bytes(req, text.encode(), "text/plain; version=0.0.4")

    @route("GET", "/diagnostics")
    def handle_diagnostics(self, req, params, path, body):
        """Local diagnostics document (the reference phones this home to
        diagnostics.pilosa.com, diagnostics.go:42; we only serve it)."""
        from pilosa_tpu import diagnostics

        self._json(req, diagnostics.payload(self.api.node))

    @route("GET", "/debug/threads")
    def handle_debug_threads(self, req, params, path, body):
        """All thread stacks — the /debug/pprof goroutine-dump analog
        (http/handler.go:280 mounts pprof unconditionally)."""
        import sys
        import traceback

        out = []
        names = {t.ident: t.name for t in threading.enumerate()}
        for ident, frame in sys._current_frames().items():
            out.append(f"--- thread {names.get(ident, ident)} ---\n"
                       + "".join(traceback.format_stack(frame)))
        self._bytes(req, "\n".join(out).encode(), "text/plain")

    @route("GET", "/debug/pprof/heap")
    def handle_debug_heap(self, req, params, path, body):
        """Heap/allocation profile — the pprof heap analog
        (http/handler.go:280-281; rates configured like
        server/config.go:151-156, here ``[profile] heap`` starting
        tracemalloc).  Reports top allocation sites (tracemalloc, which
        also tracks numpy buffers), process RSS, and the residency
        manager's device/host cache entries — the buffers that dominate
        at the 10B-column scale.

        ``?topn=N`` bounds the site list (default 25); ``?start=1``
        begins tracing at runtime when the config didn't (allocations
        before that point are invisible — restart-free but partial);
        ``?cumulative=traceback`` groups by full stack instead of
        allocation line."""
        import tracemalloc

        from pilosa_tpu.runtime import residency

        try:
            topn = int(params.get("topn", 25))
        except ValueError:
            raise ApiError("invalid topn parameter")
        if topn < 1:
            raise ApiError("topn must be >= 1")
        if params.get("start") == "1" and not tracemalloc.is_tracing():
            tracemalloc.start(self.heap_frames)
        out = {"tracing": tracemalloc.is_tracing()}
        if tracemalloc.is_tracing():
            current, peak = tracemalloc.get_traced_memory()
            out["traced_bytes"] = current
            out["traced_peak_bytes"] = peak
            group = ("traceback" if params.get("cumulative") == "traceback"
                     else "lineno")
            stats = tracemalloc.take_snapshot().statistics(group)[:topn]
            out["top_allocations"] = [
                {"site": ";".join(f"{fr.filename}:{fr.lineno}"
                                  for fr in st.traceback),
                 "bytes": st.size, "count": st.count}
                for st in stats]
        try:
            with open("/proc/self/status") as f:
                for line in f:
                    if line.startswith("VmRSS"):
                        out["rss_bytes"] = int(line.split()[1]) * 1024
                        break
        except OSError:
            pass
        mgr = residency.manager()
        out["residency"] = mgr.stats()
        out["residency_top"] = mgr.top_entries(topn)
        self._json(req, out)

    @route("GET", "/debug/pprof/profile")
    def handle_debug_profile(self, req, params, path, body):
        """Statistical wall-clock profile over ?seconds=N (default 2,
        max 30): samples every thread's stack at ~100Hz and returns
        collapsed stacks ("frame;frame;frame count" lines, flamegraph
        format) — the CPU-profile analog of /debug/pprof/profile
        (http/handler.go:280).  Wall-clock (not CPU-time) sampling also
        surfaces lock waits, covering the block/mutex profile role
        (server/config.go:151-156)."""
        import sys
        import time as _time
        from collections import Counter

        import math

        try:
            seconds = float(params.get("seconds", 2))
        except ValueError:
            raise ApiError("invalid seconds parameter")
        if not math.isfinite(seconds):  # nan/inf defeat the clamp
            raise ApiError("invalid seconds parameter")
        seconds = min(max(seconds, 0.1), 30.0)
        # one sampler at a time: concurrent samplers double-count each
        # other's stacks and pin CPU for the full window while holding
        # HTTP worker threads; a busy signal beats a corrupt profile
        if not self._profile_lock.acquire(blocking=False):
            self._error(req, 409, "a profile is already running")
            return
        try:
            interval = 0.01
            me = threading.get_ident()
            counts: Counter = Counter()
            deadline = _time.monotonic() + seconds
            while _time.monotonic() < deadline:
                for ident, frame in sys._current_frames().items():
                    if ident == me:
                        continue  # the sampler itself is noise
                    stack = []
                    f = frame
                    while f is not None:
                        code = f.f_code
                        stack.append(
                            f"{code.co_filename.rsplit('/', 1)[-1]}:"
                            f"{code.co_name}")
                        f = f.f_back
                    counts[";".join(reversed(stack))] += 1
                _time.sleep(interval)
            out = "\n".join(f"{stack} {n}"
                            for stack, n in counts.most_common())
        finally:
            self._profile_lock.release()
        self._bytes(req, out.encode(), "text/plain")

    @route("GET", "/debug/cost")
    def handle_debug_cost(self, req, params, path, body):
        """Engine observatory state (pilosa_tpu.perfobs): per-launch
        cost table keyed (engine, work size-class, sparsity bucket)
        with EWMA wall/bytes/achieved-GB/s per cell, the per-engine
        bw_util rollup against the configured bandwidth roof, shadow
        consult counters, and the device-profiler capture status."""
        from pilosa_tpu import perfobs

        self._json(req, perfobs.cost_debug())

    @route("POST", "/debug/profiler/start")
    def handle_profiler_start(self, req, params, path, body):
        """Begin an on-demand device trace (jax.profiler) into a dated
        dir under the holder's data directory.  ``?seconds=N``
        overrides the ``[observe] profiler-max-seconds`` auto-stop.
        409 while a capture is already active (the /debug/pprof/profile
        discipline: a busy signal beats a queued second capture)."""
        import tempfile

        from pilosa_tpu import perfobs

        max_seconds = None
        if "seconds" in params:
            try:
                max_seconds = float(params["seconds"])
            except ValueError:
                raise ApiError("invalid seconds parameter")
        base = self.api.holder.path or tempfile.gettempdir()
        try:
            out = perfobs.profiler_start(base, max_seconds=max_seconds)
        except perfobs.ProfilerBusy as e:
            self._error(req, 409, str(e))
            return
        self._json(req, out)

    @route("POST", "/debug/profiler/stop")
    def handle_profiler_stop(self, req, params, path, body):
        """End the active device trace and return the artifact dir +
        capture duration.  409 when no capture is active."""
        from pilosa_tpu import perfobs

        try:
            out = perfobs.profiler_stop()
        except perfobs.ProfilerIdle as e:
            self._error(req, 409, str(e))
            return
        self._json(req, out)

    def _debug_queries_payload(self, params) -> dict:
        """The /debug/queries document — factored out so the
        cluster-wide fan-in assembles the LOCAL node's section
        in-process instead of HTTP-calling itself (a self-call would
        burn a handler thread while holding one)."""
        recorder = getattr(self.api.executor, "recorder", None)
        if recorder is None:
            return {"active": [], "recent": []}
        try:
            min_ms = float(params.get("min_ms", 0))
        except ValueError:
            raise ApiError("invalid min_ms parameter")
        sort = params.get("sort", "start")
        if sort not in ("start", "elapsed"):
            raise ApiError("sort must be 'start' or 'elapsed'")

        def prepare(records):
            out = [r.to_dict() for r in records]
            if min_ms > 0:
                out = [d for d in out if d["elapsedMs"] >= min_ms]
            key = "elapsedMs" if sort == "elapsed" else "startTime"
            out.sort(key=lambda d: d[key], reverse=True)
            return out

        return {
            "active": prepare(recorder.active_records()),
            "recent": prepare(recorder.recent_records()),
        }

    @route("GET", "/debug/queries")
    def handle_debug_queries(self, req, params, path, body):
        """Query flight recorder: in-flight queries plus the ring
        buffer of recent ones (pilosa_tpu.observe).  ``?min_ms=N``
        keeps only records at least N ms long (in-flight records by
        their elapsed-so-far); ``?sort=elapsed`` orders both lists
        slowest-first (default ``start``: newest-first)."""
        self._json(req, self._debug_queries_payload(params))

    @route("GET", "/debug/resultcache")
    def handle_debug_resultcache(self, req, params, path, body):
        """Query result cache state (runtime/resultcache): budget /
        bytes / entry count, hit / miss / fill / eviction /
        invalidation totals, and the largest entries (key digest —
        matching the ``cacheKey`` on flight records — bytes, age,
        hits)."""
        from pilosa_tpu.runtime import resultcache

        self._json(req, resultcache.cache().debug())

    @route("GET", "/debug/ingest")
    def handle_debug_ingest(self, req, params, path, body):
        """Streaming-ingest state (pilosa_tpu.ingest): the [ingest]
        config in force, pending-delta totals (bits / rows / bytes /
        fragments), compaction counters (background, inline,
        admission-skipped), and the largest pending per-fragment
        deltas with their age and delta sequence."""
        from pilosa_tpu.ingest import compactor

        self._json(req, compactor.compactor().debug())

    @route("GET", "/debug/containers")
    def handle_debug_containers(self, req, params, path, body):
        """Compressed container-directory engine state
        (ops/containers.py): the [containers] config in force
        (enabled/threshold plus the kind-specialization knobs
        kinds/arrayMax/runCap) and the container.* counters (queries
        served compressed, dense fallbacks, containers gathered vs
        skipped broken out per kind — bitmap/array/run_gathered —
        and empty-domain zero-work answers).  The per-kind
        resident-byte split (compressed total plus its array/run
        sub-pools vs dense) is on /debug/devices (residency.kinds)."""
        from pilosa_tpu.ops import containers

        self._json(req, containers.debug())

    @route("GET", "/debug/ragged")
    def handle_debug_ragged(self, req, params, path, body):
        """Ragged megabatch state (ops/tape.py +
        parallel/coalescer.py): the [ragged] config in force on this
        node's coalescer, the tape.* / coalescer.shape_* counters
        (executions, queries served, per-query fallbacks, shape
        misses), and the interpreter program inventory — which
        (batch, tape-length, leaf-slot, stack-shape) bucket variants
        this process has lowered.  The ``vm`` section covers the
        Pallas bitmap VM: the [vm] knobs in force, the vm.* counters,
        the (batch, tape-length, slot, domain) program variants
        the scalar-prefetch kernel has lowered, and
        ``fallbackReasons`` — the per-reason breakdown of dense-path
        fallbacks (disabled / ineligible_leaf / kind_unsupported /
        oversize / max_prefetch / min_domain, plus the informational
        mesh_active count)."""
        from pilosa_tpu.ops import tape

        out = tape.debug()
        co = getattr(self.api.executor, "coalescer", None)
        out["coalescer"] = {"attached": co is not None}
        if co is not None:
            out["coalescer"].update({
                "enabled": co.enabled,
                "ragged": co.ragged,
                "maxTape": co.max_tape,
                "maxLeaves": co.max_leaves,
                "windowMs": co.window_s * 1e3,
                "maxBatch": co.max_batch,
                "vm": co.vm,
                "vmMinDomain": co.vm_min_domain,
                "vmMaxPrefetch": co.vm_max_prefetch,
            })
        self._json(req, out)

    @route("GET", "/debug/mesh")
    def handle_debug_mesh(self, req, params, path, body):
        """Mesh-native execution state (parallel/meshexec.py): the
        [mesh] config in force, whether the mesh is active, the axis
        layout (which local devices join the shard axis), the
        per-device shard plan for the widest index's shard fan-out,
        the mesh.* counters (launches, queries, ?nomesh fallbacks,
        placements/bytes), and the residency per-device split."""
        from pilosa_tpu.parallel import meshexec
        from pilosa_tpu.runtime import residency

        widest = max(
            [len(idx.available_shards())
             for idx in self.api.holder.indexes.values()] or [0])
        out = meshexec.debug(n_shards=widest or None)
        rs = residency.manager().stats()
        tiers = rs.get("tiers") or {}
        host = tiers.get("host") or {}
        out["residency"] = {"total": rs["total"],
                            "perDevice": rs["per_device"],
                            # per-device HBM is what one chip holds;
                            # the host tier backs ALL of them (demoted
                            # entries re-place under the shard plan in
                            # force at promotion time)
                            "hostTierBytes": host.get("bytes", 0),
                            "demotions": tiers.get("demotions", 0)}
        self._json(req, out)

    @route("GET", "/debug/devices")
    def handle_debug_devices(self, req, params, path, body):
        """Device-runtime telemetry (pilosa_tpu.devobs): per-kernel /
        per-canonical-shape XLA compile counts and wall times,
        host→device transfer bytes and chunk counts by owner,
        residency usage/budget/evictions/high-water, and per-device
        memory_stats (bytes_in_use vs bytes_limit where the backend
        reports them)."""
        from pilosa_tpu import devobs

        self._json(req, devobs.observer().snapshot())

    # ------------------------------------------------- cluster-wide fan-in

    def _fan_in(self, path: str) -> tuple[dict, dict, dict]:
        """Fan ``GET path`` out to every peer over the internal client
        (tagged ``rpc_class("internal")``, deadline-propagated) and
        return (local_id, sections, errors) — sections keyed by node
        id, the local node's section assembled in-process."""
        from pilosa_tpu.parallel.cluster import fan_in
        from pilosa_tpu.serve.admission import rpc_class

        local_id = self.api.cluster.local_id
        peers = [n for n in self.api.cluster.sorted_nodes()
                 if n.id != local_id and n.uri]
        with self._peer_client_lock:
            client = self.peer_client
            if client is None:
                from pilosa_tpu.server.client import InternalClient

                client = self.peer_client = InternalClient()
                self._owns_peer_client = True

        def fetch(node):
            with rpc_class("internal"):
                out = client.debug_json(node.uri, path,
                                        timeout=self.fanin_timeout)
                if not isinstance(out, dict):
                    # a 200 with an empty/None body (peer mid-restart
                    # behind a proxy) must degrade like an error, not
                    # crash the whole merge downstream
                    raise ValueError(f"peer returned non-JSON-object "
                                     f"debug body: {out!r}")
                return out

        sections, errors = fan_in(peers, fetch, self.fanin_timeout + 0.5)
        return local_id, sections, errors

    @route("GET", "/debug/cluster/queries")
    def handle_debug_cluster_queries(self, req, params, path, body):
        """One merged view of query records across the cluster: every
        node's /debug/queries section plus a flat ``recent`` merge
        (each record stamped with its node) sorted newest-first, and
        the cluster's ``slow`` records sorted slowest-first.  A dead
        or drowning peer degrades to an entry in ``errors``."""
        qs = ""
        passthrough = {k: v for k, v in params.items()
                       if k in ("min_ms", "sort")}
        if passthrough:
            from urllib.parse import urlencode

            qs = "?" + urlencode(passthrough)
        # assemble the local section FIRST: it validates the params, so
        # a bad min_ms 400s before any peer traffic is spent
        local_section = self._debug_queries_payload(params)
        local_id, sections, errors = self._fan_in("/debug/queries" + qs)
        sections[local_id] = local_section
        merged = []
        for node_id, sec in sections.items():
            for rec in (sec.get("recent") or []):
                merged.append({**rec, "node": node_id})
        merged.sort(key=lambda d: d.get("startTime", 0), reverse=True)
        slow = sorted((d for d in merged if d.get("slow")),
                      key=lambda d: d.get("elapsedMs", 0), reverse=True)
        self._json(req, {
            "nodes": sections,
            "errors": errors,
            "recent": merged[:512],
            "slow": slow[:128],
        })

    @route("GET", "/debug/cluster/devices")
    def handle_debug_cluster_devices(self, req, params, path, body):
        """One merged view of device health across the cluster: every
        node's /debug/devices section plus cluster totals (compiles,
        compile wall time, transfer bytes, residency usage/evictions)."""
        from pilosa_tpu import devobs

        local_id, sections, errors = self._fan_in("/debug/devices")
        sections[local_id] = devobs.observer().snapshot()
        totals = {"compiles": 0, "compileMs": 0.0, "transferBytes": 0,
                  "residencyBytes": 0, "evictions": 0}
        for sec in sections.values():
            totals["compiles"] += (sec.get("compile") or {}).get("total", 0)
            totals["compileMs"] += (sec.get("compile") or {}).get(
                "totalMs", 0.0)
            totals["transferBytes"] += (sec.get("transfer") or {}).get(
                "bytes", 0)
            res = sec.get("residency") or {}
            totals["residencyBytes"] += res.get("total", 0)
            totals["evictions"] += res.get("evictions", 0)
        totals["compileMs"] = round(totals["compileMs"], 3)
        self._json(req, {
            "nodes": sections,
            "errors": errors,
            "totals": totals,
        })

    # ------------------------------------------- trace autopsy + journal

    def _local_trace_payload(self, trace_id: str) -> dict:
        """This node's contribution to a trace: flight records whose
        (normalized) trace id matches, plus journal events stamped
        with it."""
        from pilosa_tpu import observe

        records = []
        recorder = getattr(self.api.executor, "recorder", None)
        if recorder is not None:
            records = [r.to_dict()
                       for r in recorder.records_for_trace(trace_id)]
        return {
            "records": records,
            "events": observe.journal().events(trace_id=trace_id,
                                               limit=256),
        }

    @route("GET", "/debug/trace/{id}")
    def handle_debug_trace(self, req, params, path, body):
        """Distributed query autopsy: fan per-node flight records in
        from every peer and assemble ONE causal span tree for the
        trace — admission wait, coalescer window, stages, per-node
        remote maps (the hedge loser's side included), reduce — with
        per-span walls that sum to the observed latency
        (pilosa_tpu.traceasm).  ``?local=1`` returns just this node's
        records + events (the fan-in target).  Dead peers degrade to
        ``errors``, the /debug/cluster/* contract."""
        import re as _re

        from pilosa_tpu import observe, traceasm

        trace_id = path["id"]
        if not _re.fullmatch(r"[0-9a-fA-F]{1,64}", trace_id):
            raise ValueError(f"malformed trace id: {trace_id!r}")
        local = self._local_trace_payload(trace_id)
        if params.get("local"):
            self._json(req, local)
            return
        local_id, sections, errors = self._fan_in(
            f"/debug/trace/{trace_id}?local=1")
        sections[local_id] = local
        observe.bump_trace("trace.fanins", max(0, len(sections) - 1))
        if errors:
            observe.bump_trace("trace.errors", len(errors))
        out = traceasm.assemble_trace(sections, errors, trace_id)
        observe.bump_trace("trace.assemblies")
        if out["root"] is None:
            observe.bump_trace("trace.orphans")
        self._json(req, out)

    @route("GET", "/debug/events")
    def handle_debug_events(self, req, params, path, body):
        """This node's event journal (pilosa_tpu.observe.EventJournal):
        structured state-transition events, oldest first.  ``?since=N``
        keeps events with seq > N (the incremental-poll cursor);
        ``?kind=prefix`` filters by kind prefix (``kind=breaker``
        covers open/half-open/close); ``?trace=id`` keeps events
        stamped with that trace; ``?limit=N`` keeps the newest N."""
        from pilosa_tpu import observe

        j = observe.journal()
        self._json(req, {
            "node": j.node_id,
            "events": j.events(
                since=int(params.get("since", 0) or 0),
                kind=params.get("kind") or None,
                trace_id=params.get("trace") or None,
                limit=int(params.get("limit", 512) or 512)),
            "counters": j.counters(),
        })

    @route("GET", "/debug/cluster/events")
    def handle_debug_cluster_events(self, req, params, path, body):
        """The merged cluster timeline: every node's journal slice,
        wall-clock ordered, so "p99 spiked because node2's breaker
        opened mid-backfill" is one request.  Same ``?since=``/
        ``?kind=``/``?trace=``/``?limit=`` filters as /debug/events
        (applied per node before the merge); dead peers degrade to
        ``errors``."""
        from urllib.parse import urlencode

        from pilosa_tpu import traceasm

        passthrough = {k: v for k, v in params.items()
                       if k in ("since", "kind", "trace", "limit")}
        qs = "?" + urlencode(passthrough) if passthrough else ""
        # local section FIRST: it validates the params, so a bad
        # since/limit 400s before any peer traffic is spent
        since = int(params.get("since", 0) or 0)
        kind = params.get("kind") or None
        from pilosa_tpu import observe

        j = observe.journal()
        local_section = {
            "node": j.node_id,
            "events": j.events(
                since=since, kind=kind,
                trace_id=params.get("trace") or None,
                limit=int(params.get("limit", 512) or 512)),
            "counters": j.counters(),
        }
        local_id, sections, errors = self._fan_in("/debug/events" + qs)
        sections[local_id] = local_section
        self._json(req, traceasm.merge_events(sections, errors,
                                              since=since, kind=kind))

    @route("GET", "/debug/peers")
    def handle_debug_peers(self, req, params, path, body):
        """Per-peer failure-handling state (parallel/cluster.py): each
        peer's circuit-breaker state machine (state, consecutive
        failures, transition + fast-fail counters), latency EWMA /
        deviation / sample count (the hedged-read trigger signal), and
        membership state; plus this node's hedge counters."""
        ex = self.api.executor
        with ex._hedge_lock:
            hedge = {"rpcs": ex._hedge_rpcs, "issued": ex._hedge_issued,
                     "wins": ex._hedge_wins}
        self._json(req, {
            "local": self.api.cluster.local_id,
            "peers": self.api.cluster.debug_peers(),
            "hedge": hedge,
        })

    @route("GET", "/debug/antientropy")
    def handle_debug_antientropy(self, req, params, path, body):
        """Self-healing replication state (parallel/syncer.py +
        parallel/hints.py): the resumable anti-entropy cursor, the
        last round's outcome (fragments walked, dirty / reconciled /
        pushed block counts, classified peer failures, duration), the
        cumulative ae.* counters with the digest-cache hit rate, the
        [replication] write policy in force, and each peer's hint
        queue depth / bytes / oldest-hint age."""
        from pilosa_tpu.parallel import hints as _hints
        from pilosa_tpu.parallel import syncer as _syncer

        node = self.api.node
        ctrs = _syncer.counters()
        hits = ctrs["ae.digest_cache_hits"]
        misses = ctrs["ae.digest_cache_misses"]
        cfg = _hints.config()
        # one snapshot read: the AE thread clears ae_cursor on slice
        # completion, and a two-read None-check would race it
        cur = node.ae_cursor
        self._json(req, {
            "cursor": None if cur is None else list(cur),
            "lastRound": node.ae_last_round or None,
            "counters": ctrs,
            "digestCacheHitRate": (
                round(hits / (hits + misses), 4)
                if hits + misses else None),
            "replication": {
                "writePolicy": cfg.write_policy,
                "hintMaxBytes": cfg.hint_max_bytes,
                "hintMaxAge": cfg.hint_max_age,
                "replayInterval": cfg.replay_interval,
            },
            "hints": node.hints.debug(),
            "hintCounters": _hints.counters(),
        })

    @route("GET", "/debug/rebalance")
    def handle_debug_rebalance(self, req, params, path, body):
        """Online rebalance state (parallel/rebalance.py): whether a
        plan is active, the per-shard state machine (dual-write /
        backfill / cutover / dropped with old and new owner sets), the
        cumulative rebalance.* counters, the persisted cursor path,
        and the last finished plan's outcome."""
        self._json(req, self.api.rebalance_status())

    @route("GET", "/debug/failpoints")
    def handle_debug_failpoints(self, req, params, path, body):
        """Failpoint registry state (pilosa_tpu.faultinject): armed
        points with their specs and call/trigger counters, plus the
        full compiled-in site inventory."""
        from pilosa_tpu import faultinject

        self._json(req, faultinject.snapshot())

    @route("POST", "/debug/failpoints")
    def handle_post_failpoints(self, req, params, path, body):
        """Arm/disarm failpoints live: ``{"arm": "<spec>"}`` arms
        (grammar in the faultinject module docstring), ``{"disarm":
        "<name>"}`` disarms one point, ``{"disarm": true}`` disarms
        everything.  Returns the post-change registry snapshot — the
        ops surface ``tools/loadgen.py --chaos`` toggles on a
        schedule."""
        from pilosa_tpu import faultinject

        d = json.loads(body or b"{}")
        if d.get("arm"):
            faultinject.arm(str(d["arm"]))
        dis = d.get("disarm")
        if dis is True or dis == "all":
            faultinject.disarm()
        elif isinstance(dis, str) and dis:
            faultinject.disarm(dis)
        self._json(req, faultinject.snapshot())

    @route("GET", "/debug/admission")
    def handle_debug_admission(self, req, params, path, body):
        """Admission-gate state: per-class caps, in-flight counts,
        queue depths, EWMA service times, and shed/expired totals
        (serve/admission.AdmissionController.debug)."""
        if self.admission is None:
            self._json(req, {"enabled": False})
            return
        out = self.admission.debug()
        out["acceptThreads"] = {
            "active": self._threads_active,
            "max": self.max_threads,
        }
        self._json(req, out)

    @route("GET", "/debug/tenants")
    def handle_debug_tenants(self, req, params, path, body):
        """Per-tenant isolation state (serve/tenant.py): the [tenants]
        policy in force (quotas per configured tenant + the default
        tier), and per tenant the admission picture (admitted / shed /
        expired / in-flight / waiting / queue-wait EWMA, aggregated
        across classes), result-cache bytes + hit/miss/fill/eviction
        counters against the soft budget, and residency HBM/host-tier
        bytes with the demotion pressure charged — the one surface an
        abusive-tenant triage needs."""
        from pilosa_tpu.runtime import residency as _residency
        from pilosa_tpu.runtime import resultcache as _resultcache

        cfg = _tenant.config()
        admission = (self.admission.tenants_debug()
                     if self.admission is not None else {})
        cache = _resultcache.cache().tenant_stats()
        res = _residency.manager().tenant_stats()
        tenants: dict[str, dict] = {}
        for name in sorted(set(admission) | set(cache) | set(res)):
            tenants[name] = {
                "admission": admission.get(name),
                "cache": cache.get(name),
                "residency": res.get(name),
            }
        self._json(req, {
            "enabled": cfg.enabled,
            "default": {
                "share": cfg.default_quota.share,
                "queue": cfg.default_quota.queue,
                "cacheShare": cfg.default_quota.cache_share,
                "residencyShare": cfg.default_quota.residency_share,
            },
            "quotas": {
                n: {"share": q.share, "queue": q.queue,
                    "cacheShare": q.cache_share,
                    "residencyShare": q.residency_share}
                for n, q in cfg.quotas.items()
            },
            "tenants": tenants,
        })

    @route("GET", "/debug/vars")
    def handle_debug_vars(self, req, params, path, body):
        snap = {}
        if self.stats is not None and hasattr(self.stats, "snapshot"):
            self._publish_all_gauges()
            snap = self.stats.snapshot()
        self._json(req, snap)

    def _publish_all_gauges(self) -> None:
        """Push every module gauge family into the stats registry —
        the ONE list both scrape surfaces (/metrics and /debug/vars)
        share, so a new family cannot render on one and drift off the
        other.  Telemetry never fails a scrape."""
        from pilosa_tpu import devobs
        from pilosa_tpu import faultinject as _faultinject
        from pilosa_tpu import observe as _observe_mod
        from pilosa_tpu import perfobs as _perfobs
        from pilosa_tpu.ingest import compactor
        from pilosa_tpu.models import fragment as _fragment
        from pilosa_tpu.ops import containers as _containers
        from pilosa_tpu.ops import tape
        from pilosa_tpu.parallel import hints as _hints
        from pilosa_tpu.parallel import meshexec as _meshexec
        from pilosa_tpu.parallel import rebalance as _rebalance
        from pilosa_tpu.parallel import syncer as _syncer
        from pilosa_tpu.runtime import resultcache

        try:
            devobs.observer().publish_gauges(self.stats)
            resultcache.cache().publish_gauges(self.stats)
            compactor.compactor().publish_gauges(self.stats)
            tape.publish_gauges(self.stats)
            _containers.publish_gauges(self.stats)
            _meshexec.publish_gauges(self.stats)
            # engine observatory: launch/bytes totals, cost-table
            # size, shadow consult counters, per-engine tagged
            # bandwidth — zeros on a clean server
            _perfobs.publish_gauges(self.stats)
            # chaos-round families: breakers, hedged reads, failpoints,
            # partial degradation — zeros on a clean server so the
            # families are alert-able before the first fault
            self.api.cluster.publish_breaker_gauges(self.stats)
            self.api.executor.publish_chaos_gauges(self.stats)
            _faultinject.publish_gauges(self.stats)
            # self-healing replication families: anti-entropy rounds,
            # hinted handoff (with this node's live queue depth), and
            # WAL replay health — zeros on a clean server
            _syncer.publish_gauges(self.stats)
            _hints.publish_gauges(self.stats, self.api.node.hints)
            # online-rebalance families: plan/shard-state gauges plus
            # dual-write / bytes-streamed / abort totals — zeros on a
            # clean server (and on non-coordinator nodes)
            _rebalance.publish_gauges(
                self.stats, getattr(self.api.node, "rebalance", None))
            _fragment.publish_wal_gauges(self.stats)
            # per-tenant isolation totals (zeros while [tenants] is
            # off — the family stays alert-able before the first
            # isolated tenant)
            _tenant.publish_gauges(self.stats, self.admission)
            # event journal + trace-assembly families — zeros on a
            # clean server so both are scrape-visible before the
            # first event or /debug/trace fan-in
            _observe_mod.publish_journal_gauges(self.stats)
        except Exception:  # noqa: BLE001
            pass


def _parse_ts(t):
    import datetime as dt

    if isinstance(t, (int, float)):
        # reference ImportRequest carries unix nanos; accept seconds too
        if t > 1 << 40:
            t = t / 1e9
        return dt.datetime.fromtimestamp(t, dt.timezone.utc).replace(tzinfo=None)
    return dt.datetime.fromisoformat(str(t).replace("Z", ""))
