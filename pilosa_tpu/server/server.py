"""Server: wires holder + cluster + executor + API + HTTP into one node
process.

Parity target: the reference's pilosa.NewServer / Server.Open
(server.go:297,417) and the server/ Command lifecycle
(server/server.go:60-220): build everything from options, open the
holder, join the cluster, start background loops, serve HTTP.
"""

from __future__ import annotations

import os
import threading
import uuid

from pilosa_tpu.api import API
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.parallel.cluster import (
    Cluster,
    NODE_READY,
    STATE_NORMAL,
    TransportError,
)
from pilosa_tpu.parallel.node import ClusterNode
from pilosa_tpu.server.client import HTTPTransport, InternalClient
from pilosa_tpu.server.handler import Handler


class Server:
    """One node: storage + cluster + HTTP (server.go:46)."""

    def __init__(
        self,
        data_dir: str,
        host: str = "127.0.0.1",
        port: int = 0,
        name: str | None = None,
        seeds: list[str] | None = None,
        replica_n: int = 1,
        partition_n: int = 256,
        coordinator: bool = False,
        anti_entropy_interval: float = 0.0,
        heartbeat_interval: float = 0.0,
        metric_poll_interval: float = 0.0,
        long_query_time: float = 0.0,
        max_writes_per_request: int = 0,
        tls_cert: str | None = None,
        tls_key: str | None = None,
        tls_skip_verify: bool = False,
        logger=None,
        stats=None,
        tracer=None,
        heap_profile: bool = False,
        heap_profile_frames: int = 4,
        coalescer_enabled="auto",
        coalescer_window_ms: float = 2.0,
        coalescer_max_batch: int = 32,
        ragged_enabled: bool = True,
        ragged_max_tape: int = 32,
        ragged_max_leaves: int = 16,
        ragged_prewarm: bool = True,
        vm_enabled: bool = True,
        vm_min_domain: int = 8,
        vm_max_prefetch: int = 65536,
        observe_enabled: bool = True,
        observe_recent: int = 256,
        observe_long_query_time: float = 0.0,
        observe_device_sample_interval: float = 0.0,
        observe_fanin_timeout: float = 2.0,
        observe_device_peak_gbps: float = 0.0,
        observe_profiler_max_seconds: float = 30.0,
        observe_journal: bool = True,
        observe_journal_size: int = 2048,
        observe_journal_kinds: str = "",
        cost_shadow: bool = True,
        admission_enabled: bool = True,
        admission_query_cap: int = 32,
        admission_query_queue: int = 128,
        admission_ingest_cap: int = 16,
        admission_ingest_queue: int = 64,
        admission_internal_cap: int = 16,
        admission_internal_queue: int = 64,
        admission_default_deadline: float = 0.0,
        cache_enabled: bool = True,
        cache_budget_bytes: int | None = None,
        cache_max_entry_bytes: int | None = None,
        cache_ttl: float | None = None,
        ingest_delta_enabled: bool = True,
        ingest_delta_budget_bytes: int | None = None,
        ingest_compact_threshold_bits: int | None = None,
        ingest_compact_interval: float | None = None,
        containers_enabled: bool | None = None,
        containers_threshold: float | None = None,
        containers_kinds: bool | None = None,
        containers_array_max: int | None = None,
        containers_run_cap: int | None = None,
        mesh_enabled=None,
        mesh_axis_size: int | None = None,
        residency_host_budget_bytes: int | None = None,
        residency_disk_path: str | None = None,
        residency_disk_budget_bytes: int | None = None,
        residency_promote_workers: int | None = None,
        residency_promote_queue: int | None = None,
        residency_promote_wait_ms: float | None = None,
        residency_prefetch: bool | None = None,
        residency_prefetch_interval: float | None = None,
        breaker_threshold: int = 5,
        breaker_cooldown: float = 5.0,
        hedge_min_samples: int = 8,
        hedge_deviations: float = 4.0,
        hedge_min_ms: float = 20.0,
        hedge_max_fraction: float = 0.1,
        faultinject_armed: str = "",
        write_policy: str = "all",
        hint_max_bytes: int | None = None,
        hint_max_age: float | None = None,
        hint_replay_interval: float | None = None,
        anti_entropy_jitter: float = 0.1,
        anti_entropy_round_budget: float = 0.0,
        anti_entropy_peer_timeout: float = 2.0,
        rebalance_transfer_budget: int | None = None,
        rebalance_dual_write_policy: str | None = None,
        rebalance_cursor_path: str | None = None,
        rebalance_backoff_base: float | None = None,
        rebalance_backoff_cap: float | None = None,
        rebalance_peer_timeout: float | None = None,
        tenants_enabled: bool = False,
        tenants_default_share: int | None = None,
        tenants_default_queue: int | None = None,
        tenants_default_cache_share: float | None = None,
        tenants_default_residency_share: float | None = None,
        tenants_quotas: dict | None = None,
    ):
        from pilosa_tpu import logger as _logger
        from pilosa_tpu import stats as _stats

        os.makedirs(data_dir, exist_ok=True)
        self.data_dir = data_dir
        self.logger = logger or _logger.NOP
        self.stats = stats if stats is not None else _stats.MemStatsClient()
        self.tracer = tracer
        if tracer is not None:
            # an injected tracer IS the process tracer: the middleware,
            # executor, and outbound RPC all consult the global (the
            # reference wires its jaeger tracer globally the same way,
            # tracing/tracing.go:27 GlobalTracer)
            from pilosa_tpu import tracing as _tracing

            _tracing.set_global_tracer(tracer)
        if heap_profile:
            # start tracemalloc before the holder opens so startup
            # allocations (fragment loads, stack builds) are captured —
            # the [profile] heap config (reference server/config.go:151)
            import tracemalloc

            if not tracemalloc.is_tracing():
                tracemalloc.start(heap_profile_frames)
        self.seeds = seeds or []
        self.anti_entropy_interval = anti_entropy_interval
        self.anti_entropy_jitter = anti_entropy_jitter
        self.anti_entropy_round_budget = anti_entropy_round_budget
        self.anti_entropy_peer_timeout = anti_entropy_peer_timeout
        self.heartbeat_interval = heartbeat_interval

        self.holder = Holder(data_dir)
        node_id = name or self.holder.node_id or uuid.uuid4().hex[:12]

        self._client = InternalClient(tls_skip_verify=tls_skip_verify)
        self.cluster = Cluster(
            local_id=node_id,
            replica_n=replica_n,
            partition_n=partition_n,
            transport=HTTPTransport(self._client),
            topology_path=os.path.join(data_dir, ".topology"),
            breaker_threshold=breaker_threshold,
            breaker_cooldown_s=breaker_cooldown,
        )
        self.node = ClusterNode(self.holder, self.cluster)
        # self-healing replication ([replication] config): process-wide
        # like [mesh] — the first server's retain() captures the
        # pre-server baseline, the LAST release() (in close) restores
        # it; the hint REPLAYER is per-node and starts in open()
        from pilosa_tpu.parallel import hints as _hints
        from pilosa_tpu.parallel.hints import HintReplayer

        _hints.retain()
        self._hints_retained = True
        # kept for the reopen path: close() releases the baseline, so
        # a reopened server must RE-APPLY its configured policy, not
        # silently revert to the restored default
        self._replication_cfg = dict(
            write_policy=write_policy,
            hint_max_bytes=hint_max_bytes,
            hint_max_age=hint_max_age,
            replay_interval=hint_replay_interval)
        _hints.configure(**self._replication_cfg)
        self.hint_replayer = HintReplayer(self.node)
        # [rebalance] — online shard migration; process-wide config is
        # refcounted like [replication], the coordinator DRIVER is
        # per-node (attached here so /cluster/resize can reach it)
        from pilosa_tpu.parallel import rebalance as _rebalance

        _rebalance.retain()
        self._rebalance_retained = True
        self._rebalance_cfg = {
            k: v for k, v in dict(
                transfer_budget=rebalance_transfer_budget,
                dual_write_policy=rebalance_dual_write_policy,
                cursor_path=rebalance_cursor_path,
                backoff_base=rebalance_backoff_base,
                backoff_cap=rebalance_backoff_cap,
                peer_timeout=rebalance_peer_timeout,
            ).items() if v is not None}
        if self._rebalance_cfg:
            _rebalance.configure(**self._rebalance_cfg)
        self.node.rebalance = _rebalance.RebalanceCoordinator(
            self.node, cursor_path=rebalance_cursor_path)
        self.node.executor.stats = self.stats
        self.node.executor.logger = self.logger
        self.node.executor.long_query_time = long_query_time
        # hedged replica reads ([cluster] hedge-* config)
        self.node.executor.hedge_min_samples = hedge_min_samples
        self.node.executor.hedge_deviations = hedge_deviations
        self.node.executor.hedge_min_s = hedge_min_ms / 1e3
        self.node.executor.hedge_max_fraction = hedge_max_fraction
        # failpoint registry ([faultinject] armed): armed at
        # construction, disarmed (process-wide) by close() — the
        # registry is process-global like the result cache, so only a
        # server that armed something clears it
        from pilosa_tpu import faultinject as _faultinject

        self._faultinject_armed = bool(faultinject_armed)
        if faultinject_armed:
            _faultinject.arm(faultinject_armed)
        # cross-query micro-batched dispatch ([coalescer] config);
        # "auto" resolves to on-accelerator-only
        from pilosa_tpu.parallel.coalescer import Coalescer

        self.node.executor.coalescer = Coalescer(
            window_s=coalescer_window_ms / 1e3,
            max_batch=coalescer_max_batch,
            enabled=coalescer_enabled,
            stats=self.stats,
            ragged=ragged_enabled,
            max_tape=ragged_max_tape,
            max_leaves=ragged_max_leaves,
            vm=vm_enabled,
            vm_min_domain=vm_min_domain,
            vm_max_prefetch=vm_max_prefetch,
        )
        self._ragged_prewarm = ragged_prewarm
        # query flight recorder ([observe] config): /debug/queries,
        # ?profile=1, slow-query log, pilosa_query_latency histogram
        from pilosa_tpu import observe as _observe

        self.node.executor.recorder = _observe.FlightRecorder(
            recent=observe_recent,
            long_query_time=observe_long_query_time,
            enabled=observe_enabled,
            logger=self.logger,
            stats=self.stats,
        )
        # cluster event journal ([observe] journal keys): process-wide
        # like [mesh] — the first server's retain() captures the
        # pre-server baseline, the LAST release() (in close) restores
        # it for library users sharing the process
        _observe.retain()
        self._journal_retained = True
        self._journal_cfg = dict(
            node_id=node_id,
            size=observe_journal_size,
            kinds=observe_journal_kinds,
            enabled=observe_journal,
        )
        _observe.configure(**self._journal_cfg)
        # generation-stamped query result cache ([cache] config):
        # process-wide like the residency manager — configure in place
        # so a second in-process server cannot wipe the first's warm
        # entries
        from pilosa_tpu.runtime import resultcache as _resultcache

        _resultcache.configure(
            budget_bytes=cache_budget_bytes,
            max_entry_bytes=cache_max_entry_bytes,
            ttl_s=cache_ttl,
            enabled=cache_enabled,
        )
        # streaming ingest ([ingest] config): delta planes + background
        # compaction are process-wide like the result cache — configure
        # in place; the compactor thread starts in open() and stops in
        # close().  Remember whether the package default (disabled, so
        # bare library embedders keep pre-delta semantics) was already
        # overridden: close() only restores what THIS server flipped.
        from pilosa_tpu import ingest as _ingest

        # the FIRST in-process server snapshots the pre-server config;
        # the LAST one to close restores it (ingest.restore_baseline —
        # per-server snapshots compose wrongly when servers close in
        # creation order, re-installing an earlier sibling's override)
        _ingest.capture_baseline()
        _ingest.configure(
            delta_enabled=ingest_delta_enabled,
            delta_budget_bytes=ingest_delta_budget_bytes,
            compact_threshold_bits=ingest_compact_threshold_bits,
            compact_interval=ingest_compact_interval,
        )
        self._ingest_enabled = bool(ingest_delta_enabled)
        self._ingest_retained = False
        self._closed = False
        # compressed container-directory engine ([containers] config):
        # process-wide like [ingest] — the first server's retain()
        # captures the pre-server baseline, the LAST release() (in
        # close) restores it for library users sharing the process
        from pilosa_tpu.ops import containers as _containers

        _containers.retain()
        self._containers_retained = True
        _containers.configure(enabled=containers_enabled,
                              threshold=containers_threshold,
                              kinds=containers_kinds,
                              array_max=containers_array_max,
                              run_cap=containers_run_cap)
        # mesh-native SPMD execution ([mesh] config): process-wide
        # like [containers] — the first server's retain() captures the
        # pre-server baseline, the LAST release() (in close) restores
        # it for library users sharing the process
        from pilosa_tpu.parallel import meshexec as _meshexec

        _meshexec.retain()
        self._mesh_retained = True
        _meshexec.configure(enabled=mesh_enabled,
                            axis_size=mesh_axis_size)
        # engine observatory ([observe] device-peak-gbps /
        # profiler-max-seconds + [cost] shadow): process-wide like
        # [mesh] — the first server's retain() captures the pre-server
        # baseline, the LAST release() (in close) restores it
        from pilosa_tpu import perfobs as _perfobs

        _perfobs.retain()
        self._perfobs_retained = True
        self._perfobs_cfg = dict(
            enabled_=observe_enabled,
            peak_gbps=observe_device_peak_gbps,
            shadow=cost_shadow,
            profiler_max_seconds=observe_profiler_max_seconds)
        _perfobs.configure(**self._perfobs_cfg)
        # per-tenant isolation ([tenants] config): process-wide like
        # [mesh] — the first server's retain() captures the pre-server
        # baseline, the LAST release() (in close) restores it.  The
        # admission gate, result cache and residency manager all
        # consult serve.tenant.policy() live, so this configure is the
        # single switch.
        from pilosa_tpu.serve import tenant as _tenantcfg

        _tenantcfg.retain()
        self._tenants_retained = True
        self._tenants_cfg = dict(
            enabled=tenants_enabled,
            default_share=tenants_default_share,
            default_queue=tenants_default_queue,
            default_cache_share=tenants_default_cache_share,
            default_residency_share=tenants_default_residency_share,
            quotas=tenants_quotas)
        _tenantcfg.configure(**self._tenants_cfg)
        # tiered residency ([residency] config): process-wide like
        # [mesh] — the first server's retain() captures the pre-server
        # baseline, the LAST release() (in close) restores it and
        # stops the shared promotion workers
        from pilosa_tpu.runtime import residency as _residency

        _residency.retain()
        self._residency_retained = True
        _residency.configure(
            host_budget_bytes=residency_host_budget_bytes,
            disk_path=residency_disk_path,
            disk_budget_bytes=residency_disk_budget_bytes,
            promote_workers=residency_promote_workers,
            promote_queue=residency_promote_queue,
            promote_wait_ms=residency_promote_wait_ms,
            prefetch=residency_prefetch,
            prefetch_interval=residency_prefetch_interval)
        if self._ingest_enabled:
            # reference taken at CONSTRUCTION, where the configure
            # above landed — not at open() — so a sibling's close
            # cannot restore the baseline out from under a
            # constructed-but-not-yet-opened server (the scan thread
            # idling over an empty registry until open is harmless)
            from pilosa_tpu.ingest import compactor as _compactor

            _compactor.retain()
            self._ingest_retained = True
        # device-runtime telemetry (pilosa_tpu.devobs): wire the stats
        # backend in (compile.ms histograms publish live) and start the
        # optional background gauge sampler
        from pilosa_tpu import devobs as _devobs

        _devobs.observer().stats = self.stats
        self.device_sampler = _devobs.DeviceSampler(
            self.stats, observe_device_sample_interval)
        if coordinator:
            # statically designated coordinator (reference
            # cluster.coordinator config, server/config.go:104)
            self.cluster.coordinator_id = self.cluster.local_id
            self.cluster.local_node.is_coordinator = True
        self.api = API(self.node)
        self.api.max_writes_per_request = max_writes_per_request
        # admission control ([admission] config): priority-classed
        # gating + load shedding between accept and device dispatch
        from pilosa_tpu.serve.admission import AdmissionController

        self.admission = AdmissionController(
            query_cap=admission_query_cap,
            query_queue=admission_query_queue,
            ingest_cap=admission_ingest_cap,
            ingest_queue=admission_ingest_queue,
            internal_cap=admission_internal_cap,
            internal_queue=admission_internal_queue,
            default_deadline=admission_default_deadline,
            enabled=admission_enabled,
            stats=self.stats,
        )
        # background delta compactor (pilosa_tpu.ingest.compactor):
        # process-wide; runs each scan under admission's internal class
        # so compaction yields to query pressure like anti-entropy does
        from pilosa_tpu.ingest import compactor as _compactor

        _c = _compactor.compactor()
        _c.admission = self.admission
        # tiered-residency promotion pool: each promotion admits under
        # the internal class, so query saturation sheds promotions
        # (the waiting query takes the host-compute fallback) exactly
        # like it pauses compaction
        _residency.promoter().admission = self.admission
        from pilosa_tpu.runtime.prefetch import Prefetcher

        self.prefetcher = Prefetcher()
        self.handler = Handler(self.api, host=host, port=port,
                               stats=self.stats, tracer=tracer,
                               tls_cert=tls_cert, tls_key=tls_key,
                               heap_frames=heap_profile_frames,
                               admission=self.admission,
                               peer_client=self._client,
                               fanin_timeout=observe_fanin_timeout)
        self.cluster.local_node.uri = self.handler.uri
        from pilosa_tpu.diagnostics import RuntimeMonitor

        self.runtime_monitor = RuntimeMonitor(self.stats,
                                              metric_poll_interval)
        self._closers: list = []
        self._stop = threading.Event()

    @property
    def uri(self) -> str:
        return self.handler.uri

    # ---------------------------------------------------------- lifecycle

    def open(self) -> None:
        """Serve, then join via seeds or become a standalone NORMAL
        cluster (server.go:417 Open; gossip join with retry,
        gossip/gossip.go:65-123)."""
        self._closed = False  # an instance reopened after close()
        # reopened after close(): the holder closed its indexes and
        # released the directory flock — reload persisted state (no-op
        # on first open, which holds the flock from construction)
        self.holder.reopen()
        if not self._containers_retained:
            # reopened after close(): take the [containers] reference
            # back (the first open holds the construction-time one)
            from pilosa_tpu.ops import containers as _containers

            _containers.retain()
            self._containers_retained = True
        if not self._mesh_retained:
            # reopened after close(): take the [mesh] reference back
            from pilosa_tpu.parallel import meshexec as _meshexec

            _meshexec.retain()
            self._mesh_retained = True
        if not self._perfobs_retained:
            # reopened after close(): take the observatory reference
            # back and RE-APPLY this server's knobs (close() restored
            # the process baseline)
            from pilosa_tpu import perfobs as _perfobs

            _perfobs.retain()
            self._perfobs_retained = True
            _perfobs.configure(**self._perfobs_cfg)
        if not self._tenants_retained:
            # reopened after close(): take the [tenants] reference
            # back and RE-APPLY this server's configured quotas
            # (close() restored the process baseline — without the
            # re-apply a reopened server would serve with isolation
            # silently off, the [replication] reopen bug class)
            from pilosa_tpu.serve import tenant as _tenantcfg

            _tenantcfg.retain()
            self._tenants_retained = True
            _tenantcfg.configure(**self._tenants_cfg)
        if not self._residency_retained:
            # reopened after close(): take the [residency] reference
            # back and re-wire the promotion pool's admission gate
            from pilosa_tpu.runtime import residency as _residency

            _residency.retain()
            self._residency_retained = True
            _residency.promoter().admission = self.admission
        if self._ingest_enabled and not self._ingest_retained:
            # reopened after close(): take the reference back (the
            # normal first open already holds the construction-time
            # one)
            from pilosa_tpu.ingest import compactor as _compactor

            _compactor.retain()
            self._ingest_retained = True
        if not self._hints_retained:
            # reopened after close(): take the [replication] reference
            # back, RE-APPLY this server's configured policy (close()
            # restored the process baseline), and rebuild the hint
            # store (close() released its append handles; queued hints
            # reload from disk)
            import os as _os

            from pilosa_tpu.parallel import hints as _hints
            from pilosa_tpu.parallel.hints import HintStore

            _hints.retain()
            self._hints_retained = True
            _hints.configure(**self._replication_cfg)
            self.node.hints = HintStore(
                _os.path.join(self.holder.path, "hints")
                if getattr(self.holder, "path", None) else None)
        if not self._rebalance_retained:
            from pilosa_tpu.parallel import rebalance as _rebalance1

            _rebalance1.retain()
            self._rebalance_retained = True
            if self._rebalance_cfg:
                _rebalance1.configure(**self._rebalance_cfg)
        if not self._journal_retained:
            # reopened after close(): take the event-journal reference
            # back and RE-APPLY this server's node id / ring sizing
            # (close() restored the process baseline)
            from pilosa_tpu import observe as _observe1

            _observe1.retain()
            self._journal_retained = True
            _observe1.configure(**self._journal_cfg)
        self.handler.serve_background()
        self.cluster.save_topology()
        if self.seeds:
            self._join_via_seeds()
            # announce restored shards (peers' status came back in the
            # join response's nodeStatus)
            self.node.broadcast_node_status()
        else:
            # single/static bootstrap: coordinator of own cluster
            self.cluster.coordinator_id = self.cluster.local_id
            self.cluster.local_node.is_coordinator = True
            self.cluster.set_state(STATE_NORMAL)
        try:
            # crash mid-rebalance leaves a persisted cursor: pick the
            # migration back up from the last completed shard (no-op
            # when no cursor file exists or we are not the coordinator)
            if self.cluster.is_coordinator:
                self.node.rebalance.resume()
        except Exception as e:  # noqa: BLE001 — resume must not block
            # serving; the cluster keeps the old topology either way
            self.logger.printf("rebalance resume skipped: %r", e)
        if self.anti_entropy_interval > 0:
            t = threading.Thread(target=self._anti_entropy_loop, daemon=True)
            t.start()
        if self.heartbeat_interval > 0:
            t = threading.Thread(target=self._heartbeat_loop, daemon=True)
            t.start()
        self.runtime_monitor.start()
        self.device_sampler.start()
        self.prefetcher.start()
        # hinted-handoff replay worker: drains per-peer hint queues
        # with backoff once a peer's breaker closes / heartbeat returns
        self.hint_replayer.start()
        if self._ragged_prewarm:
            # lower the ragged bucket interpreter programs off the
            # serving path ([ragged] prewarm): best-effort, background,
            # a no-op in host mode or with the coalescer/ragged off
            t = threading.Thread(target=self._prewarm_ragged,
                                 daemon=True, name="ragged-prewarm")
            t.start()

    def _prewarm_ragged(self) -> None:
        from pilosa_tpu.ops import bitmap as bm
        from pilosa_tpu.ops import tape as _tape
        from pilosa_tpu.shardwidth import SHARD_WIDTH

        co = self.node.executor.coalescer
        if co is None or not (co.enabled and co.ragged) or bm.host_mode():
            return
        try:
            from pilosa_tpu.models.field import _padded_rows
            from pilosa_tpu.parallel import meshexec

            # the leaf stack shape every fused read stages: the widest
            # index's shard fan-out, padded exactly as serving stacks
            # pad (_padded_rows keys on the [mesh] axis in force — the
            # actual device layout), SHARD_WIDTH words.  The mesh is
            # threaded through so the programs LOWERED are the ones
            # serving traffic will run: shard_map variants on an
            # active mesh, single-device ones otherwise — a 1-device
            # process never lowers mesh-shaped programs and an
            # N-device mesh never wastes the warm-up on single-device
            # ones.  An empty holder warms a nominal 1-shard stack —
            # the program structure still lowers; a different shard
            # count later re-specializes only the cheap outer shapes.
            n_shards = max(
                [len(idx.available_shards())
                 for idx in self.holder.indexes.values()] or [1])
            stack = (_padded_rows(max(1, n_shards)),
                     bm.n_words(SHARD_WIDTH))
            _tape.prewarm(stack, co.max_batch, co.max_tape,
                          co.max_leaves,
                          mesh=meshexec.active_mesh())
        except Exception as e:  # noqa: BLE001 — prewarm must never
            # break serving; the first ragged window pays the compile
            self.logger.printf("ragged prewarm skipped: %r", e)
    def _join_via_seeds(self) -> None:
        client = self._client
        me = self.cluster.local_node.to_dict()
        last_err: Exception | None = None
        for attempt in range(60):  # 60 retries (gossip/gossip.go:102)
            for seed in self.seeds:
                try:
                    resp = client.send_message(
                        seed, {"type": "node-join", "node": me})
                    if resp.get("status") and self.cluster.apply_status(
                            resp["status"]):
                        # the join response carried a stale self-DOWN
                        # (predates this restart): heal stale peer
                        # views too, or with SWIM disabled they route
                        # reads away from us forever
                        self.node.broadcast({
                            "type": "node-state",
                            "node": self.cluster.local_id,
                            "state": NODE_READY})
                    # catch up on shards created while this node was
                    # away (the coordinator's NodeStatus)
                    if resp.get("nodeStatus"):
                        self.node.apply_node_status(resp["nodeStatus"])
                    return
                except (TransportError, Exception) as e:
                    last_err = e
            self._stop.wait(0.5)
            if self._stop.is_set():
                return
        raise RuntimeError(f"could not join cluster via seeds: {last_err}")

    def _anti_entropy_loop(self) -> None:
        import random

        from pilosa_tpu.parallel.syncer import HolderSyncer

        syncer = HolderSyncer(
            self.node, peer_timeout=self.anti_entropy_peer_timeout)
        budget = self.anti_entropy_round_budget
        while True:
            wait = self.anti_entropy_interval
            if self.anti_entropy_jitter > 0:
                # jittered cadence: a fleet restarted together must
                # not run every AE sweep (and its RPC fan-out) in
                # lockstep
                wait *= 1.0 + random.uniform(-self.anti_entropy_jitter,
                                             self.anti_entropy_jitter)
            if self._stop.wait(max(0.01, wait)):
                return
            try:
                syncer.sync_holder(
                    budget_s=budget if budget and budget > 0 else None)
            except Exception:
                pass

    def _heartbeat_loop(self) -> None:
        from pilosa_tpu.parallel.membership import heartbeat_round

        while not self._stop.wait(self.heartbeat_interval):
            try:
                heartbeat_round(self.node)
            except Exception:
                pass

    def close(self) -> None:
        # idempotent: a double-close (belt-and-braces test teardown)
        # must not release the shared compactor reference twice and
        # tear it down under a still-open sibling server
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        self.runtime_monitor.stop()
        self.device_sampler.stop()
        self.prefetcher.stop()
        self.hint_replayer.stop()
        # halt (not abort) any in-flight rebalance: the persisted
        # cursor survives so a restarted coordinator resumes the
        # migration instead of stranding the cluster mid-plan
        try:
            self.node.rebalance.stop()
        except Exception:  # noqa: BLE001 — close() must stay idempotent
            pass
        from pilosa_tpu.parallel import hints as _hints0, \
            rebalance as _rebalance0

        if self._rebalance_retained:
            self._rebalance_retained = False
            _rebalance0.release()
        if self._hints_retained:
            self._hints_retained = False
            _hints0.release()
        self.node.hints.close()
        # the scan thread and [ingest] config are shared across every
        # in-process server: drop our reference, and only when we were
        # the LAST ingest-enabled server stop the thread and restore
        # the pre-server baseline config (a closed server group must
        # not leave streaming semantics — or an aggressive budget/
        # threshold/interval — in force for unrelated library users,
        # nor yank them out from under a still-open sibling).  Pending
        # bits are WAL-durable — fragment close drops the planes,
        # reopen replays them.
        from pilosa_tpu import ingest as _ingest
        from pilosa_tpu.ingest import compactor as _compactor

        if self._ingest_retained:
            self._ingest_retained = False
            last = _compactor.release()
        else:
            # ingest-disabled server: only restore when no
            # ingest-enabled sibling still holds a reference
            last = _compactor.refs() == 0
        if last:
            _ingest.restore_baseline()
        from pilosa_tpu.ops import containers as _containers

        if self._containers_retained:
            self._containers_retained = False
            _containers.release()
        from pilosa_tpu.parallel import meshexec as _meshexec

        if self._mesh_retained:
            self._mesh_retained = False
            _meshexec.release()
        from pilosa_tpu import perfobs as _perfobs0

        if self._perfobs_retained:
            self._perfobs_retained = False
            _perfobs0.release()
        from pilosa_tpu.runtime import residency as _residency2

        if self._residency_retained:
            self._residency_retained = False
            _residency2.release()
        from pilosa_tpu.serve import tenant as _tenantcfg2

        if self._tenants_retained:
            self._tenants_retained = False
            _tenantcfg2.release()
        from pilosa_tpu import observe as _observe2

        if self._journal_retained:
            self._journal_retained = False
            _observe2.release()
        if self._faultinject_armed:
            # config-armed failpoints are process-wide: the arming
            # server disarms everything on close so library users
            # sharing the process never inherit injected faults
            from pilosa_tpu import faultinject as _faultinject

            _faultinject.disarm()
            self._faultinject_armed = False
        self.handler.close()
        self._client.close()  # drop pooled keep-alive sockets
        self.holder.close()
        for closer in self._closers:
            try:
                closer()
            except Exception:
                pass
