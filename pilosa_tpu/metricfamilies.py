"""Declarative metric-family registry: every family this codebase
emits, declared exactly once.

Before this module the family lists lived embedded in
``tools/check_metrics.py`` (the live-server checker) and were
re-derived by hand in docs and review — adding a metric family meant
touching the checker, the docs, and remembering both.  Now a family is
declared here and consumed by:

- ``tools/check_metrics.check_families`` — the live-exposition gate
  (``--families`` CLI mode and tests/test_http.py) requires at least
  one sampled metric under every family whose ``live_prefixes`` is
  non-empty;
- ``tools/analyze`` pass P6 (metric-family drift) — statically
  harvests every metric-name string literal fed to the stats registry
  across ``pilosa_tpu/`` and fails when a name's family is not
  declared here, or a family declared ``static=True`` has no
  harvested emitter left (a refactor silently dropped it);
- docs cross-checks — a family naming a ``doc`` file must be
  mentioned there (rendered prefix), so operator documentation cannot
  silently rot.

Registry dot-names (``cache.hits``) render on /metrics with ``_``
(``cache_hits``); ``rendered`` is the family's Prometheus prefix.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class Family:
    """One metric family.

    ``name`` — the dot-name prefix as fed to the stats registry
    (first segment of ``cache.hits`` is ``cache``).
    ``rendered`` — the Prometheus-rendered prefix (``cache_``).
    ``live_prefixes`` — rendered prefixes a live server MUST sample
    under (empty tuple: not required on every assembly, e.g. families
    only emitted once traffic of that kind arrives).
    ``static`` — the P6 drift pass requires at least one statically
    harvested emitter for this family in ``pilosa_tpu/``.
    ``group`` — back-compat grouping for the per-subsystem constants
    ``tools/check_metrics.py`` has always exported.
    ``doc`` — docs file (under ``docs/``) that must mention the
    rendered prefix, or None.
    """

    name: str
    rendered: str
    description: str
    live_prefixes: tuple = ()
    static: bool = True
    group: str | None = None
    doc: str | None = None
    owners: tuple = field(default_factory=tuple)


#: Every metric family the package emits.  Add new families HERE —
#: check_metrics, the P6 static pass, and the docs check all consume
#: this one list.
FAMILIES: tuple[Family, ...] = (
    Family("device", "device_",
           "device memory, transfer metering (pilosa_tpu.devobs)",
           live_prefixes=("device_",), group="device",
           doc="administration.md"),
    Family("compile", "compile_",
           "jit first-lowering tracking and fused-program cache "
           "evictions (pilosa_tpu.devobs, ops/expr.py)",
           live_prefixes=("compile_",), group="device",
           doc="administration.md"),
    Family("residency", "residency_",
           "device-cache budget/evict/admit accounting plus the "
           "residency.tier.* host/disk-tier, demotion, promotion and "
           "fallback counters (runtime/residency.py)",
           live_prefixes=("residency_", "residency_tier_"),
           group="device", doc="administration.md"),
    Family("prefetch", "prefetch_",
           "predictive host-tier->HBM prefetcher "
           "(runtime/prefetch.py)",
           live_prefixes=("prefetch_",), group="tier",
           doc="administration.md"),
    Family("cache", "cache_",
           "generation-stamped result cache (runtime/resultcache.py)",
           live_prefixes=("cache_",), group="cache",
           doc="administration.md"),
    Family("ingest", "ingest_",
           "streaming-ingest delta planes and background compaction "
           "(pilosa_tpu.ingest)",
           live_prefixes=("ingest_",), group="ingest",
           doc="administration.md"),
    Family("tape", "tape_",
           "ragged op-tape interpreter (ops/tape.py)",
           live_prefixes=("tape_",), group="tape",
           doc="architecture.md"),
    Family("vm", "vm_",
           "Pallas bitmap VM: one scalar-prefetch kernel for ragged "
           "tapes over compressed containers (ops/pallas_kernels.py "
           "+ ops/tape.py); vm_fallbacks_* is the per-reason "
           "breakdown of dense-path fallbacks",
           live_prefixes=("vm_",), group="tape",
           doc="architecture.md"),
    Family("container", "container_",
           "compressed container-directory execution engine with "
           "per-kind bitmap/array/run pools (ops/containers.py); "
           "container_*_gathered breaks gathers out per kind",
           live_prefixes=("container_",), group="container",
           doc="architecture.md"),
    Family("mesh", "mesh_",
           "mesh-native SPMD execution of the fused serving path "
           "(parallel/meshexec.py)",
           live_prefixes=("mesh_",), group="mesh",
           doc="architecture.md"),
    Family("coalescer", "coalescer_",
           "cross-query batching window (parallel/coalescer.py); the "
           "shape_* heterogeneity counters are pinned on live "
           "servers, the window timings appear once traffic flows",
           live_prefixes=("coalescer_shape_",), group="tape",
           doc="architecture.md"),
    Family("admission", "admission_",
           "priority-class admission control (serve/admission.py)",
           doc="administration.md"),
    Family("breaker", "breaker_",
           "per-peer circuit breakers on the cluster fan-out "
           "(parallel/cluster.py)",
           live_prefixes=("breaker_",), group="chaos",
           doc="administration.md"),
    Family("hedge", "hedge_",
           "hedged replica reads on the remote shard map "
           "(parallel/executor.py)",
           live_prefixes=("hedge_",), group="chaos",
           doc="administration.md"),
    Family("failpoint", "failpoint_",
           "failpoint registry arming/trigger accounting "
           "(pilosa_tpu.faultinject)",
           live_prefixes=("failpoint_",), group="chaos",
           doc="administration.md"),
    Family("partial", "partial_",
           "degraded-read (?partial=1) request accounting "
           "(parallel/executor.py)",
           live_prefixes=("partial_",), group="chaos",
           doc="administration.md"),
    Family("ae", "ae_",
           "anti-entropy rounds: fragments walked, dirty/reconciled/"
           "pushed blocks, classified peer failures, digest-cache "
           "hits (parallel/syncer.py)",
           live_prefixes=("ae_",), group="repl",
           doc="administration.md"),
    Family("hint", "hint_",
           "hinted handoff for degraded writes: queued/replayed/"
           "dropped hints plus live per-node queue depth "
           "(parallel/hints.py)",
           live_prefixes=("hint_",), group="repl",
           doc="administration.md"),
    Family("rebalance", "rebalance_",
           "online shard migration: plans/cutovers/aborts/resumes, "
           "dual-write deliveries, streamed backfill bytes, breaker "
           "backoffs, live per-state shard gauges "
           "(parallel/rebalance.py)",
           live_prefixes=("rebalance_",), group="rebalance",
           doc="administration.md"),
    Family("wal", "wal_",
           "fragment WAL replay health — torn/corrupt tail records "
           "ignored at reload (models/fragment.py)",
           live_prefixes=("wal_",), group="repl",
           doc="administration.md"),
    Family("engine", "engine_",
           "engine observatory per-launch accounting: sampled launch/"
           "byte totals plus per-engine tagged wall/bandwidth/bw_util "
           "gauges (pilosa_tpu.perfobs)",
           live_prefixes=("engine_",), group="engine",
           doc="administration.md"),
    Family("cost", "cost_",
           "shadow cost model: cost-table samples/cells, shadow "
           "consults and disagreements, completed profiler captures "
           "(pilosa_tpu.perfobs)",
           live_prefixes=("cost_",), group="engine",
           doc="administration.md"),
    Family("tenant", "tenant_",
           "per-tenant isolation totals: admission admitted/shed/"
           "waiting, result-cache bytes, residency HBM/host bytes "
           "(serve/tenant.py; zeros while [tenants] is off)",
           live_prefixes=("tenant_",), group="tenant",
           doc="administration.md"),
    Family("event", "event_",
           "cluster event journal: structured state-transition events "
           "(breaker/hedge/rebalance/AE/compaction/residency/"
           "failpoint), ring depth and drop accounting "
           "(pilosa_tpu.observe.EventJournal)",
           live_prefixes=("event_",), group="trace",
           doc="administration.md"),
    Family("trace", "trace_",
           "cross-node trace assembly: /debug/trace/{id} trees "
           "assembled, per-node record fan-ins, fan-in errors, "
           "origin-less assemblies (pilosa_tpu.traceasm + "
           "server/handler.py)",
           live_prefixes=("trace_",), group="trace",
           doc="administration.md"),
    Family("http", "http_",
           "per-route request counters (server/handler.py)"),
    Family("gc", "gc_",
           "python garbage-collector sampling (diagnostics.py)"),
    Family("memory", "memory_",
           "process RSS sampling (diagnostics.py)"),
)

#: Metric names without a family prefix (no dot): the runtime sampler
#: gauges and the native-histogram latency family.  The P6 harvest
#: only considers dotted names, so these are documented rather than
#: checked; they are listed so the registry is the complete inventory.
BARE_METRICS: tuple[str, ...] = (
    "open_files",
    "threads",
    "pilosa_query_latency",
)


def by_name() -> dict[str, Family]:
    return {f.name: f for f in FAMILIES}


def live_prefixes(group: str | None = None) -> tuple[str, ...]:
    """Rendered prefixes a live server must sample under — all of
    them, or one back-compat subsystem group's."""
    out: list[str] = []
    for f in FAMILIES:
        if group is not None and f.group != group:
            continue
        out.extend(f.live_prefixes)
    return tuple(out)


def static_families() -> tuple[Family, ...]:
    """Families the P6 drift pass requires a static emitter for."""
    return tuple(f for f in FAMILIES if f.static)
