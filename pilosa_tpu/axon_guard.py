"""Dead-relay fallback for axon-tunneled TPU environments.

On axon hosts the TPU is reached through a relay process; if the relay
dies, ANY jax backend init hangs forever on the registered PJRT plugin
(even with JAX_PLATFORMS=cpu in the environment — the site hook
registered the plugin at interpreter start).  `jax.config` wins any
time before backend init, so entry points that must always complete
(bench.py, __graft_entry__, benchmarks/measure.py) call
``guard_dead_relay()`` before touching devices.

A live relay PROCESS is not a live TUNNEL: the relay is a dumb stdio
multiplexer whose far end (the orchestrator owning the chip) can stop
responding while the local process sits healthy (observed round 3:
devices enumerated, then the first compile hung >28 min).  So when the
process is up, the guard additionally runs a tiny end-to-end jax probe
in a KILLABLE subprocess with a deadline — a hang costs one timeout,
not the whole run — and falls back to CPU when the probe dies.  Probe
successes are cached on disk for a few minutes so bench.py +
measure.py back-to-back pay for one probe, not two.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

# End-to-end probe: allow a full cold compile through the relay
# (~30-60 s healthy) plus margin.
_PROBE_TIMEOUT_S = float(os.environ.get(
    "PILOSA_TPU_AXON_PROBE_TIMEOUT_S", "240"))
_PROBE_TTL_S = 600.0
_PROBE_STAMP = "/tmp/pilosa_axon_probe_ok"

# The relay tunnel is single-client: while the relay watcher is
# mid-capture (tools/relay_watcher.capturing exists) a second jax
# process would stall behind it and misread the stall as a dead tunnel.
# A full capture can legitimately hold the tunnel for hours (validate
# 1800 s + bench 1800 s + measure 5400 s budgets), so after the bounded
# wait the guard falls back WITHOUT probing — "busy with capture" is
# not "dead", and the watcher is already producing the chip artifacts.
_CAPTURE_WAIT_S = float(os.environ.get(
    "PILOSA_TPU_AXON_CAPTURE_WAIT_S", "2700"))
_CAPTURING_FLAG = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools", "relay_watcher.capturing")


def _axon_registered() -> bool:
    """True when the axon PJRT backend factory is registered (the site
    hook ran at interpreter start).  Never triggers backend init."""
    try:
        from jax._src import xla_bridge as xb

        return any("axon" in n
                   for n in getattr(xb, "_backend_factories", {}))
    except Exception:
        return False


def _relay_alive() -> bool | None:
    """True/False when pgrep answered; None when the CHECK ITSELF
    failed (pgrep missing/timed out) — callers that take destructive
    action on "dead" must treat None as unknown, not as dead."""
    try:
        out = subprocess.run(["pgrep", "-f", r"\.relay\.py"],
                             capture_output=True, timeout=5)
        return bool(out.stdout.strip())
    except Exception as e:
        print(f"axon_guard: pgrep failed ({e}); relay state unknown",
              file=sys.stderr)
        return None


def tunnel_responsive(timeout_s: float = _PROBE_TIMEOUT_S,
                      use_cache: bool = True) -> bool:
    """True when a trivial jax computation completes through the relay
    within ``timeout_s``.  Runs in a subprocess so a wedged tunnel only
    costs the deadline.  Callers must already know the relay process is
    up (a dead process would make the subprocess hang the full
    deadline for a foregone conclusion)."""
    if use_cache:
        try:
            if time.time() - os.path.getmtime(_PROBE_STAMP) < _PROBE_TTL_S:
                return True
        except OSError:
            pass
    code = ("import jax, jax.numpy as jnp\n"
            "print('probe', int(jnp.arange(9, dtype=jnp.uint32).sum()))\n")
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=timeout_s)
        ok = out.returncode == 0 and "probe 36" in out.stdout
        if not ok:
            # A fast child crash is NOT a tunnel hang — say what broke
            # (observed: PYTHONPATH overridden without :$PYTHONPATH drops
            # the axon site hook, so the child can't init the backend).
            print(f"axon_guard: probe child failed (rc={out.returncode}, "
                  f"not a timeout) stderr tail: {out.stderr[-400:]!r}",
                  file=sys.stderr)
    except subprocess.TimeoutExpired:
        print(f"axon_guard: probe timed out after {timeout_s:.0f}s",
              file=sys.stderr)
        ok = False
    except Exception as e:
        print(f"axon_guard: probe raised {type(e).__name__}: {e}",
              file=sys.stderr)
        ok = False
    if ok:
        try:
            with open(_PROBE_STAMP, "w") as f:
                f.write(str(time.time()))
        except OSError:
            pass
    return ok


def measured_transfer_gbps(nbytes: int = 32 << 20,
                           timeout_s: float = 240.0) -> float:
    """Host->device transfer bandwidth in GB/s, measured by one
    device_put in a KILLABLE subprocess (a wedged tunnel costs the
    deadline, not the caller's run).  0.0 on any failure or timeout.

    Purpose: scale benchmarks gate their device-resident configs on
    this number.  A real TPU host moves multi-GB/s over DMA; the axon
    relay tunnel has been observed at ~MB/s and WEDGES outright on
    multi-GB transfers (round 3: a 10B-config prewarm pushing 2.5 GB
    hung the tunnel end-to-end), so pushing a north-star working set
    through it is never sane."""
    code = (
        "import time, numpy as np, jax\n"
        # warm the backend first: a cold PJRT init through the relay is
        # 30-60 s and must not count against the transfer itself
        "jax.device_put(np.ones(1024, dtype=np.uint32))"
        ".block_until_ready()\n"
        f"x = np.ones({nbytes} // 4, dtype=np.uint32)\n"
        "t0 = time.time()\n"
        "d = jax.device_put(x)\n"
        "d.block_until_ready()\n"
        "dt = time.time() - t0\n"
        f"print('gbps', {nbytes} / dt / 1e9)\n")
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=timeout_s)
        if out.returncode == 0 and "gbps" in out.stdout:
            return float(out.stdout.split("gbps", 1)[1].split()[0])
    except Exception:
        pass
    return 0.0


def _wait_out_capture() -> bool:
    """Block (bounded) while the relay watcher holds the tunnel.
    Returns True when the tunnel is free to probe; False when the
    capture still holds it at the deadline (probing a busy
    single-client tunnel would misdiagnose it as dead)."""
    if os.environ.get("PILOSA_TPU_AXON_CAPTURING"):
        return True  # we ARE the capture — never wait on our own flag
    deadline = time.monotonic() + _CAPTURE_WAIT_S
    announced = False
    while os.path.exists(_CAPTURING_FLAG) and time.monotonic() < deadline:
        if not announced:
            print("axon_guard: relay watcher capture in progress; "
                  "waiting for the tunnel ...", file=sys.stderr)
            announced = True
        time.sleep(10.0)
    return not os.path.exists(_CAPTURING_FLAG)


def scrub_axon_backend() -> None:
    """Deregister the axon PJRT backend factory before first backend
    init.  With the relay PROCESS gone (not merely a wedged tunnel),
    plugin discovery hangs inside ``jax.devices()`` even when jax is
    pinned to cpu (observed round 3: ``JAX_PLATFORMS=cpu python -c
    'import jax; jax.devices()'`` never returns once the relay pid is
    gone, while the same command completes instantly with the plugin
    env unset).  Pinning the platform is not enough — the factory must
    go.  Private-API access is deliberate and fenced: on a jax upgrade
    this degrades to the documented hang plus a loud stderr line, never
    a new failure mode.  No-op after backends are initialized."""
    try:
        from jax._src import xla_bridge as xb

        for name in list(getattr(xb, "_backend_factories", {})):
            if "axon" in name:
                xb._backend_factories.pop(name, None)
    except Exception as e:  # noqa: BLE001 — degrade loudly, not fatally
        print(f"axon_guard: could not deregister axon backend "
              f"({type(e).__name__}: {e}); backend init may hang",
              file=sys.stderr)


def guard_dead_relay(wait_s: float = 0.0) -> bool:
    """When this process targets the axon backend but the relay is
    gone — process dead OR tunnel unresponsive end-to-end — pin jax to
    CPU (announced on stderr) so the run completes instead of hanging.
    Returns True when the fallback engaged.  Does nothing unless
    JAX_PLATFORMS is EXPLICITLY "axon" — on ordinary TPU/GPU hosts the
    guard must never hide the real accelerator.

    ``wait_s`` > 0 polls for the relay process to (re)appear before
    giving up — benchmark entry points use this so a briefly-restarting
    relay still yields a chip number instead of a CPU fallback."""
    if os.environ.get("JAX_PLATFORMS") != "axon":
        # Not targeting axon — but a REGISTERED axon plugin whose relay
        # process is dead still hangs backend init for ANY platform pin
        # (the discovery path blocks before the filter applies).  A dead
        # relay means no accelerator is being hidden, so scrubbing here
        # is always safe; a live relay never hangs init, so leave it.
        # Scrub only on a CONFIRMED-dead relay (pgrep answered "no
        # process") — a failed check (None) must never demote a live
        # accelerator to CPU.
        if _axon_registered() and _relay_alive() is False:
            # ROUTINE housekeeping on this box, not an anomaly: logged
            # at INFO (silent unless logging is configured) instead of
            # printed, so harness stderr tails — the multichip
            # capture's `tail` field — carry real signal only (the
            # notice polluted MULTICHIP_r05.json's tail)
            import logging

            logging.getLogger("pilosa_tpu.axon_guard").info(
                "axon plugin registered but relay process is dead; "
                "deregistering it so backend init cannot hang")
            scrub_axon_backend()
            # The site hook's register() also PINS jax_platforms config
            # to "axon,cpu" (config beats the env var), so honor the
            # caller's env choice minus the dead axon entry.
            import jax

            want = [p for p in
                    (os.environ.get("JAX_PLATFORMS") or "cpu").split(",")
                    if p and p != "axon"]
            jax.config.update("jax_platforms", ",".join(want) or "cpu")
        return False

    deadline = time.monotonic() + wait_s
    alive = _relay_alive()
    while not alive and time.monotonic() < deadline:
        remaining = deadline - time.monotonic()
        print(f"axon_guard: relay down, polling another {remaining:.0f}s ...",
              file=sys.stderr)
        time.sleep(min(5.0, max(remaining, 0.1)))
        alive = _relay_alive()
    if alive is None:
        # The CHECK failed (pgrep missing/slow) — the relay may well be
        # healthy, so let the end-to-end probe decide rather than
        # demoting a live chip to CPU on a process-listing hiccup.
        # A truly dead relay costs one probe deadline here.
        alive = True
    if alive:
        if not _wait_out_capture():
            print("axon_guard: relay watcher capture still holds the "
                  f"single-client tunnel after {_CAPTURE_WAIT_S:.0f}s "
                  "(chip artifacts are being produced by the watcher); "
                  "falling back to the CPU backend for this run "
                  "(results are exact, timings are NOT chip numbers)",
                  file=sys.stderr)
        elif tunnel_responsive():
            return False
        else:
            print("axon_guard: relay process is up but the tunnel is "
                  "unresponsive end-to-end (probe exceeded "
                  f"{_PROBE_TIMEOUT_S:.0f}s); falling back to the CPU "
                  "backend (results are exact, timings are NOT chip "
                  "numbers)", file=sys.stderr)
    else:
        print("axon_guard: axon relay is not running; falling back to the "
              "CPU backend (results are exact, timings are NOT chip "
              "numbers)", file=sys.stderr)
    import jax

    jax.config.update("jax_platforms", "cpu")
    scrub_axon_backend()
    return True
