"""Dead-relay fallback for axon-tunneled TPU environments.

On axon hosts the TPU is reached through a relay process; if the relay
dies, ANY jax backend init hangs forever on the registered PJRT plugin
(even with JAX_PLATFORMS=cpu in the environment — the site hook
registered the plugin at interpreter start).  `jax.config` wins any
time before backend init, so entry points that must always complete
(bench.py, __graft_entry__, benchmarks/measure.py) call
``guard_dead_relay()`` before touching devices.
"""

from __future__ import annotations

import os
import subprocess
import sys


def _relay_alive() -> bool:
    try:
        out = subprocess.run(["pgrep", "-f", r"\.relay\.py"],
                             capture_output=True, timeout=5)
        return bool(out.stdout.strip())
    except Exception as e:
        print(f"axon_guard: pgrep failed ({e}); assuming relay dead",
              file=sys.stderr)
        return False


def guard_dead_relay(wait_s: float = 0.0) -> bool:
    """When this process targets the axon backend but the relay is
    gone, pin jax to CPU (announced on stderr) so the run completes
    instead of hanging.  Returns True when the fallback engaged.  Does
    nothing unless JAX_PLATFORMS is EXPLICITLY "axon" — on ordinary
    TPU/GPU hosts the guard must never hide the real accelerator.

    ``wait_s`` > 0 polls for the relay to (re)appear before giving up —
    benchmark entry points use this so a briefly-restarting relay still
    yields a chip number instead of a CPU fallback."""
    if os.environ.get("JAX_PLATFORMS") != "axon":
        return False
    import time

    deadline = time.monotonic() + wait_s
    alive = _relay_alive()
    while not alive and time.monotonic() < deadline:
        remaining = deadline - time.monotonic()
        print(f"axon_guard: relay down, polling another {remaining:.0f}s ...",
              file=sys.stderr)
        time.sleep(min(5.0, max(remaining, 0.1)))
        alive = _relay_alive()
    if alive:
        return False
    print("axon_guard: axon relay is not running; falling back to the "
          "CPU backend (results are exact, timings are NOT chip "
          "numbers)", file=sys.stderr)
    import jax

    jax.config.update("jax_platforms", "cpu")
    return True
