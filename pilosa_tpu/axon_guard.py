"""Dead-relay fallback for axon-tunneled TPU environments.

On axon hosts the TPU is reached through a relay process; if the relay
dies, ANY jax backend init hangs forever on the registered PJRT plugin
(even with JAX_PLATFORMS=cpu in the environment — the site hook
registered the plugin at interpreter start).  `jax.config` wins any
time before backend init, so entry points that must always complete
(bench.py, __graft_entry__, benchmarks/measure.py) call
``guard_dead_relay()`` before touching devices.
"""

from __future__ import annotations

import os
import subprocess
import sys


def guard_dead_relay() -> bool:
    """When this process targets the axon backend but the relay is
    gone, pin jax to CPU (announced on stderr) so the run completes
    instead of hanging.  Returns True when the fallback engaged.  Does
    nothing unless JAX_PLATFORMS is EXPLICITLY "axon" — on ordinary
    TPU/GPU hosts the guard must never hide the real accelerator."""
    if os.environ.get("JAX_PLATFORMS") != "axon":
        return False
    try:
        out = subprocess.run(["pgrep", "-f", r"\.relay\.py"],
                             capture_output=True, timeout=5)
        alive = bool(out.stdout.strip())
    except Exception as e:
        print(f"axon_guard: pgrep failed ({e}); assuming relay dead",
              file=sys.stderr)
        alive = False
    if alive:
        return False
    print("axon_guard: axon relay is not running; falling back to the "
          "CPU backend (results are exact, timings are NOT chip "
          "numbers)", file=sys.stderr)
    import jax

    jax.config.update("jax_platforms", "cpu")
    return True
