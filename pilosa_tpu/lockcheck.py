"""Dynamic lock-order checker: the runtime companion to the static
``tools/analyze`` suite (the ``-race``-flavored half of the reference's
CI matrix, adapted to a GIL runtime where torn reads hide but lock
ORDER inversions still deadlock).

The codebase's documented order is **fragment -> compactor** (delta
writes under the fragment lock call ``note_delta``/``note_flushed``
which take the registry lock inside; the scan thread snapshots the
registry, RELEASES, then takes fragment locks — see
ingest/compactor.py's module docstring) and fragment/resultcache/
coalescer locks never nest into each other.  Those invariants were
re-verified by reviewer eyeballs in PR 6 rounds 1-5; this module
checks them mechanically in test runs.

With ``PILOSA_TPU_LOCKCHECK=1`` (or ``enable()`` before the guarded
objects are constructed) the fragment, compactor, result-cache, and
coalescer locks are created as :class:`CheckedLock` wrappers.  Every
acquisition records held -> acquiring edges in a process-wide order
graph, keyed by lock *class name* (``fragment``, ``compactor``,
``resultcache``, ``coalescer``) — and an acquisition that closes a
cycle (lock-order inversion: some thread has taken the same pair in
the opposite order) raises :class:`LockOrderError` immediately, at the
acquisition site, instead of deadlocking two racing threads some day
in production.

Scope notes:

- Same-name edges (fragment -> fragment across *instances*) are
  deliberately ignored: no code path nests two fragment locks, and a
  per-instance graph would make test fixtures quadratic.  The static
  P1/P3 passes own intra-class discipline.
- Disabled (the default), ``rlock()``/``lock()`` return the plain
  ``threading`` primitives — zero overhead on the hot path.
- ``CheckedLock`` implements the private Condition protocol
  (``_is_owned``/``_release_save``/``_acquire_restore``) so
  ``threading.Condition(fragment._lock)`` (the snapshot-done condvar)
  keeps working under instrumentation.
"""

from __future__ import annotations

import os
import threading

__all__ = ["enabled", "enable", "rlock", "lock", "reset",
           "CheckedLock", "LockOrderError", "order_graph"]

_enabled = os.environ.get("PILOSA_TPU_LOCKCHECK", "") == "1"

#: name -> {successor-name: first-recording thread name} — the
#: process-wide acquisition-order graph.
_graph: dict[str, dict[str, str]] = {}
_graph_lock = threading.Lock()
_tls = threading.local()


class LockOrderError(RuntimeError):
    """A lock acquisition closed a cycle in the order graph."""


def enabled() -> bool:
    return _enabled


def enable(on: bool = True) -> None:
    """Turn checking on/off for locks created AFTER this call (tests;
    the env var covers whole-process runs).  Existing plain locks are
    not retrofitted — reconstruct the guarded objects (e.g.
    ``compactor.reset()``) after enabling."""
    global _enabled
    _enabled = bool(on)


def reset() -> None:
    """Clear the recorded order graph (tests)."""
    with _graph_lock:
        _graph.clear()


def order_graph() -> dict[str, dict[str, str]]:
    """Copy of the recorded order graph: {held: {acquired: thread}}."""
    with _graph_lock:
        return {a: dict(bs) for a, bs in _graph.items()}


def rlock(name: str):
    """A named re-entrant lock — checked when the checker is enabled,
    a plain ``threading.RLock`` otherwise."""
    inner = threading.RLock()
    return CheckedLock(name, inner) if _enabled else inner


def lock(name: str):
    """A named non-reentrant lock — checked when enabled."""
    inner = threading.Lock()
    return CheckedLock(name, inner) if _enabled else inner


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def _path_exists(src: str, dst: str) -> list[str] | None:
    """DFS under _graph_lock: a recorded order path src -> ... -> dst,
    or None."""
    seen = {src}
    todo = [(src, [src])]
    while todo:
        node, path = todo.pop()
        for nxt in _graph.get(node, ()):
            if nxt == dst:
                return path + [dst]
            if nxt not in seen:
                seen.add(nxt)
                todo.append((nxt, path + [nxt]))
    return None


def _note_acquire(name: str) -> None:
    st = _stack()
    held = [h for h in st if h != name]
    if held:
        me = threading.current_thread().name
        with _graph_lock:
            for h in dict.fromkeys(held):  # unique, order-preserving
                # the reverse path existing FIRST is the inversion:
                # some earlier acquisition recorded name -> ... -> h,
                # and this thread now holds h while taking name
                rev = _path_exists(name, h)
                if rev is not None:
                    raise LockOrderError(
                        f"lock-order inversion: thread {me!r} "
                        f"acquires {name!r} while holding {h!r}, but "
                        f"the order {' -> '.join(rev)} was already "
                        f"recorded (first by thread "
                        f"{_graph[rev[0]][rev[1]]!r}); one of the "
                        "two nestings must flip or drop the outer "
                        "lock")
                _graph.setdefault(h, {}).setdefault(name, me)
    st.append(name)


def _note_release(name: str) -> None:
    st = _stack()
    # remove the innermost matching entry (re-entrant acquires push
    # one entry per acquire, releases pop symmetrically)
    for i in range(len(st) - 1, -1, -1):
        if st[i] == name:
            del st[i]
            return


class CheckedLock:
    """Order-checking wrapper over a ``threading`` lock primitive."""

    __slots__ = ("name", "_inner")

    def __init__(self, name: str, inner):
        self.name = name
        self._inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        # record BEFORE blocking: an inversion should raise at the
        # acquisition site, not deadlock first and raise never
        _note_acquire(self.name)
        try:
            ok = self._inner.acquire(blocking, timeout)
        except BaseException:
            _note_release(self.name)
            raise
        if not ok:
            _note_release(self.name)
        return ok

    def release(self) -> None:
        self._inner.release()
        _note_release(self.name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        inner_locked = getattr(self._inner, "locked", None)
        return inner_locked() if inner_locked is not None else False

    # ------------------------- threading.Condition private protocol

    def _is_owned(self) -> bool:
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def _release_save(self):
        """Fully release (Condition.wait): pop every held-stack entry
        for this name and remember how many, so the restore can
        repush them."""
        st = _stack()
        k = 0
        for i in range(len(st) - 1, -1, -1):
            if st[i] == self.name:
                del st[i]
                k += 1
        if hasattr(self._inner, "_release_save"):
            return (self._inner._release_save(), k)
        self._inner.release()
        return (None, k)

    def _acquire_restore(self, saved) -> None:
        token, k = saved
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(token)
        else:
            self._inner.acquire()
        _stack().extend([self.name] * max(1, k))

    def __repr__(self) -> str:
        return f"<CheckedLock {self.name} {self._inner!r}>"
