"""`python -m pilosa_tpu` entrypoint (reference cmd/pilosa/main.go:27)."""

import sys

from pilosa_tpu.cmd import main

sys.exit(main())
