"""Roaring codec: Pilosa's 64-bit roaring file format (cookie 12348).

Bit-compatible with the reference's serialization (docs/architecture.md:
9-24, roaring/roaring.go:1046 WriteTo, roaring/unmarshal_binary.go) so
`import-roaring` payloads, exports, and fragment transfers interoperate.

Two implementations with identical observable behavior:
- **native** (default): C++ (pilosa_tpu/native/roaring_codec.cpp) via
  ctypes, compiled on first use with the toolchain in the image.
- **numpy fallback**: vectorized Python used when no compiler exists.

Decoded form is (keys u64[n], words u64[n, 1024]) — dense 2^16-bit blocks
keyed by position>>16, which reinterpret directly as the uint32 packed
tensors the device kernels consume.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from pilosa_tpu.native_loader import NativeLib

WORDS_PER_CONTAINER = 1024
CONTAINER_BITS = 1 << 16
MAGIC = 12348
COOKIE_OFFICIAL = 12346       # official roaring, no run containers
COOKIE_OFFICIAL_RUNS = 12347  # official roaring + run-flag bitset

#: Container kinds as the DEVICE directory numbers them (the kind byte
#: in ops/containers.ContainerLeaf; the wire format's type field uses
#: 1=array/2=bitmap/3=run instead — see ``_WIRE_TYPE``).
KIND_BITMAP = 1
KIND_ARRAY = 2
KIND_RUN = 3

#: The reference's array-container cardinality ceiling: above this a
#: sorted-uint16 array costs more than the 8 KiB bitmap.
ARRAY_MAX_CARD = 4096

#: Device kind -> serialized container type (roaring/roaring.go
#: containerArray/containerBitmap/containerRun).
_WIRE_TYPE = {KIND_ARRAY: 1, KIND_BITMAP: 2, KIND_RUN: 3}


def pick_kind(card: int, n_runs: int,
              array_max: int = ARRAY_MAX_CARD) -> int:
    """The roaring cost rule: cheapest of bitmap (8192 B), sorted
    uint16 array (2*card B, card <= array_max), interval-list run
    (2 + 4*n_runs B) — byte-for-byte the serializer's choice
    (roaring/roaring.go optimize()), shared by ``_encode_py`` and the
    device directory build so wire and device kinds can never drift.
    ``array_max`` only narrows the device pick (size-class packing
    caps); serialization always passes the canonical 4096."""
    array_size = 2 * card if card <= array_max else 1 << 62
    run_size = 2 + 4 * n_runs
    if run_size < array_size and run_size < 8192:
        return KIND_RUN
    if array_size <= 8192:
        return KIND_ARRAY
    return KIND_BITMAP


def container_stats(words: np.ndarray) -> tuple[int, int]:
    """(cardinality, interval-run count) of one dense container given
    as uint64[1024] or uint32[2048] words — the two inputs of
    ``pick_kind``."""
    w = np.ascontiguousarray(words)
    card = int(np.bitwise_count(w).sum(dtype=np.uint64))
    if card == 0:
        return 0, 0
    bits = np.unpackbits(w.view(np.uint8), bitorder="little")
    runs = int(np.count_nonzero(
        np.diff(np.concatenate(([0], bits))) == 1))
    return card, runs

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_SRC = os.path.join(_NATIVE_DIR, "roaring_codec.cpp")
_SO = os.path.join(_NATIVE_DIR, "build", "libpilosa_native.so")


def _setup(lib) -> None:
    lib.pilosa_roaring_decode.restype = ctypes.c_int
    lib.pilosa_roaring_decode.argtypes = [
        ctypes.c_char_p,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint64)),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint64)),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint8),
    ]
    lib.pilosa_roaring_encode.restype = ctypes.c_int
    lib.pilosa_roaring_encode.argtypes = [
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_uint64,
        ctypes.c_uint8,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.pilosa_roaring_free_buf.argtypes = [ctypes.c_void_p]
    lib.pilosa_roaring_decode_positions.restype = ctypes.c_int
    lib.pilosa_roaring_decode_positions.argtypes = [
        ctypes.c_char_p,
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint64)),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint8),
    ]


_NATIVE = NativeLib(src=_SRC, so=_SO, setup=_setup)


def _load_native():
    return _NATIVE.load()


def native_available() -> bool:
    return _NATIVE.available()


_ERRORS = {
    -1: "truncated roaring data",
    -2: "bad roaring magic (want 12348)",
    -3: "unsupported roaring file version",
    -4: "unknown container type",
    -5: "container offset out of bounds",
    -6: "serialized size exceeds the format's 4 GiB offset limit",
    -7: "decoded positions exceed the caller's cap",
}


class RoaringError(ValueError):
    pass


# --------------------------------------------------------------- decode


def decode(data: bytes) -> tuple[np.ndarray, np.ndarray, int]:
    """Parse serialized roaring -> (keys u64[n], words u64[n,1024], flags).

    Accepts both the pilosa 64-bit format (cookie 12348) and the
    official 32-bit roaring interchange format (cookies 12346/12347),
    like the reference's UnmarshalBinary (roaring/unmarshal_binary.go
    handles both; the official-format golden file is
    roaring/testdata/bitmapcontainer.roaringbitmap)."""
    if len(data) >= 4:
        cookie16 = int.from_bytes(data[:2], "little")
        cookie32 = int.from_bytes(data[:4], "little")
        if cookie32 == COOKIE_OFFICIAL or cookie16 == COOKIE_OFFICIAL_RUNS:
            return _decode_official(data)
    lib = _load_native()
    if lib is not None:
        keys_p = ctypes.POINTER(ctypes.c_uint64)()
        words_p = ctypes.POINTER(ctypes.c_uint64)()
        n = ctypes.c_uint64()
        flags = ctypes.c_uint8()
        rc = lib.pilosa_roaring_decode(
            data, len(data),
            ctypes.byref(keys_p), ctypes.byref(words_p),
            ctypes.byref(n), ctypes.byref(flags),
        )
        if rc != 0:
            raise RoaringError(_ERRORS.get(rc, f"roaring decode error {rc}"))
        nv = n.value
        try:
            keys = np.ctypeslib.as_array(keys_p, shape=(nv,)).copy() if nv else np.empty(0, np.uint64)
            words = (
                np.ctypeslib.as_array(words_p, shape=(nv, WORDS_PER_CONTAINER)).copy()
                if nv else np.empty((0, WORDS_PER_CONTAINER), np.uint64)
            )
        finally:
            lib.pilosa_roaring_free_buf(keys_p)
            lib.pilosa_roaring_free_buf(words_p)
        return keys, words, flags.value
    return _decode_py(data)


def _decode_py(data: bytes) -> tuple[np.ndarray, np.ndarray, int]:
    buf = np.frombuffer(data, dtype=np.uint8)
    if len(buf) < 8:
        raise RoaringError("truncated roaring data")
    magic = int(buf[0]) | (int(buf[1]) << 8)
    if magic != MAGIC:
        raise RoaringError("bad roaring magic (want 12348)")
    if buf[2] != 0:
        raise RoaringError("unsupported roaring file version")
    flags = int(buf[3])
    n = int(np.frombuffer(data, dtype=np.uint32, count=1, offset=4)[0])
    if len(buf) < 8 + n * 16:
        raise RoaringError("truncated roaring data")
    # 12-byte descriptive entries, then a separate 4-byte offset section
    desc = np.frombuffer(data, dtype=np.uint8, count=n * 12, offset=8)
    keys = desc.reshape(n, 12)[:, :8].copy().view(np.uint64).reshape(n)
    typs = desc.reshape(n, 12)[:, 8:10].copy().view(np.uint16).reshape(n)
    cards = desc.reshape(n, 12)[:, 10:12].copy().view(np.uint16).reshape(n).astype(np.int64) + 1
    offs = np.frombuffer(data, dtype=np.uint32, count=n, offset=8 + n * 12).astype(np.int64)
    words = np.zeros((n, WORDS_PER_CONTAINER), dtype=np.uint64)
    for i in range(n):
        off, typ, card = int(offs[i]), int(typs[i]), int(cards[i])
        w8 = words[i].view(np.uint8)
        if typ == 1:  # array
            if off + 2 * card > len(buf):
                raise RoaringError("container offset out of bounds")
            vals = np.frombuffer(data, dtype=np.uint16, count=card, offset=off).astype(np.int64)
            np.bitwise_or.at(
                words[i], vals // 64, np.uint64(1) << (vals % 64).astype(np.uint64)
            )
        elif typ == 2:  # bitmap
            if off + 8192 > len(buf):
                raise RoaringError("container offset out of bounds")
            w8[:] = buf[off : off + 8192]
        elif typ == 3:  # run
            if off + 2 > len(buf):
                raise RoaringError("container offset out of bounds")
            rc = int(np.frombuffer(data, dtype=np.uint16, count=1, offset=off)[0])
            if off + 2 + 4 * rc > len(buf):
                raise RoaringError("container offset out of bounds")
            runs = np.frombuffer(data, dtype=np.uint16, count=2 * rc, offset=off + 2).reshape(rc, 2)
            bits = np.zeros(CONTAINER_BITS, dtype=bool)
            for start, last in runs.astype(np.int64):
                bits[start : last + 1] = True
            words[i] = np.packbits(bits, bitorder="little").view(np.uint64)
        else:
            raise RoaringError("unknown container type")
    return keys, words, flags


def _decode_official(data: bytes) -> tuple[np.ndarray, np.ndarray, int]:
    """Official 32-bit roaring (RoaringFormatSpec) -> dense containers
    with 16-bit keys widened to 64 (reference readOffsets/readWithRuns,
    roaring/unmarshal_binary.go)."""
    buf = memoryview(data)
    if len(buf) < 4:
        raise RoaringError("truncated roaring data")
    cookie16 = int.from_bytes(buf[:2], "little")
    has_runs = cookie16 == COOKIE_OFFICIAL_RUNS
    pos = 0
    if has_runs:
        n = (int.from_bytes(buf[2:4], "little")) + 1
        pos = 4
        run_flag_bytes = (n + 7) // 8
        if len(buf) < pos + run_flag_bytes:
            raise RoaringError("truncated roaring data")
        run_flags = np.unpackbits(
            np.frombuffer(buf[pos:pos + run_flag_bytes], dtype=np.uint8),
            bitorder="little")[:n]
        pos += run_flag_bytes
    else:
        if len(buf) < 8:
            raise RoaringError("truncated roaring data")
        n = int.from_bytes(buf[4:8], "little")
        pos = 8
        run_flags = np.zeros(n, dtype=np.uint8)
    if len(buf) < pos + 4 * n:
        raise RoaringError("truncated roaring data")
    desc = np.frombuffer(buf[pos:pos + 4 * n], dtype=np.uint16).reshape(n, 2)
    keys16 = desc[:, 0].astype(np.int64)
    cards = desc[:, 1].astype(np.int64) + 1
    pos += 4 * n
    # offset header present unless (runs format and n < 4)
    if not has_runs or n >= 4:
        if len(buf) < pos + 4 * n:
            raise RoaringError("truncated roaring data")
        pos += 4 * n  # offsets unused: containers are contiguous anyway
    keys = keys16.astype(np.uint64)
    words = np.zeros((n, WORDS_PER_CONTAINER), dtype=np.uint64)
    for i in range(n):
        card = int(cards[i])
        if run_flags[i]:
            if len(buf) < pos + 2:
                raise RoaringError("truncated roaring data")
            rc = int.from_bytes(buf[pos:pos + 2], "little")
            pos += 2
            if len(buf) < pos + 4 * rc:
                raise RoaringError("truncated roaring data")
            runs = np.frombuffer(buf[pos:pos + 4 * rc],
                                 dtype=np.uint16).reshape(rc, 2)
            pos += 4 * rc
            bits = np.zeros(CONTAINER_BITS, dtype=bool)
            # official runs are (start, length-1)
            for start, length in runs.astype(np.int64):
                bits[start:start + length + 1] = True
            words[i] = np.packbits(bits, bitorder="little").view(np.uint64)
        elif card <= 4096:  # array container
            if len(buf) < pos + 2 * card:
                raise RoaringError("truncated roaring data")
            vals = np.frombuffer(buf[pos:pos + 2 * card],
                                 dtype=np.uint16).astype(np.int64)
            pos += 2 * card
            np.bitwise_or.at(words[i], vals // 64,
                             np.uint64(1) << (vals % 64).astype(np.uint64))
        else:  # bitmap container
            if len(buf) < pos + 8192:
                raise RoaringError("truncated roaring data")
            words[i].view(np.uint8)[:] = np.frombuffer(
                buf[pos:pos + 8192], dtype=np.uint8)
            pos += 8192
    return keys, words, 0


# --------------------------------------------------------------- encode


def encode(keys: np.ndarray, words: np.ndarray, flags: int = 0) -> bytes:
    """Serialize dense containers -> roaring bytes.  keys must be sorted
    ascending and unique; empty containers are dropped."""
    keys = np.asarray(keys, dtype=np.uint64)
    words = np.ascontiguousarray(words, dtype=np.uint64).reshape(-1, WORDS_PER_CONTAINER)
    if len(keys) != len(words):
        raise ValueError("keys and words length mismatch")
    if len(keys) > 1 and not (keys[:-1] < keys[1:]).all():
        raise ValueError("keys must be sorted ascending and unique")
    lib = _load_native()
    if lib is not None:
        buf_p = ctypes.POINTER(ctypes.c_uint8)()
        blen = ctypes.c_uint64()
        rc = lib.pilosa_roaring_encode(
            keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            words.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            len(keys), flags,
            ctypes.byref(buf_p), ctypes.byref(blen),
        )
        if rc != 0:
            raise RoaringError(_ERRORS.get(rc, f"roaring encode error {rc}"))
        try:
            out = bytes(np.ctypeslib.as_array(buf_p, shape=(blen.value,))) if blen.value else b""
        finally:
            lib.pilosa_roaring_free_buf(buf_p)
        return out
    return _encode_py(keys, words, flags)


def _encode_py(keys: np.ndarray, words: np.ndarray, flags: int) -> bytes:
    plans = []
    for i in range(len(keys)):
        w = words[i]
        card = int(np.bitwise_count(w).sum())
        if card == 0:
            continue
        bits = np.unpackbits(w.view(np.uint8), bitorder="little")
        starts = np.nonzero(np.diff(np.concatenate(([0], bits))) == 1)[0]
        ends = np.nonzero(np.diff(np.concatenate((bits, [0]))) == -1)[0]
        runs = len(starts)
        typ = _WIRE_TYPE[pick_kind(card, runs)]
        plans.append((int(keys[i]), card, typ, runs, w, bits, starts, ends))

    out = bytearray()
    out += int(MAGIC).to_bytes(2, "little")
    out += bytes([0, flags])
    out += len(plans).to_bytes(4, "little")
    for key, card, typ, _, _, _, _, _ in plans:
        out += int(key).to_bytes(8, "little")
        out += int(typ).to_bytes(2, "little")
        out += int(card - 1).to_bytes(2, "little")
    offset = 8 + len(plans) * 12 + len(plans) * 4
    for _, card, typ, runs, _, _, _, _ in plans:
        if offset > 0xFFFFFFFF:
            raise RoaringError(_ERRORS[-6])
        out += int(offset).to_bytes(4, "little")
        offset += {1: 2 * card, 2: 8192, 3: 2 + 4 * runs}[typ]
    for _, card, typ, runs, w, bits, starts, ends in plans:
        if typ == 1:
            out += np.nonzero(bits)[0].astype(np.uint16).tobytes()
        elif typ == 2:
            out += w.tobytes()
        else:
            out += int(runs).to_bytes(2, "little")
            pairs = np.empty((runs, 2), dtype=np.uint16)
            pairs[:, 0] = starts
            pairs[:, 1] = ends
            out += pairs.tobytes()
    return bytes(out)


def payload_stats(data: bytes) -> tuple[int, int] | None:
    """Cheap (n_containers, n_set_bits) from the descriptive headers
    alone — no container expansion.  Lets ingest choose between the
    dense container merge (cost ∝ containers x 1024 words) and the
    position-space merge (cost ∝ set bits) before paying either.
    Returns None when the header can't be parsed (caller falls back to
    the dense path, which owns the error reporting)."""
    try:
        if len(data) < 8:
            return None
        cookie16 = int.from_bytes(data[:2], "little")
        cookie32 = int.from_bytes(data[:4], "little")
        if cookie16 == MAGIC:
            n = int.from_bytes(data[4:8], "little")
            if len(data) < 8 + n * 12:
                return None
            desc = np.frombuffer(data, dtype=np.uint8, count=n * 12,
                                 offset=8).reshape(n, 12)
            cards = (desc[:, 10:12].copy().view(np.uint16)
                     .astype(np.int64) + 1)
            return n, int(cards.sum())
        if cookie16 == COOKIE_OFFICIAL_RUNS:
            n = int.from_bytes(data[2:4], "little") + 1
            pos = 4 + (n + 7) // 8
        elif cookie32 == COOKIE_OFFICIAL:
            n = int.from_bytes(data[4:8], "little")
            pos = 8
        else:
            return None
        if len(data) < pos + 4 * n:
            return None
        desc = np.frombuffer(data, dtype=np.uint16, count=2 * n,
                             offset=pos).reshape(n, 2)
        return n, int((desc[:, 1].astype(np.int64) + 1).sum())
    except Exception:  # noqa: BLE001 — stats are advisory only
        return None


def decode_positions(data: bytes,
                     max_positions: int = 1 << 28) -> np.ndarray:
    """Parse serialized roaring -> sorted absolute bit positions
    (u64[n_bits]) WITHOUT materializing dense 1024-word blocks — the
    sparse-ingest fast path (the analog of the reference's streamed
    ImportRoaringBits iterator, roaring/roaring.go:1511, which likewise
    walks containers without densifying arrays).  Raises RoaringError
    when the ACTUAL emitted count would exceed ``max_positions`` —
    descriptor cardinalities are untrusted (a hostile run container can
    lie small); callers fall back to the chunk-bounded dense path."""
    if len(data) >= 2 and int.from_bytes(data[:2], "little") == MAGIC:
        lib = _load_native()
        if lib is not None:
            pos_p = ctypes.POINTER(ctypes.c_uint64)()
            n = ctypes.c_uint64()
            flags = ctypes.c_uint8()
            rc = lib.pilosa_roaring_decode_positions(
                data, len(data), int(max_positions), ctypes.byref(pos_p),
                ctypes.byref(n), ctypes.byref(flags))
            if rc != 0:
                raise RoaringError(
                    _ERRORS.get(rc, f"roaring decode error {rc}"))
            nv = n.value
            try:
                out = (np.ctypeslib.as_array(pos_p, shape=(nv,)).copy()
                       if nv else np.empty(0, np.uint64))
            finally:
                lib.pilosa_roaring_free_buf(pos_p)
            return out
    keys, words, _flags = decode(data)
    return containers_to_positions(keys, words)


# ------------------------------------------------- position conversion


def positions_to_containers(positions) -> tuple[np.ndarray, np.ndarray]:
    """Sorted absolute bit positions -> (keys, dense words) containers."""
    pos = np.asarray(positions, dtype=np.uint64)
    if len(pos) == 0:
        return np.empty(0, np.uint64), np.empty((0, WORDS_PER_CONTAINER), np.uint64)
    keys = np.unique(pos >> np.uint64(16))
    slot = np.searchsorted(keys, pos >> np.uint64(16))
    words = np.zeros((len(keys), WORDS_PER_CONTAINER), dtype=np.uint64)
    low = pos & np.uint64(0xFFFF)
    flat_idx = slot * WORDS_PER_CONTAINER + (low >> np.uint64(6)).astype(np.int64)
    np.bitwise_or.at(
        words.reshape(-1), flat_idx, np.uint64(1) << (low & np.uint64(63))
    )
    return keys, words


def containers_to_positions(keys: np.ndarray, words: np.ndarray) -> np.ndarray:
    """Inverse of positions_to_containers: sorted absolute positions."""
    out = []
    for i in range(len(keys)):
        bits = np.unpackbits(words[i].view(np.uint8), bitorder="little")
        nz = np.nonzero(bits)[0].astype(np.uint64)
        out.append((np.uint64(int(keys[i]) << 16)) + nz)
    if not out:
        return np.empty(0, dtype=np.uint64)
    return np.concatenate(out)
