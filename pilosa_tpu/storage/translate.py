"""Key translation: string keys ⇄ uint64 ids, per index and per field.

Parity target: the reference's TranslateStore interface (translate.go:35)
with its two implementations — in-memory (translate.go:195) and the
persistent BoltDB store with monotonic sequence allocation
(boltdb/translate.go:48,140).  Ours uses sqlite3 (stdlib, transactional)
for the persistent tier; ids allocate from 1 the way the reference's
bucket sequence does.

Replication model (reference holder.go:690-878, http/translator.go:30):
exactly one primary store per (index, field) accepts writes; replicas
open read-only and tail the primary's append-ordered entry stream via
``entries(after_offset)`` / ``apply_entry``.  The cluster layer decides
who is primary; this module only enforces the read-only flag.
"""

from __future__ import annotations

import os
import sqlite3
import threading


class TranslateError(ValueError):
    pass


class ReadOnlyError(TranslateError):
    """Write attempted on a non-primary translate store
    (reference ErrTranslateStoreReadOnly, translate.go:28)."""


class TranslateStore:
    """Interface; see module docstring.  Offsets are 1-based and dense:
    the entry with offset N is the Nth key ever created, so replicas
    resume from their local max offset."""

    read_only = False

    def translate_key(self, key: str, create: bool = False) -> int | None:
        raise NotImplementedError

    def translate_keys(self, keys, create: bool = False) -> list[int | None]:
        return [self.translate_key(k, create) for k in keys]

    def translate_id(self, id: int) -> str | None:
        raise NotImplementedError

    def translate_ids(self, ids) -> list[str | None]:
        return [self.translate_id(i) for i in ids]

    def max_offset(self) -> int:
        raise NotImplementedError

    def entries(self, after: int, limit: int = 10000) -> list[tuple[int, int, str]]:
        """Replication stream: [(offset, id, key)] with offset > after."""
        raise NotImplementedError

    def apply_entry(self, offset: int, id: int, key: str) -> None:
        """Replica-side apply of a streamed entry (idempotent)."""
        raise NotImplementedError

    def apply_entries(self, entries) -> None:
        """Replica-side apply of a whole streamed page
        [(offset, id, key)] — overridden where a single transaction
        beats per-entry commits (tailing a 1M-key backlog pays one
        fsync per PAGE, not per key)."""
        for off, id_, key in entries:
            self.apply_entry(off, id_, key)

    def set_read_only(self, ro: bool) -> None:
        self.read_only = ro

    def close(self) -> None:
        pass

    def _check_writable(self) -> None:
        if self.read_only:
            raise ReadOnlyError("translate store is read-only (non-primary replica)")


class MemTranslateStore(TranslateStore):
    """Dict-backed store (reference inMemTranslateStore, translate.go:195)."""

    def __init__(self):
        self._by_key: dict[str, int] = {}
        self._by_id: dict[int, str] = {}
        self._log: list[tuple[int, int, str]] = []
        self._lock = threading.Lock()

    def translate_key(self, key: str, create: bool = False) -> int | None:
        with self._lock:
            id = self._by_key.get(key)
            if id is not None or not create:
                return id
            self._check_writable()
            id = len(self._log) + 1
            self._by_key[key] = id
            self._by_id[id] = key
            self._log.append((id, id, key))
            return id

    def translate_id(self, id: int) -> str | None:
        with self._lock:
            return self._by_id.get(id)

    def max_offset(self) -> int:
        with self._lock:
            return len(self._log)

    def entries(self, after: int, limit: int = 10000) -> list[tuple[int, int, str]]:
        with self._lock:
            return self._log[after : after + limit]

    def apply_entry(self, offset: int, id: int, key: str) -> None:
        with self._lock:
            if self._by_id.get(id) == key:
                return
            self._by_key[key] = id
            self._by_id[id] = key
            self._log.append((offset, id, key))


class SQLiteTranslateStore(TranslateStore):
    """Persistent store (reference boltdb/translate.go:48).  One table of
    (id INTEGER PRIMARY KEY, key TEXT UNIQUE); AUTOINCREMENT gives the
    monotonic sequence the reference allocates from its bolt bucket
    (boltdb/translate.go:140), and rowid order IS the replication offset
    order because ids are append-only and never reused."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self._local = threading.local()
        self._lock = threading.Lock()
        # Initialize schema once via a dedicated connection.
        con = self._conn()
        with self._lock:
            con.execute(
                "CREATE TABLE IF NOT EXISTS keys ("
                "id INTEGER PRIMARY KEY AUTOINCREMENT, key TEXT UNIQUE NOT NULL)"
            )
            con.commit()

    def _conn(self) -> sqlite3.Connection:
        con = getattr(self._local, "con", None)
        if con is None:
            con = sqlite3.connect(self.path, timeout=30.0)
            con.execute("PRAGMA journal_mode=WAL")
            con.execute("PRAGMA synchronous=NORMAL")
            self._local.con = con
        return con

    def translate_key(self, key: str, create: bool = False) -> int | None:
        con = self._conn()
        cur = con.execute("SELECT id FROM keys WHERE key = ?", (key,))
        row = cur.fetchone()
        if row is not None:
            return int(row[0])
        if not create:
            return None
        self._check_writable()
        with self._lock:
            try:
                cur = con.execute("INSERT INTO keys (key) VALUES (?)", (key,))
                con.commit()
                return int(cur.lastrowid)
            except sqlite3.IntegrityError:  # lost a create race
                con.rollback()
                cur = con.execute("SELECT id FROM keys WHERE key = ?", (key,))
                return int(cur.fetchone()[0])

    def translate_keys(self, keys, create: bool = False) -> list[int | None]:
        """Batched translate: IN-chunked lookups plus ONE
        INSERT-OR-IGNORE transaction for all new keys — the per-key
        path commits (fsyncs) once per new key, which dominates keyed
        bulk imports.  Same race semantics as translate_key: a
        concurrent creator wins and the re-select picks up its id."""
        # normalize to str up front: the per-key path matched numeric
        # keys through SQLite's TEXT affinity, but a dict keyed on the
        # DB's returned strings would miss them; None never worked (it
        # crashed in the race handler) so reject it loudly
        norm = []
        for k in keys:
            if k is None:
                raise ValueError("null key")
            norm.append(k if isinstance(k, str) else str(k))
        keys = norm
        uniq = list(dict.fromkeys(keys))
        con = self._conn()
        found: dict[str, int] = {}

        def select_into(chunked):
            for k, id_ in self._select_in(con, "key", chunked):
                found[k] = int(id_)

        select_into(uniq)
        if create:
            missing = [k for k in uniq if k not in found]
            if missing:
                self._check_writable()
                with self._lock:
                    try:
                        con.executemany(
                            "INSERT OR IGNORE INTO keys (key) VALUES (?)",
                            [(k,) for k in missing])
                        con.commit()
                    except Exception:
                        con.rollback()
                        raise
                select_into(missing)
        return [found.get(k) for k in keys]

    def translate_id(self, id: int) -> str | None:
        cur = self._conn().execute("SELECT key FROM keys WHERE id = ?", (int(id),))
        row = cur.fetchone()
        return None if row is None else row[0]

    @staticmethod
    def _select_in(con, column: str, values):
        """(key, id) rows for ``values`` matched on ``column``, one
        IN-query per 500 values (comfortably under SQLite's 999
        parameter floor) — the shared chunking for both batched
        directions."""
        for i in range(0, len(values), 500):
            chunk = values[i:i + 500]
            yield from con.execute(
                "SELECT key, id FROM keys WHERE "
                f"{column} IN ({','.join('?' * len(chunk))})", chunk)

    def translate_ids(self, ids) -> list[str | None]:
        """Batched lookup: one IN-query per 500 ids instead of a
        round-trip per id (translating a large Row result is otherwise
        dominated by per-id SELECTs)."""
        ids = [int(i) for i in ids]
        found: dict[int, str] = {}
        for key, id_ in self._select_in(self._conn(), "id", ids):
            found[int(id_)] = key
        return [found.get(i) for i in ids]

    def max_offset(self) -> int:
        cur = self._conn().execute("SELECT COALESCE(MAX(rowid), 0) FROM keys")
        return int(cur.fetchone()[0])

    def entries(self, after: int, limit: int = 10000) -> list[tuple[int, int, str]]:
        cur = self._conn().execute(
            "SELECT rowid, id, key FROM keys WHERE rowid > ? ORDER BY rowid LIMIT ?",
            (int(after), int(limit)),
        )
        return [(int(o), int(i), k) for o, i, k in cur.fetchall()]

    def apply_entry(self, offset: int, id: int, key: str) -> None:
        con = self._conn()
        with self._lock:
            con.execute(
                "INSERT OR IGNORE INTO keys (id, key) VALUES (?, ?)", (int(id), key)
            )
            con.commit()

    def apply_entries(self, entries) -> None:
        """One INSERT-OR-IGNORE transaction per streamed page: a
        replica catching up a large backlog commits once per ~10k-entry
        page instead of once per key (the per-entry path fsynced every
        apply — the dominant cost of 1M-key tail catch-up)."""
        con = self._conn()
        with self._lock:
            try:
                con.executemany(
                    "INSERT OR IGNORE INTO keys (id, key) VALUES (?, ?)",
                    [(int(id_), key) for _, id_, key in entries])
                con.commit()
            except Exception:
                con.rollback()
                raise

    def close(self) -> None:
        con = getattr(self._local, "con", None)
        if con is not None:
            con.close()
            self._local.con = None


def open_translate_store(path: str | None) -> TranslateStore:
    """Persistent store when a path exists, in-memory otherwise — the same
    split the holder makes for every other storage tier."""
    if path is None:
        return MemTranslateStore()
    return SQLiteTranslateStore(path)
