"""Storage/interchange: the roaring file codec and fragment archives.

Roaring's container layout survives only at this boundary (file format
compatibility for import/export and node-to-node transfer); the compute
path is dense packed tensors (SURVEY.md §7 design stance).
"""

from pilosa_tpu.storage.roaring import (
    decode,
    encode,
    native_available,
    positions_to_containers,
    containers_to_positions,
)

__all__ = [
    "decode",
    "encode",
    "native_available",
    "positions_to_containers",
    "containers_to_positions",
]
