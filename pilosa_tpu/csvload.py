"""ctypes binding for the native bulk CSV parser (libcsvload).

The native data-loader behind ``pilosa-tpu import`` (reference
bufferBits, ctl/import.go:173): the all-integer two-column forms
("row,col[,]" and "col,value") parse in C++ straight into numpy int64
buffers; anything else — timestamps, quoting, non-integer fields —
falls back to the Python csv path, which remains the semantics oracle
(differential-tested in tests/test_csvload.py)."""

from __future__ import annotations

import ctypes
import io
import os

import numpy as np

from pilosa_tpu.native_loader import NativeLib

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "native")


def _setup(lib) -> None:
    lib.csvload_parse2.argtypes = [
        ctypes.c_char_p, ctypes.c_longlong,
        ctypes.POINTER(ctypes.c_longlong),
        ctypes.POINTER(ctypes.c_longlong),
        ctypes.c_longlong,
        ctypes.POINTER(ctypes.c_longlong),
    ]
    lib.csvload_parse2.restype = ctypes.c_longlong


_NATIVE = NativeLib(
    src=os.path.join(_NATIVE_DIR, "csv_loader.cpp"),
    so=os.path.join(_NATIVE_DIR, "build", "libcsvload.so"),
    setup=_setup,
)


def available() -> bool:
    return _NATIVE.available()


class NeedsFallback(Exception):
    """The chunk contains records the native fast path does not handle
    (timestamps, quoting, malformed or overflowing fields, or the
    library is unavailable); parse it with the Python csv path, whose
    accept/reject verdict is authoritative."""


def parse_pairs(data: bytes):
    """Parse a buffer of "A,B" integer lines -> (int64 array, int64
    array).  Raises NeedsFallback whenever the buffer needs the general
    path — the native parser never decides validity itself."""
    lib = _NATIVE.load()
    if lib is None:
        raise NeedsFallback("native loader unavailable")
    # every record is >= 4 bytes ("a,b\n"), so len/4+1 rows always fit
    cap = len(data) // 4 + 2
    a = np.empty(cap, dtype=np.int64)
    b = np.empty(cap, dtype=np.int64)
    err = ctypes.c_longlong(0)
    n = lib.csvload_parse2(
        data, len(data),
        a.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        b.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        cap, ctypes.byref(err),
    )
    if n < 0:
        raise NeedsFallback(
            f"general path needed at line {err.value} (code {n})")
    return a[:n], b[:n]


def raw_stream(stream):
    """The byte source under a possibly-text stream."""
    return getattr(stream, "buffer", stream)


def read_chunk(raw, chunk_bytes: int) -> bytes:
    chunk = raw.read(chunk_bytes)
    if isinstance(chunk, str):  # StringIO-style test streams
        chunk = chunk.encode()
    return chunk or b""


def chain_text(head: bytes, raw):
    """A universal-newlines TEXT stream reading ``head`` then the rest
    of ``raw`` — hands the un-consumed remainder of a chunked byte
    stream back to the streaming Python csv path in one piece, so
    quoted records spanning chunk boundaries are never torn."""

    class _Raw(io.RawIOBase):
        def __init__(self):
            # pending bytes: the head, then any excess a str-returning
            # source produced (N characters can encode to > N bytes)
            self._pending = memoryview(bytes(head))
            self._pos = 0

        def readable(self):
            return True

        def readinto(self, b):
            if self._pos >= len(self._pending):
                self._pending = memoryview(read_chunk(raw, len(b)))
                self._pos = 0
            n = min(len(b), len(self._pending) - self._pos)
            b[:n] = self._pending[self._pos:self._pos + n]
            self._pos += n
            return n

    # newline=None: universal-newline translation, matching what
    # open(path) did before the bytes detour
    return io.TextIOWrapper(io.BufferedReader(_Raw()), newline=None)
